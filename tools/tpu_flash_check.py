"""On-chip flash-attention validation: correctness vs the dense oracle and
an honestly-fenced flash/dense timing A/B.

The pallas kernels' unit tests run under the CPU interpreter
(tests/test_attention.py); this tool is the real-hardware counterpart —
run it whenever a chip window opens:

    timeout 600 python tools/tpu_flash_check.py

All timing uses value readbacks, never ``block_until_ready``
(docs/troubleshooting.md "Tunnel claim mechanics" #4).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import faulthandler

faulthandler.dump_traceback_later(
    int(os.environ.get("STAGE_TIMEOUT", "240")), exit=True)

import jax
import jax.numpy as jnp
import numpy as np

t0 = time.monotonic()


def note(msg):
    print(f"[+{time.monotonic() - t0:.1f}s] {msg}", flush=True)
    # Re-arm: the bound is per-STAGE, not total — a healthy cold-chip run
    # (several 10-40 s remote compiles) must not be force-exited just
    # because the stages add up (same pattern as tpu_bringup_probe.py).
    faulthandler.dump_traceback_later(
        int(os.environ.get("STAGE_TIMEOUT", "240")), exit=True)


note(f"backend={jax.default_backend()} devices={jax.devices()}")
if jax.default_backend() == "cpu":
    sys.exit("needs the real chip; got cpu")

from horovod_tpu.parallel.attention import dense_attention
from horovod_tpu.parallel.flash_attention import flash_attention

B, L, H, KVH, D = 2, 2048, 8, 2, 64
ks = jax.random.split(jax.random.key(0), 3)
q = jax.random.normal(ks[0], (B, L, H, D), jnp.bfloat16)
k = jax.random.normal(ks[1], (B, L, KVH, D), jnp.bfloat16)
v = jax.random.normal(ks[2], (B, L, KVH, D), jnp.bfloat16)


def loss_flash(q, k, v):
    return jnp.sum(flash_attention(q, k, v, causal=True).astype(jnp.float32) ** 2)


def loss_dense(q, k, v):
    return jnp.sum(
        dense_attention(q, k, v, causal=True).astype(jnp.float32) ** 2)


# ── correctness: forward + grads, flash (pallas fwd+bwd) vs dense oracle ──
f_flash = jax.jit(jax.value_and_grad(loss_flash, argnums=(0, 1, 2)))
f_dense = jax.jit(jax.value_and_grad(loss_dense, argnums=(0, 1, 2)))
lf, gf = jax.device_get(f_flash(q, k, v))
note("flash fwd+bwd executed on chip")
ld, gd = jax.device_get(f_dense(q, k, v))
note("dense oracle executed on chip")

rel = abs(lf - ld) / max(abs(ld), 1e-9)
print(f"loss rel diff: {rel:.3e}  (flash {lf:.6g} vs dense {ld:.6g})")
ok = rel < 2e-2
for name, a, b in zip("dq dk dv".split(), gf, gd):
    a, b = np.asarray(a, np.float32), np.asarray(b, np.float32)
    scale = np.abs(b).max() or 1.0
    err = np.abs(a - b).max() / scale
    print(f"grad {name}: max rel-to-peak err {err:.3e}")
    ok &= err < 5e-2   # bf16 storage dtype; kernels accumulate f32
print("CORRECTNESS:", "PASS" if ok else "FAIL")

# ── honest timing A/B (value-readback fenced, donation-chained) ──────────
# Pre-warm the fence reducer OUTSIDE any timed window: its first compile
# (+ relay RTT) would otherwise land in the FIRST arm's measurement only,
# biasing the A/B (flash is timed first).
_REPS = 20
_reduce_fence = jax.jit(lambda xs: jnp.stack(xs).sum())
jax.device_get(_reduce_fence([jnp.float32(0)] * _REPS))


def timed(fn, reps=_REPS):
    y = jax.device_get(fn(q, k, v)[0])          # warm + fence
    t = time.perf_counter()
    accs = [fn(q, k, v)[0] for _ in range(reps)]
    jax.device_get(_reduce_fence(accs))         # one fence for all reps
    return (time.perf_counter() - t) / reps * 1e3


note("timing flash fwd+bwd")
ms_flash = timed(f_flash)
note("timing dense fwd+bwd")
ms_dense = timed(f_dense)
print(f"fwd+bwd per call: flash {ms_flash:.2f} ms, dense {ms_dense:.2f} ms, "
      f"speedup {ms_dense / ms_flash:.2f}x  (B={B} L={L} H={H} D={D})")

# Longer sequence: where flash should win decisively on HBM.
L2 = 8192
q2 = jax.random.normal(ks[0], (1, L2, H, D), jnp.bfloat16)
k2 = jax.random.normal(ks[1], (1, L2, KVH, D), jnp.bfloat16)
v2 = jax.random.normal(ks[2], (1, L2, KVH, D), jnp.bfloat16)


_REPS2 = 10
jax.device_get(_reduce_fence([jnp.float32(0)] * _REPS2))  # pre-warm len-10


def timed2(loss, reps=_REPS2):
    fn = jax.jit(jax.value_and_grad(loss))
    y = jax.device_get(fn(q2, k2, v2)[0])   # scalar fence — don't haul grads
    t = time.perf_counter()
    accs = [fn(q2, k2, v2)[0] for _ in range(reps)]
    jax.device_get(_reduce_fence(accs))
    return (time.perf_counter() - t) / reps * 1e3


note("timing seq-8192 flash")
ms_f2 = timed2(lambda q, k, v: jnp.sum(
    flash_attention(q, k, v, causal=True).astype(jnp.float32) ** 2))
note("timing seq-8192 dense")
ms_d2 = timed2(lambda q, k, v: jnp.sum(
    dense_attention(q, k, v, causal=True).astype(jnp.float32) ** 2))
print(f"seq {L2}: flash {ms_f2:.2f} ms, dense {ms_d2:.2f} ms, "
      f"speedup {ms_d2 / ms_f2:.2f}x")
print("DONE")
