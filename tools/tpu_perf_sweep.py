"""One-window perf sweep: batch sizes, flash block sizes, remat — honest
readback-fenced timings, printed as a table.

Run when a chip window opens (the claim happens at first backend touch):

    STAGE_TIMEOUT=150 timeout 1800 python tools/tpu_perf_sweep.py

Reuses bench.py's measurement stack (``_aot_compile`` warmup+fence,
``_readback`` value fencing, ``_mfu`` device-kind peak lookup) so sweep
numbers are comparable to the bench artifacts and any future fence fix
lands in one place.  Prints one `RESULT {json}` line per config so the
window's findings survive as parseable logs even if the run is cut
mid-sweep.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import faulthandler


def _rearm(seconds: int | None = None):
    faulthandler.dump_traceback_later(
        seconds or int(os.environ.get("STAGE_TIMEOUT", "150")), exit=True)


_rearm()

if (os.environ.get("SWEEP_ALLOW_CPU") == "1"
        and "xla_force_host_platform_device_count" not in
        os.environ.get("XLA_FLAGS", "")):
    # The smoke is sized for the 8-device simulated mesh (bs/lbs = 8, one
    # row per device) — without this flag a bare invocation would
    # "validate" a degenerate 1-device world exercising no sharding at
    # all.  Must land before jax import / first backend touch.
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

import jax

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    # The pool plugin's sitecustomize forces jax_platforms=axon,cpu at
    # import, overriding the env var — a pinned-CPU smoke run would then
    # hang dialing the tunnel.  An explicit config update wins (same
    # trick as tests/conftest.py and the bench CPU worker).
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import optax

from bench import _aot_compile, _mfu, _readback

t0 = time.monotonic()


def note(msg):
    print(f"[+{time.monotonic() - t0:.1f}s] {msg}", flush=True)
    _rearm()


note(f"backend={jax.default_backend()} devices={jax.devices()}")
_ON_TPU = jax.default_backend() != "cpu"
if not _ON_TPU and os.environ.get("SWEEP_ALLOW_CPU") != "1":
    sys.exit("needs the real chip; got cpu (SWEEP_ALLOW_CPU=1 runs a "
             "shrunken smoke of every arm for rehearsal/verification)")

# Share the bench's persistent compile cache so the sweep warms the real
# run and vice versa (env-aware: HVD_TPU_BENCH_CACHE overrides).
from horovod_tpu.utils.env import enable_persistent_compile_cache

enable_persistent_compile_cache(
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"))

import horovod_tpu as hvd

hvd.init()


def time_steps(step, state0, batch, iters=None, group=None):
    """steps/sec over donation-chained groups, readback-fenced.

    Returns the BEST group (least interference) — a tuning signal, unlike
    bench.py's mean-of-groups reporting number.  The CPU smoke shrinks to
    one 2-step group (and re-arms the stall bound per group): smoke
    validates the code path, not the numbers.
    """
    iters = iters if iters is not None else (3 if _ON_TPU else 1)
    group = group if group is not None else (12 if _ON_TPU else 2)
    state = state0
    rates = []
    for _ in range(iters):
        t = time.perf_counter()
        for _ in range(group):
            r = step(state["p"], state["o"], batch)
            state = {"p": r.params, "o": r.opt_state, "loss": r.loss}
        _readback(state["loss"])
        rates.append(group / (time.perf_counter() - t))
        _rearm()
    return max(rates)


def result(name, **kv):
    print("RESULT " + json.dumps({"config": name, **kv}), flush=True)


# ── ResNet-101 batch sweep ────────────────────────────────────────────────
def resnet_sweep():
    import horovod_tpu.models.resnet as resnet_mod

    # (bs, donate): the bs64 donate-off arm is the donated-buffers rung of
    # the tuning ladder — same program minus donation, so the delta is
    # pure allocation/HBM-pressure cost.
    # CPU smoke: one row per mesh device (the smoke runs on the 8-device
    # simulation, where bs is the GLOBAL batch and must divide the mesh).
    configs = ((64, True), (64, False), (128, True), (256, True)) \
        if _ON_TPU else ((8, True),)
    img = 224 if _ON_TPU else 32
    for bs, donate in configs:
        note(f"resnet101 bs{bs} donate={donate}: building")
        model = resnet_mod.ResNet101(dtype=jnp.bfloat16)
        kimg, klab = jax.random.split(jax.random.key(7))
        images = jax.random.normal(kimg, (bs, img, img, 3), jnp.float32)
        labels = jax.random.randint(klab, (bs,), 0, 1000, jnp.int32)
        variables = jax.jit(model.init, static_argnames="train")(
            jax.random.key(0), images[:1], train=False)
        params, batch_stats = variables["params"], variables["batch_stats"]

        def loss_fn(params, batch):
            x, y = batch
            logits, _ = model.apply(
                {"params": params, "batch_stats": batch_stats},
                x, train=True, mutable=["batch_stats"])
            return optax.softmax_cross_entropy(
                logits, jax.nn.one_hot(y, logits.shape[-1])).mean()

        tx = hvd.DistributedOptimizer(optax.sgd(0.01, momentum=0.9))
        opt_state = jax.jit(tx.init)(params)
        tag = f"resnet101_bs{bs}" + ("" if donate else "_nodonate")
        try:
            step, flops, out = _aot_compile(
                hvd.make_train_step(loss_fn, tx, donate=donate),
                params, opt_state, (images, labels))
            note(f"{tag}: warm, timing")
            sps = time_steps(step, {"p": out.params, "o": out.opt_state},
                             (images, labels))
            mfu = _mfu(flops, sps)
            result(tag, img_per_sec=round(sps * bs, 1),
                   mfu=round(mfu, 4) if mfu is not None else None,
                   step_ms=round(1e3 / sps, 2))
        except Exception as exc:
            result(tag, error=f"{type(exc).__name__}: {exc}")
        _rearm()


# ── flash-attention block-size sweep (fwd+bwd, llama-shaped) ─────────────
def flash_sweep():
    from horovod_tpu.parallel.flash_attention import flash_attention

    B, L, H, KVH, D = (4, 2048, 16, 4, 64) if _ON_TPU else (1, 256, 2, 1, 64)
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (B, L, H, D), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, L, KVH, D), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, L, KVH, D), jnp.bfloat16)
    # Analytic attention FLOPs (fwd+bwd ≈ 3.5x fwd): fwd = 2·2·B·H·L²·D
    # (QK^T + PV); causal halves it.  cost_analysis can't see inside the
    # pallas custom call, hence analytic.
    flops = 3.5 * 2 * 2 * B * H * L * L * D / 2

    # Pre-warm the fence reducer OUTSIDE any timed window: its first
    # compile (+ relay RTT) would otherwise land inside the first
    # config's measurement and skew the block-size comparison.
    reps = 20
    reduce_fence = jax.jit(lambda xs: jnp.stack(xs).sum())
    _readback(reduce_fence([jnp.float32(0)] * reps))

    for bq, bk in ((256, 256), (512, 512), (1024, 512), (512, 1024),
                   (1024, 1024)):
        note(f"flash bq={bq} bk={bk}: compiling")

        def loss(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, causal=True, block_q=bq, block_k=bk,
            ).astype(jnp.float32) ** 2)

        fn = jax.jit(jax.value_and_grad(loss))
        try:
            _readback(fn(q, k, v)[0])
            t = time.perf_counter()
            accs = [fn(q, k, v)[0] for _ in range(reps)]
            _readback(reduce_fence(accs))
            ms = (time.perf_counter() - t) / reps * 1e3
            result(f"flash_bq{bq}_bk{bk}", ms=round(ms, 2),
                   tflops=round(flops / (ms / 1e3) / 1e12, 1))
        except Exception as exc:
            result(f"flash_bq{bq}_bk{bk}", error=f"{type(exc).__name__}: {exc}")
        _rearm()


# ── llama end-to-end: remat and attention-impl choices ───────────────────
def llama_sweep():
    from horovod_tpu.models import llama

    seq = 2048 if _ON_TPU else 128
    base_shape = dict(vocab_size=32768, dim=1024, n_layers=8, n_heads=16,
                      n_kv_heads=4, ffn_dim=4096)
    # ~570M params: MFU rises with model size (bigger matmuls occupy the
    # MXU better than the 189M bench model's); remat+donation make it fit.
    big_shape = dict(vocab_size=32768, dim=1536, n_layers=14, n_heads=16,
                     n_kv_heads=4, ffn_dim=6144)
    # 1.11B: the single-chip capacity ceiling — fits ONLY with the full
    # memory ladder (remat + fused loss + donation + SGD-momentum's 1x
    # state; fp32 params 4.4G + momentum 4.4G of the 15.75G HBM).
    onex_shape = dict(vocab_size=32768, dim=2048, n_layers=16, n_heads=16,
                      n_kv_heads=4, ffn_dim=8192)
    for name, kw, shape in (
        ("flash", dict(attn_impl="flash", remat=False), base_shape),
        ("flash_remat", dict(attn_impl="flash", remat=True), base_shape),
        ("dense", dict(attn_impl="dense", remat=False), base_shape),
        ("flash_big", dict(attn_impl="flash", remat=True), big_shape),
        ("flash_1b", dict(attn_impl="flash", remat=True,
                          fused_loss_chunk=2048), onex_shape),
    ):
        if not _ON_TPU and name == "flash_big":
            # Off-TPU the shape is discarded, which would make this rung
            # byte-identical to flash_remat — skip the duplicate (flash_1b
            # still differs off-TPU: it smokes the fused-loss path).
            continue
        note(f"llama {name}: building")
        if _ON_TPU:
            cfg = llama.llama_tiny(max_seq_len=seq, **shape, **kw)
        else:
            cfg = llama.llama_tiny(max_seq_len=seq, **kw)
        loss = llama.make_loss_fn(cfg)
        # AdamW's 2x fp32 state does not fit at 1B on one chip; SGD-momentum
        # (the reference benchmarks' optimizer) is the 1B rung's point.
        opt = optax.sgd(1e-3, momentum=0.9) if name == "flash_1b" \
            else optax.adamw(1e-4)
        tx = hvd.DistributedOptimizer(opt)
        params = llama.init_params(cfg, jax.random.key(0))
        opt_state = jax.jit(tx.init)(params)
        lbs = (2 if name == "flash_1b" else 4) if _ON_TPU else 8
        tokens = jax.random.randint(
            jax.random.key(11), (lbs, seq), 0, cfg.vocab_size, jnp.int32)
        batch = (tokens, tokens)
        try:
            step, _flops, out = _aot_compile(
                hvd.make_train_step(loss, tx, donate=True),
                params, opt_state, batch)
            note(f"llama {name}: warm, timing")
            sps = time_steps(step, {"p": out.params, "o": out.opt_state},
                             batch)
            n_par = llama.num_params(cfg)
            # 6·N·D against the device-kind peak (same convention as
            # bench.py's llama_mfu_6nd).
            mfu_6nd = _mfu(6.0 * n_par * lbs * seq, sps)
            result(f"llama_{name}",
                   tok_per_sec=round(sps * lbs * seq, 1),
                   mfu_6nd=round(mfu_6nd, 4) if mfu_6nd is not None else None,
                   step_ms=round(1e3 / sps, 2))
        except Exception as exc:
            result(f"llama_{name}", error=f"{type(exc).__name__}: {exc}")
        _rearm()


# ── ViT-B/16 batch sweep (transformer-vision MFU ladder) ─────────────────
def vit_sweep():
    from horovod_tpu.models.vit import ViT, ViT_B16

    for bs in ((64, 128) if _ON_TPU else (8,)):
        note(f"vit_b16 bs{bs}: building")
        # Dense attention: 196 tokens is far below the flash kernel's
        # ~2k-token crossover (bench.py _bench_vit).
        model = (ViT_B16(dtype=jnp.bfloat16) if _ON_TPU
                 else ViT(patch=8, dim=32, depth=2, n_heads=2,
                          num_classes=10))
        img = 224 if _ON_TPU else 32
        kimg, klab = jax.random.split(jax.random.key(29))
        images = jax.random.normal(kimg, (bs, img, img, 3), jnp.float32)
        labels = jax.random.randint(klab, (bs,), 0, model.num_classes,
                                    jnp.int32)
        variables = jax.jit(model.init, static_argnames="train")(
            jax.random.key(0), images[:1], train=False)

        def loss_fn(params, batch):
            x, y = batch
            logits = model.apply({"params": params}, x, train=True)
            return optax.softmax_cross_entropy(
                logits, jax.nn.one_hot(y, logits.shape[-1])).mean()

        tx = hvd.DistributedOptimizer(optax.adamw(1e-3))
        params = variables["params"]
        opt_state = jax.jit(tx.init)(params)
        try:
            step, flops, out = _aot_compile(
                hvd.make_train_step(loss_fn, tx, donate=True),
                params, opt_state, (images, labels))
            note(f"vit_b16 bs{bs}: warm, timing")
            sps = time_steps(step, {"p": out.params, "o": out.opt_state},
                             (images, labels))
            mfu = _mfu(flops, sps)
            result(f"vit_b16_bs{bs}", img_per_sec=round(sps * bs, 1),
                   mfu=round(mfu, 4) if mfu is not None else None,
                   step_ms=round(1e3 / sps, 2))
        except Exception as exc:
            result(f"vit_b16_bs{bs}", error=f"{type(exc).__name__}: {exc}")
        _rearm()


# ── Serving sweep: speculative decode + continuous batching ──────────────
def serving_sweep():
    """Single-chip serving rungs: plain generate vs speculative (self
    draft = acceptance upper bound; tiny draft = the realistic shape) and
    the slot-pool batcher.  All greedy, so every variant's tokens are
    bit-identical — only speed differs.

    Honest-reading note for tunneled chips: plain generate is one fully
    jitted program (zero host round-trips after launch), while the
    speculative loop and the batcher pay ≥2 host↔device round-trips per
    round by design — behind a ~69 ms tunnel (docs/artifacts frontend-tax
    capture) that RTT, not compute, dominates them.  Compare the rungs'
    RELATIVE compute cost via ms_per_token minus the known RTT share, or
    on local-attached hardware."""
    import time as _t

    import numpy as np

    from horovod_tpu.models import llama
    from horovod_tpu.serving import (ContinuousBatcher, Request,
                                     speculative_generate)

    if _ON_TPU:
        shape = dict(vocab_size=32768, dim=1024, n_layers=8, n_heads=16,
                     n_kv_heads=4, ffn_dim=4096)     # the 189M bench model
        draft_shape = dict(vocab_size=32768, dim=256, n_layers=2,
                           n_heads=8, n_kv_heads=2, ffn_dim=1024)
        b, plen, n_new, max_len = 8, 128, 256, 512
    else:
        shape = draft_shape = {}
        b, plen, n_new, max_len = 2, 8, 8, 32
    cfg = llama.llama_tiny(max_seq_len=max_len, attn_impl="dense", **shape)
    dcfg = llama.llama_tiny(max_seq_len=max_len, attn_impl="dense",
                            **draft_shape)
    params = llama.init_params(cfg, jax.random.key(0))
    dparams = llama.init_params(dcfg, jax.random.key(1))
    prompt = jax.random.randint(jax.random.key(2), (b, plen), 0,
                                cfg.vocab_size, jnp.int32)

    def timed(label, fn):
        # Serving arms make MANY host↔device round-trips per measured
        # call (that's what they measure) — behind the ~69 ms tunnel one
        # arm can legitimately run minutes, so each gets a long stall
        # budget instead of the default per-stage one.
        _rearm(900)
        try:
            jax.block_until_ready(fn())      # compile + warm
            t0 = _t.monotonic()
            jax.block_until_ready(fn())
            dt = _t.monotonic() - t0
            result(label, tok_per_sec=round(b * n_new / dt, 1),
                   ms_per_token=round(1e3 * dt / n_new, 3))
        except Exception as exc:
            result(label, error=f"{type(exc).__name__}: {exc}")
        _rearm()

    gen = jax.jit(lambda p, t: llama.generate(
        p, t, cfg, max_new_tokens=n_new, max_len=max_len))
    timed("serve_generate", lambda: np.asarray(gen(params, prompt)))
    timed("serve_spec_selfdraft", lambda: np.asarray(speculative_generate(
        params, cfg, params, cfg, prompt, max_new_tokens=n_new,
        draft_k=4, max_len=max_len + 8)))
    timed("serve_spec_tinydraft", lambda: np.asarray(speculative_generate(
        params, cfg, dparams, dcfg, prompt, max_new_tokens=n_new,
        draft_k=4, max_len=max_len + 8)))

    # ONE batcher instance: its jitted closures are per-instance, so the
    # warm run must hit the same object the timed run uses.
    srv = ContinuousBatcher(params, cfg, n_slots=b, max_len=max_len,
                            admit_width=plen)

    def batcher_run(n_requests, toks):
        reqs = [Request(prompt=list(range(1, plen + 1)),
                        max_new_tokens=toks) for _ in range(n_requests)]
        return srv.run(reqs)

    _rearm(900)
    try:
        batcher_run(1, 2)                    # compile _prefill_one/_tick
        t0 = _t.monotonic()
        res = batcher_run(b + b // 2, n_new)
        dt = _t.monotonic() - t0
        total = sum(len(r) for r in res)
        result("serve_batcher", tok_per_sec=round(total / dt, 1),
               ms_per_token=round(1e3 * dt / total, 3),
               requests=len(res), total_tokens=total)
    except Exception as exc:
        result("serve_batcher", error=f"{type(exc).__name__}: {exc}")
    _rearm()


if __name__ == "__main__":
    which = os.environ.get("SWEEP", "resnet,flash,llama,vit,serving").split(",")
    if "resnet" in which:
        resnet_sweep()
    if "flash" in which:
        flash_sweep()
    if "llama" in which:
        llama_sweep()
    if "vit" in which:
        vit_sweep()
    if "serving" in which:
        serving_sweep()
    note("sweep done")
