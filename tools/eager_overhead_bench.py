"""Eager-engine launch-overhead microbench (CPU sim).

The reference's eager cost story is its 5 ms background cycle + per-op
negotiation (reference horovod/common/operations.cc:151-155 — the knobs
`HOROVOD_CYCLE_TIME`/`HOROVOD_FUSION_THRESHOLD` exist because per-op
launch overhead dominates many-small-tensor models).  This measures our
engine's analogue where it is actually indicative — the host-side
dispatch path on the CPU sim, where the collective itself is ~free and
whatever remains IS the engine overhead:

* ops/sec for 1-KiB eager allreduces, posted async in bursts (the
  gradient-hook shape) and drained;
* fused (default 64 MiB threshold: the whole burst merges into one
  dispatch) vs solo (`HOROVOD_FUSION_THRESHOLD=0`: one dispatch per
  tensor) — Tensor Fusion's launch-overhead win in isolation;
* single-process engine vs 2-process native-controller gang (adds TCP
  negotiation per cycle).

Usage:
    python tools/eager_overhead_bench.py                 # orchestrates all arms
    python tools/eager_overhead_bench.py --mode single   # one arm, this process
    python tools/eager_overhead_bench.py --mode worker   # rank of a 2-proc gang

Prints one ``RESULT {json}`` line per arm; the orchestrator ends with
``SUMMARY {json}``.  Smoke-tested by tests/test_bench_helpers.py.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TENSOR_ELEMS = 256          # 256 f32 = 1 KiB, the reference's "small tensor"
BURST = int(os.environ.get("EAGER_OVH_BURST", "32"))   # tensors per burst
ROUNDS = int(os.environ.get("EAGER_OVH_ROUNDS", "8"))  # bursts timed
WARMUP_ROUNDS = 2


def _force_cpu() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    import jax

    jax.config.update("jax_platforms", "cpu")


def _measure(tag: str) -> dict:
    """Run the burst loop on the CURRENT engine config; returns the arm
    record.  Must be called after hvd.init().

    Bursts go through ``grouped_allreduce_eager`` — caller-delimited, so
    bucket composition is DETERMINISTIC round to round and each arm
    compiles its dispatch program(s) once in warmup.  Timing-driven flush
    (the raw async-post pattern) varies composition with scheduler jitter,
    and on XLA every novel composition is a fresh compile
    (docs/tensor-fusion.md "Determinism and compile churn") — that would
    measure the compiler, not the launch overhead.  The threshold knob
    still controls bucketing *within* the group: 64 MiB → one fused
    dispatch per burst, 0 → one dispatch per tensor."""
    import jax
    import numpy as np

    import horovod_tpu as hvd

    n = hvd.size()
    rng = np.random.RandomState(0)
    bufs = [
        rng.randn(n, TENSOR_ELEMS).astype(np.float32) for _ in range(BURST)
    ]

    def one_round() -> None:
        outs = hvd.grouped_allreduce_eager(bufs, average=True)
        jax.block_until_ready(outs)

    for _ in range(WARMUP_ROUNDS):
        one_round()
    stats0 = hvd.engine_stats()
    t0 = time.perf_counter()
    for _ in range(ROUNDS):
        one_round()
    dt = time.perf_counter() - t0

    ops = ROUNDS * BURST
    stats = hvd.engine_stats()
    return {
        "arm": tag,
        "ops_per_sec": round(ops / dt, 1),
        "us_per_op": round(dt / ops * 1e6, 1),
        "tensors_fused":
            stats.get("tensors_fused", 0) - stats0.get("tensors_fused", 0),
        "batches_dispatched": stats.get("batches_dispatched", 0)
            - stats0.get("batches_dispatched", 0),
    }


def _run_single(threshold: str) -> None:
    _force_cpu()
    os.environ["HOROVOD_FUSION_THRESHOLD"] = threshold
    os.environ.setdefault("HOROVOD_CYCLE_TIME", "1")
    import horovod_tpu as hvd

    hvd.init()
    tag = "fused" if threshold != "0" else "solo"
    print("RESULT " + json.dumps(_measure(f"single.{tag}")), flush=True)
    hvd.shutdown()


def _run_worker() -> None:
    _force_cpu()
    os.environ.setdefault("HOROVOD_CYCLE_TIME", "1")
    import horovod_tpu as hvd

    hvd.init()
    tag = "fused" if os.environ.get("HOROVOD_FUSION_THRESHOLD") != "0" \
        else "solo"
    rec = _measure(f"gang2.{tag}")
    if hvd.rank() == 0:
        print("RESULT " + json.dumps(rec), flush=True)
    hvd.shutdown()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_arm(args: list[str], env_extra: dict) -> str:
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.update(JAX_PLATFORMS="cpu", **env_extra)
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), *args],
        env=env, capture_output=True, text=True, timeout=600,
    )
    if out.returncode != 0:
        raise RuntimeError(
            f"arm {args} {env_extra} failed rc={out.returncode}:\n"
            f"{out.stdout}\n{out.stderr}"
        )
    return out.stdout


def _spawn_gang(threshold: str) -> str:
    port = _free_port()
    ctl_port = _free_port()
    env_base = {
        "HOROVOD_TPU_COORDINATOR": f"127.0.0.1:{port}",
        "HOROVOD_TPU_NUM_PROCESSES": "2",
        "HOROVOD_FUSION_THRESHOLD": threshold,
        "HOROVOD_TPU_NATIVE_CONTROLLER": "on",
        "HOROVOD_TPU_CONTROLLER_TRANSPORT": f"tcp:127.0.0.1:{ctl_port}",
    }
    env = [dict(os.environ) for _ in range(2)]
    procs = []
    for pid in range(2):
        env[pid].pop("XLA_FLAGS", None)
        env[pid].update(JAX_PLATFORMS="cpu",
                        HOROVOD_TPU_PROCESS_ID=str(pid), **env_base)
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--mode", "worker"],
            env=env[pid], stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        ))
    outs = [p.communicate(timeout=600)[0] for p in procs]
    for pid, p in enumerate(procs):
        if p.returncode != 0:
            raise RuntimeError(
                f"gang rank {pid} rc={p.returncode}:\n{outs[pid]}"
            )
    return "\n".join(outs)


def _collect(text: str) -> list[dict]:
    return [json.loads(line.split("RESULT ", 1)[1])
            for line in text.splitlines() if line.startswith("RESULT ")]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=["orchestrate", "single", "worker"],
                    default="orchestrate")
    ap.add_argument("--threshold", default=None)
    args = ap.parse_args()

    if args.mode == "single":
        _run_single(args.threshold or
                    os.environ.get("HOROVOD_FUSION_THRESHOLD", ""))
        return
    if args.mode == "worker":
        _run_worker()
        return

    results: list[dict] = []
    for thr in (str(64 * 1024 * 1024), "0"):
        results += _collect(
            _spawn_arm(["--mode", "single", "--threshold", thr], {})
        )
    for thr in (str(64 * 1024 * 1024), "0"):
        results += _collect(_spawn_gang(thr))
    for r in results:
        print("RESULT " + json.dumps(r), flush=True)

    by = {r["arm"]: r for r in results}
    summary = {
        "tensor_bytes": TENSOR_ELEMS * 4,
        "burst": BURST,
        "fusion_speedup_single":
            round(by["single.fused"]["ops_per_sec"]
                  / by["single.solo"]["ops_per_sec"], 2),
        "fusion_speedup_gang2":
            round(by["gang2.fused"]["ops_per_sec"]
                  / by["gang2.solo"]["ops_per_sec"], 2),
        "controller_cost_us_per_op":
            round(by["gang2.fused"]["us_per_op"]
                  - by["single.fused"]["us_per_op"], 1),
        "arms": by,
    }
    print("SUMMARY " + json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
