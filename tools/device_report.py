"""Render and diff device-telemetry reports in the terminal.

The ``DeviceTelemetry`` plane (``HVD_TPU_DEVICE_TELEMETRY=1``)
publishes the same report three ways; this tool reads any of them:

    python tools/device_report.py http://127.0.0.1:9400      # live /device
    python tools/device_report.py events.jsonl               # event-log replay
    python tools/device_report.py device.json [--json]       # saved report

A URL is scraped at its ``/device`` endpoint (appended when missing) —
the engine monitor serves one report, the router serves the fleet view
(each replica's report rendered in turn); a ``.jsonl`` source replays
the ``device.capture`` / ``device.tick`` / ``device.memory`` records of
the structured event log into an identical report via
:func:`horovod_tpu.device_telemetry.report_from_events` (a registered
DETERMINISM_SURFACES replay path — no wall clock, so a crashed run
diffs the same as a live scrape); anything else is a saved report JSON
— a prior ``--json`` dump, a raw ``/device`` body, or a full
``metrics_snapshot()`` (its ``"device"`` key is used).

Regression gate (gate #7 in ``tools/perf_gate.py``):

    python tools/device_report.py --compare old.json new.json \\
        [--threshold 10]

exits 1 when serving MFU / achieved FLOPs-per-second / overlap headroom
dropped more than ``--threshold`` percent, or per-tick host stall grew
more than ``--threshold`` percent AND ``--floor-ms`` absolute.  MFU
rows are skipped when either side has no honest peak (CPU rehearsals):
an unknown peak must never pass or fail a gate.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request

from horovod_tpu.device_telemetry import report_from_events


def fetch_report(url: str) -> dict:
    """Scrape a live monitor's (or router's) ``/device`` endpoint."""
    if not url.rstrip("/").endswith("/device"):
        url = url.rstrip("/") + "/device"
    with urllib.request.urlopen(url, timeout=5) as resp:
        return json.loads(resp.read())


def load_report(source: str, window: int | None = None) -> dict:
    """Dispatch on the source shape: URL, event-log JSONL, or report
    JSON (accepts a bare report, a ``/device`` body — engine or router
    flavor — or a whole ``metrics_snapshot()`` dump)."""
    if source.startswith(("http://", "https://")):
        return fetch_report(source)
    if source.endswith(".jsonl"):
        events = []
        with open(source) as f:
            for line in f:
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    pass          # torn tail line of a live/crashed log
        return report_from_events(events, window=window)
    with open(source) as f:
        data = json.load(f)
    if "win" in data or "replicas" in data:
        return data
    if "device" in data:           # a metrics_snapshot() dump
        return data["device"]
    raise SystemExit(f"{source}: neither a device report nor a "
                     f"snapshot with a 'device' key")


def _render_one(report: dict, name: str | None = None) -> list[str]:
    peak = report.get("peak_flops")
    head = (f"device report{f' [{name}]' if name else ''}: "
            f"{report['platform']}/{report['device_kind']} "
            f"x{report['n_devices']}, peak="
            + (f"{peak:.3e} FLOP/s ({report.get('peak_flops_source')})"
               if peak else "unknown (no MFU)"))
    lines = [head,
             f"{'program':12s} {'dispatches':>10s} {'MFLOPs':>10s} "
             f"{'MB accessed':>12s} {'compile ms':>11s}"]
    for prog, row in report.get("programs", {}).items():
        lines.append(
            f"{prog:12s} {row['dispatches']:10d} "
            f"{row['flops'] / 1e6:10.3f} "
            f"{row['bytes_accessed'] / 1e6:12.3f} "
            f"{row['compile_s'] * 1e3:11.2f}")
    lines.append(
        f"compiles={report['compiles']} "
        f"total={report['compile_total_s'] * 1e3:.1f} ms  "
        f"retraces={report['retraces']} "
        f"(est cost {report['retrace_compile_est_s'] * 1e3:.1f} ms)")
    w = report["win"]
    mfu = w["mfu"]
    lines.append(
        f"window ({w['n']} ticks, {w['elapsed_s'] * 1e3:.1f} ms): "
        f"mfu={'n/a' if mfu is None else f'{mfu:.4f}'} "
        f"flops/s={w['flops_per_s']:.3e} "
        f"intensity={w['arithmetic_intensity']:.2f} FLOP/B")
    lines.append(
        f"  sync={w['sync_s'] * 1e3:.2f} ms "
        f"(compute_est={w['compute_est_s'] * 1e3:.2f} "
        f"host_stall={w['host_stall_s'] * 1e3:.2f}) "
        f"headroom={w['overlap_headroom_pct']:.1f}% "
        f"h2d={w['h2d_bytes']} B d2h={w['d2h_bytes']} B")
    mem = report.get("memory")
    if mem and mem.get("available"):
        lines.append(
            f"  hbm: in_use={mem['bytes_in_use']} "
            f"peak={mem['peak_bytes_in_use']} "
            f"limit={mem['bytes_limit']}")
        rec = report.get("reconciliation")
        if rec:
            lines.append(
                f"  reconciliation: params={rec['param_bytes']} "
                f"kv={rec['kv_total_bytes']} "
                f"framework_overhead={rec['framework_overhead_bytes']}")
    else:
        lines.append("  hbm: backend reports no memory_stats")
    return lines


def render(report: dict) -> str:
    """One engine report, or the router's fleet view replica by
    replica with its summary line."""
    if "replicas" in report:        # router fleet flavor
        lines: list[str] = []
        for name in sorted(report["replicas"]):
            lines += _render_one(report["replicas"][name], name)
        s = report.get("summary", {})
        fleet = (f"fleet: reporting={s.get('n_reporting', 0)} "
                 f"flops/s={s.get('fleet_flops_per_s', 0.0):.3e}")
        if "mfu_mean" in s:
            fleet += (f" mfu min/mean/max={s['mfu_min']:.4f}/"
                      f"{s['mfu_mean']:.4f}/{s['mfu_max']:.4f}")
        without = report.get("without_telemetry")
        if without:
            fleet += f" without_telemetry={','.join(without)}"
        lines.append(fleet)
        return "\n".join(lines)
    return "\n".join(_render_one(report))


#: Gate axes: (key, higher_is_better, absolute floor in the metric's
#: own unit below which a percent move is noise, extractor).
_GATE_AXES = (
    ("mfu", True, 1e-4,
     lambda r: r["win"]["mfu"]),
    ("flops_per_s", True, 1.0,
     lambda r: r["win"]["flops_per_s"]),
    ("overlap_headroom_pct", True, 0.1,
     lambda r: r["win"]["overlap_headroom_pct"]),
    ("host_stall_ms_per_tick", False, None,   # floor: --floor-ms
     lambda r: (r["win"]["host_stall_s"] / r["win"]["n"] * 1e3
                if r["win"]["n"] else 0.0)),
)


def compare_reports(old: dict, new: dict, threshold_pct: float = 10.0,
                    floor_ms: float = 0.05) -> list[dict]:
    """Scalar-axis diff of two device reports.  Higher-is-better axes
    (MFU, achieved FLOPs/s, overlap headroom) REGRESS on a drop past
    ``threshold_pct`` and their noise floor; host stall regresses on
    growth past the threshold AND ``floor_ms``.  The MFU row is
    emitted only when BOTH sides carry an honest peak — one unknown
    side makes the axis unjudgeable, never a pass or a fail."""
    rows = []
    for key, higher_better, floor, get in _GATE_AXES:
        try:
            o, n = get(old), get(new)
        except (KeyError, TypeError):
            continue
        if o is None or n is None:
            continue                # no honest peak on one side
        if floor is None:
            floor = floor_ms
        bad = (o - n) if higher_better else (n - o)
        pct = bad / o * 100.0 if o else (float("inf") if bad > 0
                                         else 0.0)
        rows.append({
            "metric": key, "old": o, "new": n, "delta": n - o,
            "delta_pct": (n - o) / o * 100.0 if o else 0.0,
            "regressed": pct > threshold_pct and bad > floor,
        })
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("source", nargs="?",
                    help="monitor/router URL, event-log .jsonl, or "
                         "report JSON")
    ap.add_argument("--compare", nargs=2, metavar=("OLD", "NEW"),
                    help="diff two report sources; exit 1 on regression")
    ap.add_argument("--threshold", type=float, default=10.0,
                    help="regression threshold in percent (default 10)")
    ap.add_argument("--floor-ms", type=float, default=0.05,
                    help="absolute host-stall growth floor in ms below "
                         "which a percent regression is ignored")
    ap.add_argument("--window", type=int, default=None,
                    help="for .jsonl replay: use only the last N ticks")
    ap.add_argument("--json", action="store_true",
                    help="dump the report (or the comparison rows) as "
                         "JSON")
    args = ap.parse_args(argv)

    if bool(args.source) == bool(args.compare):
        ap.error("give exactly one of: a source, or --compare OLD NEW")

    if args.compare:
        old = load_report(args.compare[0], window=args.window)
        new = load_report(args.compare[1], window=args.window)
        rows = compare_reports(new=new, old=old,
                               threshold_pct=args.threshold,
                               floor_ms=args.floor_ms)
        if args.json:
            print(json.dumps(rows, indent=2))
        else:
            print(f"{'metric':24s} {'old':>12s} {'new':>12s} "
                  f"{'pct':>8s}")
            for r in rows:
                flag = "  << REGRESSED" if r["regressed"] else ""
                print(f"{r['metric']:24s} {r['old']:12.4g} "
                      f"{r['new']:12.4g} "
                      f"{r['delta_pct']:+7.1f}%{flag}")
        return 1 if any(r["regressed"] for r in rows) else 0

    report = load_report(args.source, window=args.window)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
        return 0
    print(render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
