"""Stage-by-stage timing of the bench's ResNet bring-up on the live chip.

Round-4 forensics: the r4 first-window bench worker claimed the TPU in 7 s
and was then killed 503 s later having never reached the
"inputs+params ready" note inside ``_bench_resnet`` (bench.py).  Every
stage between the claim and that note is timed here individually, and a
``faulthandler.dump_traceback_later`` fires a full-stack dump every 120 s
so a silent hang names the exact frame (the r3 lesson: bound from
outside, inspect from inside).

Usage (run it under ``timeout`` — a hung PJRT call ignores SIGINT):

    timeout 900 python tools/tpu_stage_probe.py
"""

import faulthandler
import os
import sys
import time

faulthandler.dump_traceback_later(120, repeat=True, file=sys.stderr)

_T0 = time.monotonic()


def note(msg: str) -> None:
    print(f"[probe +{time.monotonic() - _T0:.1f}s] {msg}",
          file=sys.stderr, flush=True)


note("importing jax")
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

note("enabling compile cache")
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
from horovod_tpu.utils.env import enable_persistent_compile_cache  # noqa: E402

enable_persistent_compile_cache(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                 ".jax_cache"))

note("claiming backend")
backend = jax.default_backend()
note(f"claimed backend={backend} device={jax.devices()[0].device_kind}")

note("importing horovod_tpu")
import horovod_tpu as hvd  # noqa: E402

note("hvd.init()")
hvd.init()
note(f"hvd.init done; size={hvd.size()}")

import optax  # noqa: E402

import horovod_tpu.models.resnet as resnet_mod  # noqa: E402

depth = int(os.environ.get("PROBE_DEPTH", "101"))
bs = int(os.environ.get("PROBE_BS", "64"))
img = int(os.environ.get("PROBE_IMG", "224"))
model = getattr(resnet_mod, f"ResNet{depth}")(dtype=jnp.bfloat16)

note(f"generating synthetic data bs={bs} img={img}")
kimg, klab = jax.random.split(jax.random.key(7))
images = jax.random.normal(kimg, (bs, img, img, 3), jnp.float32)
labels = jax.random.randint(klab, (bs,), 0, 1000, jnp.int32)
jax.block_until_ready((images, labels))
note("synthetic data materialized on device")

note(f"jitting model.init (ResNet-{depth})")
variables = jax.jit(model.init, static_argnames="train")(
    jax.random.key(0), images[:1], train=False
)
jax.block_until_ready(variables)
note("model.init done")
params, batch_stats = variables["params"], variables["batch_stats"]


def loss_fn(p, batch):
    x, y = batch
    logits, _ = model.apply(
        {"params": p, "batch_stats": batch_stats},
        x, train=True, mutable=["batch_stats"],
    )
    onehot = jax.nn.one_hot(y, logits.shape[-1])
    return optax.softmax_cross_entropy(logits, onehot).mean()


note("tx.init")
tx = hvd.DistributedOptimizer(optax.sgd(0.01, momentum=0.9))
opt_state = jax.jit(tx.init)(params)
jax.block_until_ready(opt_state)
note("tx.init done; lowering train step")

step_fn = hvd.make_train_step(loss_fn, tx, donate=True)
lowered = step_fn.lower(params, opt_state, (images, labels))
note("lowered; compiling")
compiled = lowered.compile()
note("compiled; warmup step")
out = compiled(params, opt_state, (images, labels))
jax.block_until_ready(out.loss)
note(f"warmup done, loss={float(out.loss):.3f}")

state = {"p": out.params, "o": out.opt_state}
for group in range(3):
    t0 = time.perf_counter()
    for _ in range(10):
        r = compiled(state["p"], state["o"], (images, labels))
        state["p"], state["o"] = r.params, r.opt_state
    float(r.loss)          # value readback fence
    dt = time.perf_counter() - t0
    note(f"group {group}: 10 steps in {dt:.3f}s -> "
         f"{10 * bs / dt:.1f} img/s")

note("probe complete")
faulthandler.cancel_dump_traceback_later()
