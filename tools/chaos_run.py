"""Run seeded chaos campaigns against an in-process serving fleet.

One campaign (the CI smoke shape — a storm over the engine fault
sites plus a replica kill, checked against the recovery oracles):

    JAX_PLATFORMS=cpu python tools/chaos_run.py --seed 7

Soak mode keeps launching consecutive-seed campaigns until the
wall-clock budget runs out:

    JAX_PLATFORMS=cpu python tools/chaos_run.py --soak 300

Regression gate (the ``profile_report.py --compare`` contract — saved
report JSONs in, exit 1 when recovery got worse):

    python tools/chaos_run.py --compare old.json new.json \\
        [--threshold 0.1]

Exit status: 0 when every oracle held (or no regression in compare
mode), 1 otherwise — wire it straight into CI.  ``--json PATH`` saves
the report for a later ``--compare``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:        # direct `python tools/chaos_run.py` runs
    sys.path.insert(0, REPO)


def _build_world():
    import jax
    import jax.numpy as jnp

    from horovod_tpu.models import llama

    cfg = llama.llama_tiny(dtype=jnp.float32)
    params = llama.init_params(cfg, jax.random.PRNGKey(11))
    return params, cfg


def _print_report(report: dict) -> None:
    oracles = report.get("oracles", {})
    for name, held in sorted(oracles.items()):
        print(f"  {'PASS' if held else 'FAIL'}  {name}")
    for key in ("seed", "campaigns", "n_requests", "faults_fired",
                "kills_fired", "respawns", "failovers", "ok_fraction",
                "min_ok_fraction", "leaked_tickets", "leaked_blocks"):
        if key in report:
            print(f"  {key}: {report[key]}")
    if report.get("failures"):
        print(f"  failing seeds: "
              f"{[f['seed'] for f in report['failures']]}")
    print(f"chaos: {'OK' if report.get('ok') else 'FAILED'}")


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(
        description="Seeded chaos campaigns over the serving fleet.")
    ap.add_argument("--seed", type=int, default=0,
                    help="campaign seed (default 0)")
    ap.add_argument("--soak", type=float, metavar="SECONDS",
                    help="run consecutive-seed campaigns for this "
                         "many wall-clock seconds")
    ap.add_argument("--compare", nargs=2, metavar=("OLD", "NEW"),
                    help="diff two saved report JSONs instead of "
                         "running; exit 1 on regression")
    ap.add_argument("--threshold", type=float, default=0.1,
                    help="--compare: max tolerated OK-fraction drop "
                         "(absolute, default 0.1)")
    ap.add_argument("--json", metavar="PATH",
                    help="write the report JSON here")
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--waves", type=int, default=4)
    ap.add_argument("--faults", type=int, default=6,
                    help="storm rules per campaign (default 6)")
    ap.add_argument("--kills", type=int, default=1,
                    help="replica kills per campaign (default 1)")
    args = ap.parse_args(argv)

    if args.compare:
        with open(args.compare[0]) as f:
            old = json.load(f)
        with open(args.compare[1]) as f:
            new = json.load(f)
        from horovod_tpu.chaos import compare_campaigns
        ok, problems = compare_campaigns(old, new,
                                         threshold=args.threshold)
        for p in problems:
            print(f"REGRESSION: {p}")
        print(f"chaos compare: {'OK' if ok else 'FAILED'}")
        return 0 if ok else 1

    from horovod_tpu.chaos import run_campaign, soak

    params, cfg = _build_world()
    kw = dict(n_replicas=args.replicas, waves=args.waves,
              n_faults=args.faults, n_kills=args.kills)
    if args.soak:
        report = soak(params, cfg, seconds=args.soak,
                      start_seed=args.seed, **kw)
    else:
        report = run_campaign(params, cfg, seed=args.seed, **kw)
    _print_report(report)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
        print(f"report written to {args.json}")
    return 0 if report.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
