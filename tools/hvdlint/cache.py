"""mtime-keyed result cache for the full-repo lint pass.

The suite runs ``run_lint(REPO_ROOT)`` on every test invocation; with
ten checkers (four of them interprocedural) that is the slowest lint
cost in the tier-1 path.  This cache keys the complete run on a
manifest of every input that can change a finding: the package
sources, the test files (HVD004 greps them), the docs knob table
(HVD003), the linter's own code (a checker edit must invalidate), and
the baseline.  Findings are stored bucketed per source file with the
file's ``(mtime_ns, size)`` stamp.

Validation is deliberately all-or-nothing: HVD007–HVD010 walk a
*whole-program* call graph, so a change in one file can create or
remove findings in another — re-checking only the dirty file would be
unsound.  Any manifest mismatch therefore discards the cache and
re-runs everything; a full match reconstructs the
:class:`~tools.hvdlint.core.LintResult` without even parsing the tree.
``--no-cache`` (or ``cache=False``, the library default) bypasses it.
"""

from __future__ import annotations

import json
import pathlib
from typing import Any

CACHE_DIR = ".hvdlint_cache"
CACHE_VERSION = 1


def _stat_key(path: pathlib.Path) -> list[int] | None:
    try:
        st = path.stat()
    except OSError:
        return None
    return [st.st_mtime_ns, st.st_size]


def manifest(project) -> dict[str, list[int] | None]:
    """``rel path -> (mtime_ns, size)`` over every input that can
    change a finding."""
    root = project.root
    out: dict[str, list[int] | None] = {}

    def add(p: pathlib.Path) -> None:
        try:
            rel = p.relative_to(root).as_posix()
        except ValueError:            # pragma: no cover — defensive
            rel = str(p)
        out[rel] = _stat_key(p)

    for sf in project.files:
        add(sf.abs)
    for p in project.test_files:
        add(p)
    add(root / project.docs_knobs_file)
    tool_dir = root / "tools" / "hvdlint"
    if tool_dir.is_dir():
        for p in sorted(tool_dir.rglob("*.py")):
            if "__pycache__" not in p.parts:
                add(p)
    from tools.hvdlint.core import BASELINE_DEFAULT
    add(root / BASELINE_DEFAULT)
    return out


def _cache_file(root: pathlib.Path) -> pathlib.Path:
    return root / CACHE_DIR / "findings.json"


def load(project) -> "Any | None":
    """The cached :class:`LintResult` when every manifest entry still
    matches, else None."""
    from tools.hvdlint.core import Finding, LintResult, Suppression
    path = _cache_file(project.root)
    try:
        data = json.loads(path.read_text())
    except (OSError, ValueError):
        return None
    if data.get("version") != CACHE_VERSION:
        return None
    if data.get("manifest") != manifest(project):
        return None
    res = data["result"]
    findings = [Finding(**f)
                for bucket in res["findings_by_path"].values()
                for f in bucket]
    findings.sort(key=lambda f: (f.path, f.line, f.code, f.symbol))
    return LintResult(
        root=res["root"],
        findings=findings,
        stale_baseline=res["stale_baseline"],
        unused_suppressions=[
            Suppression(path=s["path"], line=s["line"],
                        codes=tuple(s["codes"]),
                        justification=s.get("justification"))
            for s in res["unused_suppressions"]],
        files_scanned=res["files_scanned"])


def store(project, result) -> None:
    """Persist the (unfiltered) run, bucketed per source file.  Cache
    writes are best-effort: a read-only checkout just runs cold."""
    by_path: dict[str, list[dict]] = {}
    for f in result.findings:
        d = f.to_dict()
        d.pop("fingerprint", None)
        d["symbol"] = f.symbol
        by_path.setdefault(f.path, []).append(d)
    payload = {
        "version": CACHE_VERSION,
        "manifest": manifest(project),
        "result": {
            "root": result.root,
            "files_scanned": result.files_scanned,
            "findings_by_path": by_path,
            "stale_baseline": result.stale_baseline,
            "unused_suppressions": [
                {"path": s.path, "line": s.line, "codes": list(s.codes),
                 "justification": s.justification}
                for s in result.unused_suppressions],
        },
    }
    path = _cache_file(project.root)
    try:
        path.parent.mkdir(exist_ok=True)
        path.write_text(json.dumps(payload) + "\n")
    except OSError:                   # pragma: no cover — best-effort
        pass
