"""Built-in hvdlint checkers.  Importing this package registers every
``hvdNNN_*`` module with the core registry; third-party checkers can do
the same by importing :func:`tools.hvdlint.register` and decorating a
:class:`~tools.hvdlint.Checker` subclass."""

from tools.hvdlint.checkers import (  # noqa: F401
    hvd001_retrace,
    hvd002_locks,
    hvd003_env_knobs,
    hvd004_fault_sites,
    hvd005_names,
    hvd006_alert_rules,
    hvd007_lock_order,
    hvd008_blocking,
    hvd009_thread_roles,
    hvd010_determinism,
)
