"""HVD010 — replay determinism on declared bit-identity surfaces.

The journal replay, failover replay, ``clone_engine``, and the chaos
oracles all promise the same thing: run the same inputs again and get
*bit-identical* state.  One ``time.time()`` folded into a persisted
record, one unseeded ``random`` draw, one iteration over a ``set``
feeding replayed state, and the promise silently becomes "usually
close".  Those bugs never fail a unit test — they fail a failover
three weeks later.

The surfaces are declared in a canonical pure-literal table
(``horovod_tpu/metrics.py``, next to the other registries)::

    DETERMINISM_SURFACES = (
        ("journal-replay", "horovod_tpu/router.py", "load_journal",
         "journal parse -> replayed accept/terminal state"),
        ...
    )

For each ``(surface, path, qualname, note)`` row the checker resolves
the function or ``Class.method``, takes the transitive closure over
*same-file* calls (``self.m()`` and module functions — cross-class
aliases are other objects' internals with their own contracts), and
flags inside that closure:

* wall-clock reads: ``time.time``/``time.time_ns``,
  ``datetime.now``/``utcnow``/``today``;
* entropy: ``os.urandom``, module-level ``random.*`` draws and
  ``random.Random()`` with no seed (``random.Random(seed)`` and
  ``random.seed(...)`` are the sanctioned idiom and exempt);
* set-iteration-order dependence: ``for x in {..}`` / ``set(...)`` or
  a comprehension iterating one (wrap in ``sorted(...)`` instead).

``time.monotonic`` is exempt everywhere — it never persists as an
absolute value on these surfaces; it measures, it does not stamp.
A row whose target no longer exists is reported stale, so the table
tracks the code.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.hvdlint.checkers._concurrency import attr_chain, self_attr
from tools.hvdlint.core import Checker, Finding, Project, register

_WALLCLOCK_TIME = {"time", "time_ns"}
_WALLCLOCK_DT = {"now", "utcnow", "today"}


def _locate(tree: ast.Module, qualname: str) -> ast.AST | None:
    """Resolve ``func`` or ``Class.method`` to its def node."""
    cls_name, _, meth = qualname.rpartition(".")
    for node in tree.body:
        if not cls_name and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                node.name == qualname:
            return node
        if cls_name and isinstance(node, ast.ClassDef) and \
                node.name == cls_name:
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) and \
                        item.name == meth:
                    return item
    return None


def _same_file_closure(tree: ast.Module,
                       qualname: str) -> list[tuple[str, ast.AST]]:
    """``[(qualname, def node)]`` reachable from the surface root via
    same-file calls: module functions by bare name, and ``self.m()``
    within the root's class."""
    functions = {n.name: n for n in tree.body
                 if isinstance(n, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))}
    cls_name, _, _ = qualname.rpartition(".")
    methods: dict[str, ast.AST] = {}
    if cls_name:
        for node in tree.body:
            if isinstance(node, ast.ClassDef) and node.name == cls_name:
                methods = {i.name: i for i in node.body
                           if isinstance(i, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))}
    root = _locate(tree, qualname)
    if root is None:
        return []
    out: list[tuple[str, ast.AST]] = []
    seen: set[str] = set()
    work: list[tuple[str, ast.AST]] = [(qualname, root)]
    while work:
        qn, fn = work.pop()
        if qn in seen:
            continue
        seen.add(qn)
        out.append((qn, fn))
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name) and f.id in functions:
                work.append((f.id, functions[f.id]))
            else:
                callee = self_attr(f)
                if callee is not None and callee in methods:
                    work.append((f"{cls_name}.{callee}",
                                 methods[callee]))
    return out


def _nondeterminism(fn: ast.AST) -> Iterator[tuple[int, str, str]]:
    """``(line, kind, desc)`` for every nondeterministic site."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            ch = attr_chain(node.func)
            if ch is None:
                continue
            if ch[0] == "time" and len(ch) == 2 and \
                    ch[1] in _WALLCLOCK_TIME:
                yield node.lineno, "wall-clock", ".".join(ch)
            elif ch[0] == "datetime" and ch[-1] in _WALLCLOCK_DT:
                yield node.lineno, "wall-clock", ".".join(ch)
            elif ch == ["os", "urandom"]:
                yield node.lineno, "entropy", "os.urandom"
            elif ch[0] == "random" and len(ch) == 2:
                if ch[1] == "seed":
                    continue
                if ch[1] == "Random":
                    if not node.args and not node.keywords:
                        yield (node.lineno, "entropy",
                               "random.Random() [unseeded]")
                    continue
                yield node.lineno, "entropy", ".".join(ch)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            if _is_set_expr(node.iter):
                yield node.lineno, "set-order", "for over a set"
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            for gen in node.generators:
                if _is_set_expr(gen.iter):
                    yield (node.lineno, "set-order",
                           "comprehension over a set")


def _is_set_expr(expr: ast.expr) -> bool:
    if isinstance(expr, ast.Set):
        return True
    return (isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Name)
            and expr.func.id in ("set", "frozenset"))


@register
class ReplayDeterminismChecker(Checker):
    code = "HVD010"
    summary = ("nondeterminism (wall clock, entropy, set order) on a "
               "declared bit-identity replay surface")

    def check(self, project: Project) -> Iterator[Finding]:
        by_rel = {sf.rel: sf for sf in project.files}
        for i, row in enumerate(project.determinism_surfaces):
            if not (isinstance(row, (tuple, list)) and len(row) == 4
                    and all(isinstance(x, str) for x in row)):
                yield Finding(
                    self.code, Project.METRICS_FILE,
                    project.line_of(Project.METRICS_FILE,
                                    "DETERMINISM_SURFACES"),
                    f"DETERMINISM_SURFACES[{i}] is not a (surface, "
                    "path, qualname, note) string 4-tuple",
                    symbol=f"surface[{i}]:malformed")
                continue
            surface, rel, qualname, _note = row
            sf = by_rel.get(rel)
            tree = sf.tree if sf is not None else None
            if tree is None or _locate(tree, qualname) is None:
                yield Finding(
                    self.code, Project.METRICS_FILE,
                    project.line_of(Project.METRICS_FILE, qualname),
                    f"DETERMINISM_SURFACES entry `{qualname}` not "
                    f"found in {rel} — stale surface row",
                    symbol=f"{qualname}:stale-surface")
                continue
            for qn, fn in _same_file_closure(tree, qualname):
                for line, kind, desc in sorted(_nondeterminism(fn)):
                    yield Finding(
                        self.code, rel, line,
                        f"`{desc}` ({kind}) inside `{qn}`, reached "
                        f"from determinism surface `{qualname}` "
                        f"({surface}) — replayed/persisted state must "
                        "be bit-identical; take the value from the "
                        "journal/seed or sort before iterating",
                        symbol=f"{qn}:{desc}")
