"""HVD001 — retrace hazards on the serving decode path.

The engine's core invariant is *one jit signature per program for the
server's life* (every retrace is a multi-second stall mid-decode).
Three things break it statically:

* **branch** — a jitted function branching (``if``/``while``) on one of
  its traced parameters: under trace that raises
  ``TracerBoolConversionError`` or, with the parameter later made
  static, silently forks one compiled program per value.  Shape/dtype
  inspection (``x.shape``, ``x.ndim``, ``x.dtype``, ``x.size``,
  ``len(x)``, ``isinstance(x, ...)``) is static and exempt, as are
  parameters declared in ``static_argnums``/``static_argnames``.
* **unpinned** — a jit site whose compile count is not observable
  through a ``compile_cache_sizes()`` method (the convention the serve
  tests assert stays flat).  A jitted function bound to ``self.X`` is
  pinned when the owning class's ``compile_cache_sizes`` reads
  ``self.X._cache_size()``; module- or function-level jits have no pin
  and are flagged for an explicit suppression/baseline decision.
* **unhashable-static** — a call to a locally-jitted function passing a
  list/dict/set literal in a static position: static argument values
  are hashed as cache keys, so this raises at runtime (or, once
  "fixed" by tupling per call site, retraces per distinct value).

Scoped to the decode-path files (``serving_scheduler.py``,
``models/llama.py``, ``serving.py``) — override with
``Project(hvd001_targets=...)``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.hvdlint.core import Checker, Finding, Project, register

_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
_STATIC_CALLS = {"len", "isinstance"}


def _is_jit_name(node: ast.AST) -> bool:
    """``jit`` or ``jax.jit`` as an expression."""
    if isinstance(node, ast.Name):
        return node.id == "jit"
    return (isinstance(node, ast.Attribute) and node.attr == "jit"
            and isinstance(node.value, ast.Name) and node.value.id == "jax")


def _is_partial_name(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return node.id == "partial"
    return (isinstance(node, ast.Attribute) and node.attr == "partial"
            and isinstance(node.value, ast.Name)
            and node.value.id == "functools")


def _jit_call(node: ast.AST) -> ast.Call | None:
    """The jit ``Call`` node when ``node`` is ``jax.jit(...)`` or
    ``partial(jax.jit, ...)`` (keywords ride on the same call)."""
    if not isinstance(node, ast.Call):
        return None
    if _is_jit_name(node.func):
        return node
    if _is_partial_name(node.func) and node.args \
            and _is_jit_name(node.args[0]):
        return node
    return None


def _decorator_jit(dec: ast.AST) -> ast.Call | None | bool:
    """True for bare ``@jax.jit``, the Call for ``@jax.jit(...)`` /
    ``@partial(jax.jit, ...)``, None otherwise."""
    if _is_jit_name(dec):
        return True
    return _jit_call(dec)


def _static_params(fn: ast.FunctionDef, jit: ast.Call | bool) -> set[str]:
    """Parameter names excluded from tracing by static_argnums/names."""
    names: set[str] = set()
    if jit is True or not isinstance(jit, ast.Call):
        return names
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for kw in jit.keywords:
        try:
            val = ast.literal_eval(kw.value)
        except ValueError:
            continue
        if kw.arg == "static_argnums":
            for i in val if isinstance(val, (tuple, list)) else (val,):
                if isinstance(i, int) and 0 <= i < len(params):
                    names.add(params[i])
        elif kw.arg == "static_argnames":
            vals = val if isinstance(val, (tuple, list)) else (val,)
            names.update(v for v in vals if isinstance(v, str))
    return names


def _static_positions(jit: ast.Call | bool) -> tuple[set[int], set[str]]:
    """(static positional indices, static keyword names) of a jit call."""
    nums: set[int] = set()
    names: set[str] = set()
    if not isinstance(jit, ast.Call):
        return nums, names
    for kw in jit.keywords:
        try:
            val = ast.literal_eval(kw.value)
        except ValueError:
            continue
        if kw.arg == "static_argnums":
            nums.update(i for i in
                        (val if isinstance(val, (tuple, list)) else (val,))
                        if isinstance(i, int))
        elif kw.arg == "static_argnames":
            vals = val if isinstance(val, (tuple, list)) else (val,)
            names.update(v for v in vals if isinstance(v, str))
    return nums, names


def _traced_names(expr: ast.AST) -> set[str]:
    """Names an expression's *value* depends on, excluding statically
    evaluable contexts (shape/dtype attributes, len(), isinstance())."""
    if isinstance(expr, ast.Name):
        return {expr.id}
    if isinstance(expr, ast.Attribute):
        if expr.attr in _STATIC_ATTRS:
            return set()
        return _traced_names(expr.value)
    if isinstance(expr, ast.Call):
        if isinstance(expr.func, ast.Name) and \
                expr.func.id in _STATIC_CALLS:
            return set()
        out = set()
        for a in expr.args:
            out |= _traced_names(a)
        for kw in expr.keywords:
            out |= _traced_names(kw.value)
        return out
    out = set()
    for child in ast.iter_child_nodes(expr):
        out |= _traced_names(child)
    return out


class _JitDef:
    """One jitted function definition found in a file."""

    def __init__(self, fn: ast.FunctionDef, jit: ast.Call | bool,
                 qualname: str):
        self.fn = fn
        self.jit = jit
        self.qualname = qualname
        self.static = _static_params(fn, jit)

    @property
    def anchor(self) -> int:
        """The decorator line, so a suppression comment directly above
        the ``@jax.jit`` matches (findings match on their line or the
        line above)."""
        if self.fn.decorator_list:
            return min(d.lineno for d in self.fn.decorator_list)
        return self.fn.lineno


@register
class RetraceChecker(Checker):
    code = "HVD001"
    summary = ("retrace hazard: traced-parameter branch, jit not pinned "
               "by compile_cache_sizes, or unhashable static argument")

    DEFAULT_TARGETS = (
        "horovod_tpu/serving_scheduler.py",
        "horovod_tpu/models/llama.py",
        "horovod_tpu/serving.py",
    )

    def check(self, project: Project) -> Iterator[Finding]:
        targets = (project.hvd001_targets
                   if project.hvd001_targets is not None
                   else self.DEFAULT_TARGETS)
        for sf in project.files:
            if sf.rel not in targets or sf.tree is None:
                continue
            yield from self._check_file(sf.rel, sf.tree)

    # -- per-file ----------------------------------------------------------

    def _check_file(self, rel: str, tree: ast.AST) -> Iterator[Finding]:
        jit_defs: list[_JitDef] = []
        # jit-expression assignments outside classes: (line, target text,
        # enclosing qualname)
        loose_assigns: list[tuple[int, str, str]] = []
        pinned: set[str] = set()         # "ClassName.attr" pins
        bound: dict[str, tuple[str, int]] = {}   # defname -> (Cls.attr, line)
        class_of: dict[str, str | None] = {}     # def qualname -> class

        def visit(node: ast.AST, qual: str, cls: str | None) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    q = f"{qual}.{child.name}" if qual else child.name
                    jit = None
                    for dec in child.decorator_list:
                        jit = _decorator_jit(dec)
                        if jit:
                            break
                    if jit:
                        jd = _JitDef(child, jit, q)
                        jit_defs.append(jd)
                        class_of[q] = cls
                    visit(child, q, cls)
                elif isinstance(child, ast.ClassDef):
                    q = f"{qual}.{child.name}" if qual else child.name
                    visit(child, q, child.name)
                elif isinstance(child, ast.Assign) and cls is not None:
                    self._class_assign(child, cls, qual, jit_defs, bound,
                                       loose_assigns)
                    visit(child, qual, cls)
                elif isinstance(child, ast.Assign):
                    if _jit_call(child.value) is not None:
                        tgt = ast.unparse(child.targets[0])
                        loose_assigns.append(
                            (child.lineno, tgt, qual or "<module>"))
                    visit(child, qual, cls)
                else:
                    visit(child, qual, cls)

        visit(tree, "", None)

        # Pins: compile_cache_sizes methods reading self.X._cache_size().
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, ast.FunctionDef) and \
                            item.name == "compile_cache_sizes":
                        for sub in ast.walk(item):
                            if (isinstance(sub, ast.Attribute)
                                    and sub.attr == "_cache_size"
                                    and isinstance(sub.value, ast.Attribute)
                                    and isinstance(sub.value.value, ast.Name)
                                    and sub.value.value.id == "self"):
                                pinned.add(f"{node.name}.{sub.value.attr}")

        # Rule: traced-parameter branches.
        for jd in jit_defs:
            yield from self._branches(rel, jd)

        # Rule: unpinned jits.
        for jd in jit_defs:
            key = jd.fn.name if class_of.get(jd.qualname) else None
            binding = bound.get(jd.fn.name) if key else None
            if binding is not None:
                attr, line = binding
                if attr not in pinned:
                    yield Finding(
                        self.code, rel, line,
                        f"jitted function bound to self.{attr.split('.')[1]}"
                        f" is not pinned: add it to "
                        f"{attr.split('.')[0]}.compile_cache_sizes() so "
                        "retraces are observable",
                        symbol=f"{attr}:unpinned")
            else:
                yield Finding(
                    self.code, rel, jd.anchor,
                    f"jit site `{jd.qualname}` is not pinned through any "
                    "compile_cache_sizes(); suppress with a justification "
                    "or bind it to a pinned class attribute",
                    symbol=f"{jd.qualname}:unpinned")
        for line, tgt, qual in loose_assigns:
            yield Finding(
                self.code, rel, line,
                f"jit call assigned to `{tgt}` in {qual} is not pinned "
                "through any compile_cache_sizes(); suppress with a "
                "justification or bind it to a pinned class attribute",
                symbol=f"{qual}:{tgt}:unpinned")

        # Rule: unhashable literals in static positions at call sites.
        by_name = {jd.fn.name: jd for jd in jit_defs}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            callee = None
            if isinstance(node.func, ast.Name):
                callee = node.func.id
            elif isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == "self":
                callee = node.func.attr
            jd = by_name.get(callee or "")
            if jd is None:
                continue
            nums, names = _static_positions(jd.jit)
            params = [a.arg for a in jd.fn.args.posonlyargs
                      + jd.fn.args.args]
            for i, arg in enumerate(node.args):
                name = params[i] if i < len(params) else None
                if (i in nums or (name and name in jd.static)) and \
                        isinstance(arg, (ast.List, ast.Dict, ast.Set)):
                    yield Finding(
                        self.code, rel, node.lineno,
                        f"unhashable {type(arg).__name__.lower()} literal "
                        f"passed in static position {i} of jitted "
                        f"`{jd.qualname}` — static args are hashed as "
                        "compile-cache keys",
                        symbol=f"{jd.qualname}:static-arg-{i}")
            for kw in node.keywords:
                if kw.arg in names and \
                        isinstance(kw.value, (ast.List, ast.Dict, ast.Set)):
                    yield Finding(
                        self.code, rel, node.lineno,
                        f"unhashable {type(kw.value).__name__.lower()} "
                        f"literal passed as static `{kw.arg}` of jitted "
                        f"`{jd.qualname}` — static args are hashed as "
                        "compile-cache keys",
                        symbol=f"{jd.qualname}:static-{kw.arg}")

    def _branches(self, rel: str, jd: _JitDef) -> Iterator[Finding]:
        """Flag ``if``/``while`` tests inside a jitted body that depend
        on a traced parameter.  Only the function's own parameters count
        — closure variables are bound at trace time and are static."""
        params = {a.arg for a in jd.fn.args.posonlyargs + jd.fn.args.args
                  + jd.fn.args.kwonlyargs} - jd.static
        for node in ast.walk(jd.fn):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            hazards = _traced_names(node.test) & params
            for name in sorted(hazards):
                yield Finding(
                    self.code, rel, node.lineno,
                    f"`{jd.qualname}` branches on traced parameter "
                    f"`{name}` — this retraces per value (or raises "
                    "TracerBoolConversionError); hoist the branch out of "
                    "the jit or declare the parameter static",
                    symbol=f"{jd.qualname}:branch:{name}")

    def _class_assign(self, node: ast.Assign, cls: str, qual: str,
                      jit_defs: list[_JitDef],
                      bound: dict[str, tuple[str, int]],
                      loose: list[tuple[int, str, str]]) -> None:
        """Inside a class: record `self.X = <jitted local def>` bindings
        and flag direct `self.X = jax.jit(...)` / subscript jit assigns."""
        local_jits = {jd.fn.name for jd in jit_defs}
        for tgt in node.targets:
            if isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name) and \
                    tgt.value.id == "self":
                if isinstance(node.value, ast.Name) and \
                        node.value.id in local_jits:
                    bound[node.value.id] = (f"{cls}.{tgt.attr}",
                                            node.lineno)
                elif _jit_call(node.value) is not None:
                    loose.append((node.lineno, f"self.{tgt.attr}",
                                  qual or cls))
            elif _jit_call(node.value) is not None:
                loose.append((node.lineno, ast.unparse(tgt),
                              qual or cls))
