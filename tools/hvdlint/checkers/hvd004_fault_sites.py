"""HVD004 — fault-site coverage.

``horovod_tpu.faults`` injects deterministic faults at named sites; the
canonical site list is ``metrics.FAULT_SITES``.  A registered site that
nothing injects at is dead configuration surface; an injection site not
in the table is invisible to ops dashboards; and a site no test ever
exercises is untested failure handling.  Three rules, each anchored
where the fix goes:

* every ``FAULT_SITES`` entry has at least one ``.check("<site>")``
  call in the package (anchored at the table entry);
* every ``.check("<site>")`` call names a registered site (anchored at
  the call);
* every ``FAULT_SITES`` entry appears somewhere in ``tests/`` text —
  the weakest reference that still proves a test drives the site.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.hvdlint.core import Checker, Finding, Project, register


def iter_check_sites(tree: ast.AST) -> Iterator[tuple[str, int]]:
    """(site, line) for every ``<x>.check("site")`` / ``check("site")``
    call whose first argument is a string literal."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        is_check = (isinstance(f, ast.Attribute) and f.attr == "check") \
            or (isinstance(f, ast.Name) and f.id == "check")
        if not is_check:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                and "." in arg.value and arg.value.islower():
            yield arg.value, node.lineno


@register
class FaultSiteChecker(Checker):
    code = "HVD004"
    summary = ("FAULT_SITES entry with no injection call site or no "
               "test reference, or a .check() site not registered")

    def check(self, project: Project) -> Iterator[Finding]:
        registered = set(project.fault_sites)
        injected: dict[str, tuple[str, int]] = {}
        for sf in project.files:
            if sf.tree is None:
                continue
            for site, line in iter_check_sites(sf.tree):
                injected.setdefault(site, (sf.rel, line))
                if site not in registered:
                    yield Finding(
                        self.code, sf.rel, line,
                        f"fault injection at `{site}` but that site is "
                        "not registered in metrics.FAULT_SITES — add it "
                        "so injection configs and dashboards see it",
                        symbol=f"{site}:unregistered")

        metrics_rel = project.METRICS_FILE
        tests_text = "\n".join(
            p.read_text() for p in project.test_files)
        for site in registered:
            anchor = project.line_of(metrics_rel, f'"{site}"')
            if site not in injected:
                yield Finding(
                    self.code, metrics_rel, anchor,
                    f"FAULT_SITES entry `{site}` has no .check() "
                    "injection call site anywhere in the package — "
                    "dead site, remove it or wire the injection point",
                    symbol=f"{site}:no-injection-site")
            if site not in tests_text:
                yield Finding(
                    self.code, metrics_rel, anchor,
                    f"FAULT_SITES entry `{site}` is referenced by no "
                    "test under tests/ — the site's failure handling "
                    "is unexercised",
                    symbol=f"{site}:no-test-reference")
