"""HVD007 — lock-order cycles in the interprocedural acquisition graph.

The lockdep idea, done statically over the repo's declared-lock
convention: every ``with self.<lock>:`` (or module-lock) acquisition
that happens while another lock is already held contributes a directed
edge ``held -> acquired``.  Held state comes from lexical nesting AND
from the call graph — ``self.m()``, same-module functions, and one
level of attribute aliasing (``self.router.cordon_replica(...)``
resolves through :class:`~._concurrency.ProjectModel`), plus the
``_LOCK_HOLDER_METHODS`` / ``*_locked`` entry declarations.

Any cycle in that graph is a potential deadlock: two threads taking
the member locks in different orders can each block on the other
forever.  The finding prints every edge of the cycle with the call
chain that produced it, so the fix (a global lock order, or releasing
before calling out) is readable straight from the message.  A plain
``threading.Lock`` re-acquired while already held is a self-deadlock
and reported as a one-node cycle (``RLock`` and handed-in aliases are
exempt — re-entry is legal there).

The full edge list is emitted as ``tools/hvdlint/lock_order.json``
(``python -m tools.hvdlint --write-lock-order``) and rendered as a
table in docs/lint.md; the suite asserts the committed file is fresh
and the repo graph acyclic.
"""

from __future__ import annotations

from typing import Iterator

from tools.hvdlint.checkers._concurrency import (
    ConcurrencyWalker,
    Edge,
    ProjectModel,
)
from tools.hvdlint.core import Checker, Finding, Project, register


def build_lock_graph(project: Project) -> ConcurrencyWalker:
    """The shared entry point: the walked project (edges + blocking
    sites) for this checker, HVD008, the CLI emitter, and the tests."""
    return ConcurrencyWalker(ProjectModel(project)).walk_project()


def lock_order_payload(walker: ConcurrencyWalker) -> dict:
    """The ``lock_order.json`` schema: every acquisition edge, sorted,
    plus the node set — the raw material for the docs table."""
    edges = sorted(walker.edges.values(),
                   key=lambda e: (e.src, e.dst))
    nodes = sorted({e.src for e in edges} | {e.dst for e in edges})
    return {"version": 1, "tool": "hvdlint", "locks": nodes,
            "edges": [e.to_dict() for e in edges]}


def find_cycles(edges: dict[tuple[str, str], Edge]) \
        -> list[list[str]]:
    """Elementary cycles, one per strongly connected component (plus
    explicit self-loops).  One finding per SCC keeps the output stable
    while a multi-edge tangle is being fixed."""
    adj: dict[str, set[str]] = {}
    for (src, dst) in edges:
        adj.setdefault(src, set()).add(dst)
        adj.setdefault(dst, set())

    # Tarjan's SCC, iteratively (the graph is tiny, but recursion
    # limits are not a failure mode a linter should have).
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    sccs: list[list[str]] = []
    counter = [0]

    for root in sorted(adj):
        if root in index:
            continue
        work = [(root, iter(sorted(adj[root])))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(adj[nxt]))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))

    cycles = [comp for comp in sccs]
    for (src, dst) in sorted(edges):
        if src == dst:
            cycles.append([src])
    return cycles


@register
class LockOrderChecker(Checker):
    code = "HVD007"
    summary = ("lock-order cycle (potential deadlock) in the "
               "interprocedural lock-acquisition graph")

    def check(self, project: Project) -> Iterator[Finding]:
        walker = build_lock_graph(project)
        for comp in find_cycles(walker.edges):
            members = set(comp)
            cycle_edges = [
                e for (src, dst), e in sorted(walker.edges.items())
                if src in members and dst in members
                and (len(comp) > 1 or src == dst)]
            if not cycle_edges:        # pragma: no cover — defensive
                continue
            chains = "; ".join(
                f"{e.src} -> {e.dst} at {e.rel}:{e.line} "
                f"(via {' -> '.join(e.chain)})"
                for e in cycle_edges)
            anchor = min(cycle_edges, key=lambda e: (e.rel, e.line))
            if len(comp) == 1:
                msg = (f"lock `{comp[0]}` is re-acquired while already "
                       f"held — a plain threading.Lock self-deadlocks "
                       f"({chains}); use an RLock or split the method")
                symbol = f"self-cycle:{comp[0]}"
            else:
                msg = (f"lock-order cycle between "
                       f"{{{', '.join(comp)}}} — threads taking these "
                       f"locks in different orders can deadlock; "
                       f"acquisition chains: {chains}")
                symbol = "cycle:" + "->".join(comp)
            yield Finding(self.code, anchor.rel, anchor.line, msg,
                          symbol=symbol)
