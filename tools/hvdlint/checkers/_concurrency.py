"""Shared whole-program concurrency model for HVD007–HVD010.

The first six checkers were per-class or per-table; the concurrency
plane needs a *project-wide* view: which classes own locks, which
attributes alias which classes (so ``self.router._lock`` resolves to
``RouterServer._lock``), and what each method calls while holding a
lock.  This module builds that model once per check from the parsed
ASTs — stdlib :mod:`ast` only, never importing the package — and
provides the interprocedural walker HVD007 (lock order) and HVD008
(blocking under lock) share.

Conventions read here (documented in docs/lint.md):

* lock ownership: ``self.X = threading.Lock()/RLock()`` or the
  ``*_lock`` alias-naming convention (HVD002's rules, verbatim);
* ``_LOCK_HOLDER_METHODS`` / ``*_locked`` naming: the method runs with
  the named (or the class's only) lock already held by its caller;
* ``_THREAD_ROLES``: a pure-literal class attribute mapping a thread
  role to its entry-point methods (HVD009);
* alias resolution, one level deep: ``self.X`` resolves to a project
  class via (a) ``self.X = ClassName(...)``, (b) the ``__init__``
  parameter annotation of the value assigned to it, or (c) unique
  method evidence — every ``self.X.m(...)`` call whose method name is
  defined by exactly one project class, when all such calls agree.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

LOCK_CTORS = {"Lock", "RLock"}
QUEUE_CTORS = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue"}

#: Container-mutating method names (HVD002's list): calling one of
#: these on an attribute counts as a mutation for HVD009.
MUTATORS = {
    "append", "extend", "insert", "pop", "popleft", "popitem", "remove",
    "discard", "clear", "update", "add", "setdefault", "appendleft",
    "sort", "reverse", "write", "flush", "close",
}

#: Method names shared with builtin containers/files/locks/futures.
#: Seeing ``self.X.flush()`` is NOT evidence that ``X`` holds a project
#: class (it is usually a file), so these never feed unique-method
#: alias resolution, and calls to them are only followed when the alias
#: was resolved by the *strong* sources (ctor / annotation).
BUILTIN_METHODS = MUTATORS | {
    "get", "keys", "values", "items", "copy", "count", "index",
    "split", "strip", "startswith", "endswith", "format", "read",
    "readline", "readlines", "seek", "tell", "encode", "decode",
    "lower", "upper", "acquire", "release", "locked", "wait", "set",
    "is_set", "start", "join", "cancel", "result", "done", "put",
    "qsize", "empty", "full",
}

_DISPATCH_RE = re.compile(r"all_?reduce|all_?gather|psum|pmean")
_IO_ATTRS = {"urlopen", "urlretrieve", "getresponse", "create_connection",
             "connect", "accept", "recv", "recvfrom", "sendall"}

MAX_DEPTH = 12


def self_attr(node: ast.AST) -> str | None:
    """``self.X`` -> ``"X"``, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def attr_chain(node: ast.AST) -> list[str] | None:
    """Dotted chain for ``a.b.c`` -> ``["a", "b", "c"]``; None when the
    expression is not a pure Name/Attribute chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def _literal(node: ast.AST):
    try:
        return ast.literal_eval(node)
    except ValueError:
        return None


def _is_ctor(node: ast.AST, names: set[str],
             module: str | None = None) -> str | None:
    """``threading.Lock()`` / bare ``Lock()`` style ctor call; returns
    the ctor name or None."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Name) and f.id in names:
        return f.id
    if isinstance(f, ast.Attribute) and f.attr in names and \
            isinstance(f.value, ast.Name) and \
            (module is None or f.value.id == module):
        return f.attr
    return None


def iter_exec_calls(expr: ast.expr) -> Iterator[ast.Call]:
    """Every Call evaluated when ``expr`` is — skipping Lambda bodies,
    which run later (often on another thread entirely)."""
    stack: list[ast.AST] = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Lambda):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def expr_roots(stmt: ast.stmt) -> list[ast.expr]:
    """The expressions evaluated by this statement itself (not the
    bodies of nested compound statements)."""
    roots: list[ast.expr] = []
    for field in ("value", "test", "iter", "exc", "msg"):
        v = getattr(stmt, field, None)
        if isinstance(v, ast.expr):
            roots.append(v)
    for v in getattr(stmt, "targets", []) or []:
        if isinstance(v, ast.expr):
            roots.append(v)
    tgt = getattr(stmt, "target", None)
    if isinstance(tgt, ast.expr):
        roots.append(tgt)
    if isinstance(stmt, ast.With):
        for w in stmt.items:
            roots.append(w.context_expr)
    if isinstance(stmt, ast.Return) and stmt.value is not None:
        pass  # covered by "value"
    return roots


# ---------------------------------------------------------------------------
# Per-class / per-module models.
# ---------------------------------------------------------------------------


class ClassModel:
    """Everything the concurrency checkers need to know about one
    class: its locks, declarations, methods, thread targets, and the
    evidence that resolves ``self.X`` aliases to other classes."""

    def __init__(self, rel: str, node: ast.ClassDef):
        self.rel = rel
        self.node = node
        self.name = node.name
        self.locks: dict[str, str] = {}        # lock attr -> ctor kind
        self.guarded: dict[str, str] = {}      # attr -> lock attr
        self.holder_methods: dict[str, set[str]] = {}
        self.thread_roles: dict[str, tuple[str, ...]] | None = None
        self.thread_roles_line = node.lineno
        self.methods: dict[str, ast.FunctionDef | ast.AsyncFunctionDef] = {}
        self.queue_attrs: set[str] = set()
        self.event_attrs: set[str] = set()
        self.thread_targets: set[str] = set()  # Thread(target=self.<m>)
        self.attr_ctor: dict[str, str] = {}    # self.X = ClassName(...)
        self.attr_param: dict[str, str] = {}   # self.X = <init param>
        self.param_ann: dict[str, str] = {}    # init param -> ann source
        self.alias_calls: dict[str, set[str]] = {}  # self.X.m() evidence
        self._scan()

    def _scan(self) -> None:
        for item in self.node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[item.name] = item
            elif isinstance(item, ast.Assign) and \
                    len(item.targets) == 1 and \
                    isinstance(item.targets[0], ast.Name):
                name = item.targets[0].id
                if name == "_GUARDED_BY_LOCK":
                    val = _literal(item.value)
                    if isinstance(val, dict):
                        for lock, attrs in val.items():
                            for a in attrs:
                                self.guarded[a] = lock
                    elif isinstance(val, (tuple, list)):
                        for a in val:
                            self.guarded[a] = "_lock"
                elif name == "_LOCK_HOLDER_METHODS":
                    val = _literal(item.value)
                    if isinstance(val, dict):
                        self.holder_methods = {
                            k: set(v) for k, v in val.items()}
                elif name == "_THREAD_ROLES":
                    val = _literal(item.value)
                    self.thread_roles_line = item.lineno
                    if isinstance(val, dict):
                        self.thread_roles = {
                            str(k): tuple(v) for k, v in val.items()}
                    else:
                        self.thread_roles = {}   # malformed: flagged
        init = self.methods.get("__init__")
        if init is not None:
            args = init.args
            for a in (list(args.posonlyargs) + list(args.args)
                      + list(args.kwonlyargs)):
                if a.annotation is not None:
                    try:
                        self.param_ann[a.arg] = ast.unparse(a.annotation)
                    except Exception:       # pragma: no cover
                        pass
        for sub in ast.walk(self.node):
            if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                targets = (sub.targets if isinstance(sub, ast.Assign)
                           else [sub.target])
                value = sub.value
                if value is None:
                    continue
                # `self.x = given if given is not None else Default()`
                # carries evidence in both branches
                values = ([value.body, value.orelse]
                          if isinstance(value, ast.IfExp) else [value])
                for tgt in targets:
                    attr = self_attr(tgt)
                    if attr is None:
                        continue
                    for value in values:
                        self._attr_value(attr, value)
            elif isinstance(sub, ast.Call):
                if _is_ctor(sub, {"Thread"}, "threading"):
                    for kw in sub.keywords:
                        if kw.arg == "target":
                            t = self_attr(kw.value)
                            if t is not None:
                                self.thread_targets.add(t)
                f = sub.func
                if isinstance(f, ast.Attribute):
                    ch = attr_chain(f.value)
                    if ch is not None and len(ch) == 2 and \
                            ch[0] == "self":
                        self.alias_calls.setdefault(
                            ch[1], set()).add(f.attr)

    def _attr_value(self, attr: str, value: ast.expr) -> None:
        """Classify one ``self.<attr> = <value>`` assignment."""
        if _is_ctor(value, LOCK_CTORS, "threading"):
            self.locks[attr] = _is_ctor(
                value, LOCK_CTORS, "threading") or "Lock"
        elif (attr == "_lock" or attr.endswith("_lock")) \
                and isinstance(value, (ast.Name, ast.Attribute)):
            # handed-in lock (HVD002's aliasing rule); the real owner
            # is unknown, so treat as reentrant-unknown for self-loop
            # purposes.
            self.locks[attr] = "alias"
        elif _is_ctor(value, QUEUE_CTORS, "queue"):
            self.queue_attrs.add(attr)
        elif _is_ctor(value, {"Event"}, "threading"):
            self.event_attrs.add(attr)
        elif isinstance(value, ast.Call):
            cname = None
            if isinstance(value.func, ast.Name):
                cname = value.func.id
            elif isinstance(value.func, ast.Attribute):
                cname = value.func.attr
            if cname and cname[:1].isupper():
                self.attr_ctor.setdefault(attr, cname)
        elif isinstance(value, ast.Name):
            self.attr_param.setdefault(attr, value.id)

    def entry_held(self, mname: str) -> tuple[str, ...]:
        """Lock attrs this method holds at entry, per declaration:
        ``_LOCK_HOLDER_METHODS`` membership, or the ``*_locked`` naming
        convention when the class has exactly one lock."""
        held: list[str] = []
        for lock, methods in sorted(self.holder_methods.items()):
            if mname in methods and lock in self.locks and \
                    lock not in held:
                held.append(lock)
        if mname.endswith("_locked") and len(self.locks) == 1:
            only = next(iter(self.locks))
            if only not in held:
                held.append(only)
        return tuple(held)


class ModuleModel:
    def __init__(self, rel: str, tree: ast.Module):
        self.rel = rel
        parts = rel.removesuffix(".py").split("/")
        # `native/__init__.py` owns `native._build_lock`, not
        # `__init__._build_lock`
        self.stem = (parts[-2] if parts[-1] == "__init__"
                     and len(parts) > 1 else parts[-1])
        self.classes: list[ClassModel] = []
        self.functions: dict[str, ast.FunctionDef] = {}
        self.module_locks: dict[str, str] = {}     # NAME -> ctor kind
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                self.classes.append(ClassModel(rel, node))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.Assign) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                kind = _is_ctor(node.value, LOCK_CTORS, "threading")
                if kind:
                    self.module_locks[node.targets[0].id] = kind


class ProjectModel:
    """The whole-program view: every module's classes and functions,
    class lookup by (unique) name, and cached alias resolution."""

    def __init__(self, project) -> None:
        self.modules: list[ModuleModel] = []
        for sf in project.files:
            if sf.tree is None:
                continue
            self.modules.append(ModuleModel(sf.rel, sf.tree))
        self.class_by_name: dict[str, ClassModel | None] = {}
        self.method_owners: dict[str, set[str]] = {}
        for mod in self.modules:
            for cls in mod.classes:
                if cls.name in self.class_by_name:
                    self.class_by_name[cls.name] = None   # ambiguous
                else:
                    self.class_by_name[cls.name] = cls
                for m in cls.methods:
                    self.method_owners.setdefault(m, set()).add(cls.name)
        self.module_of: dict[int, ModuleModel] = {
            id(cls): mod for mod in self.modules for cls in mod.classes}
        self._alias_cache: dict[tuple[str, str, str], ClassModel | None] \
            = {}

    def resolve_alias(self, cls: ClassModel, attr: str,
                      with_strength: bool = False):
        """One level of attribute aliasing: which project class does
        ``self.<attr>`` hold an instance of?  With ``with_strength``,
        returns ``(target, strong)`` where ``strong`` means the
        resolution came from a ctor/annotation (not just call-shape
        evidence)."""
        key = (cls.rel, cls.name, attr)
        if key not in self._alias_cache:
            self._alias_cache[key] = self._resolve_alias(cls, attr)
        target, strong = self._alias_cache[key]
        return (target, strong) if with_strength else target

    def _unique_class(self, name: str) -> ClassModel | None:
        got = self.class_by_name.get(name)
        return got if isinstance(got, ClassModel) else None

    def _resolve_alias(self, cls: ClassModel, attr: str) \
            -> tuple[ClassModel | None, bool]:
        # (a) direct construction
        ctor = cls.attr_ctor.get(attr)
        if ctor:
            hit = self._unique_class(ctor)
            if hit is not None:
                return hit, True
        # (b) __init__ parameter annotation of the assigned value
        param = cls.attr_param.get(attr)
        if param and param in cls.param_ann:
            for ident in re.findall(r"[A-Za-z_]\w*",
                                    cls.param_ann[param]):
                hit = self._unique_class(ident)
                if hit is not None:
                    return hit, True
        # (c) unique-method evidence: every self.<attr>.m() call whose
        # (non-builtin-shaped) method is defined by exactly one project
        # class, all agreeing
        cands: set[str] = set()
        for m in cls.alias_calls.get(attr, ()):
            if m in BUILTIN_METHODS:
                continue
            owners = self.method_owners.get(m, set())
            if len(owners) == 1:
                cands |= owners
        if len(cands) == 1:
            return self._unique_class(next(iter(cands))), False
        return None, False

    def lock_node(self, cls: ClassModel, lock_attr: str) -> str:
        return f"{cls.name}.{lock_attr}"

    def lock_kind(self, node_name: str) -> str:
        cls_name, _, attr = node_name.rpartition(".")
        cls = self._unique_class(cls_name)
        if cls is not None:
            return cls.locks.get(attr, "alias")
        for mod in self.modules:
            if cls_name == mod.stem and attr in mod.module_locks:
                return mod.module_locks[attr]
        return "alias"


# ---------------------------------------------------------------------------
# Blocking-call classification (HVD008).
# ---------------------------------------------------------------------------


def classify_blocking(call: ast.Call, cls: ClassModel | None,
                      local_queues: set[str]) -> tuple[str, str] | None:
    """``(kind, description)`` when this call can block indefinitely or
    dispatch to the device; None when it cannot (or carries a
    ``timeout=``/``block=`` bound)."""
    f = call.func
    attr = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    if attr is None:
        return None
    ch = attr_chain(f)
    kwnames = {k.arg for k in call.keywords}
    bounded = bool(call.args) or "timeout" in kwnames

    try:
        desc = ast.unparse(f) + "()"
    except Exception:                      # pragma: no cover
        desc = attr + "()"

    if attr in ("wait", "join"):
        return None if bounded else ("wait", desc)
    if attr in ("get", "put"):
        recv = f.value if isinstance(f, ast.Attribute) else None
        is_queue = (
            (self_attr(recv) in (cls.queue_attrs if cls else ()))
            or (isinstance(recv, ast.Name) and recv.id in local_queues))
        if is_queue and "timeout" not in kwnames and \
                "block" not in kwnames:
            return ("queue", desc)
        return None
    if ch == ["time", "sleep"]:
        return ("sleep", desc)
    if attr in _IO_ATTRS or (ch is not None and len(ch) >= 2 and
                             ch[0] in ("urllib", "socket") or
                             (ch is not None and ch[:2]
                              == ["http", "client"])):
        return ("io", desc)
    if ch is not None and ch[0] == "subprocess" and \
            attr in ("run", "call", "check_call", "check_output"):
        return ("subprocess", desc)
    if attr == "communicate":
        return ("subprocess", desc)
    if attr in ("tick", "spec_tick", "_tick", "_spec_tick") or \
            _DISPATCH_RE.search(attr):
        return ("dispatch", desc)
    return None


def local_queue_names(fn: ast.AST) -> set[str]:
    """Local names bound to a ``queue.Queue(...)``-style ctor inside
    this function (one level — enough for the repo's idiom)."""
    out: set[str] = set()
    for sub in ast.walk(fn):
        if isinstance(sub, (ast.Assign, ast.AnnAssign)):
            targets = (sub.targets if isinstance(sub, ast.Assign)
                       else [sub.target])
            if sub.value is not None and \
                    _is_ctor(sub.value, QUEUE_CTORS, "queue"):
                for t in targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    return out


# ---------------------------------------------------------------------------
# The interprocedural walker (HVD007 edges + HVD008 blocking sites).
# ---------------------------------------------------------------------------


class Edge:
    __slots__ = ("src", "dst", "rel", "line", "chain")

    def __init__(self, src: str, dst: str, rel: str, line: int,
                 chain: tuple[str, ...]):
        self.src, self.dst = src, dst
        self.rel, self.line = rel, line
        self.chain = chain

    def to_dict(self) -> dict:
        return {"src": self.src, "dst": self.dst, "path": self.rel,
                "line": self.line, "via": " -> ".join(self.chain)}


class BlockSite:
    __slots__ = ("rel", "line", "owner", "kind", "desc", "held", "chain")

    def __init__(self, rel: str, line: int, owner: str, kind: str,
                 desc: str, held: tuple[str, ...],
                 chain: tuple[str, ...]):
        self.rel, self.line, self.owner = rel, line, owner
        self.kind, self.desc = kind, desc
        self.held, self.chain = held, chain


class ConcurrencyWalker:
    """Walks every method/function, threading the ordered held-lock
    tuple through ``with`` statements and following calls
    interprocedurally (``self.m()``, same-module functions, and one
    level of attribute aliasing).  Nested ``def``\\ s run later —
    possibly on another thread — and are walked with no held locks,
    as are Lambda bodies (skipped entirely from call-following)."""

    def __init__(self, pm: ProjectModel):
        self.pm = pm
        self.edges: dict[tuple[str, str], Edge] = {}
        self.blocking: dict[tuple[str, int, str], BlockSite] = {}
        self._visited: set = set()

    def walk_project(self) -> "ConcurrencyWalker":
        for mod in self.pm.modules:
            for cls in mod.classes:
                for mname in sorted(cls.methods):
                    if mname in ("__init__", "__new__"):
                        continue
                    held = tuple(self.pm.lock_node(cls, a)
                                 for a in cls.entry_held(mname))
                    self._walk_fn(mod, cls, cls.methods[mname], held,
                                  (f"{cls.name}.{mname}",), 0)
            for fname in sorted(mod.functions):
                self._walk_fn(mod, None, mod.functions[fname], (),
                              (fname,), 0)
        return self

    # -- internals ---------------------------------------------------------

    def _walk_fn(self, mod: ModuleModel, cls: ClassModel | None,
                 fn: ast.AST, held: tuple[str, ...],
                 chain: tuple[str, ...], depth: int) -> None:
        key = (mod.rel, cls.name if cls else "", fn.name, held)
        if key in self._visited or depth > MAX_DEPTH:
            return
        self._visited.add(key)
        lq = local_queue_names(fn)
        self._walk_stmts(mod, cls, fn.name, fn.body, held, chain,
                         depth, lq)

    def _walk_stmts(self, mod, cls, fname, stmts, held, chain, depth,
                    lq) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.With):
                new = list(held)
                for w in stmt.items:
                    for call in iter_exec_calls(w.context_expr):
                        self._call(mod, cls, fname, call, tuple(new),
                                   chain, depth, lq)
                    node = self._acquired(mod, cls, w.context_expr)
                    if node is None:
                        continue
                    if node in new:
                        # immediate re-acquisition: deadlock for a
                        # plain Lock, legal for RLock/unknown aliases
                        if self.pm.lock_kind(node) == "Lock":
                            self._edge(node, node, mod.rel,
                                       stmt.lineno, chain)
                        continue
                    for h in new:
                        self._edge(h, node, mod.rel, stmt.lineno, chain)
                    new.append(node)
                self._walk_stmts(mod, cls, fname, stmt.body, tuple(new),
                                 chain, depth, lq)
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested def: runs later, possibly on another thread
                self._walk_stmts(mod, cls, stmt.name, stmt.body, (),
                                 chain + (f"<nested {stmt.name}>",),
                                 depth, lq | local_queue_names(stmt))
                continue
            for expr in expr_roots(stmt):
                for call in iter_exec_calls(expr):
                    self._call(mod, cls, fname, call, held, chain,
                               depth, lq)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub:
                    self._walk_stmts(mod, cls, fname, sub, held, chain,
                                     depth, lq)
            for handler in getattr(stmt, "handlers", []) or []:
                self._walk_stmts(mod, cls, fname, handler.body, held,
                                 chain, depth, lq)

    def _edge(self, src, dst, rel, line, chain) -> None:
        if (src, dst) not in self.edges:
            self.edges[(src, dst)] = Edge(src, dst, rel, line, chain)

    def _acquired(self, mod: ModuleModel, cls: ClassModel | None,
                  expr: ast.expr) -> str | None:
        """The lock node this with-item acquires, or None."""
        ch = attr_chain(expr)
        if ch is None:
            return None
        if len(ch) == 1 and ch[0] in mod.module_locks:
            return f"{mod.stem}.{ch[0]}"
        if cls is None or ch[0] != "self":
            return None
        if len(ch) == 2 and ch[1] in cls.locks:
            return self.pm.lock_node(cls, ch[1])
        if len(ch) == 3 and (ch[2] == "_lock"
                             or ch[2].endswith("_lock")):
            target = self.pm.resolve_alias(cls, ch[1])
            if target is not None and ch[2] in target.locks:
                return self.pm.lock_node(target, ch[2])
            return f"{cls.name}.{ch[1]}.{ch[2]}"
        return None

    def _call(self, mod: ModuleModel, cls: ClassModel | None, fname,
              call: ast.Call, held, chain, depth, lq) -> None:
        if held:
            hit = classify_blocking(call, cls, lq)
            # A dispatch-*named* call that is really a same-class
            # method (`self._dispatch_allreduce_group(...)`) is a
            # wrapper: we walk into it, so the true dispatch site
            # inside is what gets reported, once.
            if hit is not None and hit[0] == "dispatch" and \
                    cls is not None and \
                    isinstance(call.func, ast.Attribute) and \
                    isinstance(call.func.value, ast.Name) and \
                    call.func.value.id == "self" and \
                    call.func.attr in cls.methods:
                hit = None
            if hit is not None:
                kind, desc = hit
                owner = (f"{cls.name}.{fname}" if cls else fname)
                key = (mod.rel, call.lineno, desc)
                if key not in self.blocking:
                    self.blocking[key] = BlockSite(
                        mod.rel, call.lineno, owner, kind, desc, held,
                        chain)
        f = call.func
        if isinstance(f, ast.Name):
            if f.id in mod.functions and f.id != fname:
                self._walk_fn(mod, None, mod.functions[f.id], held,
                              chain + (f.id,), depth + 1)
            return
        if not isinstance(f, ast.Attribute):
            return
        if isinstance(f.value, ast.Name) and f.value.id == "self" \
                and cls is not None:
            m = f.attr
            if m in cls.methods and m not in ("__init__", "__new__"):
                self._walk_fn(mod, cls, cls.methods[m], held,
                              chain + (f"{cls.name}.{m}",), depth + 1)
            return
        ch = attr_chain(f.value)
        if ch is not None and len(ch) == 2 and ch[0] == "self" and \
                cls is not None:
            target, strong = self.pm.resolve_alias(
                cls, ch[1], with_strength=True)
            if target is not None and f.attr in target.methods and \
                    f.attr not in ("__init__", "__new__") and \
                    (strong or f.attr not in BUILTIN_METHODS):
                tmod = self.pm.module_of.get(id(target))
                if tmod is not None:
                    self._walk_fn(
                        tmod, target, target.methods[f.attr], held,
                        chain + (f"{target.name}.{f.attr}",), depth + 1)
