"""HVD006 — the canonical alert-rule table.

``horovod_tpu.alerts.ALERT_RULES`` is what the pager keys on: the docs
table is rendered from it, the AlertManager evaluates it, and the
chaos-campaign oracle asserts coverage over it.  A rule that drifts
from the metric registry or that no test exercises is a pager that
never rings (or rings wrong), so every entry must:

* be well-formed — the shared keys (``name``/``severity``/``kind``/
  ``metric``/``pending_s``/``clear_s``/``help``) present, the ``kind``
  one the evaluator implements, names unique;
* watch a **registered** metric — ``rule["metric"]`` must have a
  ``METRIC_HELP`` entry (an alert on an unregistered name evaluates
  no-data forever);
* be **asserted under tests/** — the rule name must appear literally in
  a test file (the HVD004 fault-site pattern: unexercised alerting is
  fiction).
"""

from __future__ import annotations

from typing import Iterator

from tools.hvdlint.core import Checker, Finding, Project, register

_REQUIRED_KEYS = ("name", "severity", "kind", "metric", "pending_s",
                  "clear_s", "help")
#: The condition kinds AlertManager._condition implements.
_KINDS = ("burn_rate", "drift", "slope", "threshold", "delta")


@register
class AlertRuleChecker(Checker):
    code = "HVD006"
    summary = ("ALERT_RULES entry malformed, watching an unregistered "
               "metric, or asserted by no test")

    def check(self, project: Project) -> Iterator[Finding]:
        rules = project.alert_rules
        alerts_rel = project.ALERTS_FILE
        help_names = set(project.metric_help)
        tests_text = "\n".join(
            p.read_text() for p in project.test_files)

        seen: set[str] = set()
        for i, rule in enumerate(rules):
            if not isinstance(rule, dict) or "name" not in rule:
                yield Finding(
                    self.code, alerts_rel,
                    project.line_of(alerts_rel, "ALERT_RULES"),
                    f"ALERT_RULES[{i}] is not a rule dict with a "
                    "`name` key",
                    symbol=f"rule[{i}]:malformed")
                continue
            name = rule["name"]
            anchor = project.line_of(alerts_rel, f'"{name}"')
            if name in seen:
                yield Finding(
                    self.code, alerts_rel, anchor,
                    f"ALERT_RULES has duplicate rule name `{name}` — "
                    "state machines and dedup key on the name",
                    symbol=f"{name}:duplicate")
                continue
            seen.add(name)
            missing = [k for k in _REQUIRED_KEYS if k not in rule]
            if missing:
                yield Finding(
                    self.code, alerts_rel, anchor,
                    f"ALERT_RULES entry `{name}` is missing required "
                    f"keys {missing}",
                    symbol=f"{name}:missing-keys")
            if rule.get("kind") not in _KINDS:
                yield Finding(
                    self.code, alerts_rel, anchor,
                    f"ALERT_RULES entry `{name}` has unknown kind "
                    f"`{rule.get('kind')}` (evaluator implements "
                    f"{list(_KINDS)})",
                    symbol=f"{name}:unknown-kind")
            metric = rule.get("metric")
            if metric is not None and help_names \
                    and metric not in help_names:
                yield Finding(
                    self.code, alerts_rel, anchor,
                    f"ALERT_RULES entry `{name}` watches `{metric}` "
                    "which has no metrics.METRIC_HELP entry — the "
                    "rule would evaluate no-data forever",
                    symbol=f"{name}:unregistered-metric")
            if name not in tests_text:
                yield Finding(
                    self.code, alerts_rel, anchor,
                    f"ALERT_RULES entry `{name}` is referenced by no "
                    "test under tests/ — unexercised alerting is "
                    "fiction",
                    symbol=f"{name}:no-test-reference")
