"""HVD008 — calls that can block indefinitely (or dispatch to the
device) while a declared lock is held.

A lock-order-clean system can still stall fleet-wide if one thread
parks forever inside a critical section: every other thread needing
that lock queues behind an ``Event.wait()`` that nobody will set, an
HTTP probe to a dead replica, or a jit dispatch that takes a
compilation pause.  This checker reuses HVD007's interprocedural
walker — the same held-lock state, the same call graph — and flags,
at any point where at least one lock is held:

* unbounded waits: ``.wait()`` / ``.join()`` with no timeout,
  ``Queue.get/put`` with neither ``timeout=`` nor ``block=`` (only on
  receivers known to be queues, so ``dict.get`` stays quiet);
* network/process I/O: ``urllib``/``socket``/``http.client`` calls,
  ``subprocess.run``-family, ``.communicate()``;
* stalls by construction: ``time.sleep``;
* device dispatch: ``tick``/``spec_tick`` engine steps and
  allreduce/allgather/psum collective sites — a compile or a slow
  collective inside a lock serializes the fleet.

``timeout=`` (or a positional bound for ``wait``/``join``) exempts the
call.  Sites that are provably safe for a reason the checker cannot
see take a per-site ``# hvdlint: disable=HVD008 -- <why>`` with its
mandatory justification.
"""

from __future__ import annotations

from typing import Iterator

from tools.hvdlint.checkers.hvd007_lock_order import build_lock_graph
from tools.hvdlint.core import Checker, Finding, Project, register

_KIND_HINT = {
    "wait": "unbounded wait",
    "queue": "unbounded queue op",
    "sleep": "sleep",
    "io": "network I/O",
    "subprocess": "subprocess wait",
    "dispatch": "device dispatch",
}


@register
class BlockingUnderLockChecker(Checker):
    code = "HVD008"
    summary = ("call that can block indefinitely or dispatch to the "
               "device while a lock is held")

    def check(self, project: Project) -> Iterator[Finding]:
        walker = build_lock_graph(project)
        for site in sorted(walker.blocking.values(),
                           key=lambda s: (s.rel, s.line, s.desc)):
            hint = _KIND_HINT.get(site.kind, site.kind)
            yield Finding(
                self.code, site.rel, site.line,
                f"`{site.desc}` ({hint}) runs while holding "
                f"{{{', '.join(site.held)}}} (reached via "
                f"{' -> '.join(site.chain)}); bound it with timeout=, "
                "move it outside the lock, or suppress with a written "
                "justification",
                symbol=f"{site.owner}:{site.desc}")
