"""HVD002 — lock discipline on thread-shared classes.

The metrics registry, monitor threads, and the eager engine all share
mutable state across threads under ``threading.Lock``/``RLock``.  The
convention this checker enforces (documented in docs/lint.md):

* A class that assigns a lock in its body declares what that lock
  guards via a class attribute::

      _GUARDED_BY_LOCK = ("_counts", "_sum")          # guarded by _lock
      _GUARDED_BY_LOCK = {"_lock": ("_queue",),       # multi-lock form
                          "_flush_lock": ("_submitted",)}

* Every mutation of a declared attribute (assignment, augmented
  assignment, ``del``, item store, mutator-method call, or iteration —
  iteration of a concurrently-mutated container throws
  ``RuntimeError``) must happen inside ``with self.<lock>:`` holding
  the declared lock.

* Escape hatches, because real code takes locks in callers:
  ``__init__``/``__new__`` are construction-time and exempt; methods
  whose names end in ``_locked`` are called with the lock already held
  by convention; and ``_LOCK_HOLDER_METHODS = {"_flush_lock": (...)}``
  names methods documented to run entirely under a lock taken by their
  caller.

The checker also reports declaration drift: declared attributes never
assigned in the class (stale), declared locks that do not exist, and —
in the strict file list from the issue (``metrics.py``, ``monitor.py``,
``serving_scheduler.py``, ``ops/eager.py``, ``ops/handle_manager.py``)
— lock-holding classes with no declaration at all.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.hvdlint.core import Checker, Finding, Project, register

_LOCK_CTORS = {"Lock", "RLock"}
_MUTATORS = {
    "append", "extend", "insert", "pop", "popleft", "popitem", "remove",
    "discard", "clear", "update", "add", "setdefault", "appendleft",
    "sort", "reverse", "write", "flush", "close",
}


def _is_lock_ctor(node: ast.AST) -> bool:
    """``threading.Lock()`` / ``threading.RLock()`` / bare ``Lock()``."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Name):
        return f.id in _LOCK_CTORS
    return (isinstance(f, ast.Attribute) and f.attr in _LOCK_CTORS
            and isinstance(f.value, ast.Name)
            and f.value.id == "threading")


def _self_attr(node: ast.AST) -> str | None:
    """``self.X`` -> ``"X"``, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _literal(node: ast.AST):
    try:
        return ast.literal_eval(node)
    except ValueError:
        return None


class _ClassInfo:
    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.locks: set[str] = set()               # self attrs holding locks
        self.guarded: dict[str, str] = {}          # attr -> lock attr
        self.declared = False
        self.decl_line = node.lineno
        self.holder_methods: dict[str, set[str]] = {}  # lock -> methods
        self.assigned_attrs: set[str] = set()      # any self.X = ... seen
        self._scan()

    def _scan(self) -> None:
        for item in self.node.body:
            if isinstance(item, ast.Assign) and \
                    len(item.targets) == 1 and \
                    isinstance(item.targets[0], ast.Name):
                name = item.targets[0].id
                if name == "_GUARDED_BY_LOCK":
                    self.declared = True
                    self.decl_line = item.lineno
                    val = _literal(item.value)
                    if isinstance(val, dict):
                        for lock, attrs in val.items():
                            for a in attrs:
                                self.guarded[a] = lock
                    elif isinstance(val, (tuple, list)):
                        for a in val:
                            self.guarded[a] = "_lock"
                elif name == "_LOCK_HOLDER_METHODS":
                    val = _literal(item.value)
                    if isinstance(val, dict):
                        self.holder_methods = {
                            k: set(v) for k, v in val.items()}
        for sub in ast.walk(self.node):
            if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                targets = (sub.targets if isinstance(sub, ast.Assign)
                           else [sub.target])
                value = sub.value
                for tgt in targets:
                    attr = _self_attr(tgt)
                    if attr is None:
                        continue
                    self.assigned_attrs.add(attr)
                    if value is not None and _is_lock_ctor(value):
                        self.locks.add(attr)
                    elif value is not None and \
                            (attr == "_lock" or attr.endswith("_lock")) \
                            and isinstance(value, (ast.Name,
                                                   ast.Attribute)):
                        # `self._lock = lock` — a lock handed in by the
                        # owner (the metrics registry shares one lock
                        # across its instruments); the naming convention
                        # is the signal.
                        self.locks.add(attr)


@register
class LockDisciplineChecker(Checker):
    code = "HVD002"
    summary = ("lock discipline: guarded attribute touched outside "
               "`with self.<lock>:`, or _GUARDED_BY_LOCK declaration "
               "missing/stale")

    STRICT_FILES = (
        "horovod_tpu/metrics.py",
        "horovod_tpu/monitor.py",
        "horovod_tpu/serving_scheduler.py",
        "horovod_tpu/ops/eager.py",
        "horovod_tpu/ops/handle_manager.py",
    )

    def check(self, project: Project) -> Iterator[Finding]:
        strict = (project.hvd002_strict_files
                  if project.hvd002_strict_files is not None
                  else self.STRICT_FILES)
        for sf in project.files:
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.ClassDef):
                    yield from self._check_class(
                        sf.rel, node, strict_file=sf.rel in strict)

    def _check_class(self, rel: str, node: ast.ClassDef, *,
                     strict_file: bool) -> Iterator[Finding]:
        info = _ClassInfo(node)
        if not info.locks:
            return
        if not info.declared:
            if strict_file:
                yield Finding(
                    self.code, rel, node.lineno,
                    f"class `{node.name}` holds a threading lock but "
                    "declares no _GUARDED_BY_LOCK — declare what the "
                    "lock guards (see docs/lint.md)",
                    symbol=f"{node.name}:undeclared")
            return

        # Declaration drift.
        for attr, lock in sorted(info.guarded.items()):
            if lock not in info.locks:
                yield Finding(
                    self.code, rel, info.decl_line,
                    f"`{node.name}._GUARDED_BY_LOCK` names lock "
                    f"`{lock}` which is never assigned a "
                    "threading.Lock/RLock in this class",
                    symbol=f"{node.name}.{attr}:unknown-lock")
            if attr not in info.assigned_attrs:
                yield Finding(
                    self.code, rel, info.decl_line,
                    f"`{node.name}._GUARDED_BY_LOCK` declares `{attr}` "
                    "but the class never assigns it — stale declaration",
                    symbol=f"{node.name}.{attr}:stale-declaration")

        for item in node.body:
            if not isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if item.name in ("__init__", "__new__") or \
                    item.name.endswith("_locked"):
                continue
            held0 = {lock for lock, methods in info.holder_methods.items()
                     if item.name in methods}
            yield from self._walk_body(rel, node.name, item.name,
                                       item.body, held0, info)

    # -- body walk with the held-lock set ----------------------------------

    def _walk_body(self, rel: str, cls: str, meth: str,
                   stmts: list[ast.stmt], held: set[str],
                   info: _ClassInfo) -> Iterator[Finding]:
        for stmt in stmts:
            if isinstance(stmt, ast.With):
                extra = set()
                for w in stmt.items:
                    attr = _self_attr(w.context_expr)
                    if attr in info.locks:
                        extra.add(attr)
                yield from self._walk_body(rel, cls, meth, stmt.body,
                                           held | extra, info)
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested def: runs later, possibly on another thread —
                # analyze with no held locks
                yield from self._walk_body(rel, cls, meth, stmt.body,
                                           set(), info)
                continue
            yield from self._check_stmt(rel, cls, meth, stmt, held, info)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub:
                    yield from self._walk_body(rel, cls, meth, sub,
                                               held, info)
            for handler in getattr(stmt, "handlers", []) or []:
                yield from self._walk_body(rel, cls, meth, handler.body,
                                           held, info)

    def _check_stmt(self, rel: str, cls: str, meth: str, stmt: ast.stmt,
                    held: set[str], info: _ClassInfo) -> Iterator[Finding]:
        def bad(attr: str, line: int, what: str) -> Finding:
            lock = info.guarded[attr]
            return Finding(
                self.code, rel, line,
                f"`{cls}.{meth}` {what} `self.{attr}` without holding "
                f"`self.{lock}` (declared guard); wrap in `with "
                f"self.{lock}:` or rename the method `*_locked`",
                symbol=f"{cls}.{meth}.{attr}")

        def target_attr(tgt: ast.AST) -> str | None:
            # self.X = / self.X[...] = / self.X += ...
            attr = _self_attr(tgt)
            if attr is not None:
                return attr
            if isinstance(tgt, ast.Subscript):
                return _self_attr(tgt.value)
            return None

        # Direct assignments / deletes.
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for tgt in targets:
                tgts = tgt.elts if isinstance(
                    tgt, (ast.Tuple, ast.List)) else [tgt]
                for t in tgts:
                    attr = target_attr(t)
                    if attr in info.guarded and \
                            info.guarded[attr] not in held:
                        yield bad(attr, stmt.lineno, "assigns")
        elif isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                attr = target_attr(tgt)
                if attr in info.guarded and \
                        info.guarded[attr] not in held:
                    yield bad(attr, stmt.lineno, "deletes from")

        # Iteration over a guarded container.
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            attr = _self_attr(stmt.iter)
            if attr in info.guarded and info.guarded[attr] not in held:
                yield bad(attr, stmt.lineno, "iterates over")

        # Mutator calls and comprehension iteration inside this
        # statement's own expressions (nested statement bodies are
        # visited by _walk_body, not here, so nothing double-counts).
        for expr in self._expr_roots(stmt):
            for node in ast.walk(expr):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute):
                    attr = _self_attr(node.func.value)
                    if attr in info.guarded and \
                            node.func.attr in _MUTATORS and \
                            info.guarded[attr] not in held:
                        yield bad(attr, node.lineno,
                                  f"calls .{node.func.attr}() on")
                if isinstance(node, (ast.ListComp, ast.SetComp,
                                     ast.DictComp, ast.GeneratorExp)):
                    for gen in node.generators:
                        attr = _self_attr(gen.iter)
                        if attr in info.guarded and \
                                info.guarded[attr] not in held:
                            yield bad(attr, node.lineno, "iterates over")

    @staticmethod
    def _expr_roots(stmt: ast.stmt) -> list[ast.expr]:
        """The expressions evaluated by this statement itself (not the
        bodies of nested compound statements)."""
        roots: list[ast.expr] = []
        for field in ("value", "test", "iter", "exc", "msg"):
            v = getattr(stmt, field, None)
            if isinstance(v, ast.expr):
                roots.append(v)
        for field in ("targets",):
            for v in getattr(stmt, field, []) or []:
                if isinstance(v, ast.expr):
                    roots.append(v)
        tgt = getattr(stmt, "target", None)
        if isinstance(tgt, ast.expr):
            roots.append(tgt)
        if isinstance(stmt, ast.With):
            for w in stmt.items:
                roots.append(w.context_expr)
        return roots
