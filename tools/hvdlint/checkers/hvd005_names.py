"""HVD005 — observability name tables (the PR-4 counter-name lint,
ported into the framework; ``tools/check_counter_names.py`` is now a
shim over this checker plus HVD004).

Dashboards and the timeline-summary tool key on three name families —
Chrome-trace counter activities (``timeline.counter("track", "SCHED",
{...})``), registry metric names (``metrics.counter("monitor.scrapes")``
etc.), and the event-log lifecycle kinds — all declared once in
:mod:`horovod_tpu.metrics` (``TIMELINE_COUNTER_SERIES``,
``METRIC_HELP``, ``LIFECYCLE_EVENT_COUNTERS``).  Membership is checked
BOTH ways: an unregistered name in code fails (a dashboard would
silently miss it) and a registered name with no call site fails (dead
table entries rot).  Composed-name families (``"serve." + key`` over
the LIFECYCLE series, ``"prefix." + key`` over PREFIX) have no literal
call site and are excused from the dead-entry direction.

Fault-site membership, previously part of the same script, lives in
HVD004 now.
"""

from __future__ import annotations

import re
from typing import Iterator

from tools.hvdlint.core import Checker, Finding, Project, register

# timeline.counter("<track>", "<ACTIVITY>", {...}) — the uppercase
# second string argument distinguishes a Chrome-trace counter emission
# from MetricsRegistry.counter(name) lookups.
_TIMELINE_COUNTER = re.compile(
    r"\.counter\(\s*[\"']([^\"']+)[\"']\s*,\s*[\"']([A-Z][A-Z_]*)[\"']")
_SERIES_KEY = re.compile(r"[\"']([a-z_]+)[\"']\s*:")
# registry.counter/gauge/histogram("<name>"...) with a LITERAL name —
# the closing quote must be followed by `,` or `)` so composed names
# ("serve." + key) and f-strings stay out of scope.
_REGISTRY_METRIC = re.compile(
    r"\.(counter|gauge|histogram)\(\s*[\"']([a-z0-9_.]+)[\"']\s*[,)]")
_ACTIVITY_NEXT = re.compile(r"\s*[\"'][A-Z]")


def _scan(files) -> tuple[dict[str, set], dict[str, tuple[str, int]],
                          dict[str, tuple[str, int]]]:
    """Returns (activity -> literal series keys,
    activity -> first emission site, metric name -> first site)."""
    activities: dict[str, set] = {}
    act_sites: dict[str, tuple[str, int]] = {}
    metric_sites: dict[str, tuple[str, int]] = {}
    for sf in files:
        text = sf.text
        line_of = lambda pos: text.count("\n", 0, pos) + 1  # noqa: E731
        for m in _TIMELINE_COUNTER.finditer(text):
            activity = m.group(2)
            act_sites.setdefault(activity, (sf.rel, line_of(m.start())))
            keys = activities.setdefault(activity, set())
            # Only dict *literals* contribute keys (dict(self.counters)
            # style emissions are covered by the table itself).
            window = text[m.end():m.end() + 400]
            depth_end = window.find(")")
            keys.update(_SERIES_KEY.findall(
                window if depth_end < 0 else window[:depth_end + 1]))
        for m in _REGISTRY_METRIC.finditer(text):
            if _ACTIVITY_NEXT.match(text, m.end()):
                continue             # a timeline.counter(track, "SCHED"
            metric_sites.setdefault(m.group(2),
                                    (sf.rel, line_of(m.start())))
    return activities, act_sites, metric_sites


@register
class CounterNameChecker(Checker):
    code = "HVD005"
    summary = ("observability name not in its canonical table "
               "(TIMELINE_COUNTER_SERIES / METRIC_HELP / "
               "LIFECYCLE_EVENT_COUNTERS), or a dead table entry")

    def check(self, project: Project) -> Iterator[Finding]:
        activities, act_sites, metric_sites = _scan(project.files)
        series = project.timeline_counter_series
        metrics_rel = project.METRICS_FILE

        registered = set(series)
        for activity in sorted(activities):
            rel, line = act_sites[activity]
            if activity not in registered:
                yield Finding(
                    self.code, rel, line,
                    f"timeline counter activity `{activity}` is emitted "
                    "but not registered in "
                    "metrics.TIMELINE_COUNTER_SERIES",
                    symbol=f"{activity}:unregistered-activity")
                continue
            extra = activities[activity] - set(series[activity])
            if extra:
                yield Finding(
                    self.code, rel, line,
                    f"timeline counter `{activity}` emits series "
                    f"{sorted(extra)} not registered in "
                    f"metrics.TIMELINE_COUNTER_SERIES[{activity!r}]",
                    symbol=f"{activity}:unregistered-series")
        for activity in sorted(registered - set(activities)):
            yield Finding(
                self.code, metrics_rel,
                project.line_of(metrics_rel, f'"{activity}"'),
                f"metrics.TIMELINE_COUNTER_SERIES registers "
                f"`{activity}` but no timeline.counter call emits it",
                symbol=f"{activity}:dead-activity")

        # Registry metric names vs METRIC_HELP, both directions.
        help_names = set(project.metric_help)
        dynamic = (
            {"serve." + k for k in series.get("LIFECYCLE", ())}
            | {"prefix." + k for k in series.get("PREFIX", ())}
            # Per-endpoint scrape instruments: emitted as
            # monitor.scrape_s.<endpoint> f-strings, documented under
            # the family base name.
            | {"monitor.scrape_s", "monitor.scrape_errors"})
        for name in sorted(set(metric_sites) - help_names):
            rel, line = metric_sites[name]
            yield Finding(
                self.code, rel, line,
                f"registry metric `{name}` is emitted but has no "
                "metrics.METRIC_HELP entry (dashboards get no "
                "# HELP line)",
                symbol=f"{name}:no-help")
        for name in sorted(help_names - set(metric_sites) - dynamic):
            yield Finding(
                self.code, metrics_rel,
                project.line_of(metrics_rel, f'"{name}"'),
                f"metrics.METRIC_HELP describes `{name}` but no "
                "counter/gauge/histogram call site emits it",
                symbol=f"{name}:dead-help")

        # Internal consistency: the event-log replay map must cover
        # exactly the LIFECYCLE counter series.
        lifecycle = set(series.get("LIFECYCLE", ()))
        mapped = set(project.lifecycle_event_counters.values())
        if lifecycle != mapped:
            yield Finding(
                self.code, metrics_rel,
                project.line_of(metrics_rel, "LIFECYCLE_EVENT_COUNTERS"),
                f"LIFECYCLE_EVENT_COUNTERS values {sorted(mapped)} != "
                f"LIFECYCLE series {sorted(lifecycle)}",
                symbol="lifecycle-map:mismatch")
