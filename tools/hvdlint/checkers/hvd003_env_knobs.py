"""HVD003 — environment-knob registry.

Every ``HVD_TPU_*`` / ``HOROVOD_*`` environment variable the package
reads must appear in the canonical ``horovod_tpu.knobs.ENV_KNOBS``
table *and* in the docs knob table (``docs/observability.md``), and
both tables must be free of dead entries — four directions total:

* a getenv site whose knob is missing from ``ENV_KNOBS`` (anchored at
  the read site);
* an ``ENV_KNOBS`` row no code reads (anchored at the table);
* an ``ENV_KNOBS`` row missing from the docs table;
* a docs-table row missing from ``ENV_KNOBS``.

Read sites recognized: ``os.environ.get(K)`` / ``os.getenv(K)`` /
``os.environ[K]`` (Load context only — launch scripts *writing* child
env don't count) and the repo's typed helpers (``_get_int``,
``_get_float``, ``_get_bool``, ``_get_tristate``, ``_env_float``).
The knob-name argument may be a string literal or a module-level
string constant (``HOROVOD_TIMELINE = "HOROVOD_TIMELINE"`` — the
``utils/env.py`` idiom).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from tools.hvdlint.core import Checker, Finding, Project, register

_KNOB_RE = re.compile(r"^(?:HVD_TPU|HOROVOD)_[A-Z0-9_]+$")
_HELPERS = {"_get_int", "_get_float", "_get_bool", "_get_tristate",
            "_env_float", "env_float", "_env_int"}
_DOCS_ROW_RE = re.compile(r"^\|\s*`([A-Z0-9_]+)`\s*\|")


def _module_str_constants(tree: ast.AST) -> dict[str, str]:
    out: dict[str, str] = {}
    for node in getattr(tree, "body", []):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            out[node.targets[0].id] = node.value.value
    return out


def _knob_arg(node: ast.expr | None,
              constants: dict[str, str]) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return constants.get(node.id)
    return None


def _is_environ(node: ast.AST) -> bool:
    """``os.environ`` or bare ``environ``."""
    if isinstance(node, ast.Name):
        return node.id == "environ"
    return (isinstance(node, ast.Attribute) and node.attr == "environ"
            and isinstance(node.value, ast.Name)
            and node.value.id == "os")


def iter_read_sites(tree: ast.AST) -> Iterator[tuple[str, int]]:
    """(knob name, line) for every env read in a module."""
    constants = _module_str_constants(tree)
    for node in ast.walk(tree):
        name = None
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "get" and \
                    _is_environ(f.value):
                name = _knob_arg(node.args[0] if node.args else None,
                                 constants)
            elif isinstance(f, ast.Attribute) and f.attr == "getenv" and \
                    isinstance(f.value, ast.Name) and f.value.id == "os":
                name = _knob_arg(node.args[0] if node.args else None,
                                 constants)
            elif isinstance(f, ast.Name) and \
                    (f.id == "getenv" or f.id in _HELPERS):
                name = _knob_arg(node.args[0] if node.args else None,
                                 constants)
        elif isinstance(node, ast.Subscript) and \
                _is_environ(node.value) and \
                isinstance(node.ctx, ast.Load):
            name = _knob_arg(node.slice, constants)
        if name and _KNOB_RE.match(name):
            yield name, node.lineno


@register
class EnvKnobChecker(Checker):
    code = "HVD003"
    summary = ("env knob not in the canonical ENV_KNOBS table / docs "
               "knob table, or a table row no code reads")

    def check(self, project: Project) -> Iterator[Finding]:
        table = {row[0] for row in project.env_knobs}
        read: dict[str, tuple[str, int]] = {}   # knob -> first site
        for sf in project.files:
            if sf.tree is None:
                continue
            for name, line in iter_read_sites(sf.tree):
                read.setdefault(name, (sf.rel, line))
                if name not in table:
                    yield Finding(
                        self.code, sf.rel, line,
                        f"env knob `{name}` is read here but missing "
                        "from horovod_tpu.knobs.ENV_KNOBS — add a row "
                        "(name, default, help)",
                        symbol=f"{name}:unregistered")

        knobs_rel = project.KNOBS_FILE
        for name in sorted(table - set(read)):
            yield Finding(
                self.code, knobs_rel,
                project.line_of(knobs_rel, f'"{name}"'),
                f"ENV_KNOBS row `{name}` is never read by any "
                "getenv/helper site — dead entry, remove it",
                symbol=f"{name}:dead-entry")

        # Docs table <-> ENV_KNOBS, both directions.
        docs_rel = project.docs_knobs_file
        docs_path = project.root / docs_rel
        if not docs_path.exists():
            if table:
                yield Finding(
                    self.code, knobs_rel, 1,
                    f"docs knob table file `{docs_rel}` does not exist "
                    "but ENV_KNOBS is non-empty",
                    symbol="docs:missing")
            return
        documented: dict[str, int] = {}
        for i, ln in enumerate(docs_path.read_text().splitlines(), 1):
            m = _DOCS_ROW_RE.match(ln.strip())
            if m and _KNOB_RE.match(m.group(1)):
                documented.setdefault(m.group(1), i)
        for name in sorted(table - set(documented)):
            yield Finding(
                self.code, knobs_rel,
                project.line_of(knobs_rel, f'"{name}"'),
                f"ENV_KNOBS row `{name}` is missing from the knob table "
                f"in {docs_rel} (regenerate with "
                "`python -m horovod_tpu.knobs`)",
                symbol=f"{name}:undocumented")
        for name in sorted(set(documented) - table):
            yield Finding(
                self.code, docs_rel, documented[name],
                f"documented knob `{name}` is not in ENV_KNOBS — stale "
                "docs row, remove it or register the knob",
                symbol=f"{name}:stale-docs")
