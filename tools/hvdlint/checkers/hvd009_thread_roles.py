"""HVD009 — thread-ownership: attributes mutated from two or more
thread roles without a guarding lock.

``_GUARDED_BY_LOCK`` (HVD002) only protects what someone remembered to
declare; the blind spot is the attribute nobody declared because
nobody noticed two threads touch it.  This checker closes that gap
with a second pure-literal class declaration::

    _THREAD_ROLES = {
        "pump":   ["_pump"],                 # the replica's own thread
        "poller": ["poll_now", "_poll_loop"],
        "http":   ["handle_generate", "result"],
    }

Each role names its entry-point methods (the ``Thread(target=...)``
bodies and the public methods a given thread calls into).  The checker
computes each role's *reachable* method set — the transitive closure
over ``self.m()`` calls in executed-now position (lambdas and nested
``def`` bodies are excluded: they run later, usually on a different
thread) — then collects every ``self.X`` mutation per method with
HVD002's held-lock tracking.  An attribute mutated from ≥ 2 roles with
at least one mutation site outside any lock is a data race waiting for
load, and is reported at the first unguarded site.

Declaration honesty is checked too: role entries must name real
methods, every ``Thread(target=self.<m>)`` spawn must be assigned to a
role, and — in the strict file list — a class that spawns threads must
declare ``_THREAD_ROLES`` at all.  Attributes already covered by
``_GUARDED_BY_LOCK``, lock objects, and ``threading.Event`` attrs
(whose ``set``/``clear`` are atomic) are HVD002's jurisdiction and
skipped here, as is all of ``__init__``/``__new__`` (construction
happens before the threads exist).
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.hvdlint.checkers._concurrency import (
    MUTATORS,
    ClassModel,
    ProjectModel,
    self_attr,
)
from tools.hvdlint.core import Checker, Finding, Project, register


class _Mutation:
    __slots__ = ("attr", "line", "guarded", "what")

    def __init__(self, attr: str, line: int, guarded: bool, what: str):
        self.attr, self.line = attr, line
        self.guarded, self.what = guarded, what


def _target_attr(tgt: ast.AST) -> str | None:
    attr = self_attr(tgt)
    if attr is not None:
        return attr
    if isinstance(tgt, ast.Subscript):
        return self_attr(tgt.value)
    return None


def _collect_mutations(cls: ClassModel, mname: str) -> list[_Mutation]:
    """Every ``self.X`` mutation in this method, with whether any of
    the class's locks was held at the site (lexically or by the
    ``_LOCK_HOLDER_METHODS``/``*_locked`` entry declarations)."""
    out: list[_Mutation] = []
    fn = cls.methods[mname]
    entry_held = bool(cls.entry_held(mname))

    def walk(stmts, held: bool) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.With):
                got = held
                for w in stmt.items:
                    if self_attr(w.context_expr) in cls.locks:
                        got = True
                walk(stmt.body, got)
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk(stmt.body, False)   # runs later, maybe elsewhere
                continue
            if isinstance(stmt, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                for tgt in targets:
                    elts = tgt.elts if isinstance(
                        tgt, (ast.Tuple, ast.List)) else [tgt]
                    for t in elts:
                        attr = _target_attr(t)
                        if attr is not None:
                            out.append(_Mutation(
                                attr, stmt.lineno, held, "assigns"))
            elif isinstance(stmt, ast.Delete):
                for tgt in stmt.targets:
                    attr = _target_attr(tgt)
                    if attr is not None:
                        out.append(_Mutation(
                            attr, stmt.lineno, held, "deletes from"))
            for node in ast.walk(stmt):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.With)):
                    continue            # handled structurally above
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr in MUTATORS:
                    attr = self_attr(node.func.value)
                    if attr is not None and \
                            attr not in cls.event_attrs:
                        out.append(_Mutation(
                            attr, node.lineno, held,
                            f"calls .{node.func.attr}() on"))
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub:
                    walk(sub, held)
            for handler in getattr(stmt, "handlers", []) or []:
                walk(handler.body, held)

    walk(fn.body, entry_held)
    # ast.walk above re-visits nested compound statements' calls; the
    # held flag there may differ, so dedupe keeping the *guarded*
    # variant when both were seen for one (attr, line).
    best: dict[tuple[str, int], _Mutation] = {}
    for m in out:
        key = (m.attr, m.line)
        if key not in best or (m.guarded and not best[key].guarded):
            best[key] = m
    return sorted(best.values(), key=lambda m: (m.line, m.attr))


def _reachable(cls: ClassModel, entries: tuple[str, ...]) -> set[str]:
    """Transitive closure over executed-now ``self.m()`` calls."""
    seen: set[str] = set()
    work = [m for m in entries if m in cls.methods]
    while work:
        m = work.pop()
        if m in seen:
            continue
        seen.add(m)
        fn = cls.methods[m]
        stack: list[ast.AST] = list(fn.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.Lambda, ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                continue                  # runs later / other thread
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute):
                callee = self_attr(node.func)
                if callee is not None and callee in cls.methods and \
                        callee not in seen:
                    work.append(callee)
            stack.extend(ast.iter_child_nodes(node))
    return seen


@register
class ThreadOwnershipChecker(Checker):
    code = "HVD009"
    summary = ("thread ownership: attribute mutated from >=2 declared "
               "thread roles without a guarding lock, or _THREAD_ROLES "
               "declaration missing/stale")

    #: Files whose thread-spawning classes MUST declare _THREAD_ROLES.
    STRICT_FILES = ("horovod_tpu/router.py",)

    def check(self, project: Project) -> Iterator[Finding]:
        strict = (project.hvd009_strict_files
                  if getattr(project, "hvd009_strict_files", None)
                  is not None else self.STRICT_FILES)
        pm = ProjectModel(project)
        for mod in pm.modules:
            for cls in mod.classes:
                yield from self._check_class(
                    mod.rel, cls, strict_file=mod.rel in strict)

    def _check_class(self, rel: str, cls: ClassModel, *,
                     strict_file: bool) -> Iterator[Finding]:
        if cls.thread_roles is None:
            if strict_file and cls.thread_targets:
                yield Finding(
                    self.code, rel, cls.node.lineno,
                    f"class `{cls.name}` spawns "
                    f"threading.Thread(target=self.<m>) but declares "
                    "no _THREAD_ROLES — declare which thread role "
                    "runs which entry points (see docs/lint.md)",
                    symbol=f"{cls.name}:undeclared-roles")
            return
        if not cls.thread_roles:
            yield Finding(
                self.code, rel, cls.thread_roles_line,
                f"`{cls.name}._THREAD_ROLES` is not a pure-literal "
                "dict of role -> [entry methods]",
                symbol=f"{cls.name}:malformed-roles")
            return

        # Declaration honesty.
        for role, entries in sorted(cls.thread_roles.items()):
            for m in entries:
                if m not in cls.methods:
                    yield Finding(
                        self.code, rel, cls.thread_roles_line,
                        f"`{cls.name}._THREAD_ROLES[{role!r}]` names "
                        f"`{m}` which is not a method of this class — "
                        "stale declaration",
                        symbol=f"{cls.name}.{m}:unknown-role-entry")
        assigned = {m for entries in cls.thread_roles.values()
                    for m in entries}
        for m in sorted(cls.thread_targets):
            if m not in assigned:
                yield Finding(
                    self.code, rel, cls.thread_roles_line,
                    f"`{cls.name}` spawns Thread(target=self.{m}) but "
                    f"`{m}` appears in no _THREAD_ROLES entry — every "
                    "spawned thread needs a role",
                    symbol=f"{cls.name}.{m}:unassigned-target")

        # Role-reachability x mutations.
        reach = {role: _reachable(cls, entries)
                 for role, entries in cls.thread_roles.items()}
        mutations: dict[str, list[tuple[str, _Mutation]]] = {}
        for mname in cls.methods:
            if mname in ("__init__", "__new__"):
                continue
            for mut in _collect_mutations(cls, mname):
                if mut.attr in cls.guarded or mut.attr in cls.locks:
                    continue             # HVD002's jurisdiction
                mutations.setdefault(mut.attr, []).append((mname, mut))

        for attr, sites in sorted(mutations.items()):
            roles_mutating = sorted(
                role for role, methods in reach.items()
                if any(mname in methods for mname, _ in sites))
            unguarded = [(mname, mut) for mname, mut in sites
                         if not mut.guarded
                         and any(mname in reach[r]
                                 for r in roles_mutating)]
            if len(roles_mutating) >= 2 and unguarded:
                mname, first = min(unguarded,
                                   key=lambda s: (s[1].line, s[0]))
                where = ", ".join(
                    f"{m}:{mut.line}" for m, mut in sites
                    if any(m in reach[r] for r in roles_mutating))
                yield Finding(
                    self.code, rel, first.line,
                    f"`self.{attr}` is mutated from thread roles "
                    f"{{{', '.join(roles_mutating)}}} (sites: {where}) "
                    f"and `{cls.name}.{mname}` {first.what} it with no "
                    "lock held — guard it (and declare it in "
                    "_GUARDED_BY_LOCK) or confine it to one role",
                    symbol=f"{cls.name}.{attr}:multi-role")
