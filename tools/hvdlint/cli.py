"""hvdlint command line.

Exit codes: 0 clean (no active findings, no stale baseline entries),
1 findings/stale entries, 2 usage error.  ``--json`` prints the schema
documented in docs/lint.md; text mode prints ``path:line: CODE message``
per active finding plus a one-line summary.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import subprocess
import sys


def _ensure_importable() -> None:
    # When invoked as a console script from an arbitrary cwd, the repo
    # root may not be on sys.path; the package imports below need it.
    here = pathlib.Path(__file__).resolve()
    root = here.parents[2]
    if str(root) not in sys.path:
        sys.path.insert(0, str(root))


_ensure_importable()

from tools.hvdlint import core  # noqa: E402


def _git_changed(root: pathlib.Path) -> list[str] | None:
    """Repo-relative paths `git diff --name-only` reports (working tree
    vs HEAD, plus staged); None when git/the checkout is unavailable."""
    try:
        out = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            cwd=root, capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0:
        return None
    return [ln.strip() for ln in out.stdout.splitlines() if ln.strip()]


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="hvdlint",
        description="AST-based invariant linter for the horovod_tpu "
                    "serving stack (see docs/lint.md).")
    parser.add_argument(
        "paths", nargs="*",
        help="repo-relative path prefixes to report on (default: all)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit the machine-readable result object")
    parser.add_argument(
        "--baseline", default="auto", metavar="FILE",
        help="baseline file (default: tools/hvdlint/baseline.json when "
             "present; pass 'none' to disable)")
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="write the current active findings to the baseline file "
             "(justifications start as TODO and must be hand-edited)")
    parser.add_argument("--list-codes", action="store_true",
                        help="print the finding codes and exit")
    parser.add_argument("--root", default=None,
                        help="repo root (default: auto-detected)")
    parser.add_argument(
        "--changed", action="store_true",
        help="report only findings in files `git diff --name-only` "
             "lists (fast pre-commit loop; falls back to a full run "
             "when git is unavailable)")
    parser.add_argument(
        "--no-cache", action="store_true",
        help="bypass the mtime-keyed result cache (.hvdlint_cache/)")
    parser.add_argument(
        "--write-lock-order", action="store_true",
        help="write the HVD007 lock-acquisition edge list to "
             "tools/hvdlint/lock_order.json and exit")
    args = parser.parse_args(argv)

    if args.list_codes:
        core.all_checkers()  # populate CODES
        for code, summary in sorted(core.CODES.items()):
            print(f"{code}  {summary}")
        return 0

    try:
        root = core.find_repo_root(
            pathlib.Path(args.root).resolve() if args.root else None)
    except RuntimeError as e:
        print(f"hvdlint: {e}", file=sys.stderr)
        return 2

    baseline: str | None
    if args.baseline == "none":
        baseline = None
    elif args.baseline == "auto":
        baseline = "auto"
    else:
        baseline = args.baseline

    if args.write_lock_order:
        from tools.hvdlint.checkers.hvd007_lock_order import (
            build_lock_graph,
            lock_order_payload,
        )
        payload = lock_order_payload(
            build_lock_graph(core.Project(root)))
        out = root / "tools" / "hvdlint" / "lock_order.json"
        out.write_text(json.dumps(payload, indent=2) + "\n")
        print(f"wrote {len(payload['edges'])} edges over "
              f"{len(payload['locks'])} locks to {out}")
        return 0

    if args.write_baseline:
        result = core.run_lint(root, baseline=None)
        bpath = (root / core.BASELINE_DEFAULT if baseline in ("auto", None)
                 else pathlib.Path(baseline))
        core.save_baseline(bpath, result.active)
        print(f"wrote {len(result.active)} entries to {bpath} "
              "(edit each TODO justification before committing)")
        return 0

    paths = list(args.paths)
    if args.changed:
        changed = _git_changed(root)
        if changed is None:
            print("hvdlint: --changed: not a git checkout (or git "
                  "missing); running on everything", file=sys.stderr)
        elif not changed:
            print("hvdlint: --changed: no modified files; 0 findings")
            return 0
        else:
            paths.extend(changed)

    result = core.run_lint(root, baseline=baseline,
                           paths=paths or None,
                           cache=not args.no_cache)

    if args.as_json:
        json.dump(result.to_dict(), sys.stdout, indent=2)
        print()
        return 0 if result.ok else 1

    for f in result.active:
        print(f.render())
    for entry in result.stale_baseline:
        print(f"baseline: stale entry {entry['fingerprint']!r} — no "
              "current finding matches (or justification missing); "
              "remove it or fix its justification")
    n = len(result.active)
    print(f"hvdlint: {result.files_scanned} files, {n} active finding"
          f"{'s' if n != 1 else ''}, {len(result.suppressed)} suppressed, "
          f"{len(result.baselined)} baselined, "
          f"{len(result.stale_baseline)} stale baseline entries")
    return 0 if result.ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
