"""hvdlint — AST-based invariant linter for the horovod_tpu serving
stack (retrace hazards, lock discipline, env knobs, fault-site and
counter-name coverage, alert-rule hygiene, and the concurrency plane:
lock-order deadlocks, blocking-under-lock, thread ownership, and
replay determinism).

Public surface: :func:`run_lint`, :class:`Project`, :class:`Finding`,
:class:`Checker`, :func:`register`, :data:`CODES`.  See docs/lint.md.
"""

from tools.hvdlint.core import (  # noqa: F401
    CODES,
    Checker,
    Finding,
    LintResult,
    Project,
    all_checkers,
    find_repo_root,
    register,
    run_lint,
)
