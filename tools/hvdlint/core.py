"""hvdlint core: findings, suppressions, baselines, and the checker
plugin registry.

The serving stack rests on conventions no runtime test can enforce
globally — one jit signature per program, lock discipline on
thread-shared registries, canonical name/knob tables that dashboards
and launch scripts key on.  ``hvdlint`` turns those conventions into
machine-checked rules: each rule is a :class:`Checker` subclass with a
stable ``HVDxxx`` code, registered via :func:`register` and run over a
:class:`Project` (the parsed source tree plus the canonical tables,
extracted from the package **by AST literal parsing**, never by
importing it — the linter stays stdlib-only and jax-free).

Three escape hatches keep the tool honest instead of ignored:

* inline suppressions — ``# hvdlint: disable=HVD002 -- <justification>``
  on the flagged line (or the line above).  The justification after
  ``--`` is mandatory; a bare ``disable=`` is itself a finding
  (:data:`MALFORMED_SUPPRESSION`).
* a committed baseline (``tools/hvdlint/baseline.json``) of
  grandfathered findings keyed by line-independent fingerprints, each
  carrying a one-line justification.  Stale entries (fingerprints no
  finding matches anymore) fail the run, so the baseline only shrinks.
* per-class declarations (``_GUARDED_BY_LOCK`` etc.) documented in
  ``docs/lint.md`` — conventions the checkers read, not magic.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import pathlib
import re
import tokenize
from typing import Any, Iterable, Iterator

#: Code used for problems with the lint metadata itself: files that do
#: not parse, suppressions missing their mandatory justification.
MALFORMED_SUPPRESSION = "HVD000"

#: code -> one-line summary; filled by :func:`register` (plus HVD000).
CODES: dict[str, str] = {
    MALFORMED_SUPPRESSION:
        "unparsable file or malformed suppression (missing `-- reason`)",
}

_SUPPRESS_RE = re.compile(
    r"#\s*hvdlint:\s*disable=([A-Z0-9,\s]+?)\s*(?:--\s*(\S.*?))?\s*$")


# ---------------------------------------------------------------------------
# Findings.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Finding:
    """One rule violation.  ``symbol`` is the checker-chosen stable key
    (a qualname, attribute, or table-entry name — never a line number),
    so ``fingerprint`` survives unrelated edits that shift lines."""

    code: str
    path: str          # repo-relative, posix separators
    line: int
    message: str
    symbol: str
    status: str = "active"      # active | suppressed | baselined

    @property
    def fingerprint(self) -> str:
        return f"{self.code}:{self.path}:{self.symbol}"

    def to_dict(self) -> dict:
        return {"code": self.code, "path": self.path, "line": self.line,
                "message": self.message, "fingerprint": self.fingerprint,
                "status": self.status}

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


@dataclasses.dataclass
class Suppression:
    path: str
    line: int                     # line the comment sits on
    codes: tuple[str, ...]
    justification: str | None
    used: bool = False


# ---------------------------------------------------------------------------
# Source model.
# ---------------------------------------------------------------------------


class SourceFile:
    """One parsed source file: text, AST (lazily; ``None`` when the file
    does not parse — the runner reports that as HVD000), and the
    per-line comment map from :mod:`tokenize`."""

    def __init__(self, root: pathlib.Path, path: pathlib.Path):
        self.abs = path
        self.rel = path.relative_to(root).as_posix()
        self.text = path.read_text()
        self._tree: ast.AST | None = None
        self._parse_error: SyntaxError | None = None
        self._parsed = False
        self._comments: dict[int, str] | None = None

    @property
    def tree(self) -> ast.AST | None:
        if not self._parsed:
            self._parsed = True
            try:
                self._tree = ast.parse(self.text, filename=self.rel)
            except SyntaxError as e:
                self._parse_error = e
        return self._tree

    @property
    def parse_error(self) -> SyntaxError | None:
        self.tree  # noqa: B018 — force the parse attempt
        return self._parse_error

    @property
    def comments(self) -> dict[int, str]:
        if self._comments is None:
            self._comments = {}
            try:
                for tok in tokenize.generate_tokens(
                        io.StringIO(self.text).readline):
                    if tok.type == tokenize.COMMENT:
                        self._comments[tok.start[0]] = tok.string
            except (tokenize.TokenError, IndentationError, SyntaxError):
                pass
        return self._comments

    def suppressions(self) -> list[Suppression]:
        out = []
        for line, text in sorted(self.comments.items()):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            codes = tuple(c.strip() for c in m.group(1).split(",")
                          if c.strip())
            out.append(Suppression(self.rel, line, codes, m.group(2)))
        return out


# ---------------------------------------------------------------------------
# The project: source tree + canonical tables.
# ---------------------------------------------------------------------------


def _extract_literal(path: pathlib.Path, name: str) -> Any:
    """Read a module-level literal assignment (``NAME = <literal>`` or
    ``NAME: T = <literal>``) out of ``path`` WITHOUT importing it.
    Returns None when the file or assignment is missing or the value is
    not a pure literal."""
    if not path.exists():
        return None
    try:
        tree = ast.parse(path.read_text())
    except SyntaxError:
        return None
    for node in tree.body:
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            target = node.targets[0].id
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name) and node.value is not None:
            target = node.target.id
        if target != name:
            continue
        try:
            return ast.literal_eval(node.value)
        except ValueError:
            return None
    return None


def find_repo_root(start: pathlib.Path | None = None) -> pathlib.Path:
    """Walk up from ``start`` (default: this file) to the directory that
    holds the ``horovod_tpu`` package — the lint root."""
    here = (start or pathlib.Path(__file__)).resolve()
    for cand in [here, *here.parents]:
        if (cand / "horovod_tpu" / "__init__.py").exists():
            return cand
    raise RuntimeError("cannot locate the repo root (no horovod_tpu/ "
                       f"package above {here})")


class Project:
    """Everything a checker may look at: the parsed package sources, the
    test files, and the canonical tables.  Table keyword arguments
    override the AST-extracted defaults so fixture tests can build tiny
    synthetic projects (see tests/test_lint.py)."""

    METRICS_FILE = "horovod_tpu/metrics.py"
    KNOBS_FILE = "horovod_tpu/knobs.py"
    ALERTS_FILE = "horovod_tpu/alerts.py"

    def __init__(self, root: str | pathlib.Path, *,
                 package_dirs: tuple[str, ...] = ("horovod_tpu",),
                 test_dir: str = "tests",
                 docs_knobs_file: str = "docs/observability.md",
                 env_knobs: tuple | None = None,
                 fault_sites: tuple | None = None,
                 metric_help: dict | None = None,
                 timeline_counter_series: dict | None = None,
                 lifecycle_event_counters: dict | None = None,
                 alert_rules: tuple | None = None,
                 determinism_surfaces: tuple | None = None,
                 hvd001_targets: tuple[str, ...] | None = None,
                 hvd002_strict_files: tuple[str, ...] | None = None,
                 hvd009_strict_files: tuple[str, ...] | None = None):
        self.root = pathlib.Path(root).resolve()
        self.package_dirs = package_dirs
        self.docs_knobs_file = docs_knobs_file
        self.files: list[SourceFile] = []
        for pkg in package_dirs:
            base = self.root / pkg
            if base.is_file():
                self.files.append(SourceFile(self.root, base))
                continue
            for p in sorted(base.rglob("*.py")):
                if "__pycache__" in p.parts:
                    continue
                self.files.append(SourceFile(self.root, p))
        tdir = self.root / test_dir
        self.test_files: list[pathlib.Path] = (
            sorted(tdir.glob("*.py")) if tdir.is_dir() else [])

        self._env_knobs = env_knobs
        self._fault_sites = fault_sites
        self._metric_help = metric_help
        self._timeline_counter_series = timeline_counter_series
        self._lifecycle_event_counters = lifecycle_event_counters
        self._alert_rules = alert_rules
        self._determinism_surfaces = determinism_surfaces
        self.hvd001_targets = hvd001_targets
        self.hvd002_strict_files = hvd002_strict_files
        self.hvd009_strict_files = hvd009_strict_files

    # -- canonical tables (AST-extracted, never imported) ------------------

    def _table(self, cached: Any, relpath: str, name: str,
               default: Any) -> Any:
        if cached is not None:
            return cached
        val = _extract_literal(self.root / relpath, name)
        return default if val is None else val

    @property
    def env_knobs(self) -> tuple:
        """``horovod_tpu.knobs.ENV_KNOBS``: (name, default, help) rows."""
        return self._table(self._env_knobs, self.KNOBS_FILE,
                           "ENV_KNOBS", ())

    @property
    def fault_sites(self) -> tuple:
        return self._table(self._fault_sites, self.METRICS_FILE,
                           "FAULT_SITES", ())

    @property
    def metric_help(self) -> dict:
        return self._table(self._metric_help, self.METRICS_FILE,
                           "METRIC_HELP", {})

    @property
    def timeline_counter_series(self) -> dict:
        return self._table(self._timeline_counter_series, self.METRICS_FILE,
                           "TIMELINE_COUNTER_SERIES", {})

    @property
    def lifecycle_event_counters(self) -> dict:
        return self._table(self._lifecycle_event_counters, self.METRICS_FILE,
                           "LIFECYCLE_EVENT_COUNTERS", {})

    @property
    def alert_rules(self) -> tuple:
        """``horovod_tpu.alerts.ALERT_RULES``: the canonical alert-rule
        dicts (pure literal, like every other table)."""
        return self._table(self._alert_rules, self.ALERTS_FILE,
                           "ALERT_RULES", ())

    @property
    def determinism_surfaces(self) -> tuple:
        """``horovod_tpu.metrics.DETERMINISM_SURFACES``: the declared
        bit-identity replay surfaces — (surface, path, qualname, note)
        rows HVD010 walks for nondeterminism."""
        return self._table(self._determinism_surfaces, self.METRICS_FILE,
                           "DETERMINISM_SURFACES", ())

    # -- anchors -----------------------------------------------------------

    def line_of(self, relpath: str, needle: str) -> int:
        """First line (1-based) containing ``needle`` in ``relpath`` —
        used to anchor table-level findings at the table entry; 1 when
        the needle or file is absent."""
        path = self.root / relpath
        if not path.exists():
            return 1
        for i, ln in enumerate(path.read_text().splitlines(), 1):
            if needle in ln:
                return i
        return 1


# ---------------------------------------------------------------------------
# Checker registry.
# ---------------------------------------------------------------------------


class Checker:
    """Base class for one lint rule family.  Subclasses set ``code``
    (stable ``HVDxxx`` identifier) and ``summary``, register with
    :func:`register`, and yield :class:`Finding`\\ s from ``check``."""

    code = "HVD999"
    summary = "abstract checker"

    def check(self, project: Project) -> Iterator[Finding]:
        raise NotImplementedError
        yield  # pragma: no cover


_REGISTRY: list[type[Checker]] = []


def register(cls: type[Checker]) -> type[Checker]:
    """Class decorator: add a checker to the suite and its code to
    :data:`CODES`.  Re-registration (module reload) replaces by code."""
    global _REGISTRY
    _REGISTRY = [c for c in _REGISTRY if c.code != cls.code]
    _REGISTRY.append(cls)
    CODES[cls.code] = cls.summary
    return cls


def all_checkers() -> list[type[Checker]]:
    """The registered checkers, importing the built-in plugin package on
    first use (each ``tools/hvdlint/checkers/hvdNNN_*.py`` registers
    itself at import)."""
    from tools.hvdlint import checkers  # noqa: F401 — side-effect import
    return sorted(_REGISTRY, key=lambda c: c.code)


# ---------------------------------------------------------------------------
# Baseline.
# ---------------------------------------------------------------------------

BASELINE_DEFAULT = "tools/hvdlint/baseline.json"


def load_baseline(path: pathlib.Path) -> dict[str, dict]:
    """fingerprint -> entry.  Every entry must carry a non-empty
    ``justification`` — an unjustified entry is reported as stale so it
    cannot silently grandfather a finding."""
    data = json.loads(path.read_text())
    out = {}
    for entry in data.get("findings", []):
        out[entry["fingerprint"]] = entry
    return out


def save_baseline(path: pathlib.Path, findings: Iterable[Finding]) -> None:
    entries = [{"fingerprint": f.fingerprint, "code": f.code,
                "path": f.path,
                "justification": "TODO: one-line justification"}
               for f in sorted(findings,
                               key=lambda f: (f.path, f.line, f.code))]
    path.write_text(json.dumps(
        {"version": 1, "tool": "hvdlint", "findings": entries},
        indent=2) + "\n")


# ---------------------------------------------------------------------------
# The runner.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class LintResult:
    root: str
    findings: list[Finding]               # every finding, any status
    stale_baseline: list[dict]
    unused_suppressions: list[Suppression]
    files_scanned: int

    @property
    def active(self) -> list[Finding]:
        return [f for f in self.findings if f.status == "active"]

    @property
    def suppressed(self) -> list[Finding]:
        return [f for f in self.findings if f.status == "suppressed"]

    @property
    def baselined(self) -> list[Finding]:
        return [f for f in self.findings if f.status == "baselined"]

    @property
    def ok(self) -> bool:
        return not self.active and not self.stale_baseline

    def to_dict(self) -> dict:
        """The ``--json`` schema (documented in docs/lint.md)."""
        all_checkers()  # a cached run skips the registering import
        return {
            "version": 1,
            "root": self.root,
            "codes": dict(sorted(CODES.items())),
            "summary": {
                "files_scanned": self.files_scanned,
                "total": len(self.findings),
                "active": len(self.active),
                "suppressed": len(self.suppressed),
                "baselined": len(self.baselined),
                "stale_baseline": len(self.stale_baseline),
                "ok": self.ok,
            },
            "findings": [f.to_dict() for f in self.findings],
            "stale_baseline": self.stale_baseline,
            "unused_suppressions": [
                {"path": s.path, "line": s.line, "codes": list(s.codes)}
                for s in self.unused_suppressions],
        }


def _dedupe_fingerprints(findings: list[Finding]) -> None:
    """Same-symbol findings (two unguarded mutations of one attribute in
    one method) get ``#2``, ``#3``… suffixes in line order, so every
    fingerprint is unique and baselines stay exact."""
    seen: dict[str, int] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line)):
        n = seen.get(f.fingerprint, 0)
        seen[f.fingerprint] = n + 1
        if n:
            f.symbol = f"{f.symbol}#{n + 1}"


def _filter_paths(result: LintResult,
                  paths: Iterable[str] | None) -> LintResult:
    if not paths:
        return result
    prefixes = tuple(str(p) for p in paths)
    return dataclasses.replace(result, findings=[
        f for f in result.findings if f.path.startswith(prefixes)])


def run_lint(root: str | pathlib.Path | None = None, *,
             project: Project | None = None,
             baseline: str | pathlib.Path | None = "auto",
             checkers: Iterable[type[Checker]] | None = None,
             paths: Iterable[str] | None = None,
             cache: bool = False) -> LintResult:
    """Run the suite and resolve suppressions + baseline.

    ``baseline="auto"`` uses the committed ``tools/hvdlint/baseline.json``
    when present; ``None`` disables baselining.  ``paths`` (repo-relative
    prefixes) restricts which files' findings are reported — table-level
    findings anchor to the table file and follow its filtering.

    ``cache=True`` consults the mtime-keyed result cache under
    ``.hvdlint_cache/`` (see :mod:`tools.hvdlint.cache`) — only for
    plain full runs (default project, full suite, auto baseline), so
    synthetic fixture projects and checker subsets never alias a
    cached repo run.  ``paths`` filtering applies after the cache, to
    the same unfiltered result a cold run would produce.
    """
    cacheable = (cache and project is None and checkers is None
                 and baseline == "auto")
    if project is None:
        project = Project(find_repo_root() if root is None else root)
    if cacheable:
        from tools.hvdlint import cache as cache_mod
        hit = cache_mod.load(project)
        if hit is not None:
            return _filter_paths(hit, paths)
    suite = list(checkers) if checkers is not None else all_checkers()

    findings: list[Finding] = []
    for sf in project.files:
        if sf.parse_error is not None:
            findings.append(Finding(
                MALFORMED_SUPPRESSION, sf.rel,
                sf.parse_error.lineno or 1,
                f"file does not parse: {sf.parse_error.msg}",
                symbol="parse-error"))
    for cls in suite:
        findings.extend(cls().check(project))

    # Suppressions: collected from every scanned file; a missing
    # justification is itself a finding and suppresses nothing.
    suppressions: list[Suppression] = []
    for sf in project.files:
        for sup in sf.suppressions():
            if not sup.justification:
                findings.append(Finding(
                    MALFORMED_SUPPRESSION, sup.path, sup.line,
                    "suppression is missing its mandatory justification "
                    "(write `# hvdlint: disable=CODE -- <why>`)",
                    symbol=f"suppression:{','.join(sup.codes)}"))
            else:
                suppressions.append(sup)

    by_file: dict[str, list[Suppression]] = {}
    for sup in suppressions:
        by_file.setdefault(sup.path, []).append(sup)
    for f in findings:
        if f.code == MALFORMED_SUPPRESSION:
            continue        # the metadata rule cannot suppress itself
        for sup in by_file.get(f.path, ()):
            if sup.line in (f.line, f.line - 1) and f.code in sup.codes:
                f.status = "suppressed"
                sup.used = True
                break

    _dedupe_fingerprints(findings)

    # Baseline.
    stale: list[dict] = []
    if baseline is not None:
        bpath = (project.root / BASELINE_DEFAULT
                 if baseline == "auto" else pathlib.Path(baseline))
        if bpath.exists():
            entries = load_baseline(bpath)
            matched: set[str] = set()
            for f in findings:
                if f.status != "active":
                    continue
                entry = entries.get(f.fingerprint)
                if entry and str(entry.get("justification", "")).strip() \
                        and not str(entry["justification"]).startswith(
                            "TODO"):
                    f.status = "baselined"
                    matched.add(f.fingerprint)
            stale = [e for fp, e in sorted(entries.items())
                     if fp not in matched]

    findings.sort(key=lambda f: (f.path, f.line, f.code, f.symbol))
    result = LintResult(
        root=str(project.root), findings=findings, stale_baseline=stale,
        unused_suppressions=[s for s in suppressions if not s.used],
        files_scanned=len(project.files))
    if cacheable:
        from tools.hvdlint import cache as cache_mod
        cache_mod.store(project, result)
    return _filter_paths(result, paths)
