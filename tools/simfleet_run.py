"""Run a fleet-scale simulation campaign: hundreds of simulated
replicas under chaos through the real router / supervisor / autoscaler
/ alert control plane, on virtual time.

One campaign (the tier-1 acceptance shape — 200 replicas, ~100k
virtual requests, crash storm + partition wave + straggler epidemic +
KV-exhaustion ramp + scripted epoch bumps, all invariant oracles):

    python tools/simfleet_run.py --seed 7

Scale overrides (a laptop-quick smoke, or a bigger soak):

    python tools/simfleet_run.py --replicas 40 --requests 5000

Regression gate (saved report JSONs in, exit 1 when an oracle that
held before broke, or delivery got worse):

    python tools/simfleet_run.py --compare old.json new.json \\
        [--threshold 0.1]

Exit status: 0 when every oracle held (or no regression in compare
mode), 1 otherwise.  ``--json PATH`` saves the report for a later
``--compare``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:    # direct `python tools/simfleet_run.py` runs
    sys.path.insert(0, REPO)


def _print_report(report: dict) -> None:
    oracles = report.get("oracles", {})
    for name, held in sorted(oracles.items()):
        print(f"  {'PASS' if held else 'FAIL'}  {name}")
    for key in ("seed", "n_replicas", "n_requests", "delivered",
                "ok_fraction", "failovers", "replica_deaths",
                "respawns", "epoch", "keyed", "journal_dedups",
                "shadow_evictions", "virtual_s", "wall_s"):
        if key in report:
            print(f"  {key}: {report[key]}")
    alerts = report.get("alerts", {})
    if alerts:
        print(f"  alerts fired: {alerts.get('fired')}"
              f" unresolved: {alerts.get('unresolved')}")
    print(f"simfleet: {'OK' if report.get('ok') else 'FAILED'}")


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(
        description="Fleet-scale simulated chaos campaigns through "
                    "the real serving control plane.")
    ap.add_argument("--seed", type=int, default=None,
                    help="campaign seed (default HVD_TPU_SIM_SEED)")
    ap.add_argument("--replicas", type=int, default=None,
                    help="simulated fleet size "
                         "(default HVD_TPU_SIM_REPLICAS)")
    ap.add_argument("--requests", type=int, default=None,
                    help="offered virtual request count "
                         "(default HVD_TPU_SIM_REQUESTS)")
    ap.add_argument("--no-poll-scaling", action="store_true",
                    help="skip the poll-cost scaling measurement "
                         "(and its oracle)")
    ap.add_argument("--compare", nargs=2, metavar=("OLD", "NEW"),
                    help="diff two saved report JSONs instead of "
                         "running; exit 1 on regression")
    ap.add_argument("--threshold", type=float, default=0.1,
                    help="--compare: max tolerated OK-fraction drop "
                         "(absolute, default 0.1)")
    ap.add_argument("--json", metavar="PATH",
                    help="write the report JSON here")
    args = ap.parse_args(argv)

    if args.compare:
        with open(args.compare[0]) as f:
            old = json.load(f)
        with open(args.compare[1]) as f:
            new = json.load(f)
        from horovod_tpu.chaos import compare_campaigns
        ok, problems = compare_campaigns(old, new,
                                         threshold=args.threshold)
        for p in problems:
            print(f"REGRESSION: {p}")
        print(f"simfleet compare: {'OK' if ok else 'FAILED'}")
        return 0 if ok else 1

    from horovod_tpu.simfleet import run_sim_campaign

    report = run_sim_campaign(
        seed=args.seed, n_replicas=args.replicas,
        n_requests=args.requests,
        poll_scaling=not args.no_poll_scaling)
    _print_report(report)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
        print(f"report written to {args.json}")
    return 0 if report.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
