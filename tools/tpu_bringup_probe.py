"""Staged TPU bring-up probe — find exactly where device init or the first
training step stalls.

Usage (ALWAYS under an external bound: a hung claim is only killable from
outside — see docs/troubleshooting.md "Tunnel claim mechanics"):

    timeout 300 python tools/tpu_bringup_probe.py

Each stage prints a ``[+Ns]`` note; ``faulthandler.dump_traceback_later``
dumps every thread's Python stack and exits if any single run exceeds
``STAGE_TIMEOUT`` seconds (default 120), so a hang names its stage AND its
frame.  Diagnoses observed in the field:

* stuck in ``make_c_api_client`` at the first jax call → the pool has no
  grantable chip (tunnel down or claim held elsewhere).  Nothing in this
  process will unstick it; retry later.
* stuck in ``block_until_ready`` after "compile done" → the tunnel died
  mid-run; the device future will never resolve.
* slow-but-moving compiles with low local CPU → remote compile is doing the
  work; be patient or shrink the model.
"""

import faulthandler
import os
import sys
import time

_STAGE_TIMEOUT = int(os.environ.get("STAGE_TIMEOUT", "120"))
faulthandler.dump_traceback_later(_STAGE_TIMEOUT, exit=True)

t0 = time.monotonic()


def note(msg):
    print(f"[+{time.monotonic() - t0:.1f}s] {msg}", file=sys.stderr, flush=True)
    # Re-arm at every stage boundary so the bound is per-STAGE, as the
    # name promises — a slow-but-healthy bring-up (remote compiles) must
    # not be force-exited just because the stages add up past one window.
    faulthandler.dump_traceback_later(_STAGE_TIMEOUT, exit=True)


import jax
import jax.numpy as jnp

note(f"jax imported; initializing backend (the claim happens HERE)")
note(f"backend={jax.default_backend()} devices={jax.devices()}")

import optax

import horovod_tpu as hvd

note("horovod_tpu imported")
hvd.init()
note(f"hvd.init done, size={hvd.size()}")

import horovod_tpu.models.resnet as resnet_mod

kimg, klab = jax.random.split(jax.random.key(7))
images = jax.random.normal(kimg, (8, 64, 64, 3), jnp.float32)
labels = jax.random.randint(klab, (8,), 0, 1000, jnp.int32)
jax.block_until_ready(images)
note("synthetic data on device")

model = resnet_mod.ResNet50(dtype=jnp.bfloat16)
variables = model.init(jax.random.key(0), images[:1], train=False)
jax.block_until_ready(variables)
note("model.init done")
params, batch_stats = variables["params"], variables["batch_stats"]


def loss_fn(params, batch):
    x, y = batch
    logits, _ = model.apply(
        {"params": params, "batch_stats": batch_stats},
        x, train=True, mutable=["batch_stats"],
    )
    onehot = jax.nn.one_hot(y, logits.shape[-1])
    return optax.softmax_cross_entropy(logits, onehot).mean()


tx = hvd.DistributedOptimizer(optax.sgd(0.01, momentum=0.9))
opt_state = tx.init(params)
jax.block_until_ready(opt_state)
note("opt init done")

step = hvd.make_train_step(loss_fn, tx, donate=True)
lowered = step.lower(params, opt_state, (images, labels))
note("lower done")
compiled = lowered.compile()
note("compile done")
out = compiled(params, opt_state, (images, labels))
jax.block_until_ready(out)
note("first step done")
t1 = time.perf_counter()
for _ in range(5):
    out = compiled(out.params, out.opt_state, (images, labels))
jax.block_until_ready(out)
note(f"5 steps in {time.perf_counter() - t1:.3f}s — bring-up healthy")
