"""Summarize a Horovod-TPU timeline (Chrome-trace JSON) in the terminal.

The timeline models each tensor as a "process" whose pid groups its
events (reference timeline.cc:51-67); chrome://tracing renders it, but a
quick look during a run shouldn't need a browser:

    python tools/timeline_summary.py /tmp/timeline.json [--top 20] [--json]

Multi-rank merge (per-rank traces from a ``{rank}``-templated
``maybe_create`` path): positional order assigns ranks 0, 1, ... —
each event's ``pid`` becomes its rank (the original tensor pid moves to
``tid``), so chrome://tracing shows one process lane per rank; summary
and ``--json`` modes aggregate across the ranks, with tensors prefixed
``r<k>/``.  Per-rank traces use per-process monotonic origins, so the
merge time-aligns them on their first common event (``rank_shifts``)
before stitching:

    python tools/timeline_summary.py --merge r0.json r1.json --out all.json

Prints per-tensor negotiation and execution durations, per-phase totals,
the negotiation tick counts per rank (NEGOTIATE_TICK_r<k> instants —
reference timeline.cc:98-132 parity), aggregated counter (``ph: "C"``)
series — the serving scheduler's SCHED/LIFECYCLE/PREFIX tracks, plus
SPEC (speculative-decode rounds/proposed/accepted, spec engines only):
final values plus the delta and sample count across the trace — and
per-request async spans (the engine's ``REQ`` ``b``/``e`` pairs, one id
per request).  The serving profiler's ``phase/<name>`` spans (one id per
tick, ``HVD_TPU_PROFILE=1``) get their own per-phase table with each
top-level phase's share of the tiled tick time (``draft``/``verify``
appear there on spec engines).  ``--json`` dumps the
whole summary dict as JSON for scripting.
"""

from __future__ import annotations

import argparse
import collections
import json
import sys


def load_events(path: str) -> list[dict]:
    with open(path) as f:
        text = f.read()
    try:
        data = json.loads(text)
    except json.JSONDecodeError:
        # An in-progress trace: the writer emits ",\n"-terminated events
        # and only close() writes the final "]".  Summarizing mid-run is
        # the tool's point, so complete the array and retry.
        data = json.loads(text.rstrip().rstrip(",") + "]")
    # Chrome trace is either a bare event array or {"traceEvents": [...]}.
    return data["traceEvents"] if isinstance(data, dict) else data


def rank_shifts(traces: list[list[dict]]) -> list[float]:
    """Per-rank timestamp shifts (us, add to ``ts``) aligning traces on
    their first common event.

    Each rank's trace uses its own monotonic origin (the writer stamps
    a per-process clock), so raw merges skew lanes by process start
    time.  Wall clocks can't fix that — they step and drift — but
    monotonic *deltas* are trustworthy, so the merge anchors on the
    earliest event *name* every rank recorded (the one whose latest
    first-occurrence across ranks is smallest) and shifts each rank so
    its first occurrence of that anchor lands at the same instant (the
    minimum across ranks).  No common event → zero shifts (nothing to
    anchor on beats a wrong anchor)."""
    firsts: list[dict[str, float]] = []
    for events in traces:
        first: dict[str, float] = {}
        for e in events:
            if e.get("ph") == "M" or "ts" not in e:
                continue
            name = e.get("name", "")
            if name not in first or e["ts"] < first[name]:
                first[name] = e["ts"]
        firsts.append(first)
    common = set.intersection(*(set(f) for f in firsts)) if firsts else set()
    if not common or len(firsts) < 2:
        return [0.0] * len(traces)
    anchor = min(common, key=lambda n: max(f[n] for f in firsts))
    target = min(f[anchor] for f in firsts)
    return [target - f[anchor] for f in firsts]


def _shifted(e: dict, shift: float) -> dict:
    e = dict(e)
    if shift and "ts" in e:
        e["ts"] = e["ts"] + shift
    return e


def merge_chrome(paths: list[str]) -> list[dict]:
    """Stitch per-rank Chrome traces into ONE: rank k's events get
    ``pid=k`` (one process lane per rank in chrome://tracing) and keep
    their original tensor pid as ``tid``; the per-tensor
    ``process_name`` metadata becomes per-rank ``thread_name`` rows and
    each rank lane is labeled ``rank k``.  Lanes are time-aligned on
    the first common event (:func:`rank_shifts`)."""
    traces = [load_events(p) for p in paths]
    shifts = rank_shifts(traces)
    out: list[dict] = []
    for rank, events in enumerate(traces):
        out.append({"name": "process_name", "ph": "M", "pid": rank,
                    "args": {"name": f"rank {rank}"}})
        out.append({"name": "process_sort_index", "ph": "M", "pid": rank,
                    "args": {"sort_index": rank}})
        for e in events:
            orig_pid = e.get("pid", 0)
            if e.get("ph") == "M":
                if e.get("name") == "process_name":
                    out.append({"name": "thread_name", "ph": "M",
                                "pid": rank, "tid": orig_pid,
                                "args": dict(e.get("args", {}))})
                # drop other process-level metadata (sort indices etc.:
                # they would re-order the rank lanes)
                continue
            e = _shifted(e, shifts[rank])
            e["pid"] = rank
            # The tensor identity lives in the original pid (the writer
            # emits a constant tid 0), so tid must be overwritten, not
            # defaulted, to keep one thread row per tensor in the lane.
            e["tid"] = orig_pid
            out.append(e)
    return out


def merge_for_summary(paths: list[str]) -> list[dict]:
    """Concatenate per-rank traces for :func:`summarize`, keeping pids
    unique per (rank, tensor) — ``summarize`` pairs B/E by (pid, name),
    so colliding tensor pids across ranks would cross-pair.  Tensor
    names gain an ``r<k>/`` prefix; counter/instant/span names stay
    shared so those series aggregate fleet-wide.  Timestamps get the
    same first-common-event alignment as :func:`merge_chrome` so
    cross-rank span/counter aggregation compares like instants."""
    traces = [load_events(p) for p in paths]
    shifts = rank_shifts(traces)
    out: list[dict] = []
    for rank, events in enumerate(traces):
        for e in events:
            e = _shifted(e, shifts[rank])
            e["pid"] = rank * 1_000_000 + e.get("pid", 0)
            if (e.get("ph") == "M" and e.get("name") == "process_name"
                    and e.get("args")):
                e["args"] = {**e["args"],
                             "name": f"r{rank}/{e['args'].get('name', '')}"}
            out.append(e)
    return out


def summarize(events: list[dict]) -> dict:
    tensor_names: dict[int, str] = {}
    # (pid, name) -> B timestamp stack; durations per (pid, phase name).
    open_b: dict[tuple, list] = collections.defaultdict(list)
    durs: dict[tuple, float] = collections.defaultdict(float)
    args_by_pid: dict[int, dict] = {}
    ticks = collections.Counter()
    # counter (ph "C") aggregation: activity -> series -> running stats
    counters: dict[str, dict[str, dict]] = {}
    # async (ph "b"/"e") spans: name -> list of closed durations (us)
    span_durs: dict[str, list] = collections.defaultdict(list)
    span_ids: dict[str, set] = collections.defaultdict(set)

    for e in events:
        ph = e.get("ph")
        pid = e.get("pid", 0)
        name = e.get("name", "")
        if ph == "M" and name == "process_name":
            tensor_names[pid] = e.get("args", {}).get("name", str(pid))
        elif ph == "B":
            open_b[(pid, name)].append(e["ts"])
        elif ph == "E":
            stack = open_b.get((pid, name))
            if stack:
                durs[(pid, name)] += e["ts"] - stack.pop()
            if e.get("args"):
                args_by_pid.setdefault(pid, e["args"])
        elif ph == "i":
            # True instant events (per-rank readiness ticks, mark_cycles
            # engine ticks, scheduler lifecycle marks): counted by name.
            if name != "done":              # skip the close() terminator
                ticks[name] += 1
        elif ph == "X":
            if name.startswith("NEGOTIATE_TICK") or name == "CYCLE_START":
                # Back-compat: older traces wrote instants as zero-width
                # complete events; count them, never tabulate as tensors.
                ticks[name] += 1
            else:
                durs[(pid, name)] += e.get("dur", 0.0)
        elif ph == "C":
            series = counters.setdefault(name, {})
            for k, v in (e.get("args") or {}).items():
                s = series.get(k)
                if s is None:
                    series[k] = {"first": v, "last": v, "min": v,
                                 "max": v, "samples": 1}
                else:
                    s["last"] = v
                    s["min"] = min(s["min"], v)
                    s["max"] = max(s["max"], v)
                    s["samples"] += 1
        elif ph == "b":
            open_b[(pid, name, e.get("id"))].append(e["ts"])
            span_ids[name].add(e.get("id"))
        elif ph == "e":
            stack = open_b.get((pid, name, e.get("id")))
            if stack:
                d = e["ts"] - stack.pop()
                durs[(pid, name)] += d
                span_durs[name].append(d)

    unbalanced = sorted(
        k[1] for k, v in open_b.items() for _ in v   # one entry per open B
    )
    per_tensor: dict[str, dict] = {}
    phase_totals: collections.Counter = collections.Counter()
    for (pid, phase), us in durs.items():
        t = per_tensor.setdefault(
            tensor_names.get(pid, str(pid)), {"phases": {}, "args": {}})
        t["phases"][phase] = t["phases"].get(phase, 0.0) + us
        phase_totals[phase] += us
    for pid, a in args_by_pid.items():
        if tensor_names.get(pid) in per_tensor:
            per_tensor[tensor_names[pid]]["args"] = a
    # finalize counter series: delta over the trace + mean step delta
    for series in counters.values():
        for s in series.values():
            s["delta"] = s["last"] - s["first"]
            steps = max(s["samples"] - 1, 1)
            s["per_step"] = s["delta"] / steps
    spans = {
        name: {
            "count": len(ds),
            "open": len(span_ids[name]) - len(ds),
            "total_us": sum(ds),
            "mean_us": sum(ds) / len(ds) if ds else 0.0,
            "max_us": max(ds) if ds else 0.0,
        }
        for name, ds in span_durs.items()
    }
    # TickProfiler spans ("phase/<name>", id = step) get their own
    # section: stripped of the prefix, with each top-level phase's share
    # of the tiled tick time (dotted names are nested sub-phases —
    # contained in their parent, so excluded from the 100 % base).
    profile = {name[len("phase/"):]: spans.pop(name)
               for name in [n for n in spans if n.startswith("phase/")]}
    tiled_us = sum(sp["total_us"] for p, sp in profile.items()
                   if "." not in p)
    for p, sp in profile.items():
        sp["pct"] = (100.0 * sp["total_us"] / tiled_us
                     if tiled_us else 0.0)
    return {
        "tensors": per_tensor,
        "phase_totals": dict(phase_totals),
        "ticks": dict(ticks),
        "counters": counters,
        "spans": spans,
        "profile": profile,
        "unbalanced": unbalanced,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", nargs="?",
                    help="one Chrome-trace JSON (omit with --merge)")
    ap.add_argument("--merge", nargs="+", metavar="RANK_TRACE",
                    help="per-rank traces in rank order; summarized "
                         "together (and stitched into --out)")
    ap.add_argument("--out",
                    help="with --merge: write the merged Chrome trace "
                         "(pid=rank, tid=original tensor pid) here")
    ap.add_argument("--top", type=int, default=20,
                    help="show the N tensors with the largest total time")
    ap.add_argument("--json", action="store_true",
                    help="dump the full summary dict as JSON")
    args = ap.parse_args(argv)

    if bool(args.trace) == bool(args.merge):
        ap.error("give exactly one of: a trace path, or --merge")
    if args.out and not args.merge:
        ap.error("--out only makes sense with --merge")

    if args.merge:
        if args.out:
            with open(args.out, "w") as f:
                json.dump(merge_chrome(args.merge), f)
        s = summarize(merge_for_summary(args.merge))
        s["ranks"] = len(args.merge)
    else:
        s = summarize(load_events(args.trace))
    if args.json:
        print(json.dumps(s, indent=2, sort_keys=True))
        return 0
    if not s["tensors"] and not s["counters"]:
        print("no tensor events found")
        return 1

    print(f"{len(s['tensors'])} tensors; phase totals (ms):")
    for phase, us in sorted(s["phase_totals"].items(),
                            key=lambda kv: -kv[1]):
        print(f"  {phase:32s} {us / 1e3:10.2f}")
    if s["ticks"]:
        print("instants:",
              " ".join(f"{k}={v}" for k, v in sorted(s["ticks"].items())))
    for activity, series in sorted(s["counters"].items()):
        print(f"\ncounter {activity} (final / delta over "
              f"{max(v['samples'] for v in series.values())} samples):")
        for k, v in sorted(series.items()):
            print(f"  {k:24s} last {v['last']:10g}  delta {v['delta']:10g}"
                  f"  per-step {v['per_step']:8.3f}")
    if s["spans"]:
        print("\nasync spans:")
        for name, sp in sorted(s["spans"].items()):
            print(f"  {name:24s} n={sp['count']:5d} open={sp['open']:3d} "
                  f"mean {sp['mean_us'] / 1e3:8.2f}ms "
                  f"max {sp['max_us'] / 1e3:8.2f}ms")
    if s["profile"]:
        print("\nprofiler phases (ms):")
        # Top-level phases by descending total, each followed by its
        # own nested sub-phases (admit.* under admit, device_sync.*
        # under device_sync) — indentation reads as containment, and
        # the dotted rows stay outside the 100 % tiling base.
        prof = s["profile"]
        order = []
        for name in sorted((p for p in prof if "." not in p),
                           key=lambda p: -prof[p]["total_us"]):
            order.append(name)
            order.extend(sorted(
                (p for p in prof if p.startswith(name + ".")),
                key=lambda p: -prof[p]["total_us"]))
        order += [p for p in prof if p not in order]
        for name in order:
            sp = prof[name]
            label = ("  " + name) if "." in name else name
            print(f"  {label:24s} n={sp['count']:5d} "
                  f"total {sp['total_us'] / 1e3:10.2f} "
                  f"mean {sp['mean_us'] / 1e3:8.3f} "
                  f"max {sp['max_us'] / 1e3:8.3f}  {sp['pct']:5.1f}%")

    rows = sorted(
        s["tensors"].items(),
        key=lambda kv: -sum(kv[1]["phases"].values()),
    )[: args.top]
    print(f"\ntop {len(rows)} tensors by total time (ms):")
    for name, info in rows:
        total = sum(info["phases"].values()) / 1e3
        neg = sum(us for p, us in info["phases"].items()
                  if p.startswith("NEGOTIATE")) / 1e3
        extra = ""
        if info["args"]:
            extra = f"  {info['args'].get('dtype', '')}{info['args'].get('shape', '')}"
        print(f"  {name:40s} total {total:9.2f}  negotiate {neg:8.2f}{extra}")
    if s["unbalanced"]:
        print(f"\nWARNING: {len(s['unbalanced'])} unbalanced B/E pairs: "
              f"{sorted(set(s['unbalanced']))[:5]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
