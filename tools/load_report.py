"""Render and diff open-loop saturation-sweep reports in the terminal.

``horovod_tpu.loadgen.measure_saturation`` (and the ``serve_load_*``
bench arm) emits one JSON report per sweep: the offered-RPS ladder,
per-rung client-observed percentiles, SLO goodput, the goodput knee,
and the per-phase end-to-end latency attribution.  This tool renders
it:

    python tools/load_report.py sweep.json            # saturation table
    python tools/load_report.py sweep.json --json     # normalized dump

Regression gate (the open-loop complement to ``profile_report.py``'s
per-phase tick diff):

    python tools/load_report.py --compare old.json new.json \\
        [--threshold 10] [--floor-ms 0.5]

exits 1 when the goodput knee dropped more than ``--threshold``
percent, when any matching rung's p99 TTFT grew more than
``--threshold`` percent AND more than ``--floor-ms`` absolute, or when
knee attribution coverage fell below 0.95 from a passing baseline.
"""

from __future__ import annotations

import argparse
import json
import sys

#: Attribution phases in causal order (mirrors
#: horovod_tpu.loadgen.ATTR_PHASES, re-declared so the tool stays
#: importable without the package).
ATTR_PHASES = ("ingress_s", "route_s", "replica_queue_s",
               "queue_wait_s", "prefill_s", "decode_s", "finish_s",
               "egress_s")

#: Knee attribution coverage below this is a gate failure when the
#: baseline met it — the acceptance bar for "the report can say where
#: the p99 millisecond lives".
COVERAGE_BAR = 0.95


def load_report(source: str) -> dict:
    """A saved sweep report JSON: a ``measure_saturation`` return
    value, or a bench extras dump carrying one under ``serve_load``."""
    with open(source) as f:
        data = json.load(f)
    if "rungs" in data:
        return data
    if "serve_load" in data and "rungs" in data["serve_load"]:
        return data["serve_load"]
    raise SystemExit(f"{source}: not a saturation-sweep report "
                     f"(no 'rungs' key)")


def render(report: dict) -> str:
    """The saturation curve as a rung table plus the knee attribution."""
    rungs = report.get("rungs", [])
    knee_i = report.get("knee_index", 0)
    lines = [
        f"saturation sweep: {report.get('serve_load_requests', 0)} "
        f"requests over {len(rungs)} rungs "
        f"(process={report.get('serve_load_process', '?')}, "
        f"seed={report.get('serve_load_seed', '?')}, "
        f"{report.get('serve_load_duration_s', 0)}s/rung, "
        f"{report.get('serve_load_replicas', '?')} replicas)",
        f"{'offered':>8s} {'n':>5s} {'ok':>5s} {'shed':>5s} "
        f"{'t/o':>5s} {'p50 ttft':>9s} {'p99 ttft':>9s} "
        f"{'p99 tpot':>9s} {'p99 e2e':>9s} {'goodput':>8s}",
    ]
    for i, r in enumerate(rungs):
        mark = "  << knee" if i == knee_i else ""
        lines.append(
            f"{r['offered_rps']:7.1f}r {r['n']:5d} {r['ok_rate']:5.2f} "
            f"{r['shed_rate']:5.2f} {r['timeout_rate']:5.2f} "
            f"{r['p50_ttft_s'] * 1e3:7.1f}ms {r['p99_ttft_s'] * 1e3:7.1f}ms "
            f"{r['p99_tpot_s'] * 1e3:7.1f}ms {r['p99_e2e_s'] * 1e3:7.1f}ms "
            f"{r['goodput_rps']:6.1f}/s{mark}")
    mono = "monotone" if report.get("serve_load_p99_ttft_monotone") \
        else "NOT monotone"
    lines.append(f"p99 TTFT across rungs: {mono}; knee at "
                 f"{report.get('serve_load_knee_rps', 0):.1f} offered rps "
                 f"-> {report.get('serve_load_knee_goodput_rps', 0):.1f} "
                 f"good rps")
    if rungs:
        attr = rungs[knee_i].get("attribution", {})
        phases = attr.get("phases", {})
        mean_e2e = attr.get("mean_e2e_s", 0.0)
        lines.append(f"knee attribution over {attr.get('n', 0)} OK "
                     f"requests (mean e2e {mean_e2e * 1e3:.2f} ms, "
                     f"coverage {attr.get('coverage', 0.0) * 100:.1f}%):")
        for p in ATTR_PHASES:
            v = phases.get(p, 0.0)
            share = (v / mean_e2e * 100.0) if mean_e2e else 0.0
            lines.append(f"  {p:18s} {v * 1e3:9.3f} ms {share:6.1f}%")
        exemplars = (report.get("knee_exemplar_trace_ids")
                     or rungs[knee_i].get("exemplar_trace_ids") or [])
        if exemplars:
            lines.append("knee exemplar traces (slowest sampled "
                         "requests; feed to tools/trace_report.py):")
            for tid in exemplars:
                lines.append(f"  {tid}")
    return "\n".join(lines)


def compare_reports(old: dict, new: dict, threshold_pct: float = 10.0,
                    floor_ms: float = 0.5) -> list[dict]:
    """Sweep-level diff rows.  REGRESSED when: the knee goodput-RPS
    dropped more than ``threshold_pct``; a matching offered-RPS rung's
    p99 TTFT grew more than ``threshold_pct`` percent AND more than
    ``floor_ms`` milliseconds (both, so jitter on fast rungs can't
    gate); or knee attribution coverage fell below ``COVERAGE_BAR``
    from a baseline that met it."""
    rows = []
    o_knee = old.get("serve_load_knee_goodput_rps", 0.0)
    n_knee = new.get("serve_load_knee_goodput_rps", 0.0)
    drop_pct = ((o_knee - n_knee) / o_knee * 100.0) if o_knee else 0.0
    rows.append({
        "metric": "knee_goodput_rps", "old": o_knee, "new": n_knee,
        "delta_pct": -drop_pct,
        "regressed": drop_pct > threshold_pct,
    })
    o_rungs = {r["offered_rps"]: r for r in old.get("rungs", [])}
    for r in new.get("rungs", []):
        o = o_rungs.get(r["offered_rps"])
        if o is None:
            continue
        o_ms = o["p99_ttft_s"] * 1e3
        n_ms = r["p99_ttft_s"] * 1e3
        delta = n_ms - o_ms
        pct = (delta / o_ms * 100.0) if o_ms else \
            (float("inf") if n_ms else 0.0)
        rows.append({
            "metric": f"p99_ttft_ms@{r['offered_rps']:g}rps",
            "old": o_ms, "new": n_ms, "delta_pct": pct,
            "regressed": pct > threshold_pct and delta > floor_ms,
        })
    o_cov = old.get("serve_load_attr_coverage_knee", 0.0)
    n_cov = new.get("serve_load_attr_coverage_knee", 0.0)
    rows.append({
        "metric": "knee_attr_coverage", "old": o_cov, "new": n_cov,
        "delta_pct": ((n_cov - o_cov) / o_cov * 100.0) if o_cov else 0.0,
        "regressed": o_cov >= COVERAGE_BAR and n_cov < COVERAGE_BAR,
    })
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("source", nargs="?",
                    help="saved saturation-sweep report JSON")
    ap.add_argument("--compare", nargs=2, metavar=("OLD", "NEW"),
                    help="diff two sweep reports; exit 1 on regression")
    ap.add_argument("--threshold", type=float, default=10.0,
                    help="regression threshold in percent (default 10)")
    ap.add_argument("--floor-ms", type=float, default=0.5,
                    help="absolute p99-TTFT growth floor in ms below "
                         "which a percent regression is ignored")
    ap.add_argument("--json", action="store_true",
                    help="dump the report (or the comparison rows) as JSON")
    args = ap.parse_args(argv)

    if bool(args.source) == bool(args.compare):
        ap.error("give exactly one of: a source, or --compare OLD NEW")

    if args.compare:
        old = load_report(args.compare[0])
        new = load_report(args.compare[1])
        rows = compare_reports(old=old, new=new,
                               threshold_pct=args.threshold,
                               floor_ms=args.floor_ms)
        if args.json:
            print(json.dumps(rows, indent=2))
        else:
            print(f"{'metric':26s} {'old':>10s} {'new':>10s} {'pct':>8s}")
            for r in rows:
                flag = "  << REGRESSED" if r["regressed"] else ""
                print(f"{r['metric']:26s} {r['old']:10.3f} "
                      f"{r['new']:10.3f} {r['delta_pct']:+7.1f}%{flag}")
        return 1 if any(r["regressed"] for r in rows) else 0

    report = load_report(args.source)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
        return 0
    print(render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
