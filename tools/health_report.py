"""Render the fleet health plane: alert timeline, firing state, advice.

Two sources, one normalized timeline — the acceptance contract is that
a live ``/alerts`` scrape and an event-log replay of the same run
render the SAME alert history:

    python tools/health_report.py --url http://localhost:9123
    python tools/health_report.py --events /tmp/hvd-events.jsonl

Live mode scrapes ``/alerts`` (MonitorServer or RouterServer — both
serve it) plus ``/advice`` when an advisor is attached; replay mode
reads the structured event log (rotation-aware: a ``<path>.1``
generation is read first, torn lines are skipped) and keeps the
``alert.*`` transition records the AlertManager emitted plus the
``autoscaler.*`` action records (scale-ups, cordons, forced drains,
retires) so the timeline shows what the fleet did between pages.
Either way the result is a normalized timeline of
``{t, rule, event, state, severity, value}`` rows.

Regression gate (the ``profile_report.py --compare`` contract — two
saved ``--json`` reports in, exit 1 when alerting got worse):

    python tools/health_report.py --compare old.json new.json

Exit status: 0 healthy (or no regression), 1 when alerts are firing at
capture time, fired alerts never resolved, or compare found a
regression.  Stdlib only — importable without the package (and
without jax).
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.request


def read_events(path: str):
    """Replay the structured event log: the rotated ``<path>.1``
    generation first (when present), then the live file; non-JSON
    (torn) lines are skipped — mirrors
    ``horovod_tpu.metrics.EventLog.read`` so the tool stays
    package-independent."""
    for p in (path + ".1", path):
        try:
            f = open(p)
        except OSError:
            continue
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    continue                    # torn tail line


def timeline_from_events(events) -> list[dict]:
    """Normalized alert timeline from replayed event-log records
    (``kind`` = ``alert.fire`` / ``alert.pending`` / ``alert.cancel``
    / ``alert.resolve``), with autoscaler actions (``kind`` =
    ``autoscaler.scale_up`` / ``.cordon`` / ``.drain_force`` /
    ``.retire`` / ``.hold`` / …) interleaved so the rendered timeline
    shows what the fleet DID between the pages.  Autoscaler rows carry
    ``plane="autoscale"`` and are excluded from :func:`timeline_key`
    — the live-scrape ≡ event-replay equivalence contract is about
    alert transitions, which the ``/alerts`` payload alone carries."""
    rows = []
    for e in events:
        kind = e.get("kind", "")
        if kind.startswith("autoscaler."):
            rows.append({"t": e.get("ts"), "rule": "autoscaler",
                         "event": kind[len("autoscaler."):],
                         "state": (e.get("replica") or e.get("advice")
                                   or "-"),
                         "severity": "info",
                         "value": e.get("epoch"),
                         "plane": "autoscale"})
            continue
        if not kind.startswith("alert."):
            continue
        rows.append({"t": e.get("ts"), "rule": e.get("rule"),
                     "event": kind[len("alert."):],
                     "state": e.get("state"),
                     "severity": e.get("severity"),
                     "value": e.get("value")})
    return rows


def timeline_from_alerts(report: dict) -> list[dict]:
    """Normalized alert timeline from a live ``/alerts`` payload
    (``AlertManager.report()["history"]`` transitions)."""
    return [{"t": tr.get("t"), "rule": tr.get("rule"),
             "event": tr.get("event"), "state": tr.get("to"),
             "severity": tr.get("severity"), "value": tr.get("value")}
            for tr in report.get("history", [])]


def timeline_key(timeline: list[dict]) -> list[tuple]:
    """The timestamp-free equivalence key: live scrape and event-log
    replay of one run must agree on this exactly (timestamps differ by
    emit latency; the transition sequence must not).  Autoscaler rows
    are replay-only context, so they stay out of the key."""
    return [(r["rule"], r["event"], r["state"]) for r in timeline
            if r.get("plane", "alert") == "alert"]


def scrape(url: str) -> dict:
    """One live health capture: ``/alerts`` (required) + ``/advice``
    (optional — 404 when no advisor is attached)."""
    base = url.rstrip("/")
    with urllib.request.urlopen(base + "/alerts", timeout=10) as r:
        alerts = json.loads(r.read().decode())
    advice = None
    try:
        with urllib.request.urlopen(base + "/advice", timeout=10) as r:
            advice = json.loads(r.read().decode())
    except (urllib.error.URLError, json.JSONDecodeError):
        pass
    return {"alerts": alerts, "advice": advice}


def build_report(timeline: list[dict], *, source: str,
                 alerts: dict | None = None,
                 advice: dict | None = None) -> dict:
    """The saved/printed report shape (both sources funnel here)."""
    fired = sorted({r["rule"] for r in timeline
                    if r["event"] == "fire"})
    resolved = sorted({r["rule"] for r in timeline
                       if r["event"] == "resolve"})
    # End-state per rule from the timeline itself, so replay mode
    # (no /alerts payload) still knows what is firing at capture time.
    last_state: dict[str, str] = {}
    for r in timeline:
        last_state[r["rule"]] = r["state"]
    firing = (alerts.get("firing") if alerts is not None
              else sorted(n for n, s in last_state.items()
                          if s == "firing"))
    unresolved = sorted(set(fired) - set(resolved))
    return {
        "source": source,
        "timeline": timeline,
        "fired": fired,
        "resolved": resolved,
        "unresolved": unresolved,
        "firing": firing,
        "advice": advice,
        "ok": not firing and not unresolved,
    }


def render(report: dict) -> str:
    lines = [f"health report ({report['source']}): "
             f"{len(report['timeline'])} alert transitions, "
             f"{len(report['fired'])} rules fired, "
             f"{len(report['resolved'])} resolved"]
    if report["firing"]:
        lines.append("FIRING NOW: " + ", ".join(report["firing"]))
    if report["unresolved"]:
        lines.append("fired but never resolved: "
                     + ", ".join(report["unresolved"]))
    if report["timeline"]:
        lines.append(f"{'t':>14s} {'rule':24s} {'event':8s} "
                     f"{'state':8s} {'sev':8s} value")
        for r in report["timeline"]:
            t = f"{r['t']:.3f}" if isinstance(r["t"], (int, float)) \
                else str(r["t"])
            v = (f"{r['value']:.4g}"
                 if isinstance(r["value"], (int, float)) else "-")
            lines.append(f"{t:>14s} {str(r['rule']):24s} "
                         f"{str(r['event']):8s} {str(r['state']):8s} "
                         f"{str(r['severity']):8s} {v}")
    else:
        lines.append("no alert transitions recorded")
    adv = report.get("advice")
    if adv:
        last = adv.get("last") or adv
        lines.append(f"capacity advice: {last.get('action', '?')} "
                     f"n={last.get('n', 0)} — "
                     f"{last.get('reason', '')}")
    return "\n".join(lines)


def compare(old: dict, new: dict) -> tuple[bool, list[str]]:
    """The regression gate: alerting got worse when rules are firing
    at capture time that weren't before, or fired rules stopped
    resolving."""
    problems: list[str] = []
    for rule in new.get("firing", []):
        if rule not in old.get("firing", []):
            problems.append(f"{rule}: firing now, was not before")
    for rule in new.get("unresolved", []):
        if rule not in old.get("unresolved", []):
            problems.append(f"{rule}: fired without resolving "
                            f"(resolved before)")
    return (not problems), problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url",
                    help="live scrape: monitor/router base URL "
                         "(GET /alerts + /advice)")
    ap.add_argument("--events",
                    help="replay: structured event-log JSONL path "
                         "(reads <path>.1 generation too)")
    ap.add_argument("--compare", nargs=2, metavar=("OLD", "NEW"),
                    help="regression-gate two saved --json reports")
    ap.add_argument("--json", action="store_true",
                    help="dump the report dict as JSON")
    ap.add_argument("--out", help="also write the report JSON here")
    args = ap.parse_args(argv)

    n_sources = sum(bool(x) for x in
                    (args.url, args.events, args.compare))
    if n_sources != 1:
        ap.error("give exactly one of: --url, --events, --compare")

    if args.compare:
        with open(args.compare[0]) as f:
            old = json.load(f)
        with open(args.compare[1]) as f:
            new = json.load(f)
        ok, problems = compare(old, new)
        for p in problems:
            print(f"REGRESSION: {p}")
        if ok:
            print("no alerting regressions")
        return 0 if ok else 1

    if args.url:
        cap = scrape(args.url)
        report = build_report(timeline_from_alerts(cap["alerts"]),
                              source=args.url, alerts=cap["alerts"],
                              advice=cap["advice"])
    else:
        report = build_report(
            timeline_from_events(read_events(args.events)),
            source=args.events)

    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(render(report))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
