"""Repo tooling namespace — makes ``python -m tools.hvdlint`` work from
a checkout root.  Scripts in this directory that predate the package
(``tools/timeline_summary.py`` and friends) are still plain scripts and
do not import through this namespace."""
