"""Lint: every observability name in the code is in the canonical tables.

Dashboards and the timeline-summary tool key on four name families —
Chrome-trace counter activities (``timeline.counter("track", "SCHED",
{...})``), fault-injection sites (``faults.check("serve.tick", ...)``),
the event-log lifecycle kinds, and registry metric names
(``metrics.counter("monitor.scrapes")`` / ``hvd.step_*`` /
``serve.goodput`` ...) — all declared once in
:mod:`horovod_tpu.metrics` (``TIMELINE_COUNTER_SERIES``,
``FAULT_SITES``, ``LIFECYCLE_EVENT_COUNTERS``, ``METRIC_HELP``).
This tool greps the
package source for actual call sites and asserts membership BOTH ways:
an unregistered name in code fails (a dashboard would silently miss
it), and a registered name with no call site fails (dead table entries
rot).  Run directly or via the test suite (tests/test_metrics.py):

    python tools/check_counter_names.py
"""

from __future__ import annotations

import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "horovod_tpu"

# timeline.counter("<track>", "<ACTIVITY>", {...}) — the uppercase
# second string argument is what distinguishes a Chrome-trace counter
# emission from MetricsRegistry.counter(name) lookups.
_TIMELINE_COUNTER = re.compile(
    r"\.counter\(\s*[\"']([^\"']+)[\"']\s*,\s*[\"']([A-Z][A-Z_]*)[\"']")
# dict-literal series keys directly following the activity argument
_SERIES_KEY = re.compile(r"[\"']([a-z_]+)[\"']\s*:")
# faults.check("<site>", ...) — sites are dotted lowercase names
_FAULT_SITE = re.compile(r"\.check\(\s*[\"']([a-z0-9_.]+)[\"']")
# registry.counter/gauge/histogram("<name>"...) with a LITERAL name —
# the closing quote must be followed by `,` or `)` so composed names
# ("serve." + key) and f-strings stay out of scope (their families are
# covered by table entries directly).
_REGISTRY_METRIC = re.compile(
    r"\.(counter|gauge|histogram)\(\s*[\"']([a-z0-9_.]+)[\"']\s*[,)]")
# a timeline.counter first argument looks identical up to the comma;
# disambiguate by what FOLLOWS: an uppercase activity string literal.
_ACTIVITY_NEXT = re.compile(r"\s*[\"'][A-Z]")


def scan() -> tuple[dict[str, set], set, set, list[str]]:
    """Walk the package source; returns (activity -> literal series
    keys seen), the fault sites seen, the literal registry metric
    names seen, and any per-site problems."""
    problems: list[str] = []
    activities: dict[str, set] = {}
    sites: set = set()
    metric_names: set = set()
    for path in sorted(PKG.rglob("*.py")):
        text = path.read_text()
        for m in _TIMELINE_COUNTER.finditer(text):
            activity = m.group(2)
            keys = activities.setdefault(activity, set())
            # Only dict *literals* contribute keys (dict(self.counters)
            # style emissions are covered by the table itself).
            window = text[m.end():m.end() + 400]
            depth_end = window.find(")")
            keys.update(_SERIES_KEY.findall(
                window if depth_end < 0 else window[:depth_end + 1]))
        for m in _FAULT_SITE.finditer(text):
            sites.add(m.group(1))
        for m in _REGISTRY_METRIC.finditer(text):
            if _ACTIVITY_NEXT.match(text, m.end()):
                continue                 # a timeline.counter(track, "SCHED"
            metric_names.add(m.group(2))
    return activities, sites, metric_names, problems


def main() -> int:
    if str(REPO) not in sys.path:      # direct `python tools/...` runs
        sys.path.insert(0, str(REPO))
    from horovod_tpu import metrics

    activities, sites, metric_names, problems = scan()

    registered = set(metrics.TIMELINE_COUNTER_SERIES)
    for activity, keys in sorted(activities.items()):
        if activity not in registered:
            problems.append(
                f"timeline counter activity {activity!r} is emitted but "
                f"not registered in metrics.TIMELINE_COUNTER_SERIES")
            continue
        extra = keys - set(metrics.TIMELINE_COUNTER_SERIES[activity])
        if extra:
            problems.append(
                f"timeline counter {activity!r} emits series "
                f"{sorted(extra)} not registered in "
                f"metrics.TIMELINE_COUNTER_SERIES[{activity!r}]")
    for activity in sorted(registered - set(activities)):
        problems.append(
            f"metrics.TIMELINE_COUNTER_SERIES registers {activity!r} "
            f"but no timeline.counter call emits it")

    registered_sites = set(metrics.FAULT_SITES)
    for site in sorted(sites - registered_sites):
        problems.append(
            f"fault site {site!r} is checked but not registered in "
            f"metrics.FAULT_SITES")
    for site in sorted(registered_sites - sites):
        problems.append(
            f"metrics.FAULT_SITES registers {site!r} but no "
            f"faults.check call uses it")

    # Registry metric names (counter/gauge/histogram) vs METRIC_HELP,
    # both directions.  Composed-name families (``"serve." + key`` over
    # the LIFECYCLE series, ``"prefix." + key`` over the PREFIX series)
    # have no literal call site, so their table entries are excused
    # from the dead-entry check.
    help_names = set(metrics.METRIC_HELP)
    dynamic = (
        {"serve." + k for k in metrics.TIMELINE_COUNTER_SERIES["LIFECYCLE"]}
        | {"prefix." + k for k in metrics.TIMELINE_COUNTER_SERIES["PREFIX"]})
    for name in sorted(metric_names - help_names):
        problems.append(
            f"registry metric {name!r} is emitted but has no "
            f"metrics.METRIC_HELP entry (dashboards get no # HELP line)")
    for name in sorted(help_names - metric_names - dynamic):
        problems.append(
            f"metrics.METRIC_HELP describes {name!r} but no "
            f"counter/gauge/histogram call site emits it")

    # Internal consistency: the event-log replay map must cover exactly
    # the LIFECYCLE counter series (both are views of the same dict).
    lifecycle = set(metrics.TIMELINE_COUNTER_SERIES["LIFECYCLE"])
    mapped = set(metrics.LIFECYCLE_EVENT_COUNTERS.values())
    if lifecycle != mapped:
        problems.append(
            f"LIFECYCLE_EVENT_COUNTERS values {sorted(mapped)} != "
            f"LIFECYCLE series {sorted(lifecycle)}")

    if problems:
        for p in problems:
            print(f"check_counter_names: {p}")
        return 1
    print(f"check_counter_names: OK ({len(activities)} counter "
          f"activities, {len(sites)} fault sites, "
          f"{len(metric_names)} registry metrics)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
