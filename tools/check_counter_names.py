"""Lint: every observability name in the code is in the canonical tables.

Legacy entry point, kept for existing invocations and the
`tests/test_metrics.py` driver — the actual checks moved into the
hvdlint framework (`tools/hvdlint/`): counter/metric/lifecycle names
are HVD005, fault-site membership is HVD004.  This shim runs exactly
those two checkers over the repo and keeps the old exit contract
(0 clean, 1 problems, one line per problem on stdout).  Prefer:

    python -m tools.hvdlint

which runs the full suite (see docs/lint.md).
"""

from __future__ import annotations

import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


def main() -> int:
    if str(REPO) not in sys.path:      # direct `python tools/...` runs
        sys.path.insert(0, str(REPO))
    from tools.hvdlint import core
    from tools.hvdlint.checkers.hvd004_fault_sites import FaultSiteChecker
    from tools.hvdlint.checkers.hvd005_names import CounterNameChecker

    result = core.run_lint(
        REPO, checkers=(FaultSiteChecker, CounterNameChecker))
    for f in result.active:
        print(f"check_counter_names: {f.render()}")
    if result.active:
        return 1
    print(f"check_counter_names: OK (via hvdlint HVD004+HVD005, "
          f"{result.files_scanned} files)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
