"""Pipeline-parallel training — GPipe-style stages over the ``pp`` mesh axis.

Beyond reference parity (the reference is data-parallel only, SURVEY §2.3):
a depth-sharded model where each mesh position owns ONE stage, microbatches
stream through one ``lax.ppermute`` hop per tick, and the whole fill +
steady-state + drain schedule is a single compiled ``lax.scan`` — no
per-microbatch Python dispatch.  Backward derives automatically: ppermute
transposes to the reverse hop under ``jax.grad``.

Run on the 8-device CPU mesh (or any TPU slice):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/pipeline_mlp.py --stages 4 --microbatches 8

What to look at:
  * ``stack_stage_params`` — per-stage pytrees stacked on a leading axis
    the ``P('pp')`` in_spec consumes;
  * ``pipeline_loss_fn`` — masks the loss to the last stage and
    replicates the scalar without double-counting gradients;
  * the loss goes DOWN while every parameter lives on exactly one stage.
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu.parallel.pipeline import pipeline_loss_fn, stack_stage_params


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--stages", type=int, default=4)
    p.add_argument("--microbatches", type=int, default=8)
    p.add_argument("--microbatch-size", type=int, default=8)
    p.add_argument("--width", type=int, default=32)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--lr", type=float, default=0.05)
    args = p.parse_args()

    devs = jax.devices()[: args.stages]
    if len(devs) != args.stages:
        raise SystemExit(
            f"--stages {args.stages} needs that many devices; only "
            f"{len(devs)} visible.  On CPU set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={args.stages} (a "
            "smaller mesh would silently train only a subset of stages)."
        )
    mesh = Mesh(np.asarray(devs), ("pp",))
    d = args.width

    # One residual MLP block per stage (identical widths keep activations
    # one shape across stages — the pipeline contract).
    def stage_fn(params, h):
        return h + jnp.tanh(h @ params["w"] + params["b"])

    rng = np.random.default_rng(0)
    stage_params = stack_stage_params([
        {"w": jnp.asarray(rng.normal(0, 0.3, (d, d)), jnp.float32),
         "b": jnp.zeros((d,), jnp.float32)}
        for _ in range(args.stages)
    ])

    def loss_fn(y, target):
        return jnp.mean((y - target) ** 2)

    pipe_loss = pipeline_loss_fn(stage_fn, loss_fn)
    smapped = jax.shard_map(
        pipe_loss, mesh=mesh,
        in_specs=(P("pp"), (P(), P())), out_specs=P(),
        check_vma=False,
    )

    tx = optax.adam(args.lr)
    opt_state = tx.init(stage_params)

    @jax.jit
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(
            lambda sp: smapped(sp, batch)
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # Synthetic regression task: match a fixed random linear map.
    m, mb = args.microbatches, args.microbatch_size
    x = jnp.asarray(rng.normal(0, 1, (m, mb, d)), jnp.float32)
    w_true = jnp.asarray(rng.normal(0, 0.5, (d, d)), jnp.float32)
    target = jnp.tanh(x @ w_true)
    sharding = NamedSharding(mesh, P("pp"))
    stage_params = jax.device_put(stage_params, sharding)
    batch = (jax.device_put(x, NamedSharding(mesh, P())),
             jax.device_put(target, NamedSharding(mesh, P())))

    first = None
    for i in range(args.steps):
        stage_params, opt_state, loss = step(stage_params, opt_state, batch)
        if first is None:
            first = float(loss)
        if i % 10 == 0 or i == args.steps - 1:
            print(f"step {i}: loss {float(loss):.5f}", flush=True)
    print(f"loss {first:.5f} -> {float(loss):.5f} over {args.stages} stages",
          flush=True)
    assert float(loss) < first, "pipeline training did not reduce the loss"


if __name__ == "__main__":
    main()
