"""Llama autoregressive generation — the inference path end to end.

No reference equivalent (its docs stop at "load the checkpoint"); this
demonstrates the KV-cache decode stack (models/llama.py): one prefill,
then a jit-compiled ``lax.scan`` of cached decode steps — no per-token
retracing — with greedy or sampled decoding (temperature / top-k /
nucleus).

Run small:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python examples/llama_generate.py --tiny --max-new-tokens 16
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.models import llama


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--tiny", action="store_true", help="toy widths")
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--prompt-len", type=int, default=8)
    p.add_argument("--max-new-tokens", type=int, default=32)
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy")
    p.add_argument("--top-k", type=int, default=None)
    p.add_argument("--top-p", type=float, default=None)
    p.add_argument("--ckpt", default=None,
                   help="checkpoint dir (default: random init)")
    args = p.parse_args()

    cfg = (llama.llama_tiny if args.tiny else llama.llama3_8b)()
    if args.ckpt:
        import horovod_tpu as hvd
        from horovod_tpu.checkpoint import restore_checkpoint

        hvd.init()
        template = llama.init_params(cfg, jax.random.key(0))
        params = restore_checkpoint(args.ckpt, template)
    else:
        params = llama.init_params(cfg, jax.random.key(0))

    rng = np.random.default_rng(0)
    prompt = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (args.batch, args.prompt_len)),
        jnp.int32,
    )

    gen = jax.jit(
        lambda p, t, k: llama.generate(
            p, t, cfg, max_new_tokens=args.max_new_tokens,
            temperature=args.temperature, top_k=args.top_k,
            top_p=args.top_p, key=k,
        )
    )
    key = jax.random.key(1)
    toks = gen(params, prompt, key)          # compile + first run
    jax.block_until_ready(toks)

    t0 = time.perf_counter()
    toks = gen(params, prompt, jax.random.key(2))
    jax.block_until_ready(toks)
    dt = time.perf_counter() - t0
    total = args.batch * args.max_new_tokens
    print(f"params: {llama.num_params(cfg) / 1e6:.1f}M  "
          f"decode: {total / dt:.1f} tok/s "
          f"({args.temperature=} {args.top_k=} {args.top_p=})")
    print("tokens[0]:", np.asarray(toks)[0].tolist())


if __name__ == "__main__":
    main()
