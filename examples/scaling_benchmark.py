"""Scaling-efficiency benchmark — the reference's headline metric.

The reference's published claim is ~90% scaling efficiency for Inception V3
and ResNet-101 on 512 GPUs (/root/reference/README.md:51-57,
/root/reference/docs/benchmarks.md:1-7): per-chip throughput at n workers
divided by per-chip throughput at 1.  This harness measures the same ratio
over growing sub-meshes of the available devices: for each n in
{1, 2, 4, ..., N} it re-initializes the framework on an n-device world,
times the synthetic training step (DistributedOptimizer = fused-psum
gradient averaging), and prints the efficiency table.

On a TPU pod slice the collectives ride ICI and the ratio is the real
scaling number; under the CPU simulation mesh
(XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu) the
absolute numbers are meaningless but the harness exercises the identical
program path end to end.

Usage:
    python examples/scaling_benchmark.py [--model resnet50|inception|vit|mlp] [--bs 32]
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import optax

import horovod_tpu as hvd


def _build(model_name: str, on_tpu: bool, image_size: int):
    if model_name == "mlp":
        from horovod_tpu.models.mnist import MnistMLP as MLP

        model = MLP()
        x = jnp.ones((1, 28 * 28), jnp.float32)
        classes = 10
    elif model_name == "vit":
        from horovod_tpu.models.vit import ViT_B16

        # Dense attention: 224px/patch16 = 196 tokens, far below the
        # flash kernel's ~2k-token crossover (see bench.py _bench_vit).
        model = ViT_B16(dtype=jnp.bfloat16 if on_tpu else jnp.float32)
        x = jnp.ones((1, image_size, image_size, 3), jnp.float32)
        classes = 1000
    elif model_name == "inception":
        from horovod_tpu.models.inception import InceptionV3

        model = InceptionV3(dtype=jnp.bfloat16 if on_tpu else jnp.float32)
        x = jnp.ones((1, image_size, image_size, 3), jnp.float32)
        classes = 1000
    else:
        from horovod_tpu.models.resnet import ResNet50

        model = ResNet50(dtype=jnp.bfloat16 if on_tpu else jnp.float32)
        x = jnp.ones((1, image_size, image_size, 3), jnp.float32)
        classes = 1000
    variables = model.init(jax.random.key(0), x)
    return model, variables, x.shape[1:], classes


def _throughput(model, variables, in_shape, classes, batch_per_chip,
                iters, batches) -> float:
    """Images/sec/chip of the full distributed step on the current world."""
    n = hvd.size()
    global_bs = batch_per_chip * n
    images = jnp.ones((global_bs, *in_shape), jnp.float32)
    labels = jnp.zeros((global_bs,), jnp.int32)

    params = variables["params"]
    extra = {k: v for k, v in variables.items() if k != "params"}

    def loss_fn(params, batch):
        x, y = batch
        out = model.apply(
            {"params": params, **extra}, x,
            **({"train": True, "mutable": ["batch_stats"]} if "batch_stats" in extra else {}),
        )
        logits = out[0] if isinstance(out, tuple) else out
        return optax.softmax_cross_entropy(
            logits, jax.nn.one_hot(y, classes)
        ).mean()

    tx = hvd.DistributedOptimizer(optax.sgd(0.01, momentum=0.9))
    opt_state = tx.init(params)
    step = hvd.make_train_step(loss_fn, tx, donate=False)
    out = step(params, opt_state, (images, labels))
    jax.block_until_ready(out.loss)
    state = [out.params, out.opt_state]

    rates = []
    for _ in range(iters):
        t0 = time.perf_counter()
        for _ in range(batches):
            r = step(state[0], state[1], (images, labels))
            state[0], state[1] = r.params, r.opt_state
        jax.block_until_ready(r.loss)
        rates.append(global_bs * batches / (time.perf_counter() - t0))
    return max(rates) / n


def _contention_baseline(devices, n, batch_per_chip, iters, batches) -> float:
    """Per-chip throughput of a communication-FREE SPMD workload on the
    same ``n`` devices — the contention curve C(n).

    On the CPU simulation the n virtual devices share physical cores, so
    per-chip throughput falls with n for reasons that have nothing to do
    with collectives; dividing the model curve by C(n) isolates what the
    gradient collectives actually cost (``collective_efficiency`` in the
    output).  On a real pod slice each chip is real hardware, C(n) ≈ C(1),
    and the raw and normalized efficiencies coincide — so the same
    command is the rehearsed recipe for the v5p run."""
    import numpy as np
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.asarray(devices[:n]), ("w",))
    d = 192
    x = jnp.ones((n * batch_per_chip, d, d), jnp.float32)

    def local(chunk):  # shard-local batched matmul chain, zero collectives
        for _ in range(6):
            chunk = jnp.tanh(chunk @ chunk)
        return chunk

    f = jax.jit(shard_map(local, mesh=mesh, in_specs=P("w"),
                          out_specs=P("w")))
    r = f(x)
    jax.block_until_ready(r)
    rates = []
    for _ in range(iters):
        t0 = time.perf_counter()
        for _ in range(batches):
            r = f(r)
        jax.block_until_ready(r)
        rates.append(n * batch_per_chip * batches
                     / (time.perf_counter() - t0))
    return max(rates) / n


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50",
                   choices=["resnet50", "inception", "vit", "mlp"])
    p.add_argument("--bs", type=int, default=None, help="batch per chip")
    p.add_argument("--img", type=int, default=None)
    p.add_argument("--iters", type=int, default=3)
    p.add_argument("--batches", type=int, default=5)
    p.add_argument("--no-contention-baseline", action="store_true",
                   help="skip the communication-free C(n) normalization "
                        "arm (it is what makes CPU-sim numbers "
                        "interpretable; on a real pod it is ~free)")
    args = p.parse_args()

    on_tpu = jax.default_backend() == "tpu"
    bs = args.bs or (32 if on_tpu else 2)
    if args.model == "inception":
        # Inception V3's stride-2 VALID reductions need H,W >= 75.
        img = args.img or (299 if on_tpu else 128)
    else:
        img = args.img or (224 if on_tpu else 32)

    devices = jax.devices()
    sizes = [n for n in (1, 2, 4, 8, 16, 32, 64, 128) if n <= len(devices)]
    model, variables, in_shape, classes = _build(args.model, on_tpu, img)

    results = {}
    contention = {}
    for n in sizes:
        hvd.shutdown()
        hvd.init(devices=devices[:n])
        results[n] = _throughput(
            model, variables, in_shape, classes, bs, args.iters, args.batches
        )
        line = f"n={n:4d}  {results[n]:10.2f} img/s/chip"
        if not args.no_contention_baseline:
            contention[n] = _contention_baseline(
                devices, n, bs, args.iters, args.batches
            )
            line += f"   C(n)={contention[n]:12.1f}"
        print(line, flush=True)

    base = results[sizes[0]]
    table = {}
    for n, r in results.items():
        row = {"img_per_sec_per_chip": round(r, 2),
               "scaling_efficiency": round(r / base, 4)}
        if contention:
            c_rel = contention[n] / contention[sizes[0]]
            row["contention_factor"] = round(c_rel, 4)
            row["collective_efficiency"] = round((r / base) / c_rel, 4)
        table[n] = row
    print(json.dumps({"model": args.model, "batch_per_chip": bs,
                      "scaling": table}))


if __name__ == "__main__":
    main()
