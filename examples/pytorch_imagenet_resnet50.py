"""ImageNet ResNet-50 through ``horovod_tpu.torch`` — the reference's
canonical fault-recovery recipe (reference examples/pytorch_imagenet_resnet50.py),
every Horovod step preserved:

  1. ``hvd.init()``; rank-0-only logging/verbosity (reference :75-78)
  2. scan disk for the LAST epoch checkpoint, then
     ``hvd.broadcast(resume_from_epoch, root_rank=0)`` so every rank agrees
     even though only rank 0 has the files (reference :62-75)
  3. DistributedSampler-style sharding of the dataset (reference :91-97)
  4. LR scaled by world size; warmup from a small LR over the first epochs
     and stepwise decay after (reference :148-165 ``adjust_learning_rate``)
  5. optional fp16 wire compression (reference :125-127)
  6. ``hvd.DistributedOptimizer(named_parameters=...)`` (reference :129-132)
  7. resume: **load on rank 0 only**, then ``broadcast_parameters`` +
     ``broadcast_optimizer_state`` sync every rank from root — fresh
     processes with empty optimizer state included (reference :134-142)
  8. train; validate; rank-0 writes ``checkpoint-{epoch}.pt`` each epoch
     (reference :199-205 ``save_checkpoint``)

Run (one process per device, the reference's mpirun model):

    python -m horovod_tpu.launch --nproc 2 --cpu -- \
        python examples/pytorch_imagenet_resnet50.py --smoke

Kill it mid-run and relaunch with the same ``--checkpoint-dir``: training
resumes from the last saved epoch on every rank.

No torchvision in this image, so the model is a faithful compact
ResNet (BasicBlock v1.5: stride on the 3x3, as torchvision does) with
depth/width knobs; ``--smoke`` shrinks everything for CI.
"""

import argparse
import os

import numpy as np
import torch
import torch.nn.functional as F

import horovod_tpu.torch as hvd
from horovod_tpu.data import shard_indices


# --------------------------------------------------------------------- model


class BasicBlock(torch.nn.Module):
    def __init__(self, cin, cout, stride=1):
        super().__init__()
        self.conv1 = torch.nn.Conv2d(cin, cout, 3, stride, 1, bias=False)
        self.bn1 = torch.nn.BatchNorm2d(cout)
        self.conv2 = torch.nn.Conv2d(cout, cout, 3, 1, 1, bias=False)
        self.bn2 = torch.nn.BatchNorm2d(cout)
        self.down = None
        if stride != 1 or cin != cout:
            self.down = torch.nn.Sequential(
                torch.nn.Conv2d(cin, cout, 1, stride, bias=False),
                torch.nn.BatchNorm2d(cout),
            )

    def forward(self, x):
        r = x if self.down is None else self.down(x)
        x = F.relu(self.bn1(self.conv1(x)))
        return F.relu(self.bn2(self.conv2(x)) + r)


class ResNet(torch.nn.Module):
    """Stage layout mirrors ResNet-50's (3,4,6,3); BasicBlock keeps the
    example light on CPU — swap in a Bottleneck for exact ResNet-50."""

    def __init__(self, num_classes=1000, width=64, stages=(3, 4, 6, 3)):
        super().__init__()
        self.stem = torch.nn.Sequential(
            torch.nn.Conv2d(3, width, 7, 2, 3, bias=False),
            torch.nn.BatchNorm2d(width),
            torch.nn.ReLU(),
            torch.nn.MaxPool2d(3, 2, 1),
        )
        blocks, cin = [], width
        for i, n in enumerate(stages):
            cout = width * (2 ** i)
            for j in range(n):
                blocks.append(BasicBlock(cin, cout, 2 if (i > 0 and j == 0) else 1))
                cin = cout
        self.blocks = torch.nn.Sequential(*blocks)
        self.head = torch.nn.Linear(cin, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        return self.head(x.mean(dim=(2, 3)))


# ------------------------------------------------------------------ training


def checkpoint_path(args, epoch: int) -> str:
    return os.path.join(args.checkpoint_dir, f"checkpoint-{epoch}.pt")


def save_checkpoint(args, model, optimizer, epoch: int) -> None:
    """Rank 0 persists model+optimizer (reference :199-205)."""
    if hvd.rank() != 0:
        return
    os.makedirs(args.checkpoint_dir, exist_ok=True)
    torch.save(
        {"model": model.state_dict(), "optimizer": optimizer.state_dict()},
        checkpoint_path(args, epoch),
    )


def adjust_learning_rate(args, optimizer, epoch: int) -> None:
    """Reference :148-165: warmup from base LR to size*base over
    ``--warmup-epochs``, then stepwise decay at fixed boundaries."""
    if epoch < args.warmup_epochs:
        alpha = (epoch + 1) / max(args.warmup_epochs, 1)
        adj = 1.0 / hvd.size() * (alpha * (hvd.size() - 1) + 1)
    elif epoch < 30:
        adj = 1.0
    elif epoch < 60:
        adj = 1e-1
    elif epoch < 80:
        adj = 1e-2
    else:
        adj = 1e-3
    for group in optimizer.param_groups:
        group["lr"] = args.base_lr * hvd.size() * adj


def metric_average(value: float, name: str) -> float:
    """Reference's Metric class: average a scalar over ranks."""
    return float(hvd.allreduce(torch.tensor([value]), average=True,
                               name=name)[0])


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=90)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--val-batch-size", type=int, default=32)
    p.add_argument("--base-lr", type=float, default=0.0125)
    p.add_argument("--warmup-epochs", type=int, default=5)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--wd", type=float, default=5e-5)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--fp16-allreduce", action="store_true",
                   help="fp16 wire compression (reference --fp16-allreduce)")
    p.add_argument("--checkpoint-dir", default="./checkpoints")
    p.add_argument("--samples", type=int, default=1024,
                   help="synthetic dataset size (no ImageNet in CI)")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--width", type=int, default=64)
    p.add_argument("--smoke", action="store_true",
                   help="tiny everything: CI-sized fault-recovery drill")
    p.add_argument("--crash-after", type=int, default=0, metavar="N",
                   help="fault injection: die abruptly (os._exit) right "
                        "after saving epoch N's checkpoint, simulating a "
                        "preempted worker; relaunching resumes from N")
    args = p.parse_args()
    if args.smoke:
        args.epochs, args.batch_size, args.val_batch_size = 2, 4, 4
        args.samples, args.image_size, args.num_classes = 32, 32, 10
        args.width, args.warmup_epochs = 8, 1

    hvd.init()
    torch.manual_seed(args.seed)
    verbose = hvd.rank() == 0

    # ---- resume point discovery: only rank 0 has checkpoints; broadcast
    # the epoch index so every rank agrees (reference :62-75).
    resume_from_epoch = 0
    for try_epoch in range(args.epochs, 0, -1):
        if os.path.exists(checkpoint_path(args, try_epoch)):
            resume_from_epoch = try_epoch
            break
    resume_from_epoch = int(hvd.broadcast(
        torch.tensor(resume_from_epoch), root_rank=0,
        name="resume_from_epoch",
    ).item())

    # ---- synthetic ImageNet-shaped data, sharded DistributedSampler-style.
    rng = np.random.default_rng(args.seed)
    images = rng.standard_normal(
        (args.samples, 3, args.image_size, args.image_size), np.float32
    )
    labels = rng.integers(0, args.num_classes, args.samples)

    model = ResNet(num_classes=args.num_classes, width=args.width)
    optimizer = torch.optim.SGD(
        model.parameters(), lr=args.base_lr * hvd.size(),
        momentum=args.momentum, weight_decay=args.wd,
    )
    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)

    # ---- restore on rank 0 ONLY, then broadcast (reference :134-142).
    if resume_from_epoch > 0 and hvd.rank() == 0:
        ckpt = torch.load(checkpoint_path(args, resume_from_epoch),
                          weights_only=True)
        model.load_state_dict(ckpt["model"])
        optimizer.load_state_dict(ckpt["optimizer"])
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)

    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters(),
        compression=compression,
    )

    n_train = int(args.samples * 0.75)
    for epoch in range(resume_from_epoch, args.epochs):
        model.train()
        adjust_learning_rate(args, optimizer, epoch)
        idx = shard_indices(n_train, hvd.rank(), hvd.size(), epoch=epoch,
                            drop_last=True)
        losses, accs = [], []
        for s in range(0, len(idx) - args.batch_size + 1, args.batch_size):
            b = idx[s:s + args.batch_size]
            x = torch.from_numpy(images[b])
            y = torch.from_numpy(labels[b].astype(np.int64))
            optimizer.zero_grad()
            out = model(x)
            loss = F.cross_entropy(out, y)
            loss.backward()
            optimizer.step()
            losses.append(float(loss.detach()))
            accs.append(float((out.argmax(1) == y).float().mean()))
        train_loss = metric_average(np.mean(losses), "train_loss")
        train_acc = metric_average(np.mean(accs), "train_accuracy")

        # ---- validation on the held-out shard (reference validate()).
        model.eval()
        vidx = shard_indices(args.samples - n_train, hvd.rank(), hvd.size(),
                             drop_last=True) + n_train
        with torch.no_grad():
            vx = torch.from_numpy(images[vidx])
            vy = torch.from_numpy(labels[vidx].astype(np.int64))
            vout = model(vx)
            val_loss = metric_average(float(F.cross_entropy(vout, vy)),
                                      "val_loss")
            val_acc = metric_average(
                float((vout.argmax(1) == vy).float().mean()), "val_accuracy"
            )
        if verbose:
            print(f"epoch {epoch + 1}: train_loss {train_loss:.4f} "
                  f"train_acc {train_acc:.3f} val_loss {val_loss:.4f} "
                  f"val_acc {val_acc:.3f}", flush=True)
        save_checkpoint(args, model, optimizer, epoch + 1)
        if args.crash_after and epoch + 1 >= args.crash_after:
            # Preemption drill.  The barrier makes the drill deterministic:
            # it can only complete after rank 0 returned from torch.save,
            # so the checkpoint is durable before any worker dies.  Then a
            # NON-zero rank dies abruptly — no shutdown, no cleanup, the
            # way a preempted worker actually goes — and the launcher
            # tears down the rest of the gang.
            hvd.allreduce(torch.zeros(1), name="crash_barrier")
            if hvd.rank() != 0:
                print(f"CRASH-INJECTED after epoch {epoch + 1}", flush=True)
                os._exit(3)

    if verbose:
        print(f"done: trained epochs {resume_from_epoch + 1}..{args.epochs} "
              f"resumed_from {resume_from_epoch}", flush=True)


if __name__ == "__main__":
    main()
