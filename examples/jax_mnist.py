"""MNIST on the compiled SPMD path — the canonical minimal recipe.

Equivalent of reference examples/tensorflow_mnist.py (init → scale LR by
size → wrap optimizer → broadcast state → rank-0-only checkpoints), with
the whole train step as one jitted SPMD program over the chip mesh.

Run (CPU simulation of 8 chips):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/jax_mnist.py --epochs 2
"""

import argparse
import os

import jax
import jax.numpy as jnp
import optax

import horovod_tpu as hvd
from horovod_tpu.data import ShardedLoader, synthetic_mnist
from horovod_tpu.models.mnist import MnistMLP


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--batch-per-chip", type=int, default=32)
    p.add_argument("--base-lr", type=float, default=0.01)
    p.add_argument("--samples", type=int, default=4096)
    p.add_argument("--ckpt-dir", default="/tmp/hvd_tpu_mnist")
    args = p.parse_args()

    hvd.init()
    model = MnistMLP()
    images, labels = synthetic_mnist(args.samples)

    params = model.init(jax.random.key(42), images[:1])["params"]

    def loss_fn(params, batch):
        x, y = batch
        logits = model.apply({"params": params}, x)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y
        ).mean()

    # Scale LR by world size (the reference recipe's first rule).
    tx = hvd.DistributedOptimizer(
        optax.sgd(args.base_lr * hvd.size(), momentum=0.9)
    )
    opt_state = tx.init(params)

    # Broadcast initial state from rank 0 so all ranks agree.
    params = hvd.broadcast_parameters(params, root_rank=0)
    opt_state = hvd.broadcast_optimizer_state(opt_state, root_rank=0)

    step = hvd.make_train_step(loss_fn, tx)
    loader = ShardedLoader((images, labels), args.batch_per_chip, seed=1)

    for epoch in range(args.epochs):
        loader.set_epoch(epoch)
        losses = []
        for batch in loader:
            out = step(params, opt_state, batch)
            params, opt_state, loss = out
            losses.append(loss)
        mean = float(jnp.mean(jnp.stack(losses)))
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {mean:.4f}")
            os.makedirs(args.ckpt_dir, exist_ok=True)
            hvd.save_checkpoint(
                args.ckpt_dir,
                {"params": params, "opt": opt_state},
                step=epoch,
            )


if __name__ == "__main__":
    main()
