"""Llama-3 data-parallel training — the transformer-era flagship config
(BASELINE config 5: "Llama-3 8B DP via DistributedOptimizer on v5p-128").

No reference equivalent (its zoo stops at ResNet); this is the capability
extension the baseline tracks.  Composes:

* stacked-layer scanned transformer with remat (models/llama.py),
* bf16 activations / fp32 master weights,
* DistributedOptimizer gradient psum over the ``hvd`` mesh axis,
* optional tensor-parallel axis via --tp (GSPMD column/row splits from
  ``param_partition_specs``), sequence parallelism via --attn ring/ulysses.

Run small: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/llama_finetune.py --tiny --steps 4
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models import llama


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--tiny", action="store_true", help="toy widths")
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--batch-per-chip", type=int, default=1)
    p.add_argument("--seq-len", type=int, default=0,
                   help="0 = model max_seq_len")
    p.add_argument("--lr", type=float, default=2e-5)
    p.add_argument("--attn", default="dense",
                   choices=["dense", "blockwise", "ring", "ulysses",
                            "ulysses_flash", "flash"])
    p.add_argument("--zero", action="store_true",
                   help="ZeRO sharded optimizer (state at 1/n per chip)")
    p.add_argument("--fsdp", action="store_true",
                   help="fully-sharded params AND optimizer state "
                        "(1/n per chip between steps; docs/api.md)")
    p.add_argument("--fused-loss", action="store_true",
                   help="chunked fused linear+cross-entropy (no [B*L, V] "
                        "logits residency; docs/compression.md)")
    args = p.parse_args()
    if args.zero and args.fsdp:
        p.error("--zero and --fsdp are alternative sharding strategies")

    hvd.init()
    n = hvd.size()
    cfg = (llama.llama_tiny if args.tiny else llama.llama3_8b)(
        attn_impl=args.attn,
        fused_loss_chunk=(
            (64 if args.tiny else 8192) if args.fused_loss else None
        ),
    )
    seq = args.seq_len or min(cfg.max_seq_len, 512 if args.tiny else 4096)

    params = llama.init_params(cfg, jax.random.key(0))
    params = hvd.broadcast_parameters(params, root_rank=0)
    loss_fn = llama.make_loss_fn(cfg)

    adamw = optax.adamw(args.lr, b1=0.9, b2=0.95, weight_decay=0.1)
    if args.fsdp:
        # Params + Adam moments sharded between steps; GSPMD gathers each
        # layer just-in-time and reduce-scatters its gradients.
        step, init_opt = hvd.make_fsdp_train_step(
            loss_fn, optax.chain(optax.clip_by_global_norm(1.0), adamw)
        )
        params = hvd.shard_params(params, hvd.fsdp_partition_specs(params))
        opt_state = init_opt(params)
    elif args.zero:
        # Sharded optimizer: Adam moments at 1/n per chip; clipping uses
        # the true global norm computed from the gradient shards.
        step, init_opt = hvd.make_zero_train_step(
            loss_fn, adamw, clip_global_norm=1.0
        )
        opt_state = init_opt(params)
    else:
        tx = hvd.DistributedOptimizer(
            optax.chain(optax.clip_by_global_norm(1.0), adamw)
        )
        opt_state = tx.init(params)
        step = hvd.make_train_step(loss_fn, tx)

    if hvd.rank() == 0:
        print(f"params: {llama.num_params(cfg) / 1e6:.1f}M  chips: {n}  "
              f"seq: {seq}  attn: {cfg.attn_impl}")

    rng = np.random.default_rng(0)
    for i in range(args.steps):
        tokens = rng.integers(0, cfg.vocab_size,
                              size=(args.batch_per_chip * n, seq + 1))
        batch = (jnp.asarray(tokens[:, :-1], jnp.int32),
                 jnp.asarray(tokens[:, 1:], jnp.int32))
        out = step(params, opt_state, batch)
        params, opt_state = out.params, out.opt_state
        if i % 10 == 0 and hvd.rank() == 0:
            print(f"step {i}: loss {float(out.loss):.4f}")


if __name__ == "__main__":
    main()
