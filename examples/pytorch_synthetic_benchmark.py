"""PyTorch synthetic benchmark through ``horovod_tpu.torch`` — the
reference's in-repo harness shape (reference
examples/pytorch_synthetic_benchmark.py:96-110): random data, wrapped
optimizer, img/sec per worker as mean ± 1.96σ over ``--num-iters`` groups
of ``--num-batches-per-iter`` batches, plus the total.

The reference benches torchvision's resnet50; this image ships no
torchvision, so the default model is a compact self-contained ConvNet
(``--model mlp`` for an even lighter run).  One process per device:

    python -m horovod_tpu.launch --nproc 2 --cpu -- \
        python examples/pytorch_synthetic_benchmark.py --smoke
"""

import argparse
import time

import numpy as np
import torch
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class ConvNet(torch.nn.Module):
    def __init__(self, classes: int = 1000):
        super().__init__()
        self.c1 = torch.nn.Conv2d(3, 32, 3, stride=2, padding=1)
        self.c2 = torch.nn.Conv2d(32, 64, 3, stride=2, padding=1)
        self.c3 = torch.nn.Conv2d(64, 128, 3, stride=2, padding=1)
        self.fc = torch.nn.Linear(128, classes)

    def forward(self, x):
        x = F.relu(self.c1(x))
        x = F.relu(self.c2(x))
        x = F.relu(self.c3(x))
        return self.fc(x.mean(dim=(2, 3)))


class Mlp(torch.nn.Module):
    def __init__(self, classes: int = 1000):
        super().__init__()
        self.fc1 = torch.nn.Linear(3 * 32 * 32, 256)
        self.fc2 = torch.nn.Linear(256, classes)

    def forward(self, x):
        return self.fc2(torch.tanh(self.fc1(x.flatten(1))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="convnet", choices=["convnet", "mlp"])
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--image-size", type=int, default=64)
    p.add_argument("--num-iters", type=int, default=10)
    p.add_argument("--num-batches-per-iter", type=int, default=10)
    p.add_argument("--smoke", action="store_true")
    args = p.parse_args()
    if args.smoke:
        args.image_size = 32
        args.num_iters, args.num_batches_per_iter = 2, 2

    hvd.init()
    torch.manual_seed(0)
    model = (ConvNet if args.model == "convnet" else Mlp)()
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.01 * hvd.size(),
                        momentum=0.9),
        named_parameters=model.named_parameters(),
    )

    data = torch.randn(args.batch_size, 3, args.image_size, args.image_size)
    target = torch.randint(0, 1000, (args.batch_size,))

    def benchmark_step():
        opt.zero_grad()
        loss = F.cross_entropy(model(data), target)
        loss.backward()
        opt.step()

    if hvd.rank() == 0:
        print(f"Model: {args.model}  Batch size: {args.batch_size}  "
              f"Workers: {hvd.size()}")
    benchmark_step()                         # warmup (compile dispatches)

    img_secs = []
    for i in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            benchmark_step()
        rate = (args.batch_size * args.num_batches_per_iter
                / (time.perf_counter() - t0))
        img_secs.append(rate)
        if hvd.rank() == 0:
            print(f"Iter #{i}: {rate:.1f} img/sec per worker")

    mean, conf = np.mean(img_secs), 1.96 * np.std(img_secs)
    if hvd.rank() == 0:
        print(f"Img/sec per worker: {mean:.1f} +-{conf:.1f}")
        print(f"Total img/sec on {hvd.size()} worker(s): "
              f"{mean * hvd.size():.1f} +-{conf * hvd.size():.1f}")


if __name__ == "__main__":
    main()
