"""Skip-gram word2vec with sparse gradient communication.

Equivalent of reference examples/tensorflow_word2vec.py (skip-gram with
NCE-style sampling, distributed via allreduce).  Embedding gradients are
the classic sparse case — each step touches a few rows of a large table —
so this example shows both paths the framework offers:

* dense: embedding grads ride the normal fused allreduce;
* ``--sparse``: the fork's top-k sparse allreduce
  (reference horovod/torch/__init__.py:46-83) moves only the largest
  entries plus indices.

Text is synthesized (hermetic pods, no downloads); pass --corpus for real
token ids.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/jax_word2vec.py --steps 50
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd


def synthetic_corpus(n_tokens=20000, vocab=2000, seed=0):
    """Zipf-ish token stream with local structure (so skip-gram learns)."""
    rng = np.random.default_rng(seed)
    base = rng.zipf(1.3, size=n_tokens).clip(max=vocab - 1)
    # Add pairwise structure: even positions predict the next token.
    base[1::2] = (base[::2][: len(base[1::2])] * 7 + 1) % vocab
    return base.astype(np.int32)


def skipgram_batches(corpus, batch, window, rng):
    centers = rng.integers(window, len(corpus) - window, size=batch)
    offsets = rng.integers(1, window + 1, size=batch) * rng.choice(
        [-1, 1], size=batch
    )
    return corpus[centers], corpus[centers + offsets]


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=500)
    p.add_argument("--batch-per-chip", type=int, default=64)
    p.add_argument("--vocab", type=int, default=2000)
    p.add_argument("--dim", type=int, default=128)
    p.add_argument("--window", type=int, default=2)
    p.add_argument("--negatives", type=int, default=8)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--sparse", action="store_true")
    p.add_argument("--sparse-ratio", type=float, default=0.05)
    args = p.parse_args()

    hvd.init()
    n = hvd.size()
    corpus = synthetic_corpus(vocab=args.vocab)
    rng = np.random.default_rng(hash("w2v") % 2**31)

    key = jax.random.key(0)
    params = {
        "emb_in": jax.random.normal(key, (args.vocab, args.dim)) * 0.05,
        "emb_out": jnp.zeros((args.vocab, args.dim)),
    }
    params = hvd.broadcast_parameters(params, root_rank=0)

    def loss_fn(params, batch):
        center, context, negs = batch
        v = params["emb_in"][center]                      # [B, D]
        pos = params["emb_out"][context]                  # [B, D]
        neg = params["emb_out"][negs]                     # [B, K, D]
        pos_score = jnp.sum(v * pos, -1)
        neg_score = jnp.einsum("bd,bkd->bk", v, neg)
        # Negative-sampling objective (stable log-sigmoid form).
        return -(
            jax.nn.log_sigmoid(pos_score).mean()
            + jax.nn.log_sigmoid(-neg_score).sum(-1).mean()
        )

    opt = hvd.EagerDistributedOptimizer(
        optax.adagrad(args.lr * n),
        is_sparse=args.sparse,
        sparse_ratio=args.sparse_ratio,
    )
    opt_state = opt.init(params)

    for step in range(args.steps):
        c, t = skipgram_batches(
            corpus, args.batch_per_chip * n, args.window, rng
        )
        negs = rng.integers(0, args.vocab,
                            size=(len(c), args.negatives)).astype(np.int32)
        batch = (jnp.asarray(c), jnp.asarray(t), jnp.asarray(negs))
        opt.backward(loss_fn, params, batch)
        params, opt_state = opt.step(params, opt_state)
        if step % 100 == 0 and hvd.rank() == 0:
            print(f"step {step}: loss {float(opt.last_loss()):.4f}")


if __name__ == "__main__":
    main()
