"""Fault-tolerant PyTorch MNIST with ``hvd.elastic.TorchState`` — the
torch-frontend counterpart of examples/jax_elastic.py (Horovod grew this
API in 0.20; the 0.15.1 reference has no elastic at all).

The pattern, verbatim from horovod.elastic's torch docs reshaped for TPU
gangs: declare the model/optimizer/progress in ``TorchState``, wrap the
loop in ``@hvd.elastic.run`` (restores the newest durable commit on every
(re)start), and commit on a cadence — advance-then-commit, so a restore
never replays work the commit already covers.

One process per device under the supervising launcher:

    python -m horovod_tpu.launch --nproc 2 --cpu --restarts 3 -- \\
        python examples/pytorch_elastic.py --epochs 4
"""

import argparse

import numpy as np
import torch
import torch.nn.functional as F

import horovod_tpu.torch as hvd
from horovod_tpu.data import shard_indices, synthetic_mnist


class Net(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = torch.nn.Linear(784, 128)
        self.fc2 = torch.nn.Linear(128, 10)

    def forward(self, x):
        x = torch.tanh(self.fc1(x.reshape(x.shape[0], -1)))
        return self.fc2(x)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--samples", type=int, default=2048)
    p.add_argument("--ckpt-dir", default="/tmp/hvd_tpu_torch_elastic")
    args = p.parse_args()

    hvd.init()
    torch.manual_seed(42)
    model = Net()
    opt = torch.optim.SGD(model.parameters(), lr=args.lr * hvd.size(),
                          momentum=0.5)
    dist_opt = hvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters())

    state = hvd.elastic.TorchState(model=model, optimizer=opt,
                                   ckpt_dir=args.ckpt_dir, epoch=0)

    images, labels = synthetic_mnist(args.samples)
    images = images.reshape(len(images), -1)

    @hvd.elastic.run
    def train(state):
        # run() already restored the newest commit and synced every rank
        # (covering the reference's broadcast_parameters +
        # broadcast_optimizer_state preamble).
        losses = []                 # a resume may cover every epoch
        while state.epoch < args.epochs:
            idx = shard_indices(len(images), hvd.rank(), hvd.size(),
                                epoch=state.epoch, drop_last=True)
            losses = []
            for s in range(0, len(idx) - args.batch_size + 1,
                           args.batch_size):
                b = idx[s:s + args.batch_size]
                x = torch.from_numpy(images[b])
                y = torch.from_numpy(labels[b].astype(np.int64))
                dist_opt.zero_grad()
                loss = F.cross_entropy(state.model(x), y)
                loss.backward()
                dist_opt.step()
                losses.append(float(loss.detach()))
            if hvd.rank() == 0 and losses:
                print(f"epoch {state.epoch}: loss {np.mean(losses):.4f}",
                      flush=True)
            state.epoch += 1
            state.commit()          # epoch boundary is durable
        return float(np.mean(losses)) if losses else None

    train(state)


if __name__ == "__main__":
    main()
