"""ResNet-50 ImageNet training with checkpoint/resume — the flagship CNN
recipe.

Equivalent of reference examples/keras_imagenet_resnet50.py: resume scan on
rank 0 + broadcast of the resume epoch (:66-73), LR warmup then staircase
decay, rank-0 checkpoints per epoch (:157), metric averaging.  Data is
synthetic by default (hermetic pods); point --data-dir at real ImageNet
arrays to train for real.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/keras_imagenet_resnet50.py --epochs 1 --smoke
"""

import argparse

import jax
import jax.numpy as jnp
import optax

import horovod_tpu as hvd
from horovod_tpu.data import ShardedLoader, synthetic_imagenet
from horovod_tpu.models.resnet import ResNet50


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=90)
    p.add_argument("--batch-per-chip", type=int, default=32)
    p.add_argument("--base-lr", type=float, default=0.0125)
    p.add_argument("--warmup-epochs", type=float, default=5.0)
    p.add_argument("--wd", type=float, default=5e-5)
    p.add_argument("--ckpt-dir", default="/tmp/hvd_tpu_resnet50")
    p.add_argument("--smoke", action="store_true",
                   help="tiny images/model for CI runs")
    args = p.parse_args()

    hvd.init()
    size = args.smoke and 32 or 224
    images, labels = synthetic_imagenet(
        n=args.smoke and 256 or 2048, image_size=size
    )
    model = ResNet50(
        dtype=jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    )

    variables = model.init(jax.random.key(0), jnp.asarray(images[:1]),
                           train=False)
    state = {"params": variables["params"],
             "batch_stats": variables["batch_stats"]}

    def loss_fn(state, batch):
        x, y = batch
        logits, _ = model.apply(
            {"params": state["params"], "batch_stats": state["batch_stats"]},
            x, train=True, mutable=["batch_stats"],
        )
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
        l2 = 0.5 * args.wd * optax.global_norm(state["params"]) ** 2
        return ce + l2

    steps_per_epoch = max(len(images) // (args.batch_per_chip * hvd.size()), 1)
    # Compiled-path LR: warmup to lr*size then staircase decay — the optax
    # schedule form of the reference's callback pair (examples :101-113).
    lr = optax.join_schedules(
        [
            hvd.warmup_schedule(
                args.base_lr, warmup_epochs=args.warmup_epochs,
                steps_per_epoch=steps_per_epoch,
            ),
            optax.piecewise_constant_schedule(
                args.base_lr * hvd.size(),
                {30 * steps_per_epoch: 0.1, 60 * steps_per_epoch: 0.1,
                 80 * steps_per_epoch: 0.1},
            ),
        ],
        [int(args.warmup_epochs * steps_per_epoch)],
    )
    tx = hvd.DistributedOptimizer(optax.sgd(lr, momentum=0.9))
    opt_state = tx.init(state)

    # Resume: scan on rank 0, agree on the epoch across hosts, restore,
    # broadcast (reference :66-73, 134-142).
    resume_epoch = 0
    last = hvd.latest_checkpoint(args.ckpt_dir)
    if last is not None:
        ckpt = hvd.restore_checkpoint(last, {"state": state, "opt": opt_state,
                                             "epoch": 0})
        state, opt_state = ckpt["state"], ckpt["opt"]
        resume_epoch = int(ckpt["epoch"]) + 1
        if hvd.rank() == 0:
            print(f"resuming from epoch {resume_epoch}")
    else:
        state = hvd.broadcast_parameters(state, root_rank=0)

    step = hvd.make_train_step(loss_fn, tx)
    loader = ShardedLoader((images, labels), args.batch_per_chip)

    for epoch in range(resume_epoch, args.epochs):
        loader.set_epoch(epoch)
        losses = []
        for batch in loader:
            out = step(state, opt_state, batch)
            state, opt_state = out.params, out.opt_state
            losses.append(out.loss)
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {float(jnp.mean(jnp.stack(losses))):.4f}")
            hvd.save_checkpoint(
                args.ckpt_dir,
                {"state": jax.device_get(state),
                 "opt": jax.device_get(opt_state), "epoch": epoch},
                step=epoch,
            )


if __name__ == "__main__":
    main()
