"""MNIST with the full callback stack: warmup, schedule, metric averaging.

Equivalent of reference examples/keras_mnist_advanced.py:84-96 — LR warmup
to lr·size over 5 epochs, staircase decay windows after, metric averaging,
broadcast at start.  The LR lives in ``opt_state`` via
``optax.inject_hyperparams`` so callbacks can set it between epochs
(the functional analogue of ``K.set_value(model.optimizer.lr, ...)``).

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/keras_mnist_advanced.py --epochs 3
"""

import argparse

import jax
import optax

import horovod_tpu as hvd
from horovod_tpu.data import ShardedLoader, synthetic_mnist
from horovod_tpu.models.mnist import MnistConvNet


def set_lr(state, lr):
    params, opt_state = state
    opt_state.hyperparams["learning_rate"] = lr
    return (params, opt_state)


def scale_momentum(state, factor):
    """Momentum correction on LR change (reference _keras/callbacks.py:
    126-138): rescale trace buffers so accumulated velocity stays
    consistent with the new LR."""
    params, opt_state = state
    inner = opt_state.inner_state
    trace = jax.tree.map(lambda t: t * factor, inner[0].trace)
    inner = (inner[0]._replace(trace=trace),) + tuple(inner[1:])
    return (params, opt_state._replace(inner_state=inner))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=8)
    p.add_argument("--batch-per-chip", type=int, default=32)
    p.add_argument("--base-lr", type=float, default=0.01)
    p.add_argument("--warmup-epochs", type=float, default=3.0)
    args = p.parse_args()

    hvd.init()
    model = MnistConvNet()
    images, labels = synthetic_mnist(4096)
    eval_images, eval_labels = synthetic_mnist(1024, seed=9)
    params = model.init(jax.random.key(0), images[:1])["params"]

    def loss_fn(params, batch):
        x, y = batch
        logits = model.apply({"params": params}, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    @jax.jit
    def eval_metric_fn(params, batch):
        x, y = batch
        logits = model.apply({"params": params}, x)
        return {"accuracy": (logits.argmax(-1) == y).mean()}

    tx = hvd.DistributedOptimizer(
        optax.inject_hyperparams(
            lambda learning_rate: optax.sgd(learning_rate, momentum=0.9)
        )(learning_rate=args.base_lr)
    )

    callbacks = [
        hvd.BroadcastGlobalVariablesCallback(0),
        hvd.MetricAverageCallback(),
        # Warmup: lr -> lr*size over the first epochs (reference :91).
        hvd.LearningRateWarmupCallback(
            args.base_lr, warmup_epochs=args.warmup_epochs,
            set_lr=set_lr, verbose=True,
        ),
        # Staircase decay windows after warmup (reference :92-95).
        hvd.LearningRateScheduleCallback(
            args.base_lr * hvd.size(), multiplier=1.0,
            start_epoch=args.warmup_epochs, end_epoch=5,
            set_lr=set_lr, scale_momentum=scale_momentum,
        ),
        hvd.LearningRateScheduleCallback(
            args.base_lr * hvd.size(), multiplier=1e-1,
            start_epoch=5, end_epoch=7,
            set_lr=set_lr, scale_momentum=scale_momentum,
        ),
        hvd.LearningRateScheduleCallback(
            args.base_lr * hvd.size(), multiplier=1e-2, start_epoch=7,
            set_lr=set_lr, scale_momentum=scale_momentum,
        ),
    ]

    params, opt_state, history = hvd.fit(
        params, tx, loss_fn,
        ShardedLoader((images, labels), args.batch_per_chip),
        epochs=args.epochs,
        callbacks=callbacks,
        eval_loader=ShardedLoader(
            (eval_images, eval_labels), args.batch_per_chip, shuffle=False
        ),
        eval_metric_fn=eval_metric_fn,
        verbose=hvd.rank() == 0,
    )
    if hvd.rank() == 0:
        print("history:", history[-1])


if __name__ == "__main__":
    main()
