"""MNIST on the eager/handle frontend — the define-by-run recipe.

Equivalent of reference examples/pytorch_mnist.py: per-parameter async
allreduce fired during backward (grad hooks), ``step()`` = synchronize +
base optimizer, DistributedSampler-style sharding, broadcast at start.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/jax_mnist_eager.py --epochs 2
"""

import argparse

import jax
import jax.numpy as jnp
import optax

import horovod_tpu as hvd
from horovod_tpu.data import ShardedLoader, synthetic_mnist
from horovod_tpu.models.mnist import MnistMLP


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--batch-per-chip", type=int, default=32)
    p.add_argument("--base-lr", type=float, default=0.01)
    p.add_argument("--samples", type=int, default=4096)
    p.add_argument("--sparse", action="store_true",
                   help="use the fork's top-k sparse allreduce for grads")
    args = p.parse_args()

    hvd.init()
    model = MnistMLP()
    images, labels = synthetic_mnist(args.samples)
    params = model.init(jax.random.key(42), images[:1])["params"]
    params = hvd.broadcast_parameters(params, root_rank=0)

    def loss_fn(params, batch):
        x, y = batch
        logits = model.apply({"params": params}, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    opt = hvd.EagerDistributedOptimizer(
        optax.sgd(args.base_lr * hvd.size(), momentum=0.9),
        is_sparse=args.sparse,
        sparse_ratio=0.05,
    )
    opt_state = opt.init(params)
    loader = ShardedLoader((images, labels), args.batch_per_chip, seed=1)

    for epoch in range(args.epochs):
        loader.set_epoch(epoch)
        for batch in loader:
            opt.backward(loss_fn, params, batch)   # fires async allreduces
            params, opt_state = opt.step(params, opt_state)
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {float(opt.last_loss()):.4f}")


if __name__ == "__main__":
    main()
