"""MNIST through the Keras-3 frontend (``horovod_tpu.keras``).

Equivalent of reference examples/keras_mnist.py:28-85 (init → scale LR by
size → ``hvd.DistributedOptimizer`` → broadcast + metric-average
callbacks → rank-0-only checkpoint), written against keras>=3 on the JAX
backend.  Single-controller worlds shard the batch over the mesh with
``keras.distribution.DataParallel`` (XLA owns the gradient psum); under
the launcher (one process per chip) the optimizer wrapper averages
gradients through the eager engine instead — same script either way.

Run (single controller, CPU simulation of 8 chips):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      KERAS_BACKEND=jax python examples/keras3_mnist.py --epochs 2

Run (reference process model, 2 ranks):
  KERAS_BACKEND=jax python -m horovod_tpu.launch --nproc 2 --cpu -- \
      python examples/keras3_mnist.py --epochs 2
"""

import argparse
import os

os.environ.setdefault("KERAS_BACKEND", "jax")

import jax
import keras
import numpy as np

import horovod_tpu.keras as hvd
from horovod_tpu.data import synthetic_mnist


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--batch-per-chip", type=int, default=32)
    p.add_argument("--base-lr", type=float, default=0.05)
    p.add_argument("--samples", type=int, default=4096)
    p.add_argument("--ckpt-dir", default="/tmp/hvd_tpu_keras3_mnist")
    args = p.parse_args()

    hvd.init()
    single_controller = jax.process_count() == 1
    if single_controller and len(jax.devices()) > 1:
        keras.distribution.set_distribution(
            keras.distribution.DataParallel(devices=jax.devices())
        )

    images, labels = synthetic_mnist(args.samples)
    images = np.asarray(images, np.float32)
    labels = np.asarray(labels, np.int32)
    if not single_controller:
        # Reference data model: each rank trains on its own shard.
        images = images[hvd.rank()::hvd.size()]
        labels = labels[hvd.rank()::hvd.size()]

    keras.utils.set_random_seed(42)
    model = keras.Sequential([
        keras.layers.Input((28 * 28,)),
        keras.layers.Dense(128, activation="relu"),
        keras.layers.Dense(10),
    ])
    # Scale the LR by world size; the warmup callback ramps up to it
    # (reference keras_mnist.py: lr * hvd.size() + warmup).
    opt = hvd.DistributedOptimizer(
        keras.optimizers.SGD(args.base_lr * hvd.size(), momentum=0.9)
    )
    model.compile(
        optimizer=opt,
        loss=keras.losses.SparseCategoricalCrossentropy(from_logits=True),
        metrics=["accuracy"],
    )

    callbacks = [
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd.callbacks.MetricAverageCallback(),
        hvd.callbacks.LearningRateWarmupCallback(
            warmup_epochs=2, verbose=1 if hvd.rank() == 0 else 0
        ),
    ]
    global_bs = args.batch_per_chip * (
        len(jax.devices()) if single_controller else 1
    )
    hist = model.fit(
        images.reshape(len(images), -1), labels,
        batch_size=global_bs, epochs=args.epochs, shuffle=False,
        verbose=2 if hvd.rank() == 0 else 0, callbacks=callbacks,
    )

    if hvd.rank() == 0:
        os.makedirs(args.ckpt_dir, exist_ok=True)
        path = os.path.join(args.ckpt_dir, "model.keras")
        model.save(path)
        print(f"final loss {hist.history['loss'][-1]:.4f}; saved {path}")
        # Resume path: hvd.load_model re-wraps the optimizer with state.
        reloaded = hvd.load_model(path)
        print("reloaded optimizer:", type(reloaded.optimizer).__name__)


if __name__ == "__main__":
    main()
