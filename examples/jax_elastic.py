"""Fault-tolerant MNIST with ``hvd.elastic`` — commit/restore/replay.

The capability the 0.15.1 reference lacks entirely (Horovod grew
``hvd.elastic`` in 0.20).  The pattern:

* declare every piece of resumable state in ``elastic.State``;
* wrap the training loop in ``@hvd.elastic.run`` — on entry it restores
  the newest durable commit, so a relaunched gang resumes automatically;
* ``state.commit()`` on a cadence: everything since the last commit is
  the replay cost after a failure.

Run under the gang launcher so worker death triggers a relaunch
(CPU simulation, kill a worker mid-run to watch it resume):

  python -m horovod_tpu.launch --nproc 2 --cpu --restarts 3 -- \
      python examples/jax_elastic.py --epochs 4
"""

import argparse

import jax
import optax

import horovod_tpu as hvd
from horovod_tpu.data import ShardedLoader, synthetic_mnist
from horovod_tpu.models.mnist import MnistMLP


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--batch-per-chip", type=int, default=32)
    p.add_argument("--base-lr", type=float, default=0.01)
    p.add_argument("--samples", type=int, default=2048)
    p.add_argument("--ckpt-dir", default="/tmp/hvd_tpu_elastic")
    p.add_argument("--commit-every", type=int, default=20,
                   help="batches between durable commits")
    args = p.parse_args()

    hvd.init()
    model = MnistMLP()
    images, labels = synthetic_mnist(args.samples)
    params = model.init(jax.random.key(42), images[:1])["params"]

    def loss_fn(params, batch):
        x, y = batch
        logits = model.apply({"params": params}, x)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y
        ).mean()

    tx = hvd.DistributedOptimizer(
        optax.sgd(args.base_lr * hvd.size(), momentum=0.9)
    )
    train_step = hvd.make_train_step(loss_fn, tx)

    state = hvd.elastic.State(
        ckpt_dir=args.ckpt_dir,
        params=params, opt_state=tx.init(params), epoch=0, batch=0,
    )

    @hvd.elastic.run
    def train(state):
        # Advance-then-commit: every progress counter a commit covers is
        # incremented BEFORE the commit, and a resume skips exactly the
        # committed batches — so a restore never replays work onto params
        # that already include it.  The loader order is deterministic per
        # epoch (seed=epoch), which is what makes the mid-epoch skip
        # sound.
        while state.epoch < args.epochs:
            loader = ShardedLoader(
                (images, labels), args.batch_per_chip, seed=state.epoch,
            )
            out = None              # a resume may skip the whole epoch
            for i, batch in enumerate(loader):
                if i < state.batch:
                    continue        # covered by the restored commit
                out = train_step(state.params, state.opt_state, batch)
                state.params, state.opt_state = out.params, out.opt_state
                state.batch = i + 1
                if state.batch % args.commit_every == 0:
                    state.commit()
            if hvd.rank() == 0 and out is not None:
                print(f"epoch {state.epoch}: loss {float(out.loss):.4f}",
                      flush=True)
            state.epoch += 1
            state.batch = 0
            state.commit()          # epoch boundary is always durable
        return state

    train(state)
    hvd.wait_for_checkpoints()
    # No explicit shutdown: the atexit hook owns teardown (repo example
    # convention — an in-process caller, e.g. the example tests, keeps
    # its session world).


if __name__ == "__main__":
    main()
