"""MNIST with a train/eval estimator-style loop.

Equivalent of reference examples/tensorflow_mnist_estimator.py: hook-driven
training (broadcast hook at session start), periodic evaluation, rank-0
checkpointing, steps (not epochs) as the unit of progress.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/jax_mnist_estimator.py --train-steps 60
"""

import argparse

import jax
import jax.numpy as jnp
import optax

import horovod_tpu as hvd
from horovod_tpu.data import ShardedLoader, synthetic_mnist
from horovod_tpu.models.mnist import MnistConvNet


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--train-steps", type=int, default=200)
    p.add_argument("--eval-every", type=int, default=50)
    p.add_argument("--batch-per-chip", type=int, default=16)
    p.add_argument("--base-lr", type=float, default=0.005)
    p.add_argument("--ckpt-dir", default="/tmp/hvd_tpu_mnist_estimator")
    args = p.parse_args()

    hvd.init()
    model = MnistConvNet()
    images, labels = synthetic_mnist(4096)
    eval_images, eval_labels = synthetic_mnist(512, seed=7)

    params = model.init(jax.random.key(0), images[:1])["params"]

    def loss_fn(params, batch):
        x, y = batch
        logits = model.apply({"params": params}, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    @jax.jit
    def eval_metrics(params, batch):
        x, y = batch
        logits = model.apply({"params": params}, x)
        return {
            "accuracy": (logits.argmax(-1) == y).mean(),
            "loss": optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean(),
        }

    tx = hvd.DistributedOptimizer(optax.adam(args.base_lr * hvd.size()))
    opt_state = tx.init(params)

    # The BroadcastGlobalVariablesHook analogue: sync before step 0
    # (reference tensorflow_mnist_estimator.py bcast_hook).
    params = hvd.broadcast_parameters(params, root_rank=0)

    step_fn = hvd.make_train_step(loss_fn, tx)
    loader = ShardedLoader((images, labels), args.batch_per_chip, seed=3)
    it, epoch = iter(loader), 0

    # Steps are partitioned: each rank advances the global step together,
    # so total wall work is train_steps regardless of world size
    # (the reference divides steps by size, estimator example :172).
    for step in range(args.train_steps // hvd.size() + 1):
        try:
            batch = next(it)
        except StopIteration:
            epoch += 1
            loader.set_epoch(epoch)
            it = iter(loader)
            batch = next(it)
        out = step_fn(params, opt_state, batch)
        params, opt_state = out.params, out.opt_state
        if step % args.eval_every == 0:
            m = eval_metrics(params, (jnp.asarray(eval_images),
                                      jnp.asarray(eval_labels)))
            if hvd.rank() == 0:
                print(
                    f"step {step}: loss {float(out.loss):.4f} "
                    f"eval_acc {float(m['accuracy']):.3f}"
                )
    if hvd.rank() == 0:
        hvd.save_checkpoint(args.ckpt_dir, {"params": params}, step=step)


if __name__ == "__main__":
    main()
