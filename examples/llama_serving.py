"""Serving recipes: continuous batching and speculative decoding.

The reference has no serving story; this example shows the TPU-native
one (docs/inference.md): a fixed-slot ContinuousBatcher absorbing
mixed-length requests, and draft-and-verify speculative decoding whose
greedy output is bit-identical to the target's own.

Run: JAX_PLATFORMS=cpu python examples/llama_serving.py --requests 6
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp

import horovod_tpu as hvd
from horovod_tpu.models import llama
from horovod_tpu.serving import (ContinuousBatcher, Request,
                                 speculative_generate)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--new-tokens", type=int, default=8)
    ap.add_argument("--draft-k", type=int, default=3)
    args = ap.parse_args()

    hvd.init()
    cfg = llama.llama_tiny(dtype=jnp.float32)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))

    # -- continuous batching: more requests than slots, mixed lengths ----
    key = jax.random.PRNGKey(1)
    reqs = []
    for i in range(args.requests):
        key, sub = jax.random.split(key)
        plen = 2 + int(jax.random.randint(sub, (), 0, 5))
        key, sub = jax.random.split(key)
        ids = jax.random.randint(sub, (plen,), 0, cfg.vocab_size)
        reqs.append(Request(prompt=[int(t) for t in ids],
                            max_new_tokens=args.new_tokens))
    srv = ContinuousBatcher(params, cfg, n_slots=args.slots, max_len=32,
                            admit_width=8)
    t0 = time.monotonic()
    results = srv.run(reqs)
    dt = time.monotonic() - t0
    total = sum(len(r) for r in results)
    print(f"batcher: {len(results)} requests through {args.slots} slots, "
          f"{total} tokens in {dt:.2f}s")

    # -- speculative decoding: draft = a smaller model -------------------
    dcfg = llama.llama_tiny(dtype=jnp.float32, dim=32, n_layers=1,
                            n_heads=2, n_kv_heads=1, ffn_dim=64)
    dparams = llama.init_params(dcfg, jax.random.PRNGKey(2))
    prompt = jnp.asarray([[int(t) for t in reqs[0].prompt]], jnp.int32)
    plain = llama.generate(params, prompt, cfg,
                           max_new_tokens=args.new_tokens, max_len=32)
    spec = speculative_generate(params, cfg, dparams, dcfg, prompt,
                                max_new_tokens=args.new_tokens,
                                draft_k=args.draft_k, max_len=40)
    same = bool((jnp.asarray(spec) == plain).all())
    print(f"speculative == plain greedy: {same}")
    assert same
    hvd.shutdown()


if __name__ == "__main__":
    main()
