"""Fault-tolerant Keras-3 MNIST with ``hvd.elastic.KerasState`` — the
keras-frontend counterpart of examples/jax_elastic.py and
examples/pytorch_elastic.py (Horovod grew ``KerasState`` in 0.20; the
0.15.1 reference has no elastic at all).

The pattern: declare the model + progress in ``KerasState``, wrap the
epoch loop in ``@hvd.elastic.run`` (restores the newest durable commit —
weights, optimizer slots, epoch — on every (re)start), and commit at
epoch boundaries — advance-then-commit, so a restore never replays work
the commit already covers.

One process per device under the supervising launcher:

    KERAS_BACKEND=jax python -m horovod_tpu.launch --nproc 2 --cpu \\
        --restarts 3 -- python examples/keras_elastic.py --epochs 4
"""

import argparse
import os

os.environ.setdefault("KERAS_BACKEND", "jax")

import keras
import numpy as np

import horovod_tpu.keras as hvd
from horovod_tpu.data import shard_indices, synthetic_mnist


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--samples", type=int, default=2048)
    p.add_argument("--ckpt-dir", default="/tmp/hvd_tpu_keras_elastic")
    args = p.parse_args()

    hvd.init()
    keras.utils.set_random_seed(42)
    model = keras.Sequential([
        keras.layers.Input((28 * 28,)),
        keras.layers.Dense(128, activation="tanh"),
        keras.layers.Dense(10),
    ])
    model.compile(
        optimizer=hvd.DistributedOptimizer(
            keras.optimizers.SGD(args.lr * hvd.size(), momentum=0.5)
        ),
        loss=keras.losses.SparseCategoricalCrossentropy(from_logits=True),
    )

    state = hvd.elastic.KerasState(model, ckpt_dir=args.ckpt_dir, epoch=0)

    images, labels = synthetic_mnist(args.samples)
    images = np.asarray(images, np.float32).reshape(len(images), -1)
    labels = np.asarray(labels, np.int32)

    @hvd.elastic.run
    def train(state):
        # run() already restored the newest commit and synced every rank
        # (weights, optimizer slots, epoch).
        last = None                 # a resume may cover every epoch
        while state.epoch < args.epochs:
            idx = shard_indices(len(images), hvd.rank(), hvd.size(),
                                epoch=state.epoch, drop_last=True)
            hist = model.fit(images[idx], labels[idx],
                             batch_size=args.batch_size, shuffle=False,
                             epochs=1, verbose=0)
            last = float(hist.history["loss"][-1])
            if hvd.rank() == 0:
                print(f"epoch {state.epoch}: loss {last:.4f}", flush=True)
            state.epoch += 1
            state.commit()          # epoch boundary is durable
        return last

    train(state)


if __name__ == "__main__":
    main()
