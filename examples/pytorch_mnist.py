"""PyTorch MNIST through ``horovod_tpu.torch`` — the reference's headline
torch example (reference examples/pytorch_mnist.py), preserved recipe:

    init → scale LR by size → wrap optimizer → broadcast params+state →
    DistributedSampler-style sharding → rank-0 logging

One process per device (the reference's mpirun model):

    python -m horovod_tpu.launch --nproc 2 --cpu -- \
        python examples/pytorch_mnist.py --epochs 1 --samples 256
"""

import argparse

import numpy as np
import torch
import torch.nn.functional as F

import horovod_tpu.torch as hvd
from horovod_tpu.data import shard_indices, synthetic_mnist


class Net(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = torch.nn.Linear(784, 128)
        self.fc2 = torch.nn.Linear(128, 10)

    def forward(self, x):
        x = torch.tanh(self.fc1(x.reshape(x.shape[0], -1)))
        return self.fc2(x)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--samples", type=int, default=2048)
    args = p.parse_args()

    hvd.init()
    torch.manual_seed(42)                      # same init everywhere...
    model = Net()
    # ...but broadcast anyway, like the reference (robust to seed drift).
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

    # Scale LR by world size (reference recipe step 3).
    opt = torch.optim.SGD(model.parameters(), lr=args.lr * hvd.size(),
                          momentum=0.5)
    hvd.broadcast_optimizer_state(opt, root_rank=0)
    opt = hvd.DistributedOptimizer(opt,
                                   named_parameters=model.named_parameters())

    images, labels = synthetic_mnist(args.samples)
    images = images.reshape(len(images), -1)

    for epoch in range(args.epochs):
        # DistributedSampler semantics: this rank's reshuffled shard.
        idx = shard_indices(len(images), hvd.rank(), hvd.size(),
                            epoch=epoch, drop_last=True)
        losses = []
        for s in range(0, len(idx) - args.batch_size + 1, args.batch_size):
            b = idx[s:s + args.batch_size]
            x = torch.from_numpy(images[b])
            y = torch.from_numpy(labels[b].astype(np.int64))
            opt.zero_grad()
            loss = F.cross_entropy(model(x), y)
            loss.backward()
            opt.step()
            losses.append(float(loss))
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {np.mean(losses):.4f}")

    # Metric averaged over ranks, reported once (reference Metric class).
    final = hvd.allreduce(torch.tensor([np.mean(losses)]), average=True,
                          name="final_loss")
    if hvd.rank() == 0:
        print(f"final loss (rank-averaged): {float(final[0]):.4f}")


if __name__ == "__main__":
    main()
