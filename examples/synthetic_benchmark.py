"""ResNet-50 synthetic throughput benchmark.

Equivalent of reference examples/pytorch_synthetic_benchmark.py:96-110:
ResNet-50 on random data, img/sec per chip as mean ± 1.96σ over
``--num-iters`` groups of ``--num-batches-per-iter`` batches, plus total
img/sec and the implied scaling efficiency.

Run: python examples/synthetic_benchmark.py            (real chip)
     JAX_PLATFORMS=cpu python examples/synthetic_benchmark.py --smoke
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models.resnet import ResNet50


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=32,
                   help="per-chip batch (reference default 32)")
    p.add_argument("--num-iters", type=int, default=10)
    p.add_argument("--num-batches-per-iter", type=int, default=10)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument(
        "--compression",
        choices=["none", "fp16", "bf16", "int8", "powersgd", "ef-topk"],
        default="none",
        help="gradient compression on the wire (docs/compression.md)",
    )
    p.add_argument("--adasum", action="store_true",
                   help="combine gradients with op=Adasum instead of Average")
    p.add_argument("--smoke", action="store_true")
    args = p.parse_args()
    if args.adasum and args.compression in ("int8", "powersgd", "ef-topk"):
        p.error("--adasum composes with none/fp16/bf16 compression only")
    if args.smoke:
        args.image_size, args.num_iters, args.num_batches_per_iter = 32, 2, 2

    hvd.init()
    n = hvd.size()
    on_tpu = jax.default_backend() == "tpu"
    model = ResNet50(dtype=jnp.bfloat16 if on_tpu else jnp.float32)

    global_bs = args.batch_size * n
    images = jnp.ones((global_bs, args.image_size, args.image_size, 3),
                      jnp.float32)
    labels = jnp.zeros((global_bs,), jnp.int32)

    variables = model.init(jax.random.key(0), images[:1], train=False)
    params, batch_stats = variables["params"], variables["batch_stats"]

    def loss_fn(params, batch):
        x, y = batch
        logits, _ = model.apply(
            {"params": params, "batch_stats": batch_stats},
            x, train=True, mutable=["batch_stats"],
        )
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y
        ).mean()

    compression = {
        "none": hvd.Compression.none,
        "fp16": hvd.Compression.fp16,
        "bf16": hvd.Compression.bf16,
        "int8": hvd.Compression.int8,
        "powersgd": hvd.PowerSGDCompressor(rank=4),
        "ef-topk": hvd.ErrorFeedback(
            hvd.ops.compression.TopKCompressor(ratio=0.01)
        ),
    }[args.compression]
    tx = hvd.DistributedOptimizer(
        optax.sgd(0.01 * n, momentum=0.9),
        compression=compression,
        op=hvd.Adasum if args.adasum else hvd.Average,
    )
    opt_state = tx.init(params)
    step = hvd.make_train_step(loss_fn, tx)

    if hvd.rank() == 0:
        print(f"Model: ResNet50  Batch size/chip: {args.batch_size}  "
              f"Chips: {n}  Backend: {jax.default_backend()}  "
              f"Compression: {args.compression}"
              + ("  Op: Adasum" if args.adasum else ""))

    out = step(params, opt_state, (images, labels))  # compile + warmup
    params, opt_state = out.params, out.opt_state
    jax.block_until_ready(out.loss)

    img_secs = []
    for i in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            out = step(params, opt_state, (images, labels))
            params, opt_state = out.params, out.opt_state
        jax.block_until_ready(out.loss)
        rate = global_bs * args.num_batches_per_iter / (
            time.perf_counter() - t0
        )
        img_secs.append(rate / n)
        if hvd.rank() == 0:
            print(f"Iter #{i}: {rate / n:.1f} img/sec per chip")

    mean, conf = np.mean(img_secs), 1.96 * np.std(img_secs)
    if hvd.rank() == 0:
        print(f"Img/sec per chip: {mean:.1f} +-{conf:.1f}")
        print(f"Total img/sec on {n} chip(s): {mean * n:.1f} "
              f"+-{conf * n:.1f}")


if __name__ == "__main__":
    main()
