"""MNIST through the ``fit`` frontend with callbacks.

Equivalent of reference examples/keras_mnist.py: wrap the optimizer, add
``BroadcastGlobalVariablesCallback``, call fit — three-line distribution.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/keras_mnist.py --epochs 2
"""

import argparse

import jax
import optax

import horovod_tpu as hvd
from horovod_tpu.data import ShardedLoader, synthetic_mnist
from horovod_tpu.models.mnist import MnistMLP


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--batch-per-chip", type=int, default=32)
    p.add_argument("--base-lr", type=float, default=0.01)
    args = p.parse_args()

    hvd.init()
    model = MnistMLP()
    images, labels = synthetic_mnist(4096)
    params = model.init(jax.random.key(0), images[:1])["params"]

    def loss_fn(params, batch):
        x, y = batch
        logits = model.apply({"params": params}, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    params, opt_state, history = hvd.fit(
        params,
        hvd.DistributedOptimizer(optax.adam(args.base_lr * hvd.size())),
        loss_fn,
        ShardedLoader((images, labels), args.batch_per_chip),
        epochs=args.epochs,
        callbacks=[
            hvd.BroadcastGlobalVariablesCallback(0),
            hvd.MetricAverageCallback(),
        ],
        verbose=hvd.rank() == 0,
    )
    if hvd.rank() == 0:
        print("final loss:", history[-1]["loss"])


if __name__ == "__main__":
    main()
