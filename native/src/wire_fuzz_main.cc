// Deterministic fuzz harness for the control-plane wire parsers.
//
// The reference trusts flatbuffers for parse safety; this hand-rolled
// format claims "trivially fuzzable" (wire.h header comment) — this
// binary makes the claim checkable in CI.  Three generators:
//   1. pure-random byte strings,
//   2. round-trips of random valid messages (must parse back EXACTLY),
//   3. valid serializations with random single-byte mutations.
// Every parse must either succeed or throw std::runtime_error — any
// crash, UB-sanitizer trap, or foreign exception fails the run.
//
// Build+run (tests/test_native_controller.py):
//   g++ -std=c++17 -O1 -fsanitize=address,undefined wire_fuzz_main.cc
//   ./a.out <iterations> <seed>

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <random>
#include <string>

#include "wire.h"

using hvdtpu::Batch;
using hvdtpu::BatchList;
using hvdtpu::DType;
using hvdtpu::OpKind;
using hvdtpu::Request;
using hvdtpu::RequestList;

namespace {

std::mt19937_64 g_rng;

uint64_t Rand(uint64_t lo, uint64_t hi) {
  return lo + g_rng() % (hi - lo + 1);
}

std::string RandStr(size_t max_len) {
  std::string s(Rand(0, max_len), '\0');
  for (char& c : s) c = static_cast<char>(g_rng());
  return s;
}

RequestList RandRequestList() {
  RequestList rl;
  rl.shutdown = Rand(0, 1) != 0;
  size_t n = Rand(0, 8);
  for (size_t i = 0; i < n; ++i) {
    Request r;
    r.kind = static_cast<OpKind>(Rand(0, 6));
    r.dtype = static_cast<DType>(Rand(0, 12));
    r.op_code = static_cast<uint8_t>(Rand(0, 2));
    r.rank = static_cast<int32_t>(Rand(0, 1023));
    r.root_rank = static_cast<int32_t>(g_rng());
    r.group = static_cast<int64_t>(g_rng());
    r.name = RandStr(40);
    size_t nd = Rand(0, 5);
    for (size_t j = 0; j < nd; ++j)
      r.shape.push_back(static_cast<int64_t>(g_rng()));
    rl.requests.push_back(std::move(r));
  }
  return rl;
}

BatchList RandBatchList() {
  BatchList bl;
  bl.shutdown = Rand(0, 1) != 0;
  // Tuned-knob piggyback: exercise unset (-1), zero, and large values.
  bl.tuned_threshold_bytes = Rand(0, 3) == 0
                                 ? -1
                                 : static_cast<int64_t>(Rand(0, 1 << 30));
  // Cycle time rides as integer micros; keep randoms on the µs grid so
  // the float round-trip is exact by construction.
  bl.tuned_cycle_ms =
      Rand(0, 3) == 0 ? -1.0 : static_cast<double>(Rand(0, 100000)) / 1000.0;
  bl.last_joined = Rand(0, 3) == 0 ? -1 : static_cast<int32_t>(Rand(0, 511));
  size_t n = Rand(0, 8);
  for (size_t i = 0; i < n; ++i) {
    Batch b;
    b.kind = static_cast<OpKind>(Rand(0, 6));
    b.dtype = static_cast<DType>(Rand(0, 12));
    b.op_code = static_cast<uint8_t>(Rand(0, 2));
    b.error = RandStr(30);
    size_t m = Rand(0, 6);
    for (size_t j = 0; j < m; ++j) {
      b.names.push_back(RandStr(24));
      std::vector<int64_t> s;
      size_t nd = Rand(0, 4);
      for (size_t k = 0; k < nd; ++k)
        s.push_back(static_cast<int64_t>(g_rng()));
      b.shapes.push_back(std::move(s));
    }
    bl.batches.push_back(std::move(b));
  }
  return bl;
}

bool EqualRL(const RequestList& a, const RequestList& b) {
  if (a.shutdown != b.shutdown || a.requests.size() != b.requests.size())
    return false;
  for (size_t i = 0; i < a.requests.size(); ++i) {
    const Request &x = a.requests[i], &y = b.requests[i];
    if (x.kind != y.kind || x.dtype != y.dtype || x.op_code != y.op_code ||
        x.rank != y.rank || x.root_rank != y.root_rank ||
        x.group != y.group || x.name != y.name || x.shape != y.shape)
      return false;
  }
  return true;
}

bool EqualBL(const BatchList& a, const BatchList& b) {
  if (a.shutdown != b.shutdown || a.batches.size() != b.batches.size())
    return false;
  if (a.tuned_threshold_bytes != b.tuned_threshold_bytes ||
      a.tuned_cycle_ms != b.tuned_cycle_ms ||
      a.last_joined != b.last_joined)
    return false;
  for (size_t i = 0; i < a.batches.size(); ++i) {
    const Batch &x = a.batches[i], &y = b.batches[i];
    if (x.kind != y.kind || x.dtype != y.dtype || x.op_code != y.op_code ||
        x.error != y.error || x.names != y.names || x.shapes != y.shapes)
      return false;
  }
  return true;
}

// Parse arbitrary bytes: success or runtime_error only.
template <typename ParseFn>
void MustNotCrash(const std::string& bytes, ParseFn parse) {
  try {
    hvdtpu::wire::Reader rd(bytes);
    parse(rd);
  } catch (const std::runtime_error&) {
    // expected failure mode for corrupt input
  }
}

}  // namespace

int main(int argc, char** argv) {
  const uint64_t iters = argc > 1 ? std::strtoull(argv[1], nullptr, 10) : 20000;
  const uint64_t seed = argc > 2 ? std::strtoull(argv[2], nullptr, 10) : 1;
  g_rng.seed(seed);

  for (uint64_t it = 0; it < iters; ++it) {
    // 1. Pure random bytes.
    std::string junk = RandStr(Rand(0, 200));
    MustNotCrash(junk, [](hvdtpu::wire::Reader& r) {
      return hvdtpu::wire::ParseRequestList(r);
    });
    MustNotCrash(junk, [](hvdtpu::wire::Reader& r) {
      return hvdtpu::wire::ParseBatchList(r);
    });

    // 2. Round-trip of valid messages must be exact.
    RequestList rl = RandRequestList();
    std::string ser = hvdtpu::wire::SerializeRequestList(rl);
    {
      hvdtpu::wire::Reader rd(ser);
      RequestList back = hvdtpu::wire::ParseRequestList(rd);
      if (!EqualRL(rl, back) || !rd.Done()) {
        std::fprintf(stderr, "request round-trip mismatch at iter %llu\n",
                     static_cast<unsigned long long>(it));
        return 1;
      }
    }
    BatchList bl = RandBatchList();
    std::string bser = hvdtpu::wire::SerializeBatchList(bl);
    {
      hvdtpu::wire::Reader rd(bser);
      BatchList back = hvdtpu::wire::ParseBatchList(rd);
      if (!EqualBL(bl, back) || !rd.Done()) {
        std::fprintf(stderr, "batch round-trip mismatch at iter %llu\n",
                     static_cast<unsigned long long>(it));
        return 1;
      }
    }

    // 3. Single-byte mutations of valid serializations.
    for (int k = 0; k < 4; ++k) {
      std::string mut = ser;
      if (!mut.empty())
        mut[Rand(0, mut.size() - 1)] = static_cast<char>(g_rng());
      MustNotCrash(mut, [](hvdtpu::wire::Reader& r) {
        return hvdtpu::wire::ParseRequestList(r);
      });
      std::string bmut = bser;
      if (!bmut.empty())
        bmut[Rand(0, bmut.size() - 1)] = static_cast<char>(g_rng());
      MustNotCrash(bmut, [](hvdtpu::wire::Reader& r) {
        return hvdtpu::wire::ParseBatchList(r);
      });
    }
  }
  std::printf("wire fuzz OK: %llu iters, seed %llu\n",
              static_cast<unsigned long long>(iters),
              static_cast<unsigned long long>(seed));
  return 0;
}
