// Core control-plane types for the horovod_tpu native coordination engine.
//
// TPU-native re-design of the reference's common types
// (reference: horovod/common/common.h:28-110 Status/TensorShape and
// horovod/common/mpi_message.h:26-172 request/response vocabulary).  The
// data plane here is XLA collectives driven from Python, so the native
// layer carries only *metadata*: named-tensor requests, readiness state,
// and fused execution batches.  No tensor payload ever crosses this layer.

#ifndef HVDTPU_TYPES_H_
#define HVDTPU_TYPES_H_

#include <cstdint>
#include <string>
#include <vector>

namespace hvdtpu {

// Collective kinds.  SPARSE is the shyhuai-fork top-k path
// (reference horovod/torch/__init__.py:46-83).
enum class OpKind : uint8_t {
  kAllreduce = 0,
  kAllgather = 1,
  kBroadcast = 2,
  kSparse = 3,
  kAlltoall = 4,
  kReduceScatter = 5,
  // Control-plane pseudo-op: "this rank has no more work" (the hvd.join()
  // API Horovod grew in 0.21 for uneven data).  Never enters the message
  // table; flips the rank's joined bit so its missing submissions stop
  // blocking readiness.
  kJoin = 6,
};

// Dispatch-program codes for join support: a joined rank must launch the
// SAME compiled collective as its peers, so batches carry which program
// that is.  Anything beyond plain Sum/Average (compression, process sets,
// Adasum) is kOther and cannot complete via joined ranks.
enum OpCode : uint8_t {
  kOpPlainSum = 0,
  kOpPlainAverage = 1,
  kOpOther = 2,
};

// Dtype vocabulary (JAX-facing; sizes used only for fusion accounting).
enum class DType : uint8_t {
  kU8 = 0,
  kI8 = 1,
  kU16 = 2,
  kI16 = 3,
  kI32 = 4,
  kI64 = 5,
  kF16 = 6,
  kBF16 = 7,
  kF32 = 8,
  kF64 = 9,
  kBool = 10,
  kU32 = 11,
  kU64 = 12,
};

inline int DTypeSize(DType d) {
  switch (d) {
    case DType::kU8:
    case DType::kI8:
    case DType::kBool:
      return 1;
    case DType::kU16:
    case DType::kI16:
    case DType::kF16:
    case DType::kBF16:
      return 2;
    case DType::kI32:
    case DType::kU32:
    case DType::kF32:
      return 4;
    case DType::kI64:
    case DType::kU64:
    case DType::kF64:
      return 8;
  }
  return 1;
}

// A named-tensor collective request from one rank.
struct Request {
  OpKind kind = OpKind::kAllreduce;
  DType dtype = DType::kF32;
  uint8_t op_code = kOpOther;  // dispatch program (OpCode); join support
  int32_t rank = 0;
  int32_t root_rank = 0;
  int64_t group = -1;  // caller-delimited fusion group; -1 = none
  std::string name;
  std::vector<int64_t> shape;  // per-rank (local) shape

  int64_t PayloadBytes() const {
    int64_t n = 1;
    for (int64_t d : shape) n *= d;
    return n * DTypeSize(dtype);
  }
};

struct RequestList {
  bool shutdown = false;
  std::vector<Request> requests;
};

// One fused execution batch: every rank dispatches the named tensors of a
// batch as ONE collective program, in list order.  A non-empty `error`
// aborts those tensors only (reference semantics: mismatch errors fail the
// op, not the job — horovod/common/operations.cc:516-519).
struct Batch {
  OpKind kind = OpKind::kAllreduce;
  DType dtype = DType::kF32;
  uint8_t op_code = kOpOther;  // OpCode of the batch's dispatch program
  std::string error;
  std::vector<std::string> names;
  // Per-name per-rank shapes (parallel to `names`): lets a JOINED rank
  // fabricate identity contributions for tensors it never submitted.
  std::vector<std::vector<int64_t>> shapes;
};

struct BatchList {
  bool shutdown = false;
  std::vector<Batch> batches;
  // Rank-0-owned tuned engine knobs, piggybacked on every response so the
  // whole gang observes a move in the SAME tick (control-plane autotune).
  // Negative = "no value"; receivers keep their current setting.
  int64_t tuned_threshold_bytes = -1;
  double tuned_cycle_ms = -1.0;
  // >= 0 once EVERY rank has joined (hvd.join): the last rank to join.
  // One-shot — the joined set resets so the next epoch starts clean.
  int32_t last_joined = -1;
};

}  // namespace hvdtpu

#endif  // HVDTPU_TYPES_H_
