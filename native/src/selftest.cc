// C++ self-test for the coordination controller: N rank threads negotiate
// over LocalTransport and must all observe identical fused batch order —
// the property the reference gets from its MPI coordinator protocol
// (reference: horovod/common/operations.cc:1795-2007).  Run via
// `make -C native test`; the pytest suite drives the same scenarios
// through the C API (tests/test_native_controller.py).

#include <algorithm>
#include <cassert>
#include <cstdio>
#include <memory>
#include <thread>
#include <vector>

#include "controller.h"

using namespace hvdtpu;

namespace {

std::unique_ptr<Controller> MakeLocal(const std::string& world, int rank,
                                      int size, int64_t threshold) {
  std::string err;
  auto t = MakeTransport("local:" + world, rank, size, &err);
  assert(t && "transport create failed");
  return std::make_unique<Controller>(rank, size, std::move(t), threshold,
                                      60.0);
}

Request AR(const std::string& name, std::vector<int64_t> shape,
           DType dt = DType::kF32) {
  Request r;
  r.kind = OpKind::kAllreduce;
  r.dtype = dt;
  r.name = name;
  r.shape = std::move(shape);
  return r;
}

// Ranks submit the same three tensors in different orders; all must agree
// on one fused batch order.
void TestAgreementAndFusion() {
  const int kSize = 4;
  std::vector<BatchList> results(kSize);
  std::vector<std::thread> threads;
  for (int rank = 0; rank < kSize; ++rank) {
    threads.emplace_back([rank, &results] {
      auto c = MakeLocal("agree", rank, kSize, 1 << 20);
      // Different per-rank submission order (nondeterministic frameworks).
      std::vector<Request> reqs = {AR("a", {8}), AR("b", {4}), AR("c", {2})};
      std::rotate(reqs.begin(), reqs.begin() + rank % 3, reqs.end());
      for (auto& r : reqs) c->Submit(r);
      BatchList bl;
      while (results[rank].batches.empty()) {
        assert(c->Tick(&bl) == TickStatus::kLive);
        for (auto& b : bl.batches) results[rank].batches.push_back(b);
      }
    });
  }
  for (auto& t : threads) t.join();
  assert(results[0].batches.size() == 1);  // all fused: same dtype, tiny
  assert(results[0].batches[0].names.size() == 3);
  for (int r = 1; r < kSize; ++r) {
    assert(results[r].batches.size() == results[0].batches.size());
    assert(results[r].batches[0].names == results[0].batches[0].names);
  }
  std::printf("agreement+fusion ok\n");
}

// Fusion threshold: 3 tensors of 400 bytes with a 800-byte threshold must
// split into two batches.
void TestThresholdSplit() {
  const int kSize = 2;
  std::vector<BatchList> results(kSize);
  std::vector<std::thread> threads;
  for (int rank = 0; rank < kSize; ++rank) {
    threads.emplace_back([rank, &results] {
      auto c = MakeLocal("split", rank, kSize, 800);
      for (auto* n : {"x", "y", "z"}) c->Submit(AR(n, {100}));  // 400 B each
      BatchList bl;
      size_t total = 0;
      while (total < 3) {
        assert(c->Tick(&bl) == TickStatus::kLive);
        for (auto& b : bl.batches) {
          total += b.names.size();
          results[rank].batches.push_back(b);
        }
      }
    });
  }
  for (auto& t : threads) t.join();
  assert(results[0].batches.size() == 2);
  assert(results[0].batches[0].names.size() == 2);
  assert(results[0].batches[1].names.size() == 1);
  assert(results[1].batches[0].names == results[0].batches[0].names);
  std::printf("threshold split ok\n");
}

// Mismatched shapes across ranks must produce an error batch on all ranks.
void TestShapeMismatch() {
  const int kSize = 2;
  std::vector<BatchList> results(kSize);
  std::vector<std::thread> threads;
  for (int rank = 0; rank < kSize; ++rank) {
    threads.emplace_back([rank, &results] {
      auto c = MakeLocal("mismatch", rank, kSize, 1 << 20);
      c->Submit(AR("bad", {rank ? 4 : 8}));  // even vs odd shapes
      BatchList bl;
      while (results[rank].batches.empty()) {
        assert(c->Tick(&bl) == TickStatus::kLive);
        for (auto& b : bl.batches) results[rank].batches.push_back(b);
      }
    });
  }
  for (auto& t : threads) t.join();
  for (int r = 0; r < kSize; ++r) {
    assert(results[r].batches.size() == 1);
    assert(!results[r].batches[0].error.empty());
  }
  std::printf("shape mismatch ok: %s\n", results[0].batches[0].error.c_str());
}

// Shutdown from one rank propagates to all.
void TestShutdown() {
  const int kSize = 3;
  std::vector<std::thread> threads;
  for (int rank = 0; rank < kSize; ++rank) {
    threads.emplace_back([rank] {
      auto c = MakeLocal("shutdown", rank, kSize, 1 << 20);
      if (rank == 1) c->RequestShutdown();
      BatchList bl;
      assert(c->Tick(&bl) == TickStatus::kShutdown);
      assert(bl.shutdown);
    });
  }
  for (auto& t : threads) t.join();
  std::printf("shutdown propagation ok\n");
}

// TCP transport: same agreement property over real sockets.
void TestTcp() {
  const int kSize = 2;
  std::vector<BatchList> results(kSize);
  std::vector<std::thread> threads;
  for (int rank = 0; rank < kSize; ++rank) {
    threads.emplace_back([rank, &results] {
      std::string err;
      auto t = MakeTransport("tcp:127.0.0.1:19755", rank, kSize, &err);
      assert(t && "tcp transport failed");
      Controller c(rank, kSize, std::move(t), 1 << 20, 60.0);
      c.Submit(AR(rank ? "t2" : "t1", {4}));
      c.Submit(AR(rank ? "t1" : "t2", {4}));
      BatchList bl;
      size_t total = 0;
      while (total < 2) {
        assert(c.Tick(&bl) == TickStatus::kLive);
        for (auto& b : bl.batches) {
          total += b.names.size();
          results[rank].batches.push_back(b);
        }
      }
    });
  }
  for (auto& t : threads) t.join();
  assert(results[0].batches.size() == results[1].batches.size());
  for (size_t i = 0; i < results[0].batches.size(); ++i)
    assert(results[0].batches[i].names == results[1].batches[i].names);
  std::printf("tcp transport ok\n");
}

// hvd.join: a joined rank stops blocking readiness; the batch carries
// dtype/op_code/shapes so the joined rank can fabricate identity inputs;
// non-plain ops cannot complete via joins; once ALL ranks join, the
// response reports the last joiner and the epoch resets.
void TestJoin() {
  const int kSize = 2;
  std::vector<Batch> first(kSize);
  std::vector<Batch> gathered(kSize);
  std::vector<int> last(kSize, -1);
  std::vector<std::thread> threads;
  for (int rank = 0; rank < kSize; ++rank) {
    threads.emplace_back([rank, &first, &gathered, &last] {
      auto c = MakeLocal("join", rank, kSize, 1 << 20);
      if (rank == 0) {
        Request j;
        j.kind = OpKind::kJoin;
        c->Submit(j);
      } else {
        Request r = AR("x", {8});
        r.op_code = kOpPlainSum;
        c->Submit(r);
      }
      BatchList bl;
      bool have = false;
      while (!have) {
        assert(c->Tick(&bl) == TickStatus::kLive);
        for (auto& b : bl.batches) {
          first[rank] = b;
          have = true;
        }
      }
      // Non-plain op while rank 0 is joined: must error, not hang.
      if (rank == 1) c->Submit(AR("g", {3}));  // op_code defaults kOpOther
      have = false;
      while (!have) {
        assert(c->Tick(&bl) == TickStatus::kLive);
        for (auto& b : bl.batches) {
          gathered[rank] = b;
          have = true;
        }
      }
      // Rank 1 joins too: everyone ticks until the all-joined response.
      if (rank == 1) {
        Request j;
        j.kind = OpKind::kJoin;
        c->Submit(j);
      }
      while (last[rank] < 0) {
        assert(c->Tick(&bl) == TickStatus::kLive);
        if (bl.last_joined >= 0) last[rank] = bl.last_joined;
      }
    });
  }
  for (auto& t : threads) t.join();
  for (int r = 0; r < kSize; ++r) {
    assert(first[r].error.empty());
    assert(first[r].names == std::vector<std::string>({"x"}));
    assert(first[r].shapes == std::vector<std::vector<int64_t>>({{8}}));
    assert(first[r].op_code == kOpPlainSum);
    assert(gathered[r].names == std::vector<std::string>({"g"}));
    assert(!gathered[r].error.empty());
    assert(gathered[r].error.find("join") != std::string::npos);
    assert(last[r] == 1);
  }
  std::printf("join ok\n");
}

}  // namespace

int main() {
  TestAgreementAndFusion();
  TestThresholdSplit();
  TestShapeMismatch();
  TestShutdown();
  TestTcp();
  TestJoin();
  std::printf("all native self-tests passed\n");
  return 0;
}
