#include "controller.h"

#include <chrono>
#include <sstream>

#include "wire.h"

namespace hvdtpu {
namespace {

double NowS() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

std::string ShapeStr(const std::vector<int64_t>& s) {
  std::ostringstream os;
  os << "[";
  for (size_t i = 0; i < s.size(); ++i) os << (i ? ", " : "") << s[i];
  os << "]";
  return os.str();
}

const char* KindName(OpKind k) {
  switch (k) {
    case OpKind::kAllreduce:
      return "allreduce";
    case OpKind::kAllgather:
      return "allgather";
    case OpKind::kBroadcast:
      return "broadcast";
    case OpKind::kSparse:
      return "sparse_allreduce";
    case OpKind::kAlltoall:
      return "alltoall";
    case OpKind::kReduceScatter:
      return "reducescatter";
    case OpKind::kJoin:
      return "join";
  }
  return "?";
}

}  // namespace

Controller::Controller(int rank, int size,
                       std::unique_ptr<Transport> transport,
                       int64_t fusion_threshold_bytes, double stall_warning_s)
    : rank_(rank),
      size_(size),
      fusion_threshold_bytes_(fusion_threshold_bytes),
      stall_warning_s_(stall_warning_s),
      transport_(std::move(transport)) {}

void Controller::Submit(Request r) {
  r.rank = rank_;
  std::lock_guard<std::mutex> lk(pending_mu_);
  pending_.push_back(std::move(r));
}

void Controller::RequestShutdown() {
  std::lock_guard<std::mutex> lk(pending_mu_);
  shutdown_requested_ = true;
}

bool Controller::Complete(const TableEntry& e) const {
  for (int r = 0; r < size_; ++r) {
    if (!e.seen[r] && (joined_.empty() || !joined_[r])) return false;
  }
  return true;
}

void Controller::MaybePush(const std::string& name, TableEntry& e,
                           std::vector<std::string>* ready) {
  if (e.pushed || !Complete(e)) return;
  if (e.error.empty() && e.count < size_) {
    // Completed via joined ranks: those ranks fabricate identity
    // contributions, which is only sound for the plain Sum/Average
    // allreduce program (zeros are the identity and every rank can
    // reconstruct the exact compiled collective from the batch alone).
    if (e.first.kind != OpKind::kAllreduce ||
        e.first.op_code > kOpPlainAverage) {
      e.error = std::string(KindName(e.first.kind)) + " for " + name +
                " cannot complete while ranks are joined (hvd.join " +
                "supports plain Sum/Average allreduce only)";
    }
  }
  e.pushed = true;
  ready->push_back(name);
}

void Controller::Ingest(const Request& r, std::vector<std::string>* ready) {
  if (r.kind == OpKind::kJoin) {
    if (joined_.empty()) joined_.assign(size_, false);
    if (r.rank >= 0 && r.rank < size_ && !joined_[r.rank]) {
      joined_[r.rank] = true;
      ++joined_count_;
      last_joined_ = r.rank;
    }
    return;
  }
  auto it = table_.find(r.name);
  if (it == table_.end()) {
    TableEntry e;
    e.first = r;
    e.seen.assign(size_, false);
    e.first_seen_s = NowS();
    it = table_.emplace(r.name, std::move(e)).first;
  }
  TableEntry& e = it->second;
  if (e.seen[r.rank]) {
    // Same name enqueued twice before completion — the reference treats
    // duplicate in-flight names as a usage error (operations.cc:2124-2134).
    // Do NOT bump the count: it must keep meaning "distinct ranks seen",
    // or a double submission could release a batch with ranks missing.
    e.error = "Duplicate tensor name in flight: " + r.name;
  } else {
    e.seen[r.rank] = true;
    ++e.count;
    if (tick_trace_enabled_) tick_events_.emplace_back(r.name, r.rank);
  }

  // Consistency validation against the first-seen request — the analogue
  // of ConstructMPIResponse's checks (operations.cc:335-537).
  const Request& f = e.first;
  if (e.error.empty() && r.kind != f.kind) {
    e.error = std::string("Mismatched collective kinds for tensor ") + r.name +
              ": " + KindName(f.kind) + " vs " + KindName(r.kind);
  }
  if (e.error.empty() && r.dtype != f.dtype) {
    e.error = "Mismatched tensor dtypes for " + r.name;
  }
  if (e.error.empty()) {
    switch (r.kind) {
      case OpKind::kAllreduce:
      case OpKind::kSparse:
      case OpKind::kAlltoall:       // equal splits: identical shapes everywhere
      case OpKind::kReduceScatter:  // equal shards: identical shapes everywhere
        if (r.shape != f.shape)
          e.error = std::string("Mismatched ") + KindName(r.kind) +
                    " tensor shapes for " + r.name + ": " +
                    ShapeStr(f.shape) + " vs " + ShapeStr(r.shape);
        break;
      case OpKind::kAllgather:
        // First dim may differ per rank (ragged gather); trailing dims must
        // agree (reference operations.cc:841-901).
        if (r.shape.size() != f.shape.size() ||
            (r.shape.size() > 1 &&
             !std::equal(r.shape.begin() + 1, r.shape.end(),
                         f.shape.begin() + 1)))
          e.error = "Mismatched allgather trailing dims for " + r.name + ": " +
                    ShapeStr(f.shape) + " vs " + ShapeStr(r.shape);
        break;
      case OpKind::kBroadcast:
        if (r.root_rank != f.root_rank)
          e.error = "Mismatched broadcast root_rank for " + r.name;
        else if (r.shape != f.shape)
          e.error = "Mismatched broadcast tensor shapes for " + r.name;
        break;
      case OpKind::kJoin:
        break;  // handled (early-return) above; silences -Wswitch
    }
  }
  MaybePush(r.name, e, ready);
}

BatchList Controller::BuildBatches(const std::vector<std::string>& ready) {
  BatchList bl;
  Batch cur;
  int64_t cur_bytes = 0;
  DType cur_dtype = DType::kF32;
  int64_t cur_group = -1;
  auto flush = [&] {
    if (!cur.names.empty()) bl.batches.push_back(std::move(cur));
    cur = Batch();
    cur_bytes = 0;
  };
  for (const std::string& name : ready) {
    auto it = table_.find(name);
    TableEntry& e = it->second;
    const bool fusable = e.error.empty() && e.first.kind == OpKind::kAllreduce;
    const int64_t bytes = e.first.PayloadBytes();
    if (!fusable) {
      flush();
      Batch b;
      b.kind = e.first.kind;
      b.dtype = e.first.dtype;
      b.op_code = e.first.op_code;
      b.error = e.error;
      b.names.push_back(name);
      b.shapes.push_back(e.first.shape);
      bl.batches.push_back(std::move(b));
    } else {
      // Merge consecutive ready allreduces of one dtype and fusion group up
      // to the threshold (reference response merging, operations.cc:
      // 1916-1943).  `group` encodes caller-side fusability (reduce op,
      // compression) so the controller never merges incompatible programs.
      const bool same = !cur.names.empty() && cur_dtype == e.first.dtype &&
                        cur_group == e.first.group;
      if (!same || cur_bytes + bytes > EffectiveThreshold()) flush();
      cur.kind = OpKind::kAllreduce;
      cur.dtype = e.first.dtype;
      cur.op_code = e.first.op_code;
      cur_dtype = e.first.dtype;
      cur_group = e.first.group;
      cur.names.push_back(name);
      cur.shapes.push_back(e.first.shape);
      cur_bytes += bytes;
    }
    table_.erase(it);
  }
  flush();
  return bl;
}

TickStatus Controller::Tick(BatchList* out) {
  if (shut_down_) {
    out->shutdown = true;
    return TickStatus::kShutdown;
  }
  RequestList mine;
  {
    std::lock_guard<std::mutex> lk(pending_mu_);
    mine.requests.swap(pending_);
    mine.shutdown = shutdown_requested_;
  }
  std::vector<std::string> gathered;
  if (!transport_->GatherToRoot(wire::SerializeRequestList(mine), &gathered))
    return TickStatus::kTransportError;

  std::string response_bytes;
  if (rank_ == 0) {
    bool shutdown_seen = false;
    std::vector<std::string> ready;
    std::lock_guard<std::mutex> lk(table_mu_);
    const int joined_before = joined_count_;
    for (const std::string& payload : gathered) {
      wire::Reader rd(payload);
      RequestList rl = wire::ParseRequestList(rd);
      if (rl.shutdown) shutdown_seen = true;
      for (const Request& r : rl.requests) Ingest(r, &ready);
    }
    if (joined_count_ > joined_before) {
      // A join landed this tick: entries whose only missing contributors
      // just joined become ready NOW — rescan (std::map order, so the
      // emitted order is deterministic on the one rank that builds).
      for (auto& kv : table_) MaybePush(kv.first, kv.second, &ready);
    }
    BatchList built = BuildBatches(ready);
    built.shutdown = shutdown_seen;
    built.tuned_threshold_bytes = tuned_threshold_bytes_;
    built.tuned_cycle_ms = tuned_cycle_ms_;
    if (joined_count_ == size_) {
      // Everyone joined: report the last joiner and reset for the next
      // join epoch (reference-era Horovod returns it so callers can pick
      // a root that is guaranteed to have processed all its data).
      built.last_joined = last_joined_;
      joined_.assign(size_, false);
      joined_count_ = 0;
      last_joined_ = -1;
    }
    response_bytes = wire::SerializeBatchList(built);
  }
  std::string received;
  if (!transport_->BcastFromRoot(response_bytes, &received))
    return TickStatus::kTransportError;
  wire::Reader rd(received);
  *out = wire::ParseBatchList(rd);
  if (out->shutdown) shut_down_ = true;
  return out->shutdown ? TickStatus::kShutdown : TickStatus::kLive;
}

void Controller::SetTuned(int64_t threshold_bytes, double cycle_ms) {
  if (rank_ != 0) return;  // rank 0 owns batching; see header comment
  std::lock_guard<std::mutex> lk(table_mu_);
  if (threshold_bytes >= 0) tuned_threshold_bytes_ = threshold_bytes;
  if (cycle_ms >= 0) tuned_cycle_ms_ = cycle_ms;
}

void Controller::EnableTickTrace(bool on) {
  std::lock_guard<std::mutex> lk(table_mu_);
  tick_trace_enabled_ = on;
  if (!on) tick_events_.clear();
}

std::string Controller::DrainTicks() {
  std::ostringstream os;
  std::lock_guard<std::mutex> lk(table_mu_);
  for (const auto& ev : tick_events_) os << ev.second << " " << ev.first << "\n";
  tick_events_.clear();
  return os.str();
}

std::string Controller::StallReport() {
  if (rank_ != 0) return "";
  const double now = NowS();
  std::ostringstream os;
  bool any = false;
  std::lock_guard<std::mutex> lk(table_mu_);
  for (const auto& kv : table_) {
    const TableEntry& e = kv.second;
    if (now - e.first_seen_s < stall_warning_s_) continue;
    if (any) os << "; ";
    any = true;
    os << kv.first << " (missing ranks:";
    for (int r = 0; r < size_; ++r)
      if (!e.seen[r] && (joined_.empty() || !joined_[r])) os << " " << r;
    os << ")";
  }
  return os.str();
}

}  // namespace hvdtpu
