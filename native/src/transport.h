// Control-plane transports: how request/response bytes move between ranks.
//
// The reference's control plane is MPI_Gather/MPI_Gatherv to rank 0 plus
// MPI_Bcast back (reference: horovod/common/operations.cc:1843-1864,
// 1953-1993).  There is no MPI on a TPU pod; the idiomatic substrate for
// host-side coordination is plain TCP over the DCN (what
// jax.distributed's own coordination service rides).  Two implementations:
//
//  * LocalTransport — N ranks inside one process rendezvous through a
//    shared in-memory world.  This is the test harness, mirroring how the
//    reference simulates multi-node with `mpirun -np N` on one host
//    (SURVEY.md §4), and the backend for single-host multi-rank setups.
//  * TcpTransport — rank 0 listens, workers connect; length-prefixed
//    frames, strictly tick-aligned (gather then bcast per tick), which is
//    exactly the lockstep MPI gave the reference.

#ifndef HVDTPU_TRANSPORT_H_
#define HVDTPU_TRANSPORT_H_

#include <memory>
#include <string>
#include <vector>

namespace hvdtpu {

class Transport {
 public:
  virtual ~Transport() = default;

  // Every rank contributes `payload`; on rank 0, `out` receives all ranks'
  // payloads indexed by rank.  Blocking; one call per tick per rank.
  virtual bool GatherToRoot(const std::string& payload,
                            std::vector<std::string>* out) = 0;

  // Rank 0 sends `payload`; every rank's `out` receives it.
  virtual bool BcastFromRoot(const std::string& payload, std::string* out) = 0;
};

// spec: "local:<world-name>"  (in-process rendezvous; created on demand)
//       "tcp:<host>:<port>"   (rank 0 binds <host>:<port>; workers connect)
std::unique_ptr<Transport> MakeTransport(const std::string& spec, int rank,
                                         int size, std::string* error);

}  // namespace hvdtpu

#endif  // HVDTPU_TRANSPORT_H_
