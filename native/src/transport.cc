#include "transport.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <condition_variable>
#include <cstring>
#include <map>
#include <mutex>
#include <thread>

namespace hvdtpu {
namespace {

// ---------------------------------------------------------------- local

// Shared rendezvous state for one in-process world.  The gather/bcast
// protocol is generation-free: a rank may not contribute twice to the same
// gather round (it blocks until root resets), and the bcast that ends every
// tick is the barrier that keeps rounds aligned.
struct LocalWorld {
  std::mutex mu;
  std::condition_variable cv;
  int size = 0;
  std::vector<std::string> slots;
  std::vector<bool> contributed;
  int n_contributed = 0;
  std::string bcast_payload;
  uint64_t bcast_gen = 0;
};

std::mutex g_worlds_mu;
std::map<std::string, std::shared_ptr<LocalWorld>> g_worlds;

std::shared_ptr<LocalWorld> GetWorld(const std::string& name, int size) {
  std::lock_guard<std::mutex> lk(g_worlds_mu);
  auto it = g_worlds.find(name);
  if (it != g_worlds.end()) return it->second;
  auto w = std::make_shared<LocalWorld>();
  w->size = size;
  w->slots.resize(size);
  w->contributed.assign(size, false);
  g_worlds[name] = w;
  return w;
}

class LocalTransport : public Transport {
 public:
  LocalTransport(std::shared_ptr<LocalWorld> w, int rank)
      : world_(std::move(w)), rank_(rank) {}

  bool GatherToRoot(const std::string& payload,
                    std::vector<std::string>* out) override {
    std::unique_lock<std::mutex> lk(world_->mu);
    world_->cv.wait(lk, [&] { return !world_->contributed[rank_]; });
    world_->contributed[rank_] = true;
    world_->slots[rank_] = payload;
    ++world_->n_contributed;
    world_->cv.notify_all();
    if (rank_ == 0) {
      world_->cv.wait(lk, [&] { return world_->n_contributed == world_->size; });
      *out = world_->slots;
      std::fill(world_->contributed.begin(), world_->contributed.end(), false);
      world_->n_contributed = 0;
      world_->cv.notify_all();
    }
    return true;
  }

  bool BcastFromRoot(const std::string& payload, std::string* out) override {
    std::unique_lock<std::mutex> lk(world_->mu);
    if (rank_ == 0) {
      world_->bcast_payload = payload;
      ++world_->bcast_gen;
      *out = payload;
      world_->cv.notify_all();
    } else {
      uint64_t target = seen_gen_ + 1;
      world_->cv.wait(lk, [&] { return world_->bcast_gen >= target; });
      seen_gen_ = target;
      *out = world_->bcast_payload;
    }
    return true;
  }

 private:
  std::shared_ptr<LocalWorld> world_;
  int rank_;
  uint64_t seen_gen_ = 0;
};

// ------------------------------------------------------------------ tcp

bool SendAll(int fd, const void* data, size_t n) {
  const char* p = static_cast<const char*>(data);
  while (n > 0) {
    ssize_t k = ::send(fd, p, n, 0);
    if (k <= 0) return false;
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

bool RecvAll(int fd, void* data, size_t n) {
  char* p = static_cast<char*>(data);
  while (n > 0) {
    ssize_t k = ::recv(fd, p, n, 0);
    if (k <= 0) return false;
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

bool SendFrame(int fd, const std::string& payload) {
  uint32_t len = static_cast<uint32_t>(payload.size());
  return SendAll(fd, &len, 4) && SendAll(fd, payload.data(), payload.size());
}

// Control-plane frames carry names/shapes at millisecond cadence; anything
// approaching this bound is corruption (or an attack), not a real message.
// Failing the transport beats letting one bad length prefix drive a ~4 GiB
// allocation on rank 0's tick.
constexpr uint32_t kMaxFrameBytes = 64u << 20;  // 64 MiB

bool RecvFrame(int fd, std::string* out) {
  uint32_t len = 0;
  if (!RecvAll(fd, &len, 4)) return false;
  if (len > kMaxFrameBytes) return false;
  out->resize(len);
  return len == 0 || RecvAll(fd, &(*out)[0], len);
}

class TcpTransport : public Transport {
 public:
  ~TcpTransport() override {
    for (int fd : worker_fds_)
      if (fd >= 0) ::close(fd);
    if (conn_fd_ >= 0) ::close(conn_fd_);
    if (listen_fd_ >= 0) ::close(listen_fd_);
  }

  bool Init(const std::string& host, int port, int rank, int size,
            std::string* error) {
    rank_ = rank;
    size_ = size;
    if (rank == 0) {
      listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
      if (listen_fd_ < 0) return Fail(error, "socket() failed");
      int one = 1;
      ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
      sockaddr_in addr{};
      addr.sin_family = AF_INET;
      addr.sin_port = htons(static_cast<uint16_t>(port));
      addr.sin_addr.s_addr = INADDR_ANY;
      if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)))
        return Fail(error, "bind() failed on port " + std::to_string(port));
      if (::listen(listen_fd_, size)) return Fail(error, "listen() failed");
      worker_fds_.assign(size, -1);
      for (int i = 0; i < size - 1; ++i) {
        int fd = ::accept(listen_fd_, nullptr, nullptr);
        if (fd < 0) return Fail(error, "accept() failed");
        int nd = 1;
        ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &nd, sizeof(nd));
        uint32_t peer_rank = 0;
        if (!RecvAll(fd, &peer_rank, 4) || peer_rank >= (uint32_t)size)
          return Fail(error, "bad hello from worker");
        worker_fds_[peer_rank] = fd;
      }
    } else {
      addrinfo hints{}, *res = nullptr;
      hints.ai_family = AF_INET;
      hints.ai_socktype = SOCK_STREAM;
      std::string port_s = std::to_string(port);
      if (::getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res))
        return Fail(error, "getaddrinfo(" + host + ") failed");
      // Retry connect for up to ~60 s: workers may start before rank 0
      // binds (the reference leans on mpirun for this ordering).
      for (int attempt = 0; attempt < 600; ++attempt) {
        conn_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
        if (conn_fd_ >= 0 &&
            ::connect(conn_fd_, res->ai_addr, res->ai_addrlen) == 0)
          break;
        if (conn_fd_ >= 0) ::close(conn_fd_);
        conn_fd_ = -1;
        std::this_thread::sleep_for(std::chrono::milliseconds(100));
      }
      ::freeaddrinfo(res);
      if (conn_fd_ < 0)
        return Fail(error, "could not connect to coordinator " + host);
      int nd = 1;
      ::setsockopt(conn_fd_, IPPROTO_TCP, TCP_NODELAY, &nd, sizeof(nd));
      uint32_t r = static_cast<uint32_t>(rank);
      if (!SendAll(conn_fd_, &r, 4)) return Fail(error, "hello send failed");
    }
    return true;
  }

  bool GatherToRoot(const std::string& payload,
                    std::vector<std::string>* out) override {
    if (rank_ == 0) {
      out->assign(size_, std::string());
      (*out)[0] = payload;
      for (int r = 1; r < size_; ++r)
        if (!RecvFrame(worker_fds_[r], &(*out)[r])) return false;
      return true;
    }
    return SendFrame(conn_fd_, payload);
  }

  bool BcastFromRoot(const std::string& payload, std::string* out) override {
    if (rank_ == 0) {
      for (int r = 1; r < size_; ++r)
        if (!SendFrame(worker_fds_[r], payload)) return false;
      *out = payload;
      return true;
    }
    return RecvFrame(conn_fd_, out);
  }

 private:
  static bool Fail(std::string* error, const std::string& msg) {
    if (error) *error = msg;
    return false;
  }

  int rank_ = 0, size_ = 0;
  int listen_fd_ = -1, conn_fd_ = -1;
  std::vector<int> worker_fds_;
};

}  // namespace

std::unique_ptr<Transport> MakeTransport(const std::string& spec, int rank,
                                         int size, std::string* error) {
  if (spec.rfind("local:", 0) == 0) {
    return std::make_unique<LocalTransport>(GetWorld(spec.substr(6), size),
                                            rank);
  }
  if (spec.rfind("tcp:", 0) == 0) {
    std::string rest = spec.substr(4);
    size_t colon = rest.rfind(':');
    if (colon == std::string::npos) {
      if (error) *error = "tcp spec must be tcp:<host>:<port>";
      return nullptr;
    }
    auto t = std::make_unique<TcpTransport>();
    if (!t->Init(rest.substr(0, colon), std::stoi(rest.substr(colon + 1)),
                 rank, size, error))
      return nullptr;
    return t;
  }
  if (error) *error = "unknown transport spec: " + spec;
  return nullptr;
}

}  // namespace hvdtpu
