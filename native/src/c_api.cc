// C API for the coordination controller, consumed by Python via ctypes.
//
// The reference binds its engine through a per-framework compiled
// extension (reference: horovod/common/operations.cc:2040-2095 C API +
// horovod/common/__init__.py ctypes loader).  Here one flat C surface
// serves every frontend; batch lists travel back as wire-format bytes the
// Python side parses (no per-dtype symbol explosion).

#include <cstdlib>
#include <cstring>
#include <string>

#include "controller.h"
#include "wire.h"

using hvdtpu::BatchList;
using hvdtpu::Controller;
using hvdtpu::DType;
using hvdtpu::OpKind;
using hvdtpu::Request;

namespace {

void FillError(char* err_buf, int err_len, const std::string& msg) {
  if (err_buf && err_len > 0) {
    std::snprintf(err_buf, static_cast<size_t>(err_len), "%s", msg.c_str());
  }
}

uint8_t* CopyOut(const std::string& s, uint64_t* out_len) {
  auto* p = static_cast<uint8_t*>(std::malloc(s.size() ? s.size() : 1));
  std::memcpy(p, s.data(), s.size());
  *out_len = s.size();
  return p;
}

}  // namespace

extern "C" {

void* hvdtpu_controller_create(int rank, int size, const char* transport_spec,
                               long long fusion_threshold_bytes,
                               double stall_warning_s, char* err_buf,
                               int err_len) {
  // No exception may cross the C ABI (std::stoi on a malformed tcp port,
  // bad_alloc, ...): report through err_buf instead.
  try {
    std::string error;
    auto transport =
        hvdtpu::MakeTransport(transport_spec ? transport_spec : "", rank, size,
                              &error);
    if (!transport) {
      FillError(err_buf, err_len, error);
      return nullptr;
    }
    return new Controller(rank, size, std::move(transport),
                          fusion_threshold_bytes, stall_warning_s);
  } catch (const std::exception& e) {
    FillError(err_buf, err_len, e.what());
    return nullptr;
  }
}

void hvdtpu_controller_destroy(void* ctrl) {
  delete static_cast<Controller*>(ctrl);
}

int hvdtpu_controller_submit(void* ctrl, unsigned char kind,
                             unsigned char dtype, const char* name,
                             const long long* shape, int ndim, int root_rank,
                             long long group, unsigned char op_code) {
  if (!ctrl || !name || kind > 6 || dtype > 12 || op_code > 2) return -1;
  Request r;
  r.kind = static_cast<OpKind>(kind);
  r.dtype = static_cast<DType>(dtype);
  r.op_code = op_code;
  r.name = name;
  r.root_rank = root_rank;
  r.group = group;
  r.shape.assign(shape, shape + ndim);
  static_cast<Controller*>(ctrl)->Submit(std::move(r));
  return 0;
}

void hvdtpu_controller_request_shutdown(void* ctrl) {
  if (!ctrl) return;
  static_cast<Controller*>(ctrl)->RequestShutdown();
}

// Returns 0 on a live tick, 1 once shutdown has propagated, -1 on
// transport failure.  *out/*out_len receive wire-format BatchList bytes;
// free with hvdtpu_free.
int hvdtpu_controller_tick(void* ctrl, uint8_t** out, uint64_t* out_len) {
  *out = nullptr;
  *out_len = 0;
  if (!ctrl) return -1;
  BatchList bl;
  hvdtpu::TickStatus st;
  try {
    st = static_cast<Controller*>(ctrl)->Tick(&bl);
  } catch (const std::exception&) {
    return -1;
  }
  if (st == hvdtpu::TickStatus::kTransportError) return -1;
  *out = CopyOut(hvdtpu::wire::SerializeBatchList(bl), out_len);
  return st == hvdtpu::TickStatus::kShutdown ? 1 : 0;
}

int hvdtpu_controller_stall_report(void* ctrl, uint8_t** out,
                                   uint64_t* out_len) {
  if (!ctrl) return -1;
  *out = CopyOut(static_cast<Controller*>(ctrl)->StallReport(), out_len);
  return 0;
}

void hvdtpu_controller_enable_tick_trace(void* ctrl, int on) {
  if (!ctrl) return;
  static_cast<Controller*>(ctrl)->EnableTickTrace(on != 0);
}

// Control-plane autotune: install rank-0-tuned engine knobs (negative =
// leave that knob unchanged).  No-op on non-root ranks and null handles.
void hvdtpu_controller_set_tuned(void* ctrl, long long threshold_bytes,
                                 double cycle_ms) {
  if (!ctrl) return;
  static_cast<Controller*>(ctrl)->SetTuned(
      static_cast<int64_t>(threshold_bytes), cycle_ms);
}

// Drains rank-0's negotiation tick trace ("rank<SP>name\n" lines); empty on
// other ranks or when tracing is disabled.  Free with hvdtpu_free.
int hvdtpu_controller_drain_ticks(void* ctrl, uint8_t** out,
                                  uint64_t* out_len) {
  if (!ctrl) return -1;
  *out = CopyOut(static_cast<Controller*>(ctrl)->DrainTicks(), out_len);
  return 0;
}

void hvdtpu_free(uint8_t* p) { std::free(p); }

}  // extern "C"
