// Compact little-endian wire format for the control plane.
//
// The reference serializes its control messages with flatbuffers
// (reference: horovod/common/wire/mpi_message.fbs + 1.8k vendored LoC).
// The payloads here are tiny (names + shapes at ~5 ms cadence), so a
// hand-rolled length-prefixed format is simpler, has zero dependencies,
// and is trivially fuzzable.  All integers little-endian; strings and
// vectors are length-prefixed.

#ifndef HVDTPU_WIRE_H_
#define HVDTPU_WIRE_H_

#include <cmath>
#include <cstring>
#include <stdexcept>
#include <string>

#include "types.h"

namespace hvdtpu {
namespace wire {

class Writer {
 public:
  std::string Take() { return std::move(buf_); }

  void U8(uint8_t v) { buf_.push_back(static_cast<char>(v)); }
  void U32(uint32_t v) { Raw(&v, 4); }
  void I32(int32_t v) { Raw(&v, 4); }
  void I64(int64_t v) { Raw(&v, 8); }
  void Str(const std::string& s) {
    U32(static_cast<uint32_t>(s.size()));
    buf_.append(s);
  }

 private:
  void Raw(const void* p, size_t n) {
    buf_.append(reinterpret_cast<const char*>(p), n);
  }
  std::string buf_;
};

class Reader {
 public:
  Reader(const uint8_t* data, size_t len) : p_(data), end_(data + len) {}
  explicit Reader(const std::string& s)
      : Reader(reinterpret_cast<const uint8_t*>(s.data()), s.size()) {}

  uint8_t U8() {
    Need(1);
    return *p_++;
  }
  uint32_t U32() {
    uint32_t v;
    Need(4);
    std::memcpy(&v, p_, 4);
    p_ += 4;
    return v;
  }
  int32_t I32() {
    int32_t v;
    Need(4);
    std::memcpy(&v, p_, 4);
    p_ += 4;
    return v;
  }
  int64_t I64() {
    int64_t v;
    Need(8);
    std::memcpy(&v, p_, 8);
    p_ += 8;
    return v;
  }
  std::string Str() {
    uint32_t n = U32();
    Need(n);
    std::string s(reinterpret_cast<const char*>(p_), n);
    p_ += n;
    return s;
  }
  bool Done() const { return p_ == end_; }
  size_t Remaining() const { return static_cast<size_t>(end_ - p_); }

  // Read an element count and validate it against the bytes actually left
  // in the buffer (each element needs >= min_elem_bytes).  A corrupt or
  // hostile count prefix must fail the parse, not drive a giant reserve().
  uint32_t Count(size_t min_elem_bytes) {
    uint32_t n = U32();
    if (min_elem_bytes == 0) min_elem_bytes = 1;
    if (static_cast<size_t>(n) > Remaining() / min_elem_bytes)
      throw std::runtime_error("hvdtpu wire: implausible element count");
    return n;
  }

 private:
  void Need(size_t n) const {
    if (static_cast<size_t>(end_ - p_) < n)
      throw std::runtime_error("hvdtpu wire: truncated message");
  }
  const uint8_t* p_;
  const uint8_t* end_;
};

inline std::string SerializeRequestList(const RequestList& rl) {
  Writer w;
  w.U8(rl.shutdown ? 1 : 0);
  w.U32(static_cast<uint32_t>(rl.requests.size()));
  for (const Request& r : rl.requests) {
    w.U8(static_cast<uint8_t>(r.kind));
    w.U8(static_cast<uint8_t>(r.dtype));
    w.U8(r.op_code);
    w.I32(r.rank);
    w.I32(r.root_rank);
    w.I64(r.group);
    w.Str(r.name);
    w.U32(static_cast<uint32_t>(r.shape.size()));
    for (int64_t d : r.shape) w.I64(d);
  }
  return w.Take();
}

inline RequestList ParseRequestList(Reader& rd) {
  RequestList rl;
  rl.shutdown = rd.U8() != 0;
  // Min fixed bytes per request: kind+dtype+op_code+rank+root+group+2
  // counts = 27.
  uint32_t n = rd.Count(27);
  rl.requests.reserve(n);
  for (uint32_t i = 0; i < n; ++i) {
    Request r;
    r.kind = static_cast<OpKind>(rd.U8());
    r.dtype = static_cast<DType>(rd.U8());
    r.op_code = rd.U8();
    r.rank = rd.I32();
    r.root_rank = rd.I32();
    r.group = rd.I64();
    r.name = rd.Str();
    uint32_t nd = rd.Count(8);
    r.shape.reserve(nd);
    for (uint32_t j = 0; j < nd; ++j) r.shape.push_back(rd.I64());
    rl.requests.push_back(std::move(r));
  }
  return rl;
}

inline std::string SerializeBatchList(const BatchList& bl) {
  Writer w;
  w.U8(bl.shutdown ? 1 : 0);
  w.I64(bl.tuned_threshold_bytes);
  // Cycle time rides as micros in an i64: the wire stays integer-only.
  // llround, not a truncating cast: N/1000.0*1000.0 can land just below N
  // (e.g. 0.057 ms -> 56.999... µs) and truncation would change the value.
  w.I64(bl.tuned_cycle_ms < 0 ? -1 : llround(bl.tuned_cycle_ms * 1000.0));
  w.I32(bl.last_joined);
  w.U32(static_cast<uint32_t>(bl.batches.size()));
  for (const Batch& b : bl.batches) {
    w.U8(static_cast<uint8_t>(b.kind));
    w.U8(static_cast<uint8_t>(b.dtype));
    w.U8(b.op_code);
    w.Str(b.error);
    w.U32(static_cast<uint32_t>(b.names.size()));
    for (const std::string& nm : b.names) w.Str(nm);
    // shapes[] is parallel to names[]: one (ndim, dims...) per name.
    // Total even for malformed batches — a missing entry serializes as
    // scalar () rather than desynchronizing the stream.
    for (size_t j = 0; j < b.names.size(); ++j) {
      const std::vector<int64_t>* s = j < b.shapes.size() ? &b.shapes[j] : nullptr;
      w.U32(s ? static_cast<uint32_t>(s->size()) : 0);
      if (s)
        for (int64_t d : *s) w.I64(d);
    }
  }
  return w.Take();
}

inline BatchList ParseBatchList(Reader& rd) {
  BatchList bl;
  bl.shutdown = rd.U8() != 0;
  bl.tuned_threshold_bytes = rd.I64();
  const int64_t cyc_us = rd.I64();
  bl.tuned_cycle_ms = cyc_us < 0 ? -1.0 : cyc_us / 1000.0;
  bl.last_joined = rd.I32();
  // Min fixed bytes per batch: kind + dtype + op_code + error len +
  // name count = 11.
  uint32_t n = rd.Count(11);
  bl.batches.reserve(n);
  for (uint32_t i = 0; i < n; ++i) {
    Batch b;
    b.kind = static_cast<OpKind>(rd.U8());
    b.dtype = static_cast<DType>(rd.U8());
    b.op_code = rd.U8();
    b.error = rd.Str();
    uint32_t m = rd.Count(4);
    b.names.reserve(m);
    for (uint32_t j = 0; j < m; ++j) b.names.push_back(rd.Str());
    b.shapes.reserve(m);
    for (uint32_t j = 0; j < m; ++j) {
      uint32_t nd = rd.Count(8);
      std::vector<int64_t> s;
      s.reserve(nd);
      for (uint32_t k = 0; k < nd; ++k) s.push_back(rd.I64());
      b.shapes.push_back(std::move(s));
    }
    bl.batches.push_back(std::move(b));
  }
  return bl;
}

}  // namespace wire
}  // namespace hvdtpu

#endif  // HVDTPU_WIRE_H_
