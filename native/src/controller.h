// The coordination controller: named-tensor readiness negotiation.
//
// TPU-native re-design of the reference's coordinator protocol
// (reference: horovod/common/operations.cc — RunLoopOnce :1795-2007,
// IncrementTensorCount :302-327, ConstructMPIResponse :335-537, response
// fusion :1916-1943, stall check :1424-1470).  Frameworks enqueue named
// collectives in nondeterministic order per rank; the controller's job is
// global agreement on WHICH tensors run, in WHAT order, fused HOW.  Rank 0
// gathers every rank's request list each tick, matches readiness (a tensor
// is ready when all `size` ranks have requested it), validates consistency
// (kind/dtype/shape/root), fuses consecutive ready allreduces of one dtype
// under the fusion threshold, and broadcasts the resulting batch list.
// Every rank then dispatches identical batches in identical order — which
// is what lets the Python layer launch one compiled XLA collective per
// batch without SPMD-order guarantees from the frontend.
//
// The data plane never touches this code: batches carry tensor *names*;
// payloads stay in device HBM and move over ICI via XLA collectives.

#ifndef HVDTPU_CONTROLLER_H_
#define HVDTPU_CONTROLLER_H_

#include <cstdint>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "transport.h"
#include "types.h"

namespace hvdtpu {

// Outcome of one negotiation tick.  Transport failure is NOT shutdown:
// the Python layer must fail outstanding handles on kTransportError but
// treat kShutdown as the clean coordinated exit.
enum class TickStatus { kLive, kShutdown, kTransportError };

class Controller {
 public:
  Controller(int rank, int size, std::unique_ptr<Transport> transport,
             int64_t fusion_threshold_bytes, double stall_warning_s);

  int rank() const { return rank_; }
  int size() const { return size_; }

  // Enqueue a request from the frontend thread (thread-safe).
  void Submit(Request r);

  // Flag this rank's clean exit; propagated to all ranks on the next tick
  // (reference shutdown propagation, operations.cc:1699-1729).
  void RequestShutdown();

  // Run one negotiation round: gather -> match -> fuse -> bcast.
  // kShutdown once a shutdown response has been observed (sticky);
  // kTransportError when the control plane is broken (gather/bcast failed).
  TickStatus Tick(BatchList* out);

  // Rank-0 stall summary: tensors requested by a subset of ranks for longer
  // than the warning threshold, with the missing ranks (empty if none).
  std::string StallReport();

  // Control-plane autotune: rank 0's tuner installs new engine knobs here
  // (thread-safe).  Fusion batching is decided ONLY by rank 0's
  // BuildBatches, so the threshold takes effect for the whole gang at the
  // next tick; both values are also piggybacked on every response so all
  // ranks observe the move in the same tick (negative = leave unset).
  // No-op on non-root ranks — their local value would be a lie.
  void SetTuned(int64_t threshold_bytes, double cycle_ms);

  // Per-rank negotiation tick trace (reference timeline.cc:98-132 emits an
  // instant event on rank 0's timeline each time a rank's request for a
  // tensor arrives).  Off by default — recording without a consumer would
  // grow without bound; the Python engine enables it when HOROVOD_TIMELINE
  // is configured and drains after every tick.
  void EnableTickTrace(bool on);
  // Drains buffered events as "rank<SP>name\n" lines (rank 0 only).
  std::string DrainTicks();

 private:
  struct TableEntry {
    Request first;            // first-seen copy, the validation reference
    std::vector<bool> seen;   // which ranks have requested it
    int count = 0;
    std::string error;        // sticky validation error
    double first_seen_s = 0;  // monotonic arrival time of first request
    bool pushed = false;      // already emitted to a ready list this tick
  };

  void Ingest(const Request& r, std::vector<std::string>* ready);
  BatchList BuildBatches(const std::vector<std::string>& ready);

  // hvd.join support: an entry is complete when every rank has either
  // submitted it or joined (a joined rank's contribution is fabricated as
  // the identity by its engine).  Called under table_mu_.
  bool Complete(const TableEntry& e) const;
  // Emit `name` once if its entry just became complete; entries that
  // complete only via joined ranks are restricted to plain Sum/Average
  // allreduce — anything else needs a submission from every rank to agree
  // on the dispatch program.
  void MaybePush(const std::string& name, TableEntry& e,
                 std::vector<std::string>* ready);

  // Effective fusion threshold: the tuned value when set, else the
  // construction-time one.  Called under table_mu_.
  int64_t EffectiveThreshold() const {
    return tuned_threshold_bytes_ >= 0 ? tuned_threshold_bytes_
                                       : fusion_threshold_bytes_;
  }

  const int rank_, size_;
  const int64_t fusion_threshold_bytes_;
  const double stall_warning_s_;
  int64_t tuned_threshold_bytes_ = -1;  // guarded by table_mu_
  double tuned_cycle_ms_ = -1.0;        // guarded by table_mu_
  std::unique_ptr<Transport> transport_;

  std::mutex pending_mu_;
  std::vector<Request> pending_;
  bool shutdown_requested_ = false;
  bool shut_down_ = false;

  // Rank-0 only: the message table (reference operations.cc:1688-1690).
  // Guarded by table_mu_: Tick mutates it on the cycle thread while
  // StallReport reads it from the stall-watchdog thread.
  std::mutex table_mu_;
  std::map<std::string, TableEntry> table_;
  // hvd.join state (rank-0 only, guarded by table_mu_): joined ranks stop
  // blocking readiness; once all `size_` ranks joined, the response
  // carries the last joiner and the set resets for the next epoch.
  std::vector<bool> joined_;
  int joined_count_ = 0;
  int32_t last_joined_ = -1;
  bool tick_trace_enabled_ = false;           // guarded by table_mu_
  std::vector<std::pair<std::string, int>> tick_events_;  // guarded by table_mu_
};

}  // namespace hvdtpu

#endif  // HVDTPU_CONTROLLER_H_
