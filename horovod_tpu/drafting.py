"""Prompt-lookup drafting: free draft tokens for speculative decode.

Speculative decoding needs a proposal source; the classic recipe
(Leviathan et al. 2023) runs a second, smaller model.  Prompt-lookup
decoding (Saxena 2023; merged into HF transformers as
``prompt_lookup_num_tokens``) observes that for grounded workloads —
summarization, code edit, multi-turn chat, RAG — the continuation is
usually *already in the context*: find the most recent earlier
occurrence of the current suffix n-gram in the request's own
prompt+output history and propose the tokens that followed it.  No
draft model, no extra forward pass, no new device programs — the
drafter is pure host-side stdlib, and greedy longest-prefix acceptance
makes a bad proposal merely useless, never wrong (see
:func:`horovod_tpu.models.llama.spec_verify_paged`).

:class:`NgramDraftState` is the per-request object
:class:`~horovod_tpu.serving_scheduler.ServeEngine` hangs off each slot
when ``spec=True``: an **incremental** n-gram index (O(max_ngram) dict
updates per emitted token, O(max_ngram) lookups per proposal) so a
long-running row never rescans its history.

One alignment subtlety, documented here because it shapes
:meth:`NgramDraftState.propose`: the engine drafts *before* the tick
that emits the next token, so the token the drafts must continue
(``tok``, the argmax of the row's last logits) is still on device.  The
lookup therefore matches the suffix ending at the last *emitted* token;
the matched continuation's first element is the history's guess for
``tok`` itself and is **skipped** — the proposal starts one past it.
When the guess is right (the repeating case the drafter exists for) the
drafts align perfectly; when it is wrong they are rejected at position
0 by the verify program, which costs nothing beyond the already-fixed
``(draft_k + 1)``-wide tick.
"""

from __future__ import annotations

from typing import Iterable

#: Engine default for ``draft_k`` (the ``HVD_TPU_DRAFT_K`` knob).
DEFAULT_DRAFT_K = 4


class NgramDraftState:
    """Incremental n-gram lookup over one request's token history.

    ``tokens`` seeds the history (the engine passes prompt + replayed
    prior tokens); :meth:`extend` appends emitted tokens as they land.
    For each n in ``[min_ngram, max_ngram]`` the index maps every seen
    n-gram to the END positions (exclusive) of its two most recent
    occurrences plus its first — the two most recent because the
    current suffix is always its own latest occurrence and a proposal
    needs the one before it; the first as a fallback for short-period
    streams (e.g. a model stuck on one token), where *every* recent
    occurrence butts up against the end of the history and has no
    continuation left to propose from.
    """

    __slots__ = ("min_ngram", "max_ngram", "toks", "_index")

    def __init__(self, tokens: Iterable[int], *, max_ngram: int = 3,
                 min_ngram: int = 1):
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"[{min_ngram}, {max_ngram}]")
        self.min_ngram = min_ngram
        self.max_ngram = max_ngram
        self.toks: list[int] = []
        # one dict per n: gram -> (last_end, prev_end | None, first_end)
        self._index: list[dict[tuple[int, ...],
                               tuple[int, int | None, int]]] = [
            {} for _ in range(max_ngram - min_ngram + 1)]
        self.extend(tokens)

    def extend(self, tokens: Iterable[int]) -> None:
        """Append emitted tokens, updating the index incrementally."""
        for t in tokens:
            self.toks.append(int(t))
            i = len(self.toks)
            for n in range(self.min_ngram, self.max_ngram + 1):
                if i < n:
                    break
                d = self._index[n - self.min_ngram]
                gram = tuple(self.toks[i - n:i])
                prev = d.get(gram)
                d[gram] = ((i, prev[0], prev[2]) if prev is not None
                           else (i, None, i))

    def propose(self, k: int) -> list[int]:
        """Up to ``k`` draft tokens continuing the (still unknown)
        in-flight token, longest-n match first; ``[]`` when the history
        holds no earlier occurrence of any suffix n-gram (the verify
        tick then degrades to a plain decode for this row)."""
        L = len(self.toks)
        if k < 1:
            return []
        for n in range(min(self.max_ngram, L), self.min_ngram - 1, -1):
            ends = self._index[n - self.min_ngram].get(
                tuple(self.toks[L - n:]))
            if ends is None:
                continue
            # the suffix is always its own latest occurrence (last == L);
            # the previous one is the preferred (most recent) source of
            # the continuation, the first occurrence the fallback when
            # the previous one sits at the end of a short-period run
            # and has nothing after it
            recent = ends[1] if ends[0] == L else ends[0]
            for src in (recent, ends[2]):
                if src is None or src == L:
                    continue
                # toks[src] is the history's guess for the in-flight
                # token — skipped (see module docstring); drafts start
                # one past it
                cont = self.toks[src + 1:src + 1 + k]
                if cont:
                    return list(cont)
        return []
