"""Attention engines: dense, blockwise (online-softmax), ring, Ulysses.

Long-context sequence parallelism is absent from the reference (SURVEY.md §5
"Long-context / sequence parallelism: Absent"); the closest primitive is its
ragged allgather (operations.cc:841-901).  This module supplies the TPU-native
long-context stack as a first-class capability:

* :func:`dense_attention` — einsum softmax reference implementation.
* :func:`blockwise_attention` — ``lax.scan`` over KV chunks with the online
  (flash) softmax recurrence: O(L) memory, differentiable, jit-friendly.
* :func:`ring_attention` — sequence-parallel attention over a mesh axis:
  KV blocks rotate around the ring via ``lax.ppermute`` while each shard's
  queries accumulate, overlap-friendly on ICI (the pattern of Liu et al.'s
  Ring Attention, built from the same collective the reference's hierarchical
  allreduce uses for its ring leg).
* :func:`ulysses_attention` — DeepSpeed-Ulysses-style sequence parallelism:
  ``all_to_all`` seq→heads, full local attention, ``all_to_all`` back.

All functions take ``[B, L, H, Dh]`` Q and ``[B, L, KVH, Dh]`` K/V (GQA when
``KVH < H``) and accumulate in float32 regardless of input dtype.
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """GQA: expand KV heads to match query heads ([B, L, KVH, D] → [B, L, H, D])."""
    if n_rep == 1:
        return k
    b, l, kvh, d = k.shape
    return jnp.broadcast_to(
        k[:, :, :, None, :], (b, l, kvh, n_rep, d)
    ).reshape(b, l, kvh * n_rep, d)


def dense_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool = True,
    q_offset: int | jax.Array = 0, kv_offset: int | jax.Array = 0,
) -> jax.Array:
    """Reference O(L²)-memory attention (the ground truth for tests).

    ``q_offset``/``kv_offset`` are the global positions of element 0 of the
    q/kv sequence axes — needed for causal masking on sequence shards.
    """
    b, lq, h, d = q.shape
    kvh = k.shape[2]
    k = _repeat_kv(k, h // kvh)
    v = _repeat_kv(v, h // kvh)
    scale = 1.0 / math.sqrt(d)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        qpos = q_offset + jnp.arange(lq)[:, None]
        kpos = kv_offset + jnp.arange(k.shape[1])[None, :]
        s = jnp.where(qpos >= kpos, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


class _SoftmaxState(NamedTuple):
    """Online-softmax running state (the flash-attention recurrence)."""

    o: jax.Array      # [B, Lq, H, D] f32 unnormalized output accumulator
    m: jax.Array      # [B, H, Lq]    f32 running row max
    l: jax.Array      # [B, H, Lq]    f32 running row sum


def _init_state(q: jax.Array) -> _SoftmaxState:
    b, lq, h, d = q.shape
    return _SoftmaxState(
        o=jnp.zeros((b, lq, h, d), jnp.float32),
        m=jnp.full((b, h, lq), NEG_INF, jnp.float32),
        l=jnp.zeros((b, h, lq), jnp.float32),
    )


def _block_update(
    state: _SoftmaxState,
    q: jax.Array, k: jax.Array, v: jax.Array,
    *, causal: bool, q_offset=0, kv_offset=0,
    q_positions: jax.Array | None = None,
    kv_positions: jax.Array | None = None,
    kv_valid: jax.Array | None = None,
) -> _SoftmaxState:
    """Fold one KV block into the running softmax state.

    ``q_positions``/``kv_positions``: optional explicit [Lq]/[Lk] global
    position vectors for non-contiguous sequence layouts (zig-zag ring
    sharding); they override the ``*_offset + arange`` default.
    ``kv_valid``: optional [Lk] bool mask for padded tail keys.
    """
    b, lq, h, d = q.shape
    kvh = k.shape[2]
    r = h // kvh
    lk = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    # GQA via grouped einsum (query head g = kv·r + j ↔ kv head g // r,
    # the _repeat_kv mapping): fold the r query heads onto their KV head
    # instead of materializing the repeat-expanded K/V — in the ring this
    # block runs per rotation step, so the expansion would cost r× the KV
    # traffic every step.  The merged (kvh, r) axes are adjacent and in
    # head order, so the reshape back to [B, H, ...] is a free view.
    qg = q.reshape(b, lq, kvh, r, d)
    s = jnp.einsum("bqkjd,bmkd->bkjqm", qg.astype(jnp.float32),
                   k.astype(jnp.float32)).reshape(b, h, lq, lk) * scale
    if causal:
        qpos = (q_positions if q_positions is not None
                else q_offset + jnp.arange(lq))[:, None]
        kpos = (kv_positions if kv_positions is not None
                else kv_offset + jnp.arange(lk))[None, :]
        s = jnp.where(qpos >= kpos, s, NEG_INF)
    if kv_valid is not None:
        s = jnp.where(kv_valid[None, None, None, :], s, NEG_INF)
    m_new = jnp.maximum(state.m, s.max(axis=-1))
    # guard fully-masked rows: keep exp argument finite
    p = jnp.exp(s - m_new[..., None])
    correction = jnp.exp(state.m - m_new)
    l_new = state.l * correction + p.sum(axis=-1)
    o_new = (
        state.o * jnp.transpose(correction, (0, 2, 1))[..., None]
        + jnp.einsum("bkjqm,bmkd->bqkjd",
                     p.reshape(b, kvh, r, lq, lk),
                     v.astype(jnp.float32)).reshape(b, lq, h, d)
    )
    return _SoftmaxState(o_new, m_new, l_new)


def _finalize(state: _SoftmaxState, dtype) -> jax.Array:
    l = jnp.maximum(state.l, 1e-30)
    return (state.o / jnp.transpose(l, (0, 2, 1))[..., None]).astype(dtype)


def blockwise_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool = True,
    block_size: int = 512, q_offset=0, kv_offset=0,
) -> jax.Array:
    """O(L)-memory attention: scan over KV chunks with online softmax.

    Single-device analogue of ring attention (one ring step per local KV
    block); also the differentiable fallback the pallas flash kernel's
    backward recomputes through.
    """
    b, lkv, kvh, d = k.shape
    nblocks = max(1, math.ceil(lkv / block_size))
    pad = nblocks * block_size - lkv
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    kb = k.reshape(b, nblocks, block_size, kvh, d).transpose(1, 0, 2, 3, 4)
    vb = v.reshape(b, nblocks, block_size, kvh, d).transpose(1, 0, 2, 3, 4)

    def step(state, inputs):
        i, kblk, vblk = inputs
        valid = (i * block_size + jnp.arange(block_size)) < lkv
        new = _block_update(
            state, q, kblk, vblk, causal=causal,
            q_offset=q_offset,
            kv_offset=kv_offset + i * block_size,
            kv_valid=valid if pad else None,
        )
        return new, None

    idx = jnp.arange(nblocks)
    state, _ = lax.scan(step, _init_state(q), (idx, kb, vb))
    return _finalize(state, q.dtype)


def zigzag_positions(rank, n: int, local_len: int) -> jax.Array:
    """Global positions of rank ``rank``'s local sequence slice under
    zig-zag sharding: the sequence is cut into ``2n`` blocks and rank r
    holds blocks ``r`` (head half) and ``2n-1-r`` (tail half), so every
    rank's causal workload is equal.  ``rank`` may be a traced scalar."""
    block = local_len // 2
    head = rank * block + jnp.arange(block)
    tail = (2 * n - 1 - rank) * block + jnp.arange(block)
    return jnp.concatenate([head, tail])


def _zigzag_order(n: int) -> list[int]:
    """Block layout of the zig-zag shard: ``0, 2n-1, 1, 2n-2, …, n-1, n`` —
    slice r of a contiguous shard over n ranks is blocks ``(r, 2n-1-r)``.
    The single source of truth for :func:`zigzag_shard`/``unshard`` and
    consistent with :func:`zigzag_positions` (tested against each other)."""
    order: list[int] = []
    for r in range(n):
        order.extend([r, 2 * n - 1 - r])
    return order


def _permute_blocks(x: jax.Array, n: int, axis: int, perm: list[int]) -> jax.Array:
    l = x.shape[axis]
    if l % (2 * n):
        raise ValueError(f"sequence length {l} not divisible by 2n={2 * n}")
    block = l // (2 * n)
    xs = jnp.moveaxis(x, axis, 0).reshape(2 * n, block, *[
        s for i, s in enumerate(x.shape) if i != axis
    ])
    xs = xs[jnp.asarray(perm)]
    return jnp.moveaxis(xs.reshape(l, *xs.shape[2:]), 0, axis)


def zigzag_shard(x: jax.Array, n: int, *, axis: int = 1) -> jax.Array:
    """Reorder a global sequence axis so that *contiguous* sharding over an
    ``n``-way mesh axis hands each rank its zig-zag block pair (see
    :func:`_zigzag_order`).  Inverse: :func:`zigzag_unshard`.
    """
    return _permute_blocks(x, n, axis, _zigzag_order(n))


def zigzag_unshard(x: jax.Array, n: int, *, axis: int = 1) -> jax.Array:
    """Inverse permutation of :func:`zigzag_shard`."""
    order = _zigzag_order(n)
    inverse = [0] * len(order)
    for pos, blk in enumerate(order):
        inverse[blk] = pos
    return _permute_blocks(x, n, axis, inverse)


def ring_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, axis_name: str,
    causal: bool = True, zigzag: bool = False,
) -> jax.Array:
    """Sequence-parallel ring attention over ``axis_name``.

    Call inside ``shard_map`` where the sequence axis is sharded: each rank
    holds ``[B, L/n, H, D]`` Q/K/V chunks.  KV rotates around the ring
    (``lax.ppermute``, reference-equivalent of the NCCL ring's neighbor
    exchange) while local queries fold each visiting block into the online
    softmax.  n-1 permutes, O(L/n) memory per chip, compute/comm overlap
    scheduled by XLA.

    Causality across chunks with contiguous sharding: rank r's queries
    attend fully to KV from ranks < r, causally to its own, not at all to
    ranks > r — masked blocks idle early ranks (the classic ring-attention
    load skew).  ``zigzag=True`` removes the skew: inputs must be laid out
    by :func:`zigzag_shard` (rank r holds sequence blocks r and 2n-1-r), so
    every rank does the same causal work per ring step; the output stays in
    zig-zag layout (undo with :func:`zigzag_unshard` after unsharding).
    """
    n = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    lc = q.shape[1]
    if zigzag and lc % 2:
        raise ValueError(f"zigzag ring needs an even local length, got {lc}")
    pos = (lambda r: zigzag_positions(r, n, lc)) if zigzag else (
        lambda r: r * lc + jnp.arange(lc)
    )
    qpos = pos(rank)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, i):
        state, kcur, vcur = carry
        src_rank = (rank - i) % n  # whose chunk we currently hold
        state = _block_update(
            state, q, kcur, vcur, causal=causal,
            q_positions=qpos, kv_positions=pos(src_rank),
        )
        knext = lax.ppermute(kcur, axis_name, perm)
        vnext = lax.ppermute(vcur, axis_name, perm)
        return (state, knext, vnext), None

    # n-1 rotated steps in the scan, last block folded outside it — the
    # final rotation's result would be discarded, and XLA cannot DCE a
    # collective inside the scan body (one full KV exchange saved per call).
    state = _init_state(q)
    # The zero-init state is unvarying over the mesh axis while the
    # updated state varies with this rank's q — under shard_map's
    # varying-axes check (check_vma, on by default) the scan carry types
    # would then mismatch.  Mark the init as varying so callers don't
    # need check_vma=False.
    _pvary = (functools.partial(lax.pcast, to="varying")
              if hasattr(lax, "pcast") else lax.pvary)  # jax < 0.8
    state = jax.tree.map(lambda x: _pvary(x, axis_name), state)
    if n > 1:
        (state, k, v), _ = lax.scan(step, (state, k, v), jnp.arange(n - 1))
    state = _block_update(
        state, q, k, v, causal=causal,
        q_positions=qpos, kv_positions=pos((rank - (n - 1)) % n),
    )
    return _finalize(state, q.dtype)


def ulysses_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, axis_name: str,
    causal: bool = True, impl=None,
) -> jax.Array:
    """All-to-all sequence parallelism (DeepSpeed Ulysses pattern).

    Inside ``shard_map`` with sequence sharded: all-to-all re-shards from
    [B, L/n, H, D] (seq-sharded) to [B, L, H/n, D] (head-sharded), runs full
    attention on the n-th of the heads, and all-to-alls back.  Requires
    ``H % n == 0``; one balanced a2a each way rides ICI's full bisection
    bandwidth.

    GQA with fewer KV heads than the axis (``KVH < n``): KV heads are
    expanded to ``n`` before their a2a (``n % KVH == 0`` required), so each
    device carries one (replicated-group) KV head.  The mapping stays
    consistent: device i's query heads [i·H/n, (i+1)·H/n) all belong to
    original KV head ``i // (n/KVH)``, which is exactly what expanded head
    i holds.  Costs (n/KVH)× the KV a2a bytes — still far below the q/o
    legs when H ≫ KVH, and it is what makes 8-way Ulysses possible on
    4-KV-head models at all.
    """
    n = lax.axis_size(axis_name)
    h, kvh = q.shape[2], k.shape[2]
    if h % n or (kvh % n if kvh >= n else n % kvh):
        raise ValueError(
            f"ulysses_attention needs H divisible by the axis size and "
            f"KVH % n == 0 or n % KVH == 0: H={h}, KVH={kvh}, n={n}"
        )
    if kvh < n:
        k = _repeat_kv(k, n // kvh)
        v = _repeat_kv(v, n // kvh)
    # seq-sharded → head-sharded
    qh = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kh = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    attend = impl or dense_attention
    oh = attend(qh, kh, vh, causal=causal)
    # head-sharded → seq-sharded
    return lax.all_to_all(oh, axis_name, split_axis=1, concat_axis=2, tiled=True)
