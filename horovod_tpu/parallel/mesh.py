"""Multi-axis device meshes: dp / tp / sp / ep (+ ici/dcn nesting).

The reference's only topology concepts are world/local/cross MPI
communicators (reference horovod/common/operations.cc:1527-1590).  On TPU
the topology IS the mesh: this module builds the named meshes every
parallelism strategy composes over, with the DCN (multi-slice) axis
outermost so collectives ride ICI within a slice — the mesh-native form of
the reference's hierarchical allreduce (operations.cc:1070-1223).
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    *,
    dp: int = 1,
    tp: int = 1,
    sp: int = 1,
    ep: int = 1,
    pp: int = 1,
    devices: Sequence[jax.Device] | None = None,
    dcn_slices: int = 1,
) -> Mesh:
    """Build a named mesh ``(['dcn',] 'pp', 'dp', 'ep', 'sp', 'tp')``.

    Axes of size 1 are kept (zero-cost in XLA; specs stay uniform).  ``tp``
    is innermost so tensor-parallel collectives (the most latency-sensitive)
    map to nearest-neighbor ICI links; ``dcn_slices`` adds an outermost axis
    for multi-slice jobs so cross-slice traffic is isolated to DCN.
    """
    devs = list(devices) if devices is not None else jax.devices()
    shape = [dcn_slices, pp, dp, ep, sp, tp]
    names = ["dcn", "pp", "dp", "ep", "sp", "tp"]
    for name, size in zip(names, shape):
        if size < 1:
            raise ValueError(
                f"mesh axis {name!r} must be >= 1, got {size}"
            )
    total = int(np.prod(shape))
    if len(devs) != total:
        raise ValueError(
            f"mesh axes {dict(zip(names, shape))} need "
            f"{total} devices (product of axis sizes), have {len(devs)}: "
            f"device count must equal the axis product exactly"
        )
    if dcn_slices == 1:
        shape, names = shape[1:], names[1:]
        # Topology-aware placement: mesh_utils orders devices so the
        # innermost axes (tp) land on nearest-neighbor ICI links.  Falls
        # back to list order where topology info is unavailable (CPU mesh).
        try:
            from jax.experimental import mesh_utils

            arr = mesh_utils.create_device_mesh(tuple(shape), devices=devs)
        except Exception:
            arr = np.asarray(devs).reshape(shape)
        return Mesh(arr, tuple(names))
    try:
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_hybrid_device_mesh(
            tuple(shape[1:]), dcn_mesh_shape=(dcn_slices,) + (1,) * (len(shape) - 1),
            devices=devs,
        ).reshape(shape)
    except Exception:
        arr = np.asarray(devs).reshape(shape)
    return Mesh(arr, tuple(names))


def data_parallel_mesh(devices=None) -> Mesh:
    """1-D DP mesh with the Horovod axis name — the same world
    :func:`horovod_tpu.init` builds (basics.py); reuses AXIS_NAME so
    shard_map code works against either."""
    from horovod_tpu.basics import AXIS_NAME

    devs = np.asarray(devices if devices is not None else jax.devices())
    if devs.ndim != 1 or devs.size == 0:
        raise ValueError(
            f"data_parallel_mesh needs a non-empty flat device list, got "
            f"{devs.size} devices with shape {tuple(devs.shape)}"
        )
    return Mesh(devs, (AXIS_NAME,))


def tensor_parallel_mesh(
    tp_size: int,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Serving-facing 1-axis ``('tp',)`` mesh over ``tp_size`` devices.

    The inference engine shards attention heads / MLP columns / the paged
    KV pool over this one axis (``models/llama.py`` partition specs);
    keeping the mesh 1-D means one replica == one tp group and the block
    pool stays host-side and shard-agnostic.  Uses the first ``tp_size``
    devices — on a real slice those are ICI neighbors by enumeration
    order, on a faked CPU host they are the virtual devices.
    """
    if tp_size < 1:
        raise ValueError(f"tensor_parallel_mesh needs tp_size >= 1, got {tp_size}")
    devs = list(devices) if devices is not None else jax.devices()
    if len(devs) < tp_size:
        raise ValueError(
            f"tensor_parallel_mesh(tp_size={tp_size}) needs {tp_size} "
            f"devices, have {len(devs)}"
        )
    return Mesh(np.asarray(devs[:tp_size]), ("tp",))
