"""Parallelism strategies: DP (the reference capability), plus TP/SP (ring
and Ulysses attention), PP (GPipe-style pipeline), and EP (MoE all-to-all,
horovod_tpu.models.moe) as TPU-native extensions (SURVEY.md §2.3)."""

from horovod_tpu.parallel.attention import (  # noqa: F401
    blockwise_attention,
    dense_attention,
    ring_attention,
    ulysses_attention,
    zigzag_positions,
    zigzag_shard,
    zigzag_unshard,
)
from horovod_tpu.parallel.flash_attention import flash_attention  # noqa: F401
from horovod_tpu.parallel.mesh import (  # noqa: F401
    data_parallel_mesh,
    make_mesh,
    tensor_parallel_mesh,
)
from horovod_tpu.parallel.pipeline import (  # noqa: F401
    pipeline_forward,
    pipeline_loss_fn,
    stack_stage_params,
)
