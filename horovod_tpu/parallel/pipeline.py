"""Pipeline parallelism — GPipe-style microbatch schedule over a mesh axis.

No reference equivalent (the reference is data-parallel only, SURVEY.md
§2.3); this supplies the PP axis of the parallelism matrix, TPU-first:

* Stages are mesh positions on the ``pp`` axis; stage-to-stage transfer is
  one ``lax.ppermute`` hop per tick — nearest-neighbour ICI traffic.
* The schedule is a single ``lax.scan`` over ``M + S - 1`` ticks (fill +
  steady state + drain), so the whole pipeline is ONE compiled program —
  no per-microbatch dispatch from Python.
* Backward needs no extra code: ``ppermute`` transposes to the reverse
  permutation under ``jax.grad``, so reverse-mode AD derives the 1F1B-ish
  backward communication automatically.

Use under ``shard_map`` with ``in_specs`` placing ``stage_params`` leading
axis and the microbatch axis of ``x`` on the ``pp`` axis — see
:func:`pipeline_loss_fn` for the packaged form.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_forward(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    *,
    axis_name: str = "pp",
    remat: bool = False,
) -> jax.Array:
    """Run ``stage_fn`` as a pipeline over the ``axis_name`` mesh axis.

    Args:
      stage_fn: ``(params_for_this_stage, activations) -> activations``;
        activations keep one shape across stages.
      stage_params: this device's stage parameters.  NOTE: under shard_map
        a ``P('pp', ...)`` in_spec shards the stacked leading axis down to
        size 1 but does NOT squeeze it — strip it first
        (``jax.tree.map(lambda a: a[0], params)``), as
        :func:`pipeline_loss_fn` does.
      x: microbatched input ``[M, mb, ...]``, meaningful on stage 0 (other
        stages may pass the same array; it is ignored there).
      remat: rematerialize the stage body in backward — AD then stores one
        activation per tick instead of every intermediate inside
        ``stage_fn`` (the deep-stage memory lever; costs ~1/3 extra FLOPs).

    Returns:
      ``[M, mb, ...]`` outputs, valid on the LAST stage (zeros elsewhere —
      mask by ``lax.axis_index(axis_name) == S-1`` when reducing a loss;
      :func:`pipeline_loss_fn` does this for you).
    """
    s = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    m = x.shape[0]
    ticks = m + s - 1

    fwd_perm = [(i, (i + 1) % s) for i in range(s)]
    # prevent_cse=False: the checkpointed body is differentiated under
    # lax.scan, where the CSE-prevention barriers are unnecessary and block
    # XLA fusion (the jax.checkpoint-documented scan-over-layers setting).
    body = (jax.checkpoint(stage_fn, prevent_cse=False) if remat
            else stage_fn)

    def tick(carry, t):
        recv, ys = carry
        # Stage 0 injects microbatch t (fill phase); later stages consume
        # what the previous tick's ppermute delivered.
        mb = lax.dynamic_index_in_dim(x, jnp.minimum(t, m - 1), 0,
                                      keepdims=False)
        inp = jnp.where(stage == 0, mb.astype(recv.dtype), recv)
        out = body(stage_params, inp)
        # Last stage banks its result at microbatch slot t - (S - 1).
        slot = t - (s - 1)
        ys = lax.cond(
            (stage == s - 1) & (slot >= 0),
            lambda ys: lax.dynamic_update_index_in_dim(ys, out, jnp.maximum(slot, 0), 0),
            lambda ys: ys,
            ys,
        )
        recv = lax.ppermute(out, axis_name, fwd_perm)
        return (recv, ys), None

    recv0 = jnp.zeros_like(stage_fn(stage_params, x[0]))
    ys0 = jnp.zeros((m,) + recv0.shape, recv0.dtype)
    (_, ys), _ = lax.scan(tick, (recv0, ys0), jnp.arange(ticks))
    return ys


def pipeline_loss_fn(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    loss_fn: Callable[[jax.Array, Any], jax.Array],
    *,
    axis_name: str = "pp",
    remat: bool = False,
) -> Callable[[Any, tuple[jax.Array, Any]], jax.Array]:
    """Package a per-stage body + final loss into a pipeline loss.

    Returns ``fn(stage_params, (x_micro, target_micro)) -> scalar`` for use
    under shard_map: runs the pipeline, evaluates ``loss_fn(outputs,
    targets)`` per microbatch on the last stage, and ``psum``s the masked
    mean so every stage returns the same scalar (gradients flow back
    through the ppermute chain).
    """

    def fn(stage_params, batch):
        # Consume the pp-sharded leading axis (shard_map shards it to
        # size 1 but does not squeeze it).
        stage_params = jax.tree.map(lambda a: a[0], stage_params)
        x_micro, tgt_micro = batch
        ys = pipeline_forward(stage_fn, stage_params, x_micro,
                              axis_name=axis_name, remat=remat)
        s = lax.axis_size(axis_name)
        is_last = (lax.axis_index(axis_name) == s - 1).astype(jnp.float32)
        losses = jax.vmap(loss_fn)(ys, tgt_micro)       # [M]
        local = jnp.mean(losses) * is_last
        # VALUE: replicate via psum so every stage reports the true loss.
        # GRADIENT: must flow from the LOCAL term only — under
        # value_and_grad-inside-shard_map every device seeds a cotangent
        # for its replicated copy, and psum's transpose would sum those S
        # seeds into an S-times-too-large gradient.  stop_gradient on the
        # correction keeps the grad path single-sourced (the last stage),
        # whose cotangents reach earlier stages through the ppermute
        # transposes.
        total = lax.psum(local, axis_name)
        return local + lax.stop_gradient(total - local)

    return fn


def stack_stage_params(params_per_stage: list) -> Any:
    """Stack per-stage parameter pytrees on a leading axis for ``pp``
    sharding (``in_specs=P('pp', ...)`` consumes it under shard_map)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *params_per_stage)
