"""Pallas flash-attention kernel (TPU).

The hot op of the flagship transformer, written for the MXU/VMEM model of
/opt/skills/guides/pallas_guide.md: the KV loop is the innermost grid
dimension, the online-softmax state (acc / row-max / row-sum) lives in VMEM
scratch that persists across KV steps, and the normalized output tile is
written once on the last step.  Causally-masked-out KV blocks are skipped
with ``pl.when`` (no wasted MXU work past the diagonal).

Backward: the standard two-pass flash scheme as two more pallas kernels —
the forward saves the per-row log-sum-exp, ``delta = rowsum(dO·O)`` is
computed in XLA, then one kernel accumulates dK/dV over query blocks and one
accumulates dQ over key blocks.  No [L, L] materialization anywhere, and the
training hot path stays at MXU-kernel speed end to end.  Set
``HVD_TPU_FLASH_BWD=blockwise`` to fall back to recomputing gradients
through :func:`horovod_tpu.parallel.attention.blockwise_attention` (the
cross-check oracle the tests compare against).

On non-TPU backends the kernels run in interpreter mode so the whole test
matrix exercises the same code path on the CPU mesh.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from horovod_tpu.parallel.attention import blockwise_attention

NEG_INF = -1e30


def _out_vma(*arrays):
    """Varying-mesh-axes set for kernel outputs: the union of the inputs'.

    Under ``shard_map``'s default varying-axes check a ``pallas_call``
    out_shape with no ``vma`` is an error — declaring "varies like the
    inputs" lets the flash kernels run without ``check_vma=False``.
    Outside shard_map every input vma is empty → ``None`` (a plain aval).
    """
    typeof = getattr(jax, "typeof", None)
    if typeof is None:             # older jax: no vma system at all
        return None
    vma: frozenset = frozenset()
    for a in arrays:
        vma = vma | getattr(typeof(a), "vma", frozenset())
    return vma or None


def _sds(shape, dtype, vma):
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:          # older jax: no vma parameter
        return jax.ShapeDtypeStruct(shape, dtype)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                  *, scale: float, causal: bool, block_q: int, block_k: int,
                  seq_len: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = qi * block_q
    k_start = ki * block_k

    # Skip blocks entirely above the causal diagonal (no MXU work there).
    @pl.when((not causal) or (k_start <= q_start + block_q - 1))
    def _compute():
        # Operands stay in their storage dtype (bf16 in training): the MXU
        # runs bf16×bf16→f32 at full rate, while upcasting operands first
        # would force f32×f32 matmuls at a fraction of peak.  All
        # accumulation below is f32 via preferred_element_type / scratch.
        q = q_ref[0]                                 # [bq, D]
        k = k_ref[0]                                 # [bk, D]
        v = v_ref[0]                                 # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                    # [bq, bk] f32
        qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = kpos < seq_len                        # padded tail keys
        if causal:
            mask = mask & (qpos >= kpos)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:, 0:1]                       # [bq, 1]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                       # [bq, bk]
        corr = jnp.exp(m_prev - m_new)               # [bq, 1]
        l_ref[:, 0:1] = l_ref[:, 0:1] * corr + p.sum(axis=-1, keepdims=True)
        m_ref[:, 0:1] = m_new
        # p rides the MXU in the storage dtype (standard flash practice —
        # the f32 row-sum/max state above carries the precision).
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, 0:1], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        # Per-row log-sum-exp, saved for the backward kernels.
        lse_ref[0] = m_ref[:, 0:1] + jnp.log(l)


def _flash_forward(q, k, v, *, n_heads: int, n_kv_heads: int, causal: bool,
                   block_q: int, block_k: int, interpret: bool) -> jax.Array:
    """q: [B·H, L, D]; k/v: [B·KVH, L, D] — GQA resolved by the KV BlockSpec
    index map (head ``bh`` reads kv head ``bh%H // (H/KVH)``), so each KV
    tile is fetched once per group instead of being materialized H/KVH×."""
    bh, l, d = q.shape
    n_rep = n_heads // n_kv_heads
    nq = math.ceil(l / block_q)
    nk = math.ceil(l / block_k)
    lq_pad, lk_pad = nq * block_q, nk * block_k
    if lq_pad != l:
        q = jnp.pad(q, ((0, 0), (0, lq_pad - l), (0, 0)))
    if lk_pad != l:
        k = jnp.pad(k, ((0, 0), (0, lk_pad - l), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, lk_pad - l), (0, 0)))
    kernel = functools.partial(
        _flash_kernel,
        scale=1.0 / math.sqrt(d),
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        seq_len=l,
    )

    def kv_index(b, i, j):
        batch = b // n_heads
        head = b % n_heads
        return (batch * n_kv_heads + head // n_rep, j, 0)

    vma = _out_vma(q, k, v)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), kv_index, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), kv_index, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            _sds((bh, lq_pad, d), q.dtype, vma),
            _sds((bh, lq_pad, 1), jnp.float32, vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),    # acc
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max
            pltpu.VMEM((block_q, 128), jnp.float32),  # running sum
        ],
        interpret=interpret,
    )(q, k, v)
    return out[:, :l], lse


def _mask_scores(causal, q_start, k_start, block_q, block_k, seq_len):
    qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    mask = kpos < seq_len
    if causal:
        mask = mask & (qpos >= kpos)
    return mask


def _flash_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                     acc_ref, *, scale, causal, block_q, block_k, seq_len):
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start, k_start = qi * block_q, ki * block_k

    @pl.when((not causal) or (k_start <= q_start + block_q - 1))
    def _compute():
        # Storage-dtype operands on the MXU, f32 accumulation — see the
        # forward kernel's note.
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        mask = _mask_scores(causal, q_start, k_start, block_q, block_k, seq_len)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse_ref[0])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0]) * scale
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _flash_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dk_ref, dv_ref, dk_acc, dv_acc,
                      *, scale, causal, block_q, block_k, seq_len):
    ki, qi = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start, k_start = qi * block_q, ki * block_k

    # Skip q blocks entirely above the causal diagonal (p would be all 0).
    @pl.when((not causal) or (q_start + block_q - 1 >= k_start))
    def _compute():
        # Storage-dtype operands on the MXU, f32 accumulation — see the
        # forward kernel's note.
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        mask = _mask_scores(causal, q_start, k_start, block_q, block_k, seq_len)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse_ref[0])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0]) * scale
        # Contract the query (sublane) dim of both operands — dK/dV tiles
        # accumulate without any materialized transpose.
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse, g, *, n_heads, n_kv_heads, causal,
                    block_q, block_k, interpret):
    """Two-pass flash backward: dQ kernel + dK/dV kernel.

    q/o/g: [B·H, L, D]; k/v: [B·KVH, L, D]; lse: [B·H, Lq_pad, 1].
    dK/dV are computed at query-head resolution (KV tiles read through the
    same GQA index map as the forward) and group-summed to KV heads outside.
    """
    bh, l, d = q.shape
    n_rep = n_heads // n_kv_heads
    nq = math.ceil(l / block_q)
    nk = math.ceil(l / block_k)
    lq_pad, lk_pad = nq * block_q, nk * block_k
    delta = (g.astype(jnp.float32) * o.astype(jnp.float32)).sum(-1)  # [BH, L]
    if lq_pad != l:
        q = jnp.pad(q, ((0, 0), (0, lq_pad - l), (0, 0)))
        g = jnp.pad(g, ((0, 0), (0, lq_pad - l), (0, 0)))
        delta = jnp.pad(delta, ((0, 0), (0, lq_pad - l)))
    if lk_pad != l:
        k = jnp.pad(k, ((0, 0), (0, lk_pad - l), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, lk_pad - l), (0, 0)))
    delta = delta[..., None]                                         # [BH, Lq, 1]
    scale = 1.0 / math.sqrt(d)
    vma = _out_vma(q, k, v, g)

    def kv_index(b, i, j):
        batch = b // n_heads
        head = b % n_heads
        return (batch * n_kv_heads + head // n_rep, j, 0)

    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                          memory_space=pltpu.VMEM)
    r_spec = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, block_k, d), kv_index,
                           memory_space=pltpu.VMEM)

    dq = pl.pallas_call(
        functools.partial(
            _flash_dq_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, seq_len=l,
        ),
        grid=(bh, nq, nk),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, r_spec, r_spec],
        out_specs=q_spec,
        out_shape=_sds((bh, lq_pad, d), q.dtype, vma),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, g, lse, delta)

    # dK/dV: kv blocks in the second grid dim, q innermost; per-q-head
    # output tiles indexed by the *query* head so GQA groups don't race.
    qk_spec = pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0),
                           memory_space=pltpu.VMEM)
    rk_spec = pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0),
                           memory_space=pltpu.VMEM)
    kvk_spec = pl.BlockSpec(
        (1, block_k, d),
        lambda b, j, i: kv_index(b, i, j),
        memory_space=pltpu.VMEM,
    )
    dkv_out_spec = pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0),
                                memory_space=pltpu.VMEM)
    dk_h, dv_h = pl.pallas_call(
        functools.partial(
            _flash_dkv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, seq_len=l,
        ),
        grid=(bh, nk, nq),
        in_specs=[qk_spec, kvk_spec, kvk_spec, qk_spec, rk_spec, rk_spec],
        out_specs=[dkv_out_spec, dkv_out_spec],
        out_shape=[
            _sds((bh, lk_pad, d), k.dtype, vma),
            _sds((bh, lk_pad, d), v.dtype, vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, g, lse, delta)

    b = bh // n_heads
    dk = dk_h.reshape(b, n_kv_heads, n_rep, lk_pad, d).sum(2)
    dv = dv_h.reshape(b, n_kv_heads, n_rep, lk_pad, d).sum(2)
    return (
        dq[:, :l],
        dk.reshape(b * n_kv_heads, lk_pad, d)[:, :l].astype(k.dtype),
        dv.reshape(b * n_kv_heads, lk_pad, d)[:, :l].astype(v.dtype),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, n_heads, n_kv_heads, causal, block_q, block_k, bwd_impl):
    interpret = jax.default_backend() != "tpu"
    out, _ = _flash_forward(q, k, v, n_heads=n_heads, n_kv_heads=n_kv_heads,
                            causal=causal, block_q=block_q, block_k=block_k,
                            interpret=interpret)
    return out


def _flash_fwd(q, k, v, n_heads, n_kv_heads, causal, block_q, block_k,
               bwd_impl):
    interpret = jax.default_backend() != "tpu"
    out, lse = _flash_forward(q, k, v, n_heads=n_heads, n_kv_heads=n_kv_heads,
                              causal=causal, block_q=block_q, block_k=block_k,
                              interpret=interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(n_heads, n_kv_heads, causal, block_q, block_k, bwd_impl,
               res, g):
    q, k, v, o, lse = res
    if bwd_impl == "blockwise":
        # Cross-check oracle: recompute gradients through the XLA blockwise
        # scan instead of the pallas kernels.
        b = q.shape[0] // n_heads
        l, d = q.shape[1], q.shape[2]

        def ref(q, k, v):
            qb = q.reshape(b, n_heads, l, d).transpose(0, 2, 1, 3)
            kb = k.reshape(b, n_kv_heads, l, d).transpose(0, 2, 1, 3)
            vb = v.reshape(b, n_kv_heads, l, d).transpose(0, 2, 1, 3)
            out = blockwise_attention(qb, kb, vb, causal=causal,
                                      block_size=block_k)
            return out.transpose(0, 2, 1, 3).reshape(b * n_heads, l, d)

        _, vjp = jax.vjp(ref, q, k, v)
        return vjp(g)
    interpret = jax.default_backend() != "tpu"
    return _flash_backward(
        q, k, v, o, lse, g, n_heads=n_heads, n_kv_heads=n_kv_heads,
        causal=causal, block_q=block_q, block_k=block_k, interpret=interpret,
    )


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool = True,
    block_q: int = 512, block_k: int = 512, bwd: str | None = None,
) -> jax.Array:
    """Flash attention for [B, L, H, D] q and [B, L, KVH, D] k/v (GQA ok).

    Forward on the MXU via pallas — KV stays at KVH heads, grouped heads
    share tiles through the BlockSpec index map.  Backward is the two-pass
    pallas scheme (dQ kernel + dK/dV kernel over saved log-sum-exp), O(L)
    memory.  Blocks are clamped to the sequence length.

    ``bwd``: ``"pallas"`` (default) or ``"blockwise"`` — the cross-check
    oracle that recomputes gradients through the XLA blockwise scan.  The
    choice is resolved at TRACE time (``HVD_TPU_FLASH_BWD`` env var when
    ``bwd`` is None); under jit it is baked into the compiled program, so
    switching an existing step function requires rebuilding it (fresh jit)
    or passing ``bwd=`` explicitly.
    """
    import os

    bwd_impl = (bwd or os.environ.get("HVD_TPU_FLASH_BWD", "pallas")).lower()
    if bwd_impl not in ("pallas", "blockwise"):
        raise ValueError(f"bwd must be 'pallas' or 'blockwise', got {bwd!r}")
    if not (q.dtype == k.dtype == v.dtype):
        # The kernels run matmuls on the operands' storage dtype (full-rate
        # bf16 MXU); mixed inputs would otherwise die deep inside a
        # dot_general trace.  Cast at the call site — typically the KV
        # cache's dtype is the one to keep.
        raise ValueError(
            f"flash_attention requires q/k/v of one dtype, got "
            f"{q.dtype}/{k.dtype}/{v.dtype}"
        )
    b, l, h, d = q.shape
    kvh = k.shape[2]
    block_q = min(block_q, max(l, 1))
    block_k = min(block_k, max(l, 1))
    # [B, L, H, D] → [B*H, L, D]
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, l, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * kvh, l, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * kvh, l, d)
    out = _flash(qt, kt, vt, h, kvh, causal, block_q, block_k, bwd_impl)
    return out.reshape(b, h, l, d).transpose(0, 2, 1, 3)
