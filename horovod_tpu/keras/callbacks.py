"""Keras-3 callbacks with the reference's Horovod callback surface
(reference horovod/keras/callbacks.py + horovod/_keras/callbacks.py:1-168).

* ``BroadcastGlobalVariablesCallback`` — rank-0 state sync at train begin
* ``MetricAverageCallback``            — epoch-end metric allreduce
* ``LearningRateScheduleCallback``     — epoch-window LR multiplier
* ``LearningRateWarmupCallback``       — gradual ``lr/size → lr`` ramp

Keras-3 / JAX-backend mechanics, where they differ from the keras-2
reference:

* LR lives in an optimizer *variable*, which the JAX trainer re-reads on
  the first batch after every ``on_epoch_begin`` — staircase adjustments
  are free.  Smooth (per-batch) adjustments must round-trip the jitted
  state (``model.jax_state_sync()`` + ``_jax_state_synced``), which costs
  a host sync per batch; prefer ``staircase=True`` on TPU.
* Momentum correction: the reference temporarily sets the momentum
  *hyperparameter* to ``m·new_lr/old_lr`` for one batch
  (_keras/callbacks.py:104-117).  With keras 3's jitted update the
  hyperparameter is trace-time constant, so we apply the mathematically
  identical buffer form instead: ``v *= new_lr/old_lr`` right before the
  first update at the new LR (``v' = m·(new/old)·v + g`` either way).
"""

from __future__ import annotations

import math

import numpy as np

from horovod_tpu import basics as _basics


try:  # pragma: no cover - exercised only in keras-less envs
    import keras as _keras_mod

    _KerasCallback = _keras_mod.callbacks.Callback
except ImportError:  # keep the module importable; constructing raises
    class _KerasCallback:  # type: ignore[no-redef]
        def __init__(self, *a, **kw):
            raise ImportError(
                "horovod_tpu.keras.callbacks requires keras>=3 "
                "(KERAS_BACKEND=jax)."
            )


def _multiprocess() -> bool:
    from horovod_tpu.keras import _multiprocess as _mp

    return _mp()


def _var_value(v) -> np.ndarray:
    return np.asarray(v.numpy() if hasattr(v, "numpy") else v)


class BroadcastGlobalVariablesCallback(_KerasCallback):
    """Broadcast all model (and any built optimizer) variables from
    ``root_rank`` at train begin (reference _keras/callbacks.py:20-30)."""

    def __init__(self, root_rank: int = 0, device: str = ""):
        super().__init__()
        del device  # placement is runtime-owned on TPU
        self.root_rank = root_rank
        self.broadcast_done = False

    def on_train_begin(self, logs=None):
        if self.broadcast_done or not _multiprocess():
            return
        from horovod_tpu.keras import broadcast_variables, _model_variables

        broadcast_variables(_model_variables(self.model), self.root_rank)
        self.broadcast_done = True


class MetricAverageCallback(_KerasCallback):
    """Allreduce-average numeric epoch metrics over ranks so rank-0 logs
    (and checkpoint/early-stop decisions) see global values
    (reference _keras/callbacks.py:33-67).

    Metrics ride the float32 wire (TPUs have no 64-bit hardware path;
    the same limitation the torch frontend documents under
    ``HOROVOD_TPU_X64``): float64 metrics lose ~1e-7 relative precision
    and integer metrics above 2**24 lose exactness.  For a
    tighter-than-f32 early-stop criterion, average that metric yourself
    through the torch frontend's x64 path."""

    def __init__(self, device: str = ""):
        super().__init__()
        del device

    def on_epoch_end(self, epoch, logs=None):
        if not logs or not _multiprocess():
            return
        from horovod_tpu.keras import _from_device, _np_to_rank_major
        from horovod_tpu.ops import eager as _eager

        # Post every metric async, then drain: one fused negotiation
        # window instead of one round-trip per metric (sorted keys keep
        # the enqueue order identical on every rank).
        handles = {}
        for k in sorted(logs):
            v = logs[k]
            if isinstance(v, (int, float, np.floating, np.integer)) \
                    and not isinstance(v, bool):
                handles[k] = _eager.allreduce_async(
                    _np_to_rank_major(np.asarray(v, np.float32)),
                    average=True, name=f"keras.metric.{k}",
                )
        for k, h in handles.items():
            logs[k] = float(_from_device(_eager.synchronize(h)))


class LearningRateScheduleCallback(_KerasCallback):
    """``lr = initial_lr · multiplier(epoch)`` inside
    ``[start_epoch, end_epoch)`` (reference _keras/callbacks.py:70-146)."""

    def __init__(self, multiplier, start_epoch: int = 0,
                 end_epoch: int | None = None, staircase: bool = True,
                 momentum_correction: bool = True,
                 steps_per_epoch: int | None = None):
        super().__init__()
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.steps_per_epoch = steps_per_epoch
        self.initial_lr: float | None = None
        self.current_epoch: int | None = None
        if not callable(multiplier):
            self.staircase = True
            self.multiplier = lambda epoch: multiplier
        else:
            self.multiplier = multiplier

    # -- keras-3 state plumbing ------------------------------------------

    def _get_lr(self) -> float:
        return float(_var_value(self.model.optimizer.learning_rate))

    def _set_lr(self, value: float) -> None:
        # Mutability is validated once, at on_train_begin.
        self.model.optimizer.learning_rate.assign(value)

    def _momentum_buffers(self):
        opt = self.model.optimizer
        if not getattr(opt, "momentum", 0.0):
            return []
        bufs = getattr(opt, "momentums", None)
        if bufs:
            return list(bufs)
        return [v for v in opt.variables if "momentum" in getattr(v, "path", "")]

    def _mid_epoch_sync(self) -> None:
        """Round-trip the jitted train state through the variables so a
        mid-epoch assignment is visible to the next step (the trainer's
        own 'synced by a callback' hook)."""
        m = self.model
        if getattr(m, "_jax_state", None) is not None \
                and hasattr(m, "jax_state_sync"):
            m.jax_state_sync()

    def _adjust_learning_rate(self, epoch: float, *, mid_epoch: bool) -> None:
        if mid_epoch:
            # jax_state_sync() also flags the synced state so the next
            # step re-reads the variables we're about to assign.
            self._mid_epoch_sync()
        old_lr = self._get_lr()
        new_lr = self.initial_lr * float(self.multiplier(epoch))
        self._set_lr(new_lr)
        if self.momentum_correction and old_lr > 0 and new_lr != old_lr:
            scale = new_lr / old_lr
            for buf in self._momentum_buffers():
                buf.assign(_var_value(buf) * scale)

    # -- reference-shaped hooks ------------------------------------------

    def _autodetect_steps_per_epoch(self) -> int:
        if self.params and self.params.get("steps"):
            return self.params["steps"]
        raise ValueError(
            "Could not autodetect steps_per_epoch; pass steps_per_epoch= "
            f"to {self.__class__.__name__}()."
        )

    def on_train_begin(self, logs=None):
        if not hasattr(self.model.optimizer.learning_rate, "assign"):
            # Fail at train begin, not mid-epoch: an optimizer built on a
            # LearningRateSchedule object owns the LR itself.
            raise ValueError(
                f"{self.__class__.__name__} requires a mutable "
                "learning_rate variable; the optimizer was constructed "
                "with a schedule object instead."
            )
        self.initial_lr = self._get_lr()
        if not self.staircase and not self.steps_per_epoch:
            self.steps_per_epoch = self._autodetect_steps_per_epoch()

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch

    def on_train_batch_begin(self, batch, logs=None):
        if (self.current_epoch is None
                or self.current_epoch < self.start_epoch
                or (self.end_epoch is not None
                    and self.current_epoch >= self.end_epoch)):
            return
        if self.staircase and batch == 0:
            # Epoch boundary: the trainer re-reads variables on this very
            # step (no state round-trip needed).
            self._adjust_learning_rate(self.current_epoch, mid_epoch=False)
        elif not self.staircase:
            epoch = self.current_epoch + float(batch) / self.steps_per_epoch
            self._adjust_learning_rate(epoch, mid_epoch=batch != 0)

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None:
            logs["lr"] = self._get_lr()


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual ramp from ``initial_lr/size`` to ``initial_lr`` over
    ``warmup_epochs`` (reference _keras/callbacks.py:149-168; the
    Goyal et al. warm-up — the user scales the configured LR by ``size``,
    the callback walks it up from the single-worker value)."""

    def __init__(self, warmup_epochs: float = 5, momentum_correction:
                 bool = True, steps_per_epoch: int | None = None,
                 verbose: int = 0):
        def multiplier(epoch):
            # +1/steps so epoch-end values land on round numbers
            # (reference's TensorBoard nicety).
            epoch += 1.0 / self.steps_per_epoch
            n = _basics.size()
            return 1.0 / n * (epoch * (n - 1) / warmup_epochs + 1)

        super().__init__(multiplier, start_epoch=0, end_epoch=warmup_epochs,
                         staircase=False,
                         momentum_correction=momentum_correction,
                         steps_per_epoch=steps_per_epoch)
        self.verbose = verbose

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        # ceil-1: warmup_epochs may be fractional (e.g. 2.5) — the ramp
        # finishes during epoch ceil(end)-1, and an int == float-.5
        # comparison would never fire the message.
        if epoch == math.ceil(self.end_epoch) - 1 and self.verbose > 0 \
                and _basics.rank() == 0:
            print(f"\nEpoch {epoch + 1}: finished gradual learning rate "
                  f"warmup to {self._get_lr():g}.")


__all__ = [
    "BroadcastGlobalVariablesCallback",
    "MetricAverageCallback",
    "LearningRateScheduleCallback",
    "LearningRateWarmupCallback",
]
