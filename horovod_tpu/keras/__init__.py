"""``import horovod_tpu.keras as hvd`` — the reference's Keras frontend,
re-targeted at Keras 3 on the JAX backend.

Parity surface (reference horovod/keras/__init__.py:1-148 and
horovod/_keras/__init__.py): ``init/shutdown/size/local_size/rank/
local_rank``, ``DistributedOptimizer``, ``broadcast_global_variables``,
value-level ``allreduce/allgather/broadcast``, ``load_model`` (optimizer
re-wrapped at deserialization so its slot state survives), and the four
callbacks in :mod:`horovod_tpu.keras.callbacks`.

TPU-native design — two regimes, same surface:

* **Multi-process** (one process per chip, the reference's process model,
  ``jax.process_count() > 1``): gradients are averaged through the eager
  engine — the same native-controller negotiation + fused XLA collectives
  the torch frontend uses.  Inside Keras's jitted train step the allreduce
  rides ``jax.experimental.io_callback`` (ordered), exactly where the
  reference splices its graph-mode allreduce op into ``get_gradients``
  (reference horovod/_keras/__init__.py:23-43).
* **Single-controller** (one process driving the whole mesh): Keras 3's
  ``keras.distribution.DataParallel`` shards the batch over the mesh and
  XLA inserts the gradient ``psum`` during compilation — the idiomatic TPU
  path; ``DistributedOptimizer`` is then a deliberate pass-through because
  the gradients it sees are already global-batch gradients.

Keras is imported lazily: everything here degrades to a clear
``ImportError`` when keras isn't installed, without poisoning
``import horovod_tpu``.
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

import horovod_tpu as _hvd
from horovod_tpu import basics as _basics
from horovod_tpu.ops import eager as _eager
from horovod_tpu.ops.compression import Compression  # noqa: F401

init = _hvd.init
shutdown = _hvd.shutdown
size = _hvd.size
local_size = _hvd.local_size
rank = _hvd.rank
local_rank = _hvd.local_rank
cross_size = _hvd.cross_size
cross_rank = _hvd.cross_rank
is_initialized = _hvd.is_initialized
mpi_threads_supported = _hvd.mpi_threads_supported


def _keras():
    try:
        import keras
    except ImportError as e:  # pragma: no cover - env without keras
        raise ImportError(
            "horovod_tpu.keras requires keras>=3 (JAX backend).  Install "
            "keras and set KERAS_BACKEND=jax before importing it."
        ) from e
    major = int(str(getattr(keras, "__version__", "0")).split(".")[0] or 0)
    if major < 3:  # pragma: no cover - env pins keras 3
        raise ImportError(
            f"horovod_tpu.keras requires keras>=3, found {keras.__version__}."
        )
    return keras


def _multiprocess() -> bool:
    """The reference's process model: one rank per process.  In a
    single-controller world the compiled SPMD path owns the collectives
    (XLA inserts them), so the eager engine must NOT re-reduce.

    Requires ``init()`` first: before it, ``jax.process_count()`` is 1
    even in a launched multi-process world, and a silent single-controller
    pass-through would train every rank unsynced — so ops raise
    ``NotInitializedError`` instead (reference horovod/common/basics.py
    pre-init behavior)."""
    _basics._require_init()
    import jax

    return jax.process_count() > 1


# ---------------------------------------------------------------------------
# Value-level collectives (reference horovod/keras/__init__.py:73-115).
#
# The keras surface is per-PROCESS values (the reference's model), while
# the eager engine speaks rank-major arrays; this is the same bridge the
# torch frontend uses (torch.py:113-139): this process's local array
# becomes its row of the rank-major global via
# ``jax.make_array_from_process_local_data``, and the replicated result
# is materialized back with ``device_get``.
# ---------------------------------------------------------------------------


def _np_to_rank_major(local: np.ndarray):
    import jax

    if local.dtype == np.int64:
        # The wire is int32 (jax x64 off); a silently wrapped value would
        # corrupt the collective (same guard as torch.py:118-127).
        if local.size and (local.max() > 0x7FFFFFFF
                           or local.min() < -0x80000000):
            raise ValueError(
                "int64 value holds numbers outside int32 range; the TPU "
                "wire carries int32 (use the torch frontend's "
                "HOROVOD_TPU_X64=1 path for exact 64-bit collectives, or "
                "split the value)"
            )
    if _basics.size() == 1:
        return jax.device_put(local[None], _basics.rank_sharding())
    return jax.make_array_from_process_local_data(
        _basics.rank_sharding(), np.ascontiguousarray(local)[None]
    )


def _from_device(arr) -> np.ndarray:
    import jax

    return np.asarray(jax.device_get(arr))


def allreduce(value, name: str | None = None, average: bool = True):
    """Allreduce a tensor-compatible value over ranks (identity in
    single-controller worlds, where values are already global)."""
    if not _multiprocess():
        return value
    arr = np.asarray(value)
    out = _from_device(_eager.allreduce(
        _np_to_rank_major(arr), average=average,
        name=name or "keras.allreduce",
    )).astype(arr.dtype, copy=False)  # 64-bit callers get their dtype back
    return out.item() if np.ndim(value) == 0 else out


def allgather(value, name: str | None = None):
    """Allgather along dim 0; ranks may disagree on dim 0 (the
    reference's unequal-first-dim allgather,
    horovod/keras/__init__.py:89-101 → operations.cc:841-901).  Sizes
    are negotiated through the engine up front."""
    if not _multiprocess():
        return np.asarray(value)
    local = np.asarray(value)
    name = name or "keras.allgather"
    sizes = _eager.negotiate_gather_sizes(local.shape, str(local.dtype),
                                          name)
    pad = max(sizes)
    if local.shape[0] != pad:
        padded = np.zeros((pad,) + local.shape[1:], local.dtype)
        padded[: local.shape[0]] = local
        local = padded
    # The engine slices the ragged concatenation itself (sizes=).
    return _from_device(_eager.allgather(
        _np_to_rank_major(local), name=name, sizes=sizes
    )).astype(local.dtype, copy=False)


def broadcast(value, root_rank: int, name: str | None = None):
    """Broadcast a tensor-compatible value from ``root_rank``."""
    if not _multiprocess():
        return value
    arr = np.asarray(value)
    out = _from_device(_eager.broadcast(
        _np_to_rank_major(arr), root_rank, name=name or "keras.broadcast"
    )).astype(arr.dtype, copy=False)
    return out.item() if np.ndim(value) == 0 else out


def _model_variables(model) -> list:
    vs = list(model.variables)
    opt = getattr(model, "optimizer", None)
    if opt is not None and getattr(opt, "built", False):
        known = {id(v) for v in vs}
        vs += [v for v in opt.variables if id(v) not in known]
    return vs


def broadcast_variables(variables: Sequence[Any], root_rank: int = 0) -> None:
    """Assign every variable its root-rank value (eager engine broadcast).

    The keras-3 analogue of the reference's session-wide
    ``broadcast_global_variables`` (horovod/_keras/__init__.py:46-47):
    keras 3 has no global-variable registry, so the caller names the
    variables (typically ``model.variables`` — see
    :func:`broadcast_global_variables` and the callback, which do)."""
    if not _multiprocess():
        return
    handles = []
    for i, v in enumerate(variables):
        arr = np.asarray(v.numpy() if hasattr(v, "numpy") else v)
        h = _eager.broadcast_async(_np_to_rank_major(arr), root_rank,
                                   name=f"keras.bcast.{i}")
        # keep shape/dtype only, not the array — holding every host copy
        # until the drain would double a large model's host footprint
        handles.append((v, arr.shape, arr.dtype, h))
    for v, shape, dtype, h in handles:
        out = _from_device(_eager.synchronize(h))
        # reshape: a scalar variable's wire form is (1,), not ().
        v.assign(out.reshape(shape).astype(dtype, copy=False))


def broadcast_global_variables(root_rank: int, model=None) -> None:
    """Broadcast all of ``model``'s (and its optimizer's) variables from
    ``root_rank`` (reference horovod/keras/__init__.py:62-70).

    Keras 3 keeps no global-variable collection, so the model must be
    passed (or use ``callbacks.BroadcastGlobalVariablesCallback``, which
    picks it up from ``fit``)."""
    if model is None:
        if not _multiprocess():
            return  # nothing to sync and no registry to walk
        raise ValueError(
            "keras 3 has no global-variable registry; pass the model: "
            "broadcast_global_variables(root_rank, model=model), or use "
            "callbacks.BroadcastGlobalVariablesCallback."
        )
    broadcast_variables(_model_variables(model), root_rank)


# ---------------------------------------------------------------------------
# DistributedOptimizer (reference horovod/keras/__init__.py:32-59).
# ---------------------------------------------------------------------------


def _host_allreduce(prefix: str, compression, average: bool, arrays):
    """One caller-delimited fusion group per gradient burst (the
    reference's tensor-fusion behavior, SURVEY.md §2.1 C5).  The grouped
    call — not individual asyncs — is what actually fuses here:
    multi-controller fusion is restricted to caller-delimited groups
    (timing-based bucketing would diverge across ranks,
    docs/tensor-fusion.md), and the per-tensor host bridging between
    individual posts spans cycle ticks anyway."""
    outs = _eager.grouped_allreduce_eager(
        [_np_to_rank_major(np.asarray(a)) for a in arrays],
        average=average,
        names=[f"{prefix}.grad_{i}" for i in range(len(arrays))],
        compression=compression,
    )
    return tuple(_from_device(o) for o in outs)


def _allreduce_gradients(grads: list, *, prefix: str, compression,
                         average: bool) -> list:
    import jax
    import jax.numpy as jnp

    if not _multiprocess():
        # Single-controller: keras.distribution (or a single device) means
        # these are already global-batch gradients; XLA owns the psum.
        return grads
    idx = [i for i, g in enumerate(grads) if g is not None]
    if not idx:
        return grads
    arrays = [grads[i] for i in idx]
    if any(isinstance(g, jax.core.Tracer) for g in arrays):
        # Inside keras's jitted train step: splice the host-side eager
        # allreduce into the compiled program.  ``ordered=True`` pins the
        # enqueue order so every rank negotiates the same tensor sequence.
        from jax.experimental import io_callback

        shapes = tuple(
            jax.ShapeDtypeStruct(jnp.shape(g), jnp.result_type(g))
            for g in arrays
        )

        def host(*np_grads, _p=prefix, _c=compression, _a=average):
            return _host_allreduce(_p, _c, _a, np_grads)

        reduced = io_callback(host, shapes, *arrays, ordered=True)
    else:
        reduced = _host_allreduce(
            prefix, compression, average, [np.asarray(g) for g in arrays]
        )
    out = list(grads)
    for j, i in enumerate(idx):
        out[i] = reduced[j]
    return out


_DIST_CLS_CACHE: dict[type, type] = {}


def _dist_class(cls: type) -> type:
    """One ``Distributed<Cls>`` subclass per wrapped optimizer class,
    registered in keras's serialization registry so models saved with a
    wrapped optimizer deserialize (registered_name
    ``horovod_tpu.keras>Distributed<Cls>``)."""
    dc = _DIST_CLS_CACHE.get(cls)
    if dc is None:
        import keras

        dc = type("Distributed" + cls.__name__,
                  (_DistributedApplyMixin, cls), {})
        keras.saving.register_keras_serializable(
            package="horovod_tpu.keras")(dc)
        _DIST_CLS_CACHE[cls] = dc
    return dc


class _DistributedApplyMixin:
    """Overrides ``apply`` — the single funnel both ``apply_gradients``
    and (via ``StatelessScope``) ``stateless_apply`` drain through in
    keras 3 — to average gradients across ranks first."""

    _hvd_compression = Compression.none
    _hvd_average = True
    _hvd_prefix = "DistributedOptimizer"

    def apply(self, grads, trainable_variables=None):
        grads = _allreduce_gradients(
            list(grads), prefix=self._hvd_prefix,
            compression=self._hvd_compression, average=self._hvd_average,
        )
        return super().apply(grads, trainable_variables)

    def get_config(self):
        # average/name must survive a save→load_model round trip (sum
        # semantics silently becoming mean would shrink the effective LR
        # by size()).  Compression objects aren't config-serializable;
        # load_model's compression= parameter is the restore path.
        cfg = super().get_config()
        cfg["hvd_average"] = self._hvd_average
        cfg["hvd_prefix"] = self._hvd_prefix
        return cfg

    @classmethod
    def from_config(cls, config, custom_objects=None):
        config = dict(config)
        average = config.pop("hvd_average", True)
        prefix = config.pop("hvd_prefix", None)
        try:
            inst = super().from_config(config, custom_objects)
        except TypeError:
            inst = super().from_config(config)
        inst._hvd_average = average
        if prefix:
            inst._hvd_prefix = prefix
        return inst


def DistributedOptimizer(optimizer, name: str | None = None,
                         device_dense: str = "", device_sparse: str = "",
                         compression=Compression.none,
                         sparse_as_dense: bool = False, *,
                         average: bool = True):
    """Wrap a keras optimizer so gradients are averaged over ranks before
    the update (reference horovod/keras/__init__.py:32-59; signature kept
    for drop-in parity — ``device_dense``/``device_sparse``/
    ``sparse_as_dense`` are placement hints with no TPU meaning, the
    runtime owns placement)."""
    del device_dense, device_sparse, sparse_as_dense
    keras = _keras()
    if keras.backend.backend() != "jax":
        raise RuntimeError(
            "horovod_tpu.keras.DistributedOptimizer requires the JAX "
            f"backend (got '{keras.backend.backend()}').  Set "
            "KERAS_BACKEND=jax before importing keras."
        )
    cls = optimizer.__class__
    if isinstance(optimizer, _DistributedApplyMixin):
        raise ValueError(
            "optimizer is already a horovod_tpu.keras DistributedOptimizer"
        )
    wrapped = _dist_class(cls).from_config(optimizer.get_config())
    wrapped._hvd_compression = compression
    wrapped._hvd_average = average
    wrapped._hvd_prefix = name or ("Distributed" + cls.__name__)
    if getattr(optimizer, "built", False):
        # Preserve slot state (momentum/velocity/iteration) so wrapping a
        # live optimizer — e.g. inside load_model — resumes training.
        wrapped.build(optimizer._trainable_variables)
        for sv, dv in zip(optimizer.variables, wrapped.variables):
            dv.assign(sv)
    return wrapped


# ---------------------------------------------------------------------------
# load_model (reference horovod/keras/__init__.py:116-148).
# ---------------------------------------------------------------------------


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=Compression.none):
    """Load a saved keras model with its optimizer wrapped in
    :func:`DistributedOptimizer`, the saved optimizer state (iterations,
    momenta) carried into the wrapper
    (reference horovod/keras/__init__.py:116-148).

    Keras 3 mechanics: keras restores the optimizer and its variables
    itself; a plain optimizer is then wrapped in place (state copied —
    see :func:`DistributedOptimizer`), while a model that was SAVED with
    a wrapped optimizer deserializes directly through the
    ``Distributed<Cls>`` registry entries this function pre-registers."""
    keras = _keras()
    base = keras.optimizers.Optimizer
    for attr in dir(keras.optimizers):
        c = getattr(keras.optimizers, attr)
        if isinstance(c, type) and issubclass(c, base) and c is not base:
            _dist_class(c)
    for c in custom_optimizers or []:
        _dist_class(c)
    model = keras.saving.load_model(filepath,
                                    custom_objects=custom_objects)
    opt = getattr(model, "optimizer", None)
    if isinstance(opt, _DistributedApplyMixin):
        opt._hvd_compression = compression
    elif opt is not None:
        # Retype in place rather than swapping the attribute: the model
        # already tracks this optimizer's variables, and a replacement
        # object would leave the old ones tracked-but-orphaned (their
        # buffers get purged/donated by the JAX trainer and never
        # restored).  The subclass only adds behavior, no state.
        opt.__class__ = _dist_class(opt.__class__)
        opt._hvd_compression = compression
    return model


from horovod_tpu.keras import callbacks  # noqa: E402,F401
# hvd.elastic.KerasState / hvd.elastic.run — horovod's keras elastic
# parity (Horovod 0.20+; see horovod_tpu/keras_elastic.py).
from horovod_tpu import keras_elastic as elastic  # noqa: E402,F401

__all__ = [
    "init", "shutdown", "size", "local_size", "rank", "local_rank",
    "cross_size", "cross_rank", "is_initialized", "mpi_threads_supported",
    "Compression", "DistributedOptimizer", "allreduce", "allgather",
    "broadcast", "broadcast_variables", "broadcast_global_variables",
    "load_model", "callbacks", "elastic",
]
