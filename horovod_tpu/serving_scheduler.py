"""Continuous-batching decode engine: slot recycling over a paged KV pool.

:class:`~horovod_tpu.serving.ContinuousBatcher` admits into a fixed slot
pool but each admission runs its whole prefill at once and the pool's
dense cache reserves max_len per slot.  :class:`ServeEngine` is the next
step toward a production scheduler (Orca OSDI '22 / vLLM SOSP '23):

* a **request queue** feeding a slot table — a finished row's slot (and
  its cache blocks) are recycled for the next queued request on the very
  next step;
* **chunked prefill interleaved with decode**: admission runs one
  fixed-width prompt window per step, between decode ticks, so a long
  prompt never stalls in-flight rows for more than one window;
* a **paged KV cache** (:class:`~horovod_tpu.models.llama.PagedKVCache`):
  admission allocates only the blocks a request needs (host free-list),
  retirement returns them — recycling reuses memory without
  re-allocating device buffers or re-compiling anything;
* a **fixed-shape compiled tick**: every device program (`tick`,
  `prefill chunk`, `table write`) has one jit signature for the life of
  the server — admission/retirement changes table *data*, never shapes,
  so XLA never re-traces (pinned by ``compile_cache_sizes`` in tests).

Scheduler invariants:

1. *Write-before-read*: a row's blocks hold garbage beyond its length;
   every reader masks past the length and every writer writes a position
   before anything attends to it.  Free rows tick along with the batch
   (one program) and scatter into the trash block (block 0).
2. *Row independence*: attention never crosses rows, so each request's
   greedy output is bit-identical to its solo ``llama.generate`` run —
   including requests admitted mid-flight (pinned by
   ``tests/test_serving_scheduler.py``).
3. *Fixed signature*: host state (queue, slot states, free blocks) makes
   every decision; device programs only ever see [n_slots]-shaped data.

The engine is greedy-only; sampling pools stay on
:class:`~horovod_tpu.serving.ContinuousBatcher`.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.models import llama
from horovod_tpu.serving import Request

FREE, PREFILL, DECODE = "free", "prefill", "decode"


@dataclasses.dataclass
class SchedulerEvent:
    """One scheduler decision, for tests/telemetry: ``kind`` is
    ``"admit"`` or ``"recycle"``; ``step`` the engine step index."""

    kind: str
    step: int
    slot: int
    request_id: int


@dataclasses.dataclass
class _Slot:
    state: str = FREE
    request_id: int = -1
    padded: np.ndarray | None = None     # [1, n_win * chunk] prompt
    n_win: int = 0
    w_done: int = 0
    true_len: int = 0
    budget: int = 0
    eos: int | None = None
    out: list[int] = dataclasses.field(default_factory=list)
    n_blocks: int = 0                    # blocks allocated to this slot
    blocks: list[int] = dataclasses.field(default_factory=list)


class ServeEngine:
    """Serve a queue of greedy requests through a recycled slot pool.

    ``n_slots``: compiled batch width.  ``max_len``: per-request logical
    depth bound (prompt + generation).  ``chunk``: the chunked-prefill
    window — one [1, chunk] prompt window runs per step per admitting
    slot, which is the knob trading admission latency against how much a
    long prompt delays the next decode tick.  ``block_size`` (default:
    ``chunk``) and ``n_blocks`` size the paged pool; the default pool
    fully backs every slot, smaller pools overcommit and admission waits
    for free blocks.  ``timeline``: an optional
    :class:`horovod_tpu.timeline.Timeline` receiving admit/recycle
    instants and per-step queue/occupancy counters.
    """

    def __init__(self, params: dict, cfg: llama.LlamaConfig, *,
                 n_slots: int, max_len: int, chunk: int,
                 block_size: int | None = None,
                 n_blocks: int | None = None,
                 timeline: Any = None):
        if chunk < 1 or chunk > max_len:
            raise ValueError(f"chunk {chunk} must be in [1, max_len "
                             f"{max_len}]")
        block_size = chunk if block_size is None else block_size
        self.params = params
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_len = max_len
        self.chunk = chunk
        self.block_size = block_size
        self.timeline = timeline
        self.pcache = llama.init_paged_cache(
            cfg, n_slots, max_len, block_size=block_size,
            n_blocks=n_blocks)
        self.blocks_per_slot = self.pcache.block_table.shape[1]
        total = self.pcache.k.shape[1]
        # block 0 is trash — never allocated; pop() takes low ids first
        self._free_blocks = list(range(total - 1, 0, -1))
        self._trash_row = np.zeros((self.blocks_per_slot,), np.int32)
        self.last_logits = jnp.zeros((n_slots, cfg.vocab_size),
                                     jnp.float32)
        self._slots = [_Slot() for _ in range(n_slots)]
        self._queue: deque[tuple[int, Request]] = deque()
        self._next_id = 0
        self.results: dict[int, list[int]] = {}
        self.events: list[SchedulerEvent] = []
        self.step_index = 0

        @partial(jax.jit, donate_argnums=(1, 2))
        def _tick(params, pcache, last_logits, active):
            # the fixed-signature decode tick: every row argmaxes its
            # last logits and decodes one position; `active` [B] gates
            # the length advance so idle/prefilling rows hold position
            # (their garbage write lands in their own blocks or trash —
            # invariant 1).  Donation matters: decode cost IS cache
            # traffic, an undonated pool would copy every block per tick.
            tok = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
            logits, pcache = llama.decode_chunk_paged(
                params, tok[:, None], cfg, pcache, advance=active)
            return tok, logits[:, 0], pcache

        @partial(jax.jit, donate_argnums=(1, 2))
        def _chunk(params, pcache, last_logits, toks, slot, new_len, sel):
            # one chunked-prefill window for one slot: [1, chunk] tokens
            # continue the row from its current length; `sel` picks the
            # window position whose logits seed decoding (only the final
            # window's pick survives — later windows overwrite).
            logits, pcache = llama.decode_chunk_paged_row(
                params, toks, cfg, pcache, slot, new_length=new_len)
            last_logits = last_logits.at[slot].set(logits[0, sel])
            return pcache, last_logits

        @partial(jax.jit, donate_argnums=(0,))
        def _set_row(pcache, slot, row):
            # admission/retirement table write: swaps which physical
            # blocks a slot row maps to and rewinds its length — data
            # only, so slot recycling reuses the same compiled programs
            return pcache._replace(
                block_table=pcache.block_table.at[slot].set(row),
                length=pcache.length.at[slot].set(0))

        self._tick = _tick
        self._chunk = _chunk
        self._set_row = _set_row

    # -- introspection -----------------------------------------------------

    def compile_cache_sizes(self) -> dict[str, int]:
        """Per-program jit cache entry counts — the no-retrace pin:
        admission/recycling must keep every count constant."""
        return {
            "tick": self._tick._cache_size(),
            "chunk": self._chunk._cache_size(),
            "set_row": self._set_row._cache_size(),
        }

    def free_block_count(self) -> int:
        return len(self._free_blocks)

    def pending(self) -> bool:
        return bool(self._queue) or any(
            s.state != FREE for s in self._slots)

    # -- queue -------------------------------------------------------------

    def submit(self, req: Request) -> int:
        """Queue a request; returns its id (key into ``results``).
        Validation happens here so a rejected request never holds a
        queue position."""
        L = len(req.prompt)
        if L < 1:
            raise ValueError("empty prompt")
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if req.temperature not in (None, 0.0) or req.sample_key is not None:
            raise ValueError(
                "ServeEngine is greedy-only; serve sampled requests "
                "through ContinuousBatcher")
        if req.prefix is not None:
            raise ValueError(
                "ServeEngine does not splice prefix caches yet; use "
                "ContinuousBatcher for prefix requests")
        if L + req.max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {L} + max_new_tokens {req.max_new_tokens} "
                f"exceeds max_len {self.max_len}")
        n_win = -(-L // self.chunk)
        if n_win * self.chunk > self.max_len:
            raise ValueError(
                f"prompt {L} padded to {n_win * self.chunk} prefill "
                f"windows exceeds max_len {self.max_len}")
        need = -(-(L + req.max_new_tokens) // self.block_size)
        if need > len(self._free_blocks) + sum(
                s.n_blocks for s in self._slots):
            raise ValueError(
                f"request needs {need} cache blocks but the pool only "
                f"has {self.pcache.k.shape[1] - 1} allocatable")
        rid = self._next_id
        self._next_id += 1
        self._queue.append((rid, req))
        return rid

    # -- scheduling --------------------------------------------------------

    def _admit_ready(self) -> None:
        """FIFO admission: move queued requests into free slots while
        both a slot and enough cache blocks are available.  Head-of-line
        blocking is deliberate — FIFO keeps per-request latency fair."""
        while self._queue:
            free = [i for i, s in enumerate(self._slots)
                    if s.state == FREE]
            if not free:
                return
            rid, req = self._queue[0]
            L = len(req.prompt)
            need = -(-(L + req.max_new_tokens) // self.block_size)
            if need > len(self._free_blocks):
                return                       # blocks free on retirement
            self._queue.popleft()
            slot = free[0]
            s = self._slots[slot]
            blocks = [self._free_blocks.pop() for _ in range(need)]
            row = self._trash_row.copy()
            row[:need] = blocks
            self.pcache = self._set_row(
                self.pcache, jnp.asarray(slot, jnp.int32),
                jnp.asarray(row))
            n_win = -(-L // self.chunk)
            padded = np.zeros((1, n_win * self.chunk), np.int32)
            padded[0, :L] = req.prompt
            s.state = PREFILL
            s.request_id = rid
            s.padded = padded
            s.n_win = n_win
            s.w_done = 0
            s.true_len = L
            s.budget = req.max_new_tokens
            s.eos = req.eos_id
            s.out = []
            s.n_blocks = need
            s.blocks = blocks
            self._event("admit", slot, rid)

    def _retire(self, slot: int) -> None:
        s = self._slots[slot]
        self.results[s.request_id] = s.out
        self._free_blocks.extend(reversed(s.blocks))
        self.pcache = self._set_row(
            self.pcache, jnp.asarray(slot, jnp.int32),
            jnp.asarray(self._trash_row))
        self._event("recycle", slot, s.request_id)
        self._slots[slot] = _Slot()

    def _event(self, kind: str, slot: int, rid: int) -> None:
        self.events.append(
            SchedulerEvent(kind, self.step_index, slot, rid))
        if self.timeline is not None:
            self.timeline.instant("serving.scheduler", kind.upper())

    def step(self) -> dict[int, list[int]]:
        """One engine step: admit, run one prefill window per admitting
        slot, then one decode tick over the pool.  Returns
        ``{request_id: tokens}`` for requests that finished."""
        self._admit_ready()
        for slot, s in enumerate(self._slots):
            if s.state != PREFILL:
                continue
            w = s.w_done
            final = w == s.n_win - 1
            toks = s.padded[:, w * self.chunk:(w + 1) * self.chunk]
            new_len = s.true_len if final else (w + 1) * self.chunk
            sel = s.true_len - 1 - w * self.chunk if final else 0
            self.pcache, self.last_logits = self._chunk(
                self.params, self.pcache, self.last_logits,
                jnp.asarray(toks), jnp.asarray(slot, jnp.int32),
                jnp.asarray(new_len, jnp.int32),
                jnp.asarray(sel, jnp.int32))
            s.w_done += 1
            if final:
                s.state = DECODE      # joins this step's tick
        finished: dict[int, list[int]] = {}
        decoding = [i for i, s in enumerate(self._slots)
                    if s.state == DECODE]
        if decoding:
            active = np.zeros((self.n_slots,), np.int32)
            active[decoding] = 1
            tok, self.last_logits, self.pcache = self._tick(
                self.params, self.pcache, self.last_logits,
                jnp.asarray(active))
            tok_host = np.asarray(tok)
            for slot in decoding:
                s = self._slots[slot]
                t = int(tok_host[slot])
                s.out.append(t)
                s.budget -= 1
                if s.budget <= 0 or t == s.eos:
                    finished[s.request_id] = s.out
                    self._retire(slot)
        if self.timeline is not None:
            self.timeline.counter(
                "serving.scheduler", "SCHED",
                {"queued": len(self._queue),
                 "decoding": len(decoding),
                 "prefilling": sum(1 for s in self._slots
                                   if s.state == PREFILL),
                 "free_blocks": len(self._free_blocks)})
        self.step_index += 1
        return finished

    def run(self, requests: list[Request]) -> list[list[int]]:
        """Serve ``requests`` to completion; returns each request's
        tokens in submission order."""
        ids = [self.submit(r) for r in requests]
        while self.pending():
            self.step()
        return [self.results[i] for i in ids]


# ---------------------------------------------------------------------------
# Throughput measurement (the serve_tokens_per_sec bench metric).
# ---------------------------------------------------------------------------


def measure_throughput(
    params: dict, cfg: llama.LlamaConfig, requests: list[Request], *,
    n_slots: int, max_len: int, chunk: int,
    block_size: int | None = None, n_blocks: int | None = None,
) -> dict:
    """Continuous-batching vs fixed-batch throughput on one workload.

    The engine serves the queue with slot recycling; the static baseline
    is plain :func:`llama.generate` over fixed batches of ``n_slots`` in
    submission order — every batch decodes until its LONGEST budget is
    spent and prompts pad to the global maximum (the costs continuous
    batching exists to remove).  Both paths are warmed (compiled) before
    timing; only true emitted tokens count, for both.  Returns
    ``serve_tokens_per_sec``, ``static_tokens_per_sec``,
    ``serve_vs_static_ratio`` and workload shape fields.
    """
    if not requests:
        raise ValueError("empty workload")

    eng = ServeEngine(params, cfg, n_slots=n_slots, max_len=max_len,
                      chunk=chunk, block_size=block_size,
                      n_blocks=n_blocks)
    warm = eng.run(requests)                 # compiles every program
    n_tokens = sum(len(t) for t in warm)
    # timed pass reuses the SAME engine (its jit programs are
    # per-instance): after run() every slot is free, so the pool is in
    # its admission-ready state again
    t0 = time.perf_counter()
    out = eng.run(requests)
    jax.block_until_ready(eng.pcache.k)
    t_serve = time.perf_counter() - t0
    assert [len(t) for t in out] == [len(t) for t in warm]

    # static baseline: batches of n_slots, one compiled generate per
    # distinct batch budget (compiles excluded by per-batch warmup)
    pad_w = max(len(r.prompt) for r in requests)
    batches = []
    for i in range(0, len(requests), n_slots):
        group = requests[i:i + n_slots]
        while len(group) < n_slots:          # pad rows don't count below
            group.append(group[0])
        toks = np.zeros((n_slots, pad_w), np.int32)
        lens = np.zeros((n_slots,), np.int32)
        for j, r in enumerate(group):
            toks[j, :len(r.prompt)] = r.prompt
            lens[j] = len(r.prompt)
        mn = max(r.max_new_tokens for r in group)
        batches.append((jnp.asarray(toks), jnp.asarray(lens), mn))
    gen_cache: dict[int, Any] = {}
    for _, _, mn in batches:
        if mn not in gen_cache:
            gen_cache[mn] = jax.jit(partial(
                llama.generate, cfg=cfg, max_new_tokens=mn,
                max_len=max_len))
    for toks, lens, mn in batches:           # warm every batch shape
        jax.block_until_ready(
            gen_cache[mn](params, toks, prompt_lengths=lens))
    t0 = time.perf_counter()
    outs = [gen_cache[mn](params, toks, prompt_lengths=lens)
            for toks, lens, mn in batches]
    jax.block_until_ready(outs)
    t_static = time.perf_counter() - t0

    return {
        "serve_tokens_per_sec": n_tokens / t_serve,
        "static_tokens_per_sec": n_tokens / t_static,
        "serve_vs_static_ratio": t_static / t_serve,
        "tokens": n_tokens,
        "n_requests": len(requests),
        "n_slots": n_slots,
        "max_len": max_len,
        "chunk": chunk,
    }
