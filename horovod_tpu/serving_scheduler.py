"""Continuous-batching decode engine: slot recycling over a paged KV pool.

:class:`~horovod_tpu.serving.ContinuousBatcher` admits into a fixed slot
pool but each admission runs its whole prefill at once and the pool's
dense cache reserves max_len per slot.  :class:`ServeEngine` is the next
step toward a production scheduler (Orca OSDI '22 / vLLM SOSP '23):

* a **request queue** feeding a slot table — a finished row's slot (and
  its cache blocks) are recycled for the next queued request on the very
  next step;
* **chunked prefill interleaved with decode**: admission runs one
  fixed-width prompt window per step, between decode ticks, so a long
  prompt never stalls in-flight rows for more than one window;
* a **paged KV cache** (:class:`~horovod_tpu.models.llama.PagedKVCache`):
  admission allocates only the blocks a request needs (host free-list),
  retirement returns them — recycling reuses memory without
  re-allocating device buffers or re-compiling anything;
* a **fixed-shape compiled tick**: every device program (`tick`,
  `prefill chunk`, `table write`) has one jit signature for the life of
  the server — admission/retirement changes table *data*, never shapes,
  so XLA never re-traces (pinned by ``compile_cache_sizes`` in tests).

Request lifecycle & fault tolerance (the production layer the above
schedulers treat as first-class scheduler transitions, not crashes):

* every request terminates with a typed
  :class:`~horovod_tpu.serving.RequestResult` — status ``OK / TIMEOUT /
  CANCELLED / FAILED / REJECTED`` plus tokens-so-far;
* ``cancel(rid)`` works in any state (queued, prefilling, decoding);
  per-request ``deadline_s`` (wall clock) and ``max_queue_steps``
  (step-counted admission budget → ``REJECTED``) bound waiting;
* **KV-pressure preemption with replay**: when the queue head has
  starved ``preempt_after`` consecutive steps on an overcommitted block
  pool, a decoding row is preempted — blocks freed, request re-queued
  with ``prompt + out`` as the replay prompt.  Which row is the victim
  (and in what order the queue admits) is a pluggable
  :class:`~horovod_tpu.scheduling.SchedulerPolicy` — FIFO (default,
  bit-compatible: evicts the youngest), priority, or EDF (evicts the
  slack-richest).  Greedy determinism makes the resumed output
  bit-identical to the uninterrupted run whoever is chosen, and
  everything rides the existing ``_set_row`` program so no new jit
  signatures appear;
* **poison-request quarantine**: a raising prefill window or decode-tick
  readback fails only the implicated request — transient faults get
  bounded step-counted retries with exponential backoff (decode retries
  reuse the replay path), then a ``FAILED`` result carrying the
  exception.  All other rows keep serving;
* deterministic fault injection via :mod:`horovod_tpu.faults` sites
  ``serve.admit`` / ``serve.prefill`` / ``serve.tick`` /
  ``serve.cache`` / ``serve.draft``, and a no-progress watchdog that
  raises with a full scheduler-state dump instead of spinning
  ``run()`` forever.

Shared-prefix KV reuse (``prefix_cache=True``; PagedAttention block
sharing + RadixAttention-style automatic indexing — see
:mod:`horovod_tpu.prefix_cache`):

* physical blocks become **reference-counted**
  (:class:`~horovod_tpu.models.llama.BlockPool`) and retirement
  **releases to cache** instead of freeing: every full, immutable
  block of a cleanly finished row is registered in a radix tree keyed
  by its token-chunk path, parking zero-ref blocks in LRU order;
* admission does a **longest-prefix match** and maps the hit blocks
  straight into the new slot's block-table row — chunked prefill
  starts at the first uncached token (a full hit recomputes only the
  final chunk: the copy-on-write rule keeping the write-frontier block
  private, and the source of the logits that seed decoding);
* under KV pressure, **cache evicts before rows preempt**: admission
  reclaims zero-ref LRU leaves first, and only a starved head that
  outlasts eviction triggers row preemption.  A preempted row's blocks
  release-to-cache too, so its replay re-admits through the cache and
  is nearly free;
* none of it adds device programs: cache hits change block-table
  *data*, never shapes — the same jit signatures serve, pinned by
  ``compile_cache_sizes()``, and every output stays bit-identical to
  the cache-off solo greedy run.

Self-drafting speculative decode (``spec=True`` / ``HVD_TPU_SPEC=1``;
prompt-lookup decoding in the continuous batch — see
:mod:`horovod_tpu.drafting` and
:func:`~horovod_tpu.models.llama.spec_verify_paged`):

* each decoding slot drafts up to ``draft_k`` tokens per tick from an
  incremental n-gram index over its own prompt + output — no draft
  model, no extra forward pass, pure host work (the ``draft``
  profiler phase);
* ONE wide verify program replaces the 1-wide tick: every row decodes
  a fixed ``(draft_k + 1)``-window per dispatch, greedy
  longest-matching-prefix acceptance runs on device, and the per-row
  cache length advances by ``1 + accepted`` — rejected positions roll
  back by the length alone (write-before-read: the frontier rewrites
  them before they can be read);
* acceptance only ever keeps the model's own argmax, so spec on/off
  is bit-identical to the solo greedy run for any draft quality, and
  ``compile_cache_sizes()`` stays frozen at one signature per program
  (``spec_tick`` replacing ``tick``).

Scheduler invariants:

1. *Write-before-read*: a row's blocks hold garbage beyond its length;
   every reader masks past the length and every writer writes a position
   before anything attends to it.  Free rows tick along with the batch
   (one program) and scatter into the trash block (block 0).
2. *Row independence*: attention never crosses rows, so each request's
   greedy output is bit-identical to its solo ``llama.generate`` run —
   including requests admitted mid-flight and requests resumed after a
   preemption (pinned by ``tests/test_serving_scheduler.py`` and
   ``tests/test_serving_faults.py``).
3. *Fixed signature*: host state (queue, slot states, free blocks) makes
   every decision; device programs only ever see [n_slots]-shaped data.
   Preempt/requeue/cancel/timeout paths reuse the same programs, and
   scheduler policies (:mod:`horovod_tpu.scheduling`) only reorder
   host decisions — invariant 2 makes any admission order or victim
   choice output-preserving.

The engine is greedy-only; sampling pools stay on
:class:`~horovod_tpu.serving.ContinuousBatcher`.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import threading
import time
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from horovod_tpu import alerts as alerts_mod
from horovod_tpu import device_telemetry as device_telemetry_mod
from horovod_tpu import drafting as drafting_mod
from horovod_tpu import faults as faults_mod
from horovod_tpu import metrics as metrics_mod
from horovod_tpu import monitor as monitor_mod
from horovod_tpu import profiler as profiler_mod
from horovod_tpu import scheduling as scheduling_mod
from horovod_tpu import timeseries as timeseries_mod
from horovod_tpu import tracing as tracing_mod
from horovod_tpu.metrics import Trace
from horovod_tpu.models import llama
from horovod_tpu.parallel.mesh import tensor_parallel_mesh
from horovod_tpu.prefix_cache import RadixPrefixCache
from horovod_tpu.serving import (
    CANCELLED, FAILED, OK, REJECTED, TIMEOUT, Request, RequestResult,
)

FREE, PREFILL, DECODE = "free", "prefill", "decode"


@dataclasses.dataclass
class SchedulerEvent:
    """One scheduler decision, for tests/telemetry: ``kind`` is
    ``"admit"``, ``"hit"`` (admission with a prefix-cache match),
    ``"recycle"`` (OK retirement), ``"preempt"``, ``"retry"``,
    ``"cancel"``, ``"timeout"``, ``"reject"`` or ``"fail"``; ``step``
    the engine step index; ``slot`` is -1 for queue-side events
    (reject, queued cancel/timeout, admit retry)."""

    kind: str
    step: int
    slot: int
    request_id: int


@dataclasses.dataclass
class _QueueEntry:
    """A queued request plus its lifecycle state.  ``prior`` holds
    tokens already emitted before a preemption/replay re-queue (the
    replay prompt is ``req.prompt + prior``); ``wait_steps`` is the
    step-counted retry backoff; ``deadline`` is absolute monotonic."""

    rid: int
    req: Request
    prior: list[int] = dataclasses.field(default_factory=list)
    retries: int = 0
    wait_steps: int = 0
    queued_steps: int = 0
    deadline: float | None = None
    slo_deadline: float | None = None    # enqueue + slo_s (EDF policy)


@dataclasses.dataclass
class _Slot:
    state: str = FREE
    request_id: int = -1
    padded: np.ndarray | None = None     # [1, n_win * chunk] prompt
    n_win: int = 0
    w_done: int = 0
    true_len: int = 0
    budget: int = 0
    eos: int | None = None
    out: list[int] = dataclasses.field(default_factory=list)
    n_blocks: int = 0                    # blocks mapped by this slot
    blocks: list[int] = dataclasses.field(default_factory=list)
    base: int = 0                        # cached-prefix positions skipped
    n_hit: int = 0                       # leading shared (hit) blocks
    req: Request | None = None           # original request (for replay)
    prior: list[int] = dataclasses.field(default_factory=list)
    retries: int = 0
    wait_steps: int = 0                  # prefill-retry backoff
    deadline: float | None = None
    slo_deadline: float | None = None    # enqueue + slo_s (EDF policy)
    admit_seq: int = -1                  # monotonic; max = youngest row
    draft: "drafting_mod.NgramDraftState | None" = None


class ServeEngine:
    """Serve a queue of greedy requests through a recycled slot pool.

    ``n_slots``: compiled batch width.  ``max_len``: per-request logical
    depth bound (prompt + generation).  ``chunk``: the chunked-prefill
    window — one [1, chunk] prompt window runs per step per admitting
    slot, which is the knob trading admission latency against how much a
    long prompt delays the next decode tick.  ``block_size`` (default:
    ``chunk``) and ``n_blocks`` size the paged pool; the default pool
    fully backs every slot, smaller pools overcommit and admission waits
    for free blocks.  ``timeline``: an optional
    :class:`horovod_tpu.timeline.Timeline` receiving admit/recycle
    instants plus per-step queue/occupancy (``SCHED``) and lifecycle
    (``LIFECYCLE``: preemptions/timeouts/retries/…) counters.

    Fault-tolerance knobs:

    ``preempt_after``: consecutive steps the queue head may starve on an
    overcommitted block pool before the youngest decoding row is
    preempted and re-queued for replay (``None`` disables preemption).
    ``max_retries``: bounded retries for transient per-request faults
    (prefill windows retry in place after a ``2**retries``-step backoff;
    decode readback retries re-queue through the replay path); once
    exhausted — or immediately on a
    :class:`~horovod_tpu.faults.PermanentFault` — the request terminates
    ``FAILED`` with the exception attached, and every other row keeps
    serving.  ``watchdog_steps``: consecutive no-progress steps (no
    admission, prefill window, decode tick, retirement, preemption, or
    backoff countdown while work is pending) before ``step()`` raises
    ``RuntimeError`` with a scheduler-state dump instead of letting
    ``run()`` spin forever.  ``faults``: a
    :class:`~horovod_tpu.faults.FaultRegistry` consulted at the
    ``serve.admit`` / ``serve.prefill`` / ``serve.tick`` /
    ``serve.cache`` sites (defaults to the shared registry, which is a
    no-op unless armed).

    ``metrics``: a :class:`horovod_tpu.metrics.MetricsRegistry` fed on
    every step — TTFT / TPOT / queue-wait / e2e latency histograms
    (``serve.*_s``), lifecycle counters mirroring ``self.counters``,
    and KV-pool + prefix-cache gauges — plus one structured event per
    request state transition when the registry has an event log
    (``HVD_TPU_EVENT_LOG``).  Defaults to the process-shared
    :data:`horovod_tpu.metrics.DEFAULT` registry (one scrape sees
    training and serving together); pass
    :data:`horovod_tpu.metrics.NULL` to opt out.  Every request also
    carries a :class:`~horovod_tpu.metrics.Trace` (surfaced on
    ``RequestResult.trace`` and mirrored into the timeline as a
    per-rid ``REQ`` async span) regardless of the registry.
    ``metrics_snapshot()`` returns the registry's plain-dict snapshot.

    ``prefix_cache``: enable transparent shared-prefix KV reuse
    (:mod:`horovod_tpu.prefix_cache`) — admission longest-prefix-matches
    each prompt against the radix index of previously served requests
    and maps the hit blocks straight into the new row, so chunked
    prefill starts at the first uncached token; retirement releases
    blocks *to the cache* (zero-ref blocks park in LRU order) instead
    of freeing, and admission under KV pressure evicts cached blocks
    before any decoding row is preempted.  Off by default: block
    accounting is then exactly the classic free list and every code
    path is unchanged.  Set ``HVD_TPU_VERIFY_BLOCKS=1`` to walk the
    block tables after every step asserting refcount consistency (debug
    aid; O(slots * blocks) host work per step).

    ``spec`` / ``draft_k``: self-drafting speculative decode — each
    decoding row's prompt-lookup drafter
    (:class:`~horovod_tpu.drafting.NgramDraftState`) proposes up to
    ``draft_k`` tokens per tick from the request's own history and ONE
    always-``(draft_k + 1)``-wide batched verify program
    (:func:`~horovod_tpu.models.llama.spec_verify_paged`) decodes every
    row's chunk with per-row greedy longest-prefix acceptance; rejected
    positions roll back by the row's length alone (write-before-read).
    One extra jit signature for the life of the server (``spec_tick``
    replaces ``tick`` in ``compile_cache_sizes()``), every output stays
    bit-identical to solo greedy generate, and a round can emit up to
    ``1 + draft_k`` tokens per row.  ``None`` reads ``HVD_TPU_SPEC`` /
    ``HVD_TPU_DRAFT_K`` (off / 4).

    ``policy``: admission-order + preemption-victim policy — a
    :class:`~horovod_tpu.scheduling.SchedulerPolicy` instance, a name
    (``fifo`` / ``priority`` / ``edf``), or ``None`` to read
    ``HVD_TPU_SCHED_POLICY``.  FIFO is bit-compatible with the
    pre-policy engine; policies reorder who waits and who is evicted,
    never any request's tokens (scheduler invariant 2).

    ``tp_size``: tensor-parallel serving — the decode path runs on a
    1-axis ``('tp',)`` device mesh
    (:func:`~horovod_tpu.parallel.mesh.tensor_parallel_mesh`) with
    params Megatron-split and the paged KV pool head-split, so KV HBM
    and the matmul work divide across ``tp_size`` chips while the
    block pool / prefix cache / block tables stay host-side and
    shard-agnostic.  Greedy outputs are token-identical to the
    unsharded engine and ``compile_cache_sizes()`` stays at one
    signature per program.  ``None`` reads ``HVD_TPU_TP`` (default
    1); at 1 there is no mesh and every code path is the
    single-device one.
    """

    def __init__(self, params: dict, cfg: llama.LlamaConfig, *,
                 n_slots: int, max_len: int, chunk: int,
                 block_size: int | None = None,
                 n_blocks: int | None = None,
                 tp_size: int | None = None,
                 timeline: Any = None,
                 preempt_after: int | None = None,
                 max_retries: int = 2,
                 watchdog_steps: int = 256,
                 faults: "faults_mod.FaultRegistry | None" = None,
                 metrics: "metrics_mod.MetricsRegistry | None" = None,
                 prefix_cache: bool = False,
                 monitor: "monitor_mod.MonitorServer | int | bool | None"
                     = None,
                 slo_window: int = 256,
                 slo_e2e_s: float | None = None,
                 profile: bool | None = None,
                 profile_window: int | None = None,
                 spec: bool | None = None,
                 draft_k: int | None = None,
                 policy: "scheduling_mod.SchedulerPolicy | str | None"
                     = None,
                 sampler: "timeseries_mod.MetricsSampler | bool | None"
                     = None,
                 alerts: "alerts_mod.AlertManager | bool | None"
                     = None,
                 device_telemetry:
                     "device_telemetry_mod.DeviceTelemetry | bool | None"
                     = None):
        if chunk < 1 or chunk > max_len:
            raise ValueError(f"chunk {chunk} must be in [1, max_len "
                             f"{max_len}]")
        if preempt_after is not None and preempt_after < 1:
            raise ValueError("preempt_after must be >= 1 (or None)")
        if watchdog_steps < 1:
            raise ValueError("watchdog_steps must be >= 1")
        block_size = chunk if block_size is None else block_size
        # Tensor-parallel serving: tp_size > 1 puts the decode path on a
        # 1-axis ('tp',) mesh — params Megatron-split per
        # llama.param_partition_specs, the paged KV pool head-split per
        # llama.paged_cache_partition_specs — while the block pool /
        # prefix cache / block tables stay host-side and shard-agnostic
        # (one logical block id addresses the same slot of every chip's
        # head slice).  None reads HVD_TPU_TP (default 1); at tp_size=1
        # no mesh exists and every code path is the single-device one.
        if tp_size is None:
            raw = os.environ.get("HVD_TPU_TP", "")
            tp_size = int(raw) if raw else 1
        tp_size = int(tp_size)
        if tp_size < 1:
            raise ValueError(f"tp_size must be >= 1, got {tp_size}")
        if tp_size > 1:
            for dim_name, dim in (("n_heads", cfg.n_heads),
                                  ("n_kv_heads", cfg.n_kv_heads),
                                  ("dim", cfg.dim),
                                  ("ffn_dim", cfg.ffn_dim),
                                  ("vocab_size", cfg.vocab_size)):
                if dim % tp_size:
                    raise ValueError(
                        f"tp_size={tp_size} does not divide "
                        f"cfg.{dim_name}={dim}: every tp-sharded axis "
                        f"must split evenly across the mesh")
        self.tp_size = tp_size
        if tp_size > 1:
            self.mesh = tensor_parallel_mesh(tp_size)
            pspecs = llama.param_partition_specs(cfg, tp_axis="tp")
            cspecs = llama.paged_cache_partition_specs(tp_axis="tp")
            self._param_sh = jax.tree.map(
                lambda s: NamedSharding(self.mesh, s), pspecs,
                is_leaf=lambda x: isinstance(x, PartitionSpec))
            self._cache_sh = llama.PagedKVCache(
                *(NamedSharding(self.mesh, s) for s in cspecs))
            self._repl_sh = NamedSharding(self.mesh, PartitionSpec())
            # Pre-commit the persistent state to its exact target
            # sharding: jit cache keys distinguish committed from
            # uncommitted inputs, so an uncommitted first call would
            # mint a second signature and trip the retrace sentry.
            params = jax.tree.map(jax.device_put, params, self._param_sh)
        else:
            self.mesh = None
            self._param_sh = self._cache_sh = self._repl_sh = None
        self.params = params
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_len = max_len
        self.chunk = chunk
        self.block_size = block_size
        self.timeline = timeline
        self.preempt_after = preempt_after
        self.max_retries = max_retries
        self.watchdog_steps = watchdog_steps
        self.faults = faults if faults is not None else faults_mod.DEFAULT
        self.metrics = metrics if metrics is not None else metrics_mod.DEFAULT
        # Scheduler policy (admission order + preemption victim): FIFO
        # default is bit-compatible with the pre-policy engine.
        self.policy = scheduling_mod.resolve_policy(policy)
        # Self-drafting speculation: env-driven when unset.
        if spec is None:
            spec = os.environ.get("HVD_TPU_SPEC", "") == "1"
        if draft_k is None:
            raw = os.environ.get("HVD_TPU_DRAFT_K", "")
            draft_k = int(raw) if raw else drafting_mod.DEFAULT_DRAFT_K
        if spec and draft_k < 1:
            raise ValueError(f"draft_k must be >= 1, got {draft_k}")
        self.spec = bool(spec)
        self.draft_k = int(draft_k)
        self.spec_counters = {"rounds": 0, "row_rounds": 0,
                              "proposed": 0, "accepted": 0}
        if self.spec:
            # Registered up front (literal names — the HVD005 contract)
            # so spec snapshots are schema-stable from step 0.
            self.metrics.counter("serve.spec.rounds")
            self.metrics.counter("serve.spec.row_rounds")
            self.metrics.counter("serve.spec.proposed")
            self.metrics.counter("serve.spec.accepted")
            self.metrics.counter("serve.spec.draft_faults")
            self.metrics.histogram("serve.spec.accepted_per_round")
        # Register the latency histograms up front so metrics_snapshot()
        # is schema-stable from step 0 (empty histograms report zeros).
        for h in ("serve.ttft_s", "serve.tpot_s", "serve.queue_wait_s",
                  "serve.e2e_s"):
            self.metrics.histogram(h)
        # Causal tracing plane (horovod_tpu.tracing): spans are emitted
        # post-hoc from Trace stamps at terminal time, so with sampling
        # off the hot path pays one None-check per request.
        self.tracer = tracing_mod.Tracer(self.metrics)
        self._trace_fraction = tracing_mod.env_sample_fraction()
        self._trace_seed = tracing_mod.env_trace_seed()
        # Per-tick phase profiler: None = env-driven (HVD_TPU_PROFILE=1).
        # Off means prof is None and every call site is one `is not
        # None` test — the hot path pays nothing.
        if profile is None:
            profile = os.environ.get("HVD_TPU_PROFILE", "") == "1"
        self.prof = (profiler_mod.TickProfiler(
            self.metrics, timeline=timeline, window=profile_window)
            if profile else None)
        # Device telemetry plane (horovod_tpu.device_telemetry): XLA
        # cost model + compile ledger + HBM polling + the device_sync
        # compute/stall split.  None = env-driven
        # (HVD_TPU_DEVICE_TELEMETRY=1), False = off, True = on, an
        # instance is used as-is.  Off means device is None and every
        # hot-path call site is one `is not None` test.
        if device_telemetry is False:
            self.device = None
        elif device_telemetry is None:
            self.device = device_telemetry_mod.maybe_telemetry(
                self.metrics, n_devices=tp_size)
        elif device_telemetry is True:
            self.device = device_telemetry_mod.DeviceTelemetry(
                self.metrics, n_devices=tp_size)
        else:
            self.device = device_telemetry
        # Retrace sentry: the dynamic complement to hvdlint HVD001 —
        # compile_cache_sizes() is diffed every step and any mid-serve
        # growth bumps serve.retrace (fatal under HVD_TPU_RETRACE_FATAL=1).
        self._retrace_fatal = os.environ.get(
            "HVD_TPU_RETRACE_FATAL", "") == "1"
        self.metrics.counter("serve.retrace")
        self._t0 = time.monotonic()
        self._last_step_ts: float | None = None
        # SLO goodput window: every terminal trace lands here; the
        # serve.goodput gauge tracks the windowed good fraction.
        self.slo = monitor_mod.SLOWindow(window=slo_window,
                                         slo_e2e_s=slo_e2e_s)
        self._slo_targets: dict[int, float | None] = {}
        # Health plane: time-series sampler + alert rules, ticked from
        # step() bookkeeping (no threads).  None = env-driven
        # (HVD_TPU_SAMPLE_S / HVD_TPU_ALERTS), False = off, an instance
        # is used as-is; the capacity advisor rides along whenever a
        # sampler is live.
        if sampler is False:
            self.sampler = None
        elif sampler is None:
            self.sampler = timeseries_mod.maybe_sampler(self.metrics)
        else:
            self.sampler = sampler
        if alerts is False or self.sampler is None:
            self.alerts = None
        elif alerts is None:
            self.alerts = alerts_mod.maybe_alerts(
                self.sampler, self.metrics)
        else:
            self.alerts = alerts
        self.advisor = (alerts_mod.CapacityAdvisor(
            self.sampler, alerts=self.alerts, registry=self.metrics)
            if self.sampler is not None else None)
        # Live exporter: False = off; None = env-driven
        # (HVD_TPU_MONITOR_PORT); int = bind that port; an existing
        # MonitorServer re-attaches to this engine.
        if monitor is False:
            self.monitor = None
        elif monitor is None:
            self.monitor = monitor_mod.maybe_start_monitor(
                self.metrics, self)
        elif isinstance(monitor, monitor_mod.MonitorServer):
            monitor.attach_engine(self)
            self.monitor = monitor
        elif isinstance(monitor, int) and monitor is not True:
            self.monitor = monitor_mod.MonitorServer(
                self.metrics, self, port=monitor).start()
        else:
            raise ValueError(
                f"monitor must be None / False / port int / "
                f"MonitorServer, got {monitor!r}")
        self.pcache = llama.init_paged_cache(
            cfg, n_slots, max_len, block_size=block_size,
            n_blocks=n_blocks)
        if self.tp_size > 1:
            self.pcache = llama.PagedKVCache(*(
                jax.device_put(x, s)
                for x, s in zip(self.pcache, self._cache_sh)))
        self.blocks_per_slot = self.pcache.block_table.shape[1]
        total = self.pcache.k.shape[1]
        # block 0 is trash — never allocated; the pool's free list pops
        # low ids first, matching the classic free-list order
        self.pool = llama.BlockPool(total)
        # legacy alias: the SAME list object the pool allocates from
        # (white-box tests drain it to force block starvation)
        self._free_blocks = self.pool._free
        # KV memory accounting: one physical block holds block_size
        # positions of K and V across every layer, so its device
        # footprint follows directly from the cache dtype and shape
        # ([n_layers, n_blocks, block_size, n_kv_heads, head_dim]).
        kb = self.pcache.k
        self._block_bytes = (2 * kb.dtype.itemsize * kb.shape[0]
                             * kb.shape[2] * kb.shape[3] * kb.shape[4])
        self.metrics.gauge("kv.block_bytes").set(self._block_bytes)
        self.metrics.gauge("kv.total_bytes").set(
            self._block_bytes * total)
        # Per-shard KV accounting: each chip holds n_kv_heads / tp of
        # every block (head-split pool), so shard bytes are the logical
        # bytes over tp — exact, the head axis divides evenly (checked
        # above).  Uniform schema: at tp_size=1 shard gauges equal the
        # logical ones, and the tp gauges always exist so scrapes and
        # router capacity probes never branch on engine flavor.
        self._shard_block_bytes = self._block_bytes // self.tp_size
        self.metrics.gauge("tp.size").set(self.tp_size)
        self.metrics.gauge("kv.shard_block_bytes").set(
            self._shard_block_bytes)
        self.metrics.gauge("kv.shard_total_bytes").set(
            self._shard_block_bytes * total)
        self.prefix = (RadixPrefixCache(self.pool, block_size,
                                        metrics=self.metrics)
                       if prefix_cache else None)
        self.prefix_counters = {"hits": 0, "blocks_reused": 0,
                                "tokens_skipped": 0, "evictions": 0}
        self._verify_blocks = os.environ.get(
            "HVD_TPU_VERIFY_BLOCKS", "") == "1"
        self._trash_row = np.zeros((self.blocks_per_slot,), np.int32)
        self.last_logits = jnp.zeros((n_slots, cfg.vocab_size),
                                     jnp.float32)
        if self.tp_size > 1:
            self.last_logits = jax.device_put(self.last_logits,
                                              self._repl_sh)
        self._slots = [_Slot() for _ in range(n_slots)]
        self._queue: list[_QueueEntry] = []
        self._next_id = 0
        self._admit_seq = 0
        self._starve_steps = 0
        self._idle_steps = 0
        self._finished: dict[int, RequestResult] = {}
        self.results: dict[int, RequestResult] = {}
        self.traces: dict[int, Trace] = {}
        self.events: list[SchedulerEvent] = []
        self.counters = {"preemptions": 0, "timeouts": 0,
                         "cancellations": 0, "rejections": 0,
                         "retries": 0, "failures": 0}
        self.step_index = 0

        # Sharded program signatures: explicit in/out shardings pin the
        # GSPMD layout at every jit boundary (params Megatron-split, KV
        # pool head-split, everything the host reads replicated) — XLA
        # then keeps Q·Kᵀ and the MLP matmuls chip-local with one psum
        # per attention/MLP block (the row-parallel wo/w_down reduction)
        # and tp>1 stays at one signature per program.  At tp_size=1 the
        # kwargs are empty and the decorators are byte-identical to the
        # single-device engine.
        if self.tp_size > 1:
            _p, _c, _r = self._param_sh, self._cache_sh, self._repl_sh
            _tick_sh = dict(in_shardings=(_p, _c, _r, _r),
                            out_shardings=(_r, _r, _c))
            _chunk_sh = dict(in_shardings=(_p, _c, _r, _r, _r, _r, _r),
                             out_shardings=(_c, _r))
            _row_sh = dict(in_shardings=(_c, _r, _r, _r),
                           out_shardings=_c)
            _spec_sh = dict(in_shardings=(_p, _c, _r, _r, _r),
                            out_shardings=(_r, _r, _r, _c))
        else:
            _tick_sh = _chunk_sh = _row_sh = _spec_sh = {}

        @partial(jax.jit, donate_argnums=(1, 2), **_tick_sh)
        def _tick(params, pcache, last_logits, active):
            # the fixed-signature decode tick: every row argmaxes its
            # last logits and decodes one position; `active` [B] gates
            # the length advance so idle/prefilling rows hold position
            # (their garbage write lands in their own blocks or trash —
            # invariant 1).  Donation matters: decode cost IS cache
            # traffic, an undonated pool would copy every block per tick.
            tok = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
            logits, pcache = llama.decode_chunk_paged(
                params, tok[:, None], cfg, pcache, advance=active)
            return tok, logits[:, 0], pcache

        @partial(jax.jit, donate_argnums=(1, 2), **_chunk_sh)
        def _chunk(params, pcache, last_logits, toks, slot, new_len, sel):
            # one chunked-prefill window for one slot: [1, chunk] tokens
            # continue the row from its current length; `sel` picks the
            # window position whose logits seed decoding (only the final
            # window's pick survives — later windows overwrite).
            logits, pcache = llama.decode_chunk_paged_row(
                params, toks, cfg, pcache, slot, new_length=new_len)
            last_logits = last_logits.at[slot].set(logits[0, sel])
            return pcache, last_logits

        @partial(jax.jit, donate_argnums=(0,), **_row_sh)
        def _set_row(pcache, slot, row, length):
            # admission/retirement table write: swaps which physical
            # blocks a slot row maps to and sets its length — data
            # only, so slot recycling (and every lifecycle transition:
            # preempt, cancel, timeout, fail) reuses the same compiled
            # programs.  `length` is 0 except on a prefix-cache hit,
            # where it is the cached frontier so the first prefill
            # window continues from the first uncached token.
            return pcache._replace(
                block_table=pcache.block_table.at[slot].set(row),
                length=pcache.length.at[slot].set(length))

        if self.spec:
            @partial(jax.jit, donate_argnums=(1, 2), **_spec_sh)
            def _spec_tick(params, pcache, last_logits, drafts, active):
                # the always-wide speculative tick: one (draft_k+1)-wide
                # verify for the whole pool, acceptance and the gated
                # length advance computed in-program so the host reads
                # back tokens AND accepted counts in one sync.  Replaces
                # _tick entirely on a spec engine — still one signature
                # per program for the life of the server.
                return llama.spec_verify_paged(
                    params, cfg, pcache, last_logits, drafts, active)

            self._spec_tick = _spec_tick
        else:
            self._spec_tick = None
        self._tick = _tick
        self._chunk = _chunk
        self._set_row = _set_row
        # Device cost-model capture happens BEFORE the sentry baseline
        # on purpose: AOT lowering never mints jit call-cache entries,
        # and taking the baseline after it proves that property every
        # construction (the sentry would flag any drift immediately).
        if self.device is not None:
            self._device_capture_programs(self.device)
        # Sentry baseline: all zeros pre-warmup.  The first compile of
        # each program (0 -> 1) is legitimate; the sentry only counts
        # growth BEYOND one signature per program.
        self._jit_cache_seen = self.compile_cache_sizes()

    # -- introspection -----------------------------------------------------

    def compile_cache_sizes(self) -> dict[str, int]:
        """Per-program jit cache entry counts — the no-retrace pin:
        admission/recycling/preemption must keep every count constant.
        A spec engine adds the ``spec_tick`` key (its always-wide verify
        program, which replaces ``tick`` so that count stays 0)."""
        sizes = {
            "tick": self._tick._cache_size(),
            "chunk": self._chunk._cache_size(),
            "set_row": self._set_row._cache_size(),
        }
        if self._spec_tick is not None:
            sizes["spec_tick"] = self._spec_tick._cache_size()
        return sizes

    def _device_capture_programs(
            self, dev: "device_telemetry_mod.DeviceTelemetry") -> None:
        """AOT-capture the XLA cost model of every pinned program into
        ``dev`` (FLOPs / bytes-accessed / compile wall time per
        dispatch) and hand it the exact model-side device bytes for HBM
        reconciliation.  Built from ``ShapeDtypeStruct`` avals of the
        live arrays, so each capture lowers the very signature serving
        will call — and ``jitfn.lower()`` never touches the jit call
        cache, so ``compile_cache_sizes()`` is identical telemetry-on
        vs off (pinned by tests/test_device_telemetry.py)."""
        aval = lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)  # noqa: E731
        p_av = jax.tree.map(aval, self.params)
        c_av = jax.tree.map(aval, self.pcache)
        ll_av = aval(self.last_logits)
        i32 = jax.ShapeDtypeStruct((), jnp.int32)
        active_av = jax.ShapeDtypeStruct((self.n_slots,), jnp.int32)
        toks_av = jax.ShapeDtypeStruct((1, self.chunk), jnp.int32)
        row_av = jax.ShapeDtypeStruct((self.blocks_per_slot,), jnp.int32)
        dev.capture("tick", self._tick, p_av, c_av, ll_av, active_av)
        dev.capture("chunk", self._chunk, p_av, c_av, ll_av, toks_av,
                    i32, i32, i32)
        dev.capture("set_row", self._set_row, c_av, i32, row_av, i32)
        if self._spec_tick is not None:
            drafts_av = jax.ShapeDtypeStruct(
                (self.n_slots, self.draft_k), jnp.int32)
            dev.capture("spec_tick", self._spec_tick, p_av, c_av,
                        ll_av, drafts_av, active_av)
        param_bytes = sum(
            int(np.prod(x.shape)) * x.dtype.itemsize
            for x in jax.tree.leaves(self.params))
        dev.set_model_bytes(
            param_bytes=param_bytes,
            kv_total_bytes=self._block_bytes * self.pcache.k.shape[1])

    def free_block_count(self) -> int:
        return len(self._free_blocks)

    def cached_block_count(self) -> int:
        """Zero-ref blocks parked in the prefix cache (0 without it)."""
        return self.pool.cached_count()

    def pending(self) -> bool:
        return bool(self._queue) or any(
            s.state != FREE for s in self._slots)

    def metrics_snapshot(self) -> dict:
        """Plain-dict snapshot of the engine's registry: counters,
        gauges, and the TTFT / TPOT / queue-wait / e2e histograms with
        p50/p90/p99 — plus the windowed ``slo`` report, the ``memory``
        accounting report, and (with profiling on) the rolling
        ``profile`` phase breakdown — queryable with no timeline
        attached."""
        mem = self.memory_report()    # refreshes kv.*/mem.* gauges
        snap = self.metrics.snapshot()
        snap["slo"] = self.slo_report()
        snap["memory"] = mem
        if self.prefix is not None:
            # Bounded radix-path digest summary: what a prefix-affinity
            # router needs to know about THIS replica's cached prefixes
            # (rides /snapshot via the monitor for free).
            snap["prefix"] = self.prefix.key_digest()
        if self.prof is not None:
            snap["profile"] = self.prof.report()
        if self.device is not None:
            snap["device"] = self.device.report()
        if self.sampler is not None:
            # Trailing points only: the full rings stay behind the
            # /timeseries endpoint; snapshots ride merge_snapshots and
            # state dumps, where bounded beats complete.
            snap["timeseries"] = self.sampler.report(points=16)
        if self.alerts is not None:
            snap["alerts"] = self.alerts.report()
        if self.advisor is not None:
            snap["advice"] = self.advisor.recommend()
        return snap

    def memory_report(self) -> dict:
        """Where the memory is: the paged KV pool by state (free /
        referenced / cached, in blocks AND device bytes derived from the
        cache dtype/shape) and the host-side observability footprint
        (registry instruments, trace ring + SLO window, event-log file,
        prefix radix index).  Also refreshes the ``kv.*`` / ``mem.*``
        gauges so a scrape sees the same numbers."""
        free = self.pool.free_count()
        referenced = self.pool.ref_count()
        cached = self.pool.cached_count()
        bb = self._block_bytes
        sbb = self._shard_block_bytes
        kv = {
            "block_bytes": bb,
            "total_bytes": bb * self.pcache.k.shape[1],
            "free_blocks": free, "free_bytes": free * bb,
            "referenced_blocks": referenced,
            "referenced_bytes": referenced * bb,
            "cached_blocks": cached, "cached_bytes": cached * bb,
            # per-chip view of the same pool (logical / tp_size; block
            # *counts* are per-chip already — every chip maps every
            # block, each holding its own head slice)
            "tp_size": self.tp_size,
            "shard_block_bytes": sbb,
            "shard_total_bytes": sbb * self.pcache.k.shape[1],
            "shard_free_bytes": free * sbb,
            "shard_referenced_bytes": referenced * sbb,
            "shard_cached_bytes": cached * sbb,
        }
        # host side: getsizeof-level approximations — trend lines for
        # leak spotting, not byte-exact accounting
        trace_ring = sum(sys.getsizeof(t) for t in
                         list(self.traces.values()))
        trace_ring += len(self.slo) * 128    # SLO ring holds Trace refs
        log = self.metrics.active_event_log()
        try:
            log_bytes = (os.path.getsize(log.path)
                         if log is not None else 0)
        except OSError:
            log_bytes = 0
        host = {
            "registry_bytes": self.metrics.approx_footprint_bytes(),
            "trace_ring_bytes": trace_ring,
            "event_log_bytes": log_bytes,
            "prefix_index_bytes": (self.prefix.approx_footprint_bytes()
                                   if self.prefix is not None else 0),
        }
        self.metrics.gauge("kv.free_blocks").set(free)
        self.metrics.gauge("kv.free_bytes").set(free * bb)
        self.metrics.gauge("kv.referenced_blocks").set(referenced)
        self.metrics.gauge("kv.referenced_bytes").set(referenced * bb)
        self.metrics.gauge("kv.cached_blocks").set(cached)
        self.metrics.gauge("kv.cached_bytes").set(cached * bb)
        self.metrics.gauge("kv.shard_free_bytes").set(free * sbb)
        self.metrics.gauge("kv.shard_referenced_bytes").set(
            referenced * sbb)
        self.metrics.gauge("kv.shard_cached_bytes").set(cached * sbb)
        self.metrics.gauge("mem.registry_bytes").set(
            host["registry_bytes"])
        self.metrics.gauge("mem.trace_ring_bytes").set(trace_ring)
        self.metrics.gauge("mem.event_log_bytes").set(log_bytes)
        self.metrics.gauge("mem.prefix_index_bytes").set(
            host["prefix_index_bytes"])
        return {"kv": kv, "host": host}

    def slo_report(self) -> dict:
        """The SLO window's answer to "are we meeting SLOs *now*":
        goodput, status mix, and windowed TTFT/TPOT/E2E percentiles over
        the last ``slo_window`` terminal requests."""
        return self.slo.report()

    def state_dump(self) -> str:
        """Human-readable scheduler state (the watchdog's evidence):
        uptime / step totals, per-state slot and terminal-status
        counts, pool and prefix-cache pictures, every queued and live
        request, and the metrics snapshot — a full postmortem."""
        states = {FREE: 0, PREFILL: 0, DECODE: 0}
        for s in self._slots:
            states[s.state] += 1
        by_status: dict[str, int] = {}
        for r in self.results.values():
            by_status[r.status] = by_status.get(r.status, 0) + 1
        lines = [
            f"rank={metrics_mod.current_rank()} pid={os.getpid()} "
            f"step={self.step_index} uptime_s="
            f"{time.monotonic() - self._t0:.3f} "
            f"queue_depth={len(self._queue)} "
            f"free_blocks={len(self._free_blocks)}/"
            f"{self.pcache.k.shape[1] - 1} starve_steps="
            f"{self._starve_steps} counters={self.counters}",
            f"  slots: free={states[FREE]} prefill={states[PREFILL]} "
            f"decode={states[DECODE]}; submitted={self._next_id} "
            f"finished={dict(sorted(by_status.items()))}",
            "  metrics=" + json.dumps(self.metrics_snapshot(),
                                      sort_keys=True),
        ]
        if self.alerts is not None:
            arep = self.alerts.report()
            lines.append(
                f"  alerts: firing={arep['firing']} "
                f"pending={arep['pending']} "
                f"transitions={len(arep['history'])}")
        if self.advisor is not None:
            rec = self.advisor.recommend()
            lines.append(f"  advice: {rec['action']} n={rec['n']} "
                         f"({rec['reason']})")
        bb = self._block_bytes
        lines.append(
            f"  kv bytes: block={bb} free={self.pool.free_count() * bb}"
            f" referenced={self.pool.ref_count() * bb}"
            f" cached={self.pool.cached_count() * bb}"
            f" total={bb * self.pcache.k.shape[1]}"
            f" tp_size={self.tp_size}"
            f" shard_total="
            f"{self._shard_block_bytes * self.pcache.k.shape[1]}")
        if self.prof is not None:
            rep = self.prof.report()
            lines.append(
                "  profile (mean ms over last "
                f"{rep['n']} ticks): " + " ".join(
                    f"{p}={rep['phases'][p]['mean_s'] * 1e3:.3f}"
                    for p in rep["phases"] if "." not in p)
                + f" tick={rep['tick']['mean_s'] * 1e3:.3f}")
        if self.device is not None:
            drep = self.device.report()
            mfu = drep["win"]["mfu"]
            lines.append(
                f"  device: {drep['platform']}/{drep['device_kind']}"
                f" x{drep['n_devices']}"
                f" peak_known={drep['peak_flops_known']}"
                f" mfu={'n/a' if mfu is None else f'{mfu:.4f}'}"
                f" flops/s={drep['win']['flops_per_s']:.3e}"
                f" headroom={drep['win']['overlap_headroom_pct']:.1f}%"
                f" compiles={drep['compiles']}"
                f" retrace_est_s={drep['retrace_compile_est_s']:.3f}")
        lines += ["  " + ln for ln in self.pool.state_lines()]
        if self.prefix is not None:
            lines.append(
                f"  prefix cache: indexed="
                f"{self.prefix.indexed_blocks()} "
                f"counters={self.prefix_counters} "
                f"stats={self.prefix.stats}")
        for e in self._queue:
            lines.append(
                f"  queued rid={e.rid} prompt={len(e.req.prompt)} "
                f"prior={len(e.prior)} need={self._need_blocks(e.req)} "
                f"retries={e.retries} wait={e.wait_steps} "
                f"queued_steps={e.queued_steps}")
        for i, s in enumerate(self._slots):
            lines.append(
                f"  slot {i}: {s.state}" + (
                    "" if s.state == FREE else
                    f" rid={s.request_id} w={s.w_done}/{s.n_win} "
                    f"out={len(s.out)} budget={s.budget} "
                    f"blocks={s.n_blocks} shared={s.n_hit} "
                    f"retries={s.retries} wait={s.wait_steps}"))
        return "\n".join(lines)

    # -- queue -------------------------------------------------------------

    def _need_blocks(self, req: Request) -> int:
        # constant across replays: replay prompt grows by exactly the
        # tokens the remaining budget shrinks by
        return -(-(len(req.prompt) + req.max_new_tokens)
                 // self.block_size)

    def submit(self, req: Request) -> int:
        """Queue a request; returns its id (key into ``results``).
        Validation happens here so a rejected request never holds a
        queue position."""
        L = len(req.prompt)
        if L < 1 or req.max_new_tokens < 1:
            # Malformed client data (as opposed to caller programming
            # errors below, which still raise): reject with the same
            # terminal-status contract the queue-overflow shed and the
            # router's admission-control shed use, so one status check
            # covers every "the fleet would not serve this" path.
            return self._reject_submit(req, L)
        if req.temperature not in (None, 0.0) or req.sample_key is not None:
            raise ValueError(
                "ServeEngine is greedy-only; serve sampled requests "
                "through ContinuousBatcher")
        if req.prefix is not None:
            raise ValueError(
                "ServeEngine does not splice prefix caches yet; use "
                "ContinuousBatcher for prefix requests")
        if req.slo_s is not None and req.slo_s <= 0:
            raise ValueError(f"slo_s must be positive, got {req.slo_s}")
        if L + req.max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {L} + max_new_tokens {req.max_new_tokens} "
                f"exceeds max_len {self.max_len}")
        n_win = -(-L // self.chunk)
        if n_win * self.chunk > self.max_len:
            raise ValueError(
                f"prompt {L} padded to {n_win * self.chunk} prefill "
                f"windows exceeds max_len {self.max_len}")
        need = self._need_blocks(req)
        if need > self.pcache.k.shape[1] - 1:
            raise ValueError(
                f"request needs {need} cache blocks but the pool only "
                f"has {self.pcache.k.shape[1] - 1} allocatable")
        rid = self._next_id
        self._next_id += 1
        now = time.monotonic()
        deadline = None if req.deadline_s is None else now + req.deadline_s
        slo_deadline = None if req.slo_s is None else now + req.slo_s
        self._queue.append(_QueueEntry(rid=rid, req=req,
                                       deadline=deadline,
                                       slo_deadline=slo_deadline))
        self.traces[rid] = Trace(rid=rid, enqueue_ts=now,
                                 enqueue_step=self.step_index)
        self._maybe_open_trace(req, rid, self.traces[rid], now)
        self._slo_targets[rid] = req.slo_s
        self.metrics.counter("serve.requests_submitted").inc()
        self.metrics.event("serve.submit", rid=rid, step=self.step_index,
                           prompt_len=L,
                           max_new_tokens=req.max_new_tokens)
        if self.timeline is not None:
            self.timeline.async_start("serving.requests", "REQ", rid)
        return rid

    def _maybe_open_trace(self, req: Request, rid: int, tr: Trace,
                          now: float) -> None:
        """Join the causal tracing plane at submit: adopt a propagated
        context (the router's ``replica.attempt`` span) as parent, or
        head-sample an engine-origin root keyed on ``serve:<rid>`` —
        a pure function of (seed, rid), so sampling decisions replay
        bit-identically (HVD010)."""
        ctx = getattr(req, "trace_ctx", None)
        if ctx is not None:
            sctx = ctx.child("serve.request")
            tr.parent_span_id = ctx.span_id
        elif self._trace_fraction > 0.0:
            sctx = tracing_mod.TraceContext.root(
                f"serve:{rid}", "serve.request",
                self._trace_fraction, self._trace_seed)
            if sctx is None:
                return
            tracing_mod.count_sampled(self.metrics)
        else:
            return
        tr.trace_id = sctx.trace_id
        tr.span_id = sctx.span_id
        self.tracer.span_open(sctx, "serve.request", now,
                              parent_id=tr.parent_span_id, rid=rid)

    def _reject_submit(self, req: Request, L: int) -> int:
        """Terminal ``REJECTED`` for a request invalid on its face
        (empty prompt, non-positive budget).  It gets a real rid, a
        trace, and the full submit/reject event pair — never a queue
        position — so callers poll ``results`` exactly as they would
        for a load-shed request."""
        rid = self._next_id
        self._next_id += 1
        now = time.monotonic()
        self.traces[rid] = Trace(rid=rid, enqueue_ts=now,
                                 enqueue_step=self.step_index)
        self._maybe_open_trace(req, rid, self.traces[rid], now)
        self._slo_targets[rid] = req.slo_s
        self.metrics.counter("serve.requests_submitted").inc()
        self.metrics.event("serve.submit", rid=rid, step=self.step_index,
                           prompt_len=L,
                           max_new_tokens=req.max_new_tokens)
        if self.timeline is not None:
            self.timeline.async_start("serving.requests", "REQ", rid)
        self._finish_queued(_QueueEntry(rid=rid, req=req), REJECTED)
        return rid

    def cancel(self, rid: int) -> bool:
        """Cancel a request in ANY live state — queued, prefilling, or
        decoding.  Its result becomes ``CANCELLED`` with tokens-so-far;
        blocks return to the pool on the same ``_set_row`` program
        retirement uses.  Returns False when ``rid`` is unknown or
        already terminal (cancel-after-finish is not an error)."""
        for i, e in enumerate(self._queue):
            if e.rid == rid:
                self._queue.pop(i)
                self._finish_queued(e, CANCELLED)
                return True
        for slot, s in enumerate(self._slots):
            if s.state != FREE and s.request_id == rid:
                self._terminate(slot, CANCELLED)
                return True
        return False

    # -- scheduling --------------------------------------------------------

    def _admit_entry(self, e: _QueueEntry, slot: int,
                     hit: list[int] | None = None) -> None:
        """Map a queue entry into a free slot.  ``hit`` is the
        prefix-cache match (already referenced by ``acquire``): its
        blocks lead the row's block table and prefill starts at the
        first position past them — the match is capped so the write
        frontier always lands in a freshly allocated private block
        (the COW rule; see :mod:`horovod_tpu.prefix_cache`)."""
        hit = hit or []
        prompt = list(e.req.prompt) + list(e.prior)
        L = len(prompt)
        need = self._need_blocks(e.req)
        base = len(hit) * self.block_size
        s = self._slots[slot]
        blocks = list(hit)
        for _ in range(need - len(hit)):
            b = self.pool.alloc()
            self.pool.incref(b)
            blocks.append(b)
        row = self._trash_row.copy()
        row[:need] = blocks
        if self.device is not None:
            self.device.dispatch("set_row", h2d_bytes=row.nbytes + 8)
        self.pcache = self._set_row(
            self.pcache, jnp.asarray(slot, jnp.int32),
            jnp.asarray(row), jnp.asarray(base, jnp.int32))
        rem = L - base                    # tokens still to prefill (>= 1)
        n_win = -(-rem // self.chunk)
        padded = np.zeros((1, n_win * self.chunk), np.int32)
        padded[0, :rem] = prompt[base:]
        s.state = PREFILL
        s.request_id = e.rid
        s.padded = padded
        s.n_win = n_win
        s.w_done = 0
        s.true_len = L
        s.base = base
        s.n_hit = len(hit)
        s.budget = e.req.max_new_tokens - len(e.prior)
        s.eos = e.req.eos_id
        s.out = []
        s.n_blocks = need
        s.blocks = blocks
        s.req = e.req
        s.prior = list(e.prior)
        s.retries = e.retries
        s.wait_steps = 0
        s.deadline = e.deadline
        s.slo_deadline = e.slo_deadline
        s.admit_seq = self._admit_seq
        # drafting state seeds from the full replay context (prompt +
        # prior); emitted tokens extend it as they land
        s.draft = (drafting_mod.NgramDraftState(prompt)
                   if self.spec else None)
        self._admit_seq += 1
        tr = self.traces.get(e.rid)
        if tr is not None:
            if tr.admit_ts is None:       # first admission only: replay
                tr.admit_ts = time.monotonic()   # re-admits don't re-queue
                tr.admit_step = self.step_index
                self.metrics.histogram("serve.queue_wait_s").observe(
                    tr.admit_ts - tr.enqueue_ts)
            tr.prefix_tokens_skipped += base
        self._event("admit", slot, e.rid)
        if hit:
            self.prefix_counters["hits"] += 1
            self.prefix_counters["blocks_reused"] += len(hit)
            self.prefix_counters["tokens_skipped"] += base
            self._event("hit", slot, e.rid)

    def _admit_ready(self) -> tuple[int, int | None]:
        """Policy-ordered admission: move queued requests into free
        slots while both a slot and enough cache blocks are available.
        ``self.policy.admission_order`` decides the order candidates
        are considered (FIFO by default), and head-of-line blocking on
        BLOCK pressure applies to the first block-starved candidate in
        that order — which is what feeds the preemption trigger, so the
        policy decides who waits under pressure.  Note the order is a
        liveness/fairness lever ONLY: per-request output determinism is
        pinned by the policy-interface contract — row independence plus
        greedy determinism (scheduler invariant 2) make every request's
        tokens bit-identical to its solo run under ANY admission order
        or victim choice, so a policy can never change what anyone's
        output is, only when it arrives.  Entries serving a retry
        backoff are skipped past.  With the prefix cache on, each
        candidate first longest-prefix-matches (``serve.cache`` faults
        quarantine to that request alone — shared blocks are untouched)
        and zero-ref cached blocks are evicted LRU-leaf-first to cover
        any shortfall before the head counts as starved.  Returns
        ``(admitted, starved_need)`` — the NEW block count the stalled
        head needs (its cache hit already discounted), or None when
        nothing block-starved."""
        admitted = 0
        for e in self.policy.admission_order(self._queue):
            free = [j for j, s in enumerate(self._slots)
                    if s.state == FREE]
            if not free:
                return admitted, None
            if e.wait_steps > 0:          # admit-retry backoff
                continue
            need = self._need_blocks(e.req)
            hit: list[int] = []
            if self.prefix is not None:
                try:
                    self.faults.check("serve.cache", key=e.rid)
                    t_cq = (0.0 if self.prof is None
                            else time.perf_counter())
                    hit = self.prefix.acquire(
                        list(e.req.prompt) + list(e.prior))
                    if self.prof is not None:
                        self.prof.add("admit.cache_acquire", t_cq,
                                      time.perf_counter())
                except Exception as exc:
                    # quarantine: nothing was referenced, the index and
                    # every shared block are intact — only this request
                    # retries or fails
                    if (isinstance(exc, faults_mod.PermanentFault)
                            or e.retries >= self.max_retries):
                        self._queue.remove(e)
                        self._finish_queued(e, FAILED, exc)
                    else:
                        e.retries += 1
                        e.wait_steps = 2 ** e.retries
                        self._bump_counter("retries")
                        self._event("retry", -1, e.rid)
                    continue
                short = (need - len(hit)) - self.pool.free_count()
                if short > 0:             # cache evicts before rows do
                    self.prefix_counters["evictions"] += \
                        self.prefix.evict(short)
            if need - len(hit) > len(self._free_blocks):
                if hit:                   # hit blocks re-park in LRU
                    self.prefix.release(reversed(hit))
                return admitted, need - len(hit)
            try:
                self.faults.check("serve.admit", key=e.rid)
            except Exception as exc:
                if hit:
                    self.prefix.release(reversed(hit))
                if (isinstance(exc, faults_mod.PermanentFault)
                        or e.retries >= self.max_retries):
                    self._queue.remove(e)
                    self._finish_queued(e, FAILED, exc)
                else:
                    e.retries += 1
                    e.wait_steps = 2 ** e.retries
                    self._bump_counter("retries")
                    self._event("retry", -1, e.rid)
                continue
            self._queue.remove(e)
            self._admit_entry(e, free[0], hit)
            admitted += 1
        return admitted, None

    def _replay_len(self, s: _Slot) -> int:
        return len(s.req.prompt) + len(s.prior) + len(s.out)

    def _replayable(self, s: _Slot) -> bool:
        # the replay prompt must still fit the chunked-prefill padding
        n_win = -(-self._replay_len(s) // self.chunk)
        return n_win * self.chunk <= self.max_len

    def _release_row_blocks(self, s: _Slot, *, register: bool) -> None:
        """Drop a retiring row's block references.  With the prefix
        cache on and ``register`` set (OK retirement or a requeue whose
        KV is known-good), the row's fully written blocks first join
        the radix index — release-to-cache — so zero-ref blocks park in
        LRU order instead of freeing; otherwise (cache off, or a FAILED
        / expired row whose frontier is not trusted) references drop
        straight back toward the free list, in the classic order."""
        if self.prefix is not None and register and s.req is not None:
            toks = (list(s.req.prompt) + list(s.prior) + list(s.out))
            self.prefix.insert(toks, s.blocks, s.true_len + len(s.out))
        for b in reversed(s.blocks):
            self.pool.decref(b)

    def _requeue(self, slot: int, *, retried: bool) -> None:
        """Free a row and put its request back in the queue with
        ``prompt + out`` as the replay prompt (preemption, or a decode
        retry — which replays rather than re-ticking because the faulted
        tick already advanced the row's cache position).  With the
        prefix cache on the row's KV releases to cache, so the replay
        re-admits through a longest-prefix hit and is nearly free."""
        s = self._slots[slot]
        entry = _QueueEntry(
            rid=s.request_id, req=s.req,
            prior=list(s.prior) + list(s.out),
            retries=s.retries + (1 if retried else 0),
            wait_steps=2 ** (s.retries + 1) if retried else 0,
            deadline=s.deadline,
            slo_deadline=s.slo_deadline)
        self._release_row_blocks(s, register=True)
        if self.device is not None:
            self.device.dispatch(
                "set_row", h2d_bytes=self._trash_row.nbytes + 8)
        self.pcache = self._set_row(
            self.pcache, jnp.asarray(slot, jnp.int32),
            jnp.asarray(self._trash_row), jnp.asarray(0, jnp.int32))
        self._slots[slot] = _Slot()
        self._queue.append(entry)

    def _preempt(self, need: int) -> int:
        """Free blocks for a starved head: evict zero-ref cached blocks
        first (they hold no live work), then preempt the policy's
        victims — FIFO evicts youngest, EDF the slack-richest (largest
        time-to-SLO-deadline, i.e. least-regretted), priority the
        lowest-priority — until ``need`` blocks are free (or no
        candidate remains).  Preempted requests re-queue for replay;
        greedy determinism makes their resumed output bit-identical
        whoever is chosen.  A preempted row's blocks release-to-cache,
        so the loop re-evicts them on the next pass — preemption still
        converges on a cache-on engine."""
        preempted = 0
        while len(self._free_blocks) < need:
            if self.prefix is not None:
                evicted = self.prefix.evict(
                    need - len(self._free_blocks))
                if evicted:
                    self.prefix_counters["evictions"] += evicted
                    continue
            cands = [(i, s) for i, s in enumerate(self._slots)
                     if s.state == DECODE and self._replayable(s)]
            if not cands:
                break
            slot = self.policy.victim(cands)
            self._event("preempt", slot, self._slots[slot].request_id)
            self._bump_counter("preemptions")
            self._requeue(slot, retried=False)
            preempted += 1
        return preempted

    def _terminate(self, slot: int, status: str,
                   error: BaseException | None = None) -> RequestResult:
        """Retire a row with a terminal status: blocks back to the pool
        (release-to-cache on a clean OK finish when the prefix cache is
        on), row to the trash block (the same fixed-signature table
        write for every status — OK, TIMEOUT, CANCELLED, FAILED)."""
        s = self._slots[slot]
        res = RequestResult(list(s.prior) + list(s.out), status, error)
        self.results[s.request_id] = res
        self._finished[s.request_id] = res
        self._finalize_trace(s.request_id, res)
        self._release_row_blocks(s, register=status == OK)
        if self.device is not None:
            self.device.dispatch(
                "set_row", h2d_bytes=self._trash_row.nbytes + 8)
        self.pcache = self._set_row(
            self.pcache, jnp.asarray(slot, jnp.int32),
            jnp.asarray(self._trash_row), jnp.asarray(0, jnp.int32))
        kind = {OK: "recycle", TIMEOUT: "timeout",
                CANCELLED: "cancel", FAILED: "fail"}[status]
        self._event(kind, slot, s.request_id)
        self._bump_status(status)
        self._slots[slot] = _Slot()
        return res

    def _finish_queued(self, e: _QueueEntry, status: str,
                       error: BaseException | None = None) -> None:
        """Terminal result for a request that never (re)entered a slot:
        tokens-so-far is whatever a previous stint emitted."""
        res = RequestResult(list(e.prior), status, error)
        self.results[e.rid] = res
        self._finished[e.rid] = res
        self._finalize_trace(e.rid, res)
        kind = {TIMEOUT: "timeout", CANCELLED: "cancel",
                REJECTED: "reject", FAILED: "fail"}[status]
        self._event(kind, -1, e.rid)
        self._bump_status(status)

    def _bump_status(self, status: str) -> None:
        key = {TIMEOUT: "timeouts", CANCELLED: "cancellations",
               REJECTED: "rejections", FAILED: "failures"}.get(status)
        if key is not None:
            self._bump_counter(key)

    def _bump_counter(self, key: str) -> None:
        """Advance a lifecycle counter in ``self.counters`` AND its
        mirror in the metrics registry, so both always agree (the event
        log's replay invariant is pinned against ``self.counters``)."""
        self.counters[key] += 1
        self.metrics.counter("serve." + key).inc()

    def _bump_spec(self, key: str, n: int = 1) -> None:
        """Advance a speculation counter in ``self.spec_counters`` AND
        its registry mirror (the ``SPEC`` timeline series keys)."""
        self.spec_counters[key] += n
        self.metrics.counter("serve.spec." + key).inc(n)

    def _finalize_trace(self, rid: int, res: RequestResult) -> None:
        """Terminal bookkeeping for a request's :class:`Trace`: stamp the
        end, attach it to the result (every terminal status — OK, TIMEOUT,
        CANCELLED, REJECTED, FAILED — flows through here), and feed the
        end-to-end latency histograms."""
        tr = self.traces.pop(rid, None)
        if tr is None:
            return
        tr.terminal_ts = time.monotonic()
        tr.terminal_step = self.step_index
        tr.status = res.status
        tr.n_tokens = len(res.tokens)
        res.trace = tr
        self.slo.add(tr, self._slo_targets.pop(rid, None))
        self.metrics.gauge("serve.goodput").set(self.slo.goodput())
        self.metrics.histogram("serve.e2e_s").observe(
            tr.e2e_s, exemplar=tr.trace_id)
        if tr.trace_id is not None:
            self._emit_request_spans(tr)
        tpot = tr.tpot_s
        if tpot is not None:
            self.metrics.histogram("serve.tpot_s").observe(tpot)
        self.metrics.counter("serve.requests_completed").inc()
        if tr.n_tokens:
            self.metrics.counter("serve.tokens_emitted").inc(tr.n_tokens)
        if self.timeline is not None:
            self.timeline.async_end("serving.requests", "REQ", rid)

    def _emit_request_spans(self, tr: Trace) -> None:
        """Post-hoc span emission for a sampled request at terminal
        time: ``serve.queue`` / ``serve.prefill`` / ``serve.decode``
        children tiled from the Trace stamps, then the
        ``serve.request`` close.  Phases a request never reached
        (queue-side REJECTED/TIMEOUT) are simply absent."""
        sctx = tracing_mod.TraceContext(tr.trace_id, tr.span_id)
        if tr.admit_ts is not None:
            self.tracer.span(sctx.child("serve.queue"), "serve.queue",
                             tr.enqueue_ts, tr.admit_ts,
                             parent_id=tr.span_id, rid=tr.rid,
                             steps=tr.queue_steps)
            if tr.first_token_ts is not None:
                self.tracer.span(
                    sctx.child("serve.prefill"), "serve.prefill",
                    tr.admit_ts, tr.first_token_ts,
                    parent_id=tr.span_id, rid=tr.rid,
                    chunks=tr.prefill_chunks)
                self.tracer.span(
                    sctx.child("serve.decode"), "serve.decode",
                    tr.first_token_ts, tr.terminal_ts,
                    parent_id=tr.span_id, rid=tr.rid,
                    n_tokens=tr.n_tokens, admit_step=tr.admit_step,
                    terminal_step=tr.terminal_step)
        self.tracer.span(sctx, "serve.request", tr.enqueue_ts,
                         tr.terminal_ts, parent_id=tr.parent_span_id,
                         rid=tr.rid, status=tr.status)

    def _emit_chunk_span(self, tr: Trace, t0: float, t1: float) -> None:
        """One ``serve.prefill_chunk`` span per dispatched prefill
        window of a sampled request, parented under the request's
        ``serve.prefill`` span.  The parent id is *derived* (same
        ``child_span_id`` the close in :meth:`_emit_request_spans`
        uses), so chunks emit before their parent exists and still
        join the tree at reconstruction."""
        prefill_id = tracing_mod.child_span_id(
            tr.trace_id, tr.span_id, "serve.prefill")
        ctx = tracing_mod.TraceContext(
            tr.trace_id,
            tracing_mod.child_span_id(tr.trace_id, prefill_id,
                                      "serve.prefill_chunk",
                                      seq=tr.prefill_chunks))
        self.tracer.span(ctx, "serve.prefill_chunk", t0, t1,
                         parent_id=prefill_id, rid=tr.rid,
                         seq=tr.prefill_chunks)

    def _slot_fault(self, slot: int, exc: BaseException) -> None:
        """Quarantine a prefill-window fault to its own request:
        transient → bounded in-place retry after a ``2**retries``-step
        backoff (the window never ran, so state is intact); permanent or
        retries exhausted → ``FAILED``, everything else keeps serving."""
        s = self._slots[slot]
        if (isinstance(exc, faults_mod.PermanentFault)
                or s.retries >= self.max_retries):
            self._terminate(slot, FAILED, exc)
            return
        s.retries += 1
        s.wait_steps = 2 ** s.retries
        self._bump_counter("retries")
        self._event("retry", slot, s.request_id)

    def _row_fault(self, slot: int, exc: BaseException) -> None:
        """Quarantine a decode-tick readback fault: the faulted tick
        already advanced the row's cache, so a transient retry goes
        through the replay path (free blocks, re-queue with prompt+out —
        greedy determinism reproduces the discarded token exactly);
        permanent or exhausted → ``FAILED``."""
        s = self._slots[slot]
        if (isinstance(exc, faults_mod.PermanentFault)
                or s.retries >= self.max_retries
                or not self._replayable(s)):
            self._terminate(slot, FAILED, exc)
            return
        self._bump_counter("retries")
        self._event("retry", slot, s.request_id)
        self._requeue(slot, retried=True)

    def _expire(self, now: float | None) -> int:
        """Deadline (wall-clock) and queue-budget (step-counted)
        enforcement; returns how many requests terminated."""
        done = 0
        if now is not None:
            i = 0
            while i < len(self._queue):
                e = self._queue[i]
                if e.deadline is not None and now >= e.deadline:
                    self._queue.pop(i)
                    self._finish_queued(e, TIMEOUT)
                    done += 1
                    continue
                i += 1
            for slot, s in enumerate(self._slots):
                if (s.state != FREE and s.deadline is not None
                        and now >= s.deadline):
                    self._terminate(slot, TIMEOUT)
                    done += 1
        return done

    def _event(self, kind: str, slot: int, rid: int) -> None:
        self.events.append(
            SchedulerEvent(kind, self.step_index, slot, rid))
        tr = self.traces.get(rid)
        if tr is not None:
            if kind == "retry":
                tr.retries += 1
            elif kind == "preempt":
                tr.preemptions += 1
        # One structured-log line per scheduler event: counter bumps are
        # 1:1 with _event() calls, so replaying the JSONL reproduces
        # ``self.counters`` exactly (tested in test_metrics.py).
        self.metrics.event("serve." + kind, rid=rid, slot=slot,
                           step=self.step_index)
        if self.timeline is not None:
            self.timeline.instant("serving.scheduler", kind.upper())

    def _check_block_invariants(self) -> None:
        """The ``HVD_TPU_VERIFY_BLOCKS=1`` debug walk: block tables,
        slot bookkeeping and the pool must agree after every step —
        each live row's table row is exactly its block list (trash
        elsewhere), no live row references a freed block or trash,
        every block's pool refcount equals the number of rows mapping
        it, every pool reference belongs to some live row, the radix
        index is structurally sound, and free + cached + referenced
        blocks account for the whole pool."""
        table = np.asarray(self.pcache.block_table)
        free = set(self._free_blocks)
        usage: dict[int, int] = {}
        for slot, s in enumerate(self._slots):
            row = table[slot]
            if s.state == FREE:
                if row.any():
                    raise AssertionError(
                        f"free slot {slot} maps blocks "
                        f"{[int(b) for b in row if b]}")
                continue
            if [int(b) for b in row[:s.n_blocks]] != s.blocks:
                raise AssertionError(
                    f"slot {slot} table row {row[:s.n_blocks]} != "
                    f"bookkeeping {s.blocks}")
            if row[s.n_blocks:].any():
                raise AssertionError(
                    f"slot {slot} maps blocks beyond its "
                    f"{s.n_blocks} allocated")
            for b in s.blocks:
                if b == 0:
                    raise AssertionError(
                        f"slot {slot} maps the trash block")
                if b in free:
                    raise AssertionError(
                        f"live slot {slot} references freed block {b}")
                usage[b] = usage.get(b, 0) + 1
        for b, n in usage.items():
            if self.pool.refcount(b) != n:
                raise AssertionError(
                    f"block {b}: {n} rows map it but pool refcount is "
                    f"{self.pool.refcount(b)}")
        for b in self.pool._ref:
            if b not in usage:
                raise AssertionError(
                    f"block {b} holds {self.pool.refcount(b)} pool "
                    f"references but no live row maps it")
        if self.prefix is not None:
            self.prefix.check_consistency()
        total = self.pcache.k.shape[1] - 1
        accounted = (len(free) + self.pool.cached_count()
                     + len(self.pool._ref))
        if accounted != total:
            raise AssertionError(
                f"pool accounting leak: free={len(free)} "
                f"cached={self.pool.cached_count()} "
                f"referenced={len(self.pool._ref)} != {total}")

    def step(self) -> dict[int, RequestResult]:
        """One engine step: expire deadlines, admit (preempting for a
        starved head if enabled), run one prefill window per admitting
        slot, then one decode tick over the pool.  Returns
        ``{request_id: RequestResult}`` for every request that reached a
        terminal state during the step."""
        self._finished = {}
        progress = 0
        # Phase profiling is mark-based: each boundary charges the time
        # since the previous one, so the phases tile the tick.  prof is
        # None when disabled — the only cost then is these None tests.
        prof = self.prof
        if prof is not None:
            prof.begin(self.step_index)
        # deadlines first: an expired request must not admit or tick
        now = None
        if (any(e.deadline is not None for e in self._queue)
                or any(s.deadline is not None for s in self._slots
                       if s.state != FREE)):
            now = time.monotonic()
        progress += self._expire(now)
        # queue bookkeeping: backoff countdown + admission budgets
        i = 0
        while i < len(self._queue):
            e = self._queue[i]
            if (e.req.max_queue_steps is not None
                    and e.queued_steps >= e.req.max_queue_steps):
                self._queue.pop(i)
                self._finish_queued(e, REJECTED)
                progress += 1
                continue
            e.queued_steps += 1
            tr = self.traces.get(e.rid)
            if tr is not None:
                tr.queue_steps += 1
            if e.wait_steps > 0:
                e.wait_steps -= 1
                progress += 1
            i += 1
        if prof is not None:
            prof.mark("expire")       # deadlines + queue bookkeeping
        admitted, starved_need = self._admit_ready()
        progress += admitted
        if starved_need is None:
            self._starve_steps = 0
        else:
            self._starve_steps += 1
            if (self.preempt_after is not None
                    and self._starve_steps >= self.preempt_after):
                freed = self._preempt(starved_need)
                if freed:
                    progress += freed
                    self._starve_steps = 0
                    more, _ = self._admit_ready()  # head admits this step
                    progress += more
        t_pf = 0.0 if prof is None else time.perf_counter()
        for slot, s in enumerate(self._slots):
            if s.state != PREFILL:
                continue
            if s.wait_steps > 0:          # prefill-retry backoff
                s.wait_steps -= 1
                progress += 1
                continue
            w = s.w_done
            final = w == s.n_win - 1
            toks = s.padded[:, w * self.chunk:(w + 1) * self.chunk]
            # windows cover prompt[base:] — a prefix-cache hit rewound
            # nothing: the row's length started at base, so positions
            # [0, base) are the shared blocks' KV, never rewritten
            new_len = (s.true_len if final
                       else s.base + (w + 1) * self.chunk)
            sel = (s.true_len - 1 - s.base - w * self.chunk
                   if final else 0)
            tr = self.traces.get(s.request_id)
            traced = tr is not None and tr.trace_id is not None
            t_chunk = time.monotonic() if traced else 0.0
            try:
                self.faults.check("serve.prefill", key=s.request_id)
                self.pcache, self.last_logits = self._chunk(
                    self.params, self.pcache, self.last_logits,
                    jnp.asarray(toks), jnp.asarray(slot, jnp.int32),
                    jnp.asarray(new_len, jnp.int32),
                    jnp.asarray(sel, jnp.int32))
            except Exception as exc:
                self._slot_fault(slot, exc)
                progress += 1
                continue
            if self.device is not None:
                # chunk args materialized per call: the token window
                # plus three int32 scalars (slot / new_len / sel).
                self.device.dispatch("chunk",
                                     h2d_bytes=toks.nbytes + 12)
            s.w_done += 1
            progress += 1
            if tr is not None:
                if traced:
                    self._emit_chunk_span(tr, t_chunk, time.monotonic())
                tr.prefill_chunks += 1
            if final:
                s.state = DECODE      # joins this step's tick
        if prof is not None:
            # admit covers _admit_ready + preemption + the prefill
            # windows; the dispatch portion is also attributed to the
            # nested admit.prefill_dispatch sub-phase.
            prof.add("admit.prefill_dispatch", t_pf, time.perf_counter())
            prof.mark("admit")
        decoding = [i for i, s in enumerate(self._slots)
                    if s.state == DECODE]
        spec = self.spec and bool(decoding)
        drafts_host: np.ndarray | None = None
        if spec:
            # draft phase: each decoding row proposes up to draft_k
            # continuation tokens from its own history; -1 pads can
            # never be accepted (argmax preds are >= 0).  Drafting is
            # an optimization, so a faulting drafter (serve.draft)
            # degrades its row to plain decode for the round — the
            # request never fails or retries over a draft.
            drafts_host = np.full((self.n_slots, self.draft_k), -1,
                                  np.int32)
            for slot in decoding:
                s = self._slots[slot]
                try:
                    self.faults.check("serve.draft", key=s.request_id)
                    prop = (s.draft.propose(self.draft_k)
                            if s.draft is not None else [])
                except Exception:
                    self.metrics.counter("serve.spec.draft_faults").inc()
                    prop = []
                if prop:
                    drafts_host[slot, :len(prop)] = prop
                    self._bump_spec("proposed", len(prop))
            if prof is not None:
                prof.mark("draft")
        if decoding:
            try:
                active = np.zeros((self.n_slots,), np.int32)
                active[decoding] = 1
                accept_host = None
                if spec:
                    tok, accept, self.last_logits, self.pcache = \
                        self._spec_tick(
                            self.params, self.pcache, self.last_logits,
                            jnp.asarray(drafts_host),
                            jnp.asarray(active))
                else:
                    tok, self.last_logits, self.pcache = self._tick(
                        self.params, self.pcache, self.last_logits,
                        jnp.asarray(active))
                if self.device is not None:
                    self.device.dispatch(
                        "spec_tick" if spec else "tick",
                        h2d_bytes=active.nbytes + (
                            drafts_host.nbytes if spec else 0))
                if prof is not None:
                    prof.mark("decode_dispatch")
                # np.asarray on the device token array is the readback
                # boundary: everything the tick queued must complete
                # first, so this wait is the device-time share.
                t_sync0 = time.perf_counter()
                tok_host = np.asarray(tok)
                if spec:
                    accept_host = np.asarray(accept)
                if self.device is not None:
                    # split the measured readback wait into the cost
                    # model's predicted device-compute share vs host
                    # stall; the profiler gets the same split as nested
                    # device_sync.* intervals so phase tables can show
                    # where the wait went.
                    t_sync1 = time.perf_counter()
                    d2h = tok_host.nbytes + (
                        accept_host.nbytes
                        if accept_host is not None else 0)
                    est, stall = self.device.on_sync(
                        "spec_tick" if spec else "tick",
                        t_sync0, t_sync1, d2h_bytes=d2h)
                    if prof is not None:
                        prof.add("device_sync.compute_est",
                                 t_sync0, t_sync0 + est)
                        prof.add("device_sync.host_stall",
                                 t_sync0 + est, t_sync1)
                if prof is not None:
                    prof.mark("device_sync")
            except Exception as exc:
                # a whole-tick failure cannot be attributed to one row;
                # quarantine every decoding row (transients replay)
                for slot in decoding:
                    self._row_fault(slot, exc)
                progress += len(decoding)
            else:
                progress += len(decoding)
                if spec:
                    self._bump_spec("rounds")
                for slot in decoding:
                    s = self._slots[slot]
                    emit = [int(tok_host[slot])]
                    if accept_host is not None:
                        acc = int(accept_host[slot])
                        emit += [int(x) for x in
                                 drafts_host[slot, :acc]]
                        self._bump_spec("row_rounds")
                        self._bump_spec("accepted", acc)
                        self.metrics.histogram(
                            "serve.spec.accepted_per_round").observe(acc)
                    try:
                        self.faults.check("serve.tick", key=s.request_id)
                        for t in emit:
                            if not 0 <= t < self.cfg.vocab_size:
                                raise faults_mod.PermanentFault(
                                    "serve.tick", s.request_id, -1)
                    except Exception as exc:
                        self._row_fault(slot, exc)
                        continue
                    if not s.prior and not s.out:
                        tr = self.traces.get(s.request_id)
                        if tr is not None and tr.first_token_ts is None:
                            tr.first_token_ts = time.monotonic()
                            self.metrics.histogram(
                                "serve.ttft_s").observe(tr.ttft_s)
                    # accepted drafts emit in order behind the
                    # unconditional token; a terminal token (budget or
                    # eos) discards the rest of the round — the row's
                    # over-advanced device length dies with the slot
                    for t in emit:
                        s.out.append(t)
                        s.budget -= 1
                        if s.draft is not None:
                            s.draft.extend((t,))
                        if s.budget <= 0 or t == s.eos:
                            self._terminate(slot, OK)
                            break
        if prof is not None:
            # spec engines account their acceptance/emission loop as
            # `verify`; plain engines keep the classic name
            prof.mark("verify" if spec else "sample_postprocess")
        if self.timeline is not None:
            self.timeline.counter(
                "serving.scheduler", "SCHED",
                {"queued": len(self._queue),
                 "decoding": len(decoding),
                 "prefilling": sum(1 for s in self._slots
                                   if s.state == PREFILL),
                 "free_blocks": len(self._free_blocks)})
            self.timeline.counter(
                "serving.scheduler", "LIFECYCLE", dict(self.counters))
            if self.spec:
                self.timeline.counter(
                    "serving.scheduler", "SPEC",
                    dict(self.spec_counters))
            if self.prefix is not None:
                self.timeline.counter(
                    "serving.scheduler", "PREFIX",
                    dict(self.prefix_counters))
        # Registry mirror of the SCHED track: occupancy gauges sampled
        # once per step, plus the step odometer — available with no
        # timeline attached (the scrape path).
        self.metrics.counter("serve.steps").inc()
        self.metrics.gauge("serve.queue_depth").set(len(self._queue))
        self.metrics.gauge("serve.decoding").set(len(decoding))
        self.metrics.gauge("serve.prefilling").set(
            sum(1 for s in self._slots if s.state == PREFILL))
        self.metrics.gauge("serve.free_blocks").set(len(self._free_blocks))
        self.metrics.gauge("serve.cached_blocks").set(
            self.pool.cached_count())
        if self.prefix is not None:
            self.metrics.gauge("serve.prefix_indexed_blocks").set(
                self.prefix.indexed_blocks())
        # KV pool accounting in blocks and bytes, refreshed per step so
        # a scrape between snapshots still sees live occupancy.
        bb = self._block_bytes
        free_b = self.pool.free_count()
        ref_b = self.pool.ref_count()
        cached_b = self.pool.cached_count()
        self.metrics.gauge("kv.free_blocks").set(free_b)
        self.metrics.gauge("kv.free_bytes").set(free_b * bb)
        self.metrics.gauge("kv.referenced_blocks").set(ref_b)
        self.metrics.gauge("kv.referenced_bytes").set(ref_b * bb)
        self.metrics.gauge("kv.cached_blocks").set(cached_b)
        self.metrics.gauge("kv.cached_bytes").set(cached_b * bb)
        sbb = self._shard_block_bytes
        self.metrics.gauge("kv.shard_free_bytes").set(free_b * sbb)
        self.metrics.gauge("kv.shard_referenced_bytes").set(
            ref_b * sbb)
        self.metrics.gauge("kv.shard_cached_bytes").set(cached_b * sbb)
        # Retrace sentry: a jit cache that grows past one signature per
        # program mid-serve means some host value leaked into a traced
        # shape/dtype — the exact regression HVD001 lints for statically.
        sizes = self.compile_cache_sizes()
        grew = {k: (self._jit_cache_seen[k], v)
                for k, v in sizes.items()
                if v > self._jit_cache_seen[k] and v > 1}
        self._jit_cache_seen = sizes
        if grew:
            n = sum(v - max(prev, 1) for prev, v in grew.values())
            self.metrics.counter("serve.retrace").inc(n)
            if self.device is not None:
                # compile ledger: charge the growth with the captured
                # per-program compile cost — retraces become seconds.
                self.device.on_retrace(grew)
            self.metrics.event(
                "serve.retrace", step=self.step_index,
                programs={k: {"before": prev, "after": v}
                          for k, (prev, v) in grew.items()})
            if self._retrace_fatal:
                raise RuntimeError(
                    f"retrace sentry: jit cache grew mid-serve "
                    f"(HVD_TPU_RETRACE_FATAL=1) — "
                    + ", ".join(f"{k}: {prev} -> {v}"
                                for k, (prev, v) in sorted(grew.items()))
                    + f"; a device program saw a new signature at step "
                    f"{self.step_index}.  State:\n{self.state_dump()}")
        if self._verify_blocks:
            self._check_block_invariants()
        if self.pending() and progress == 0:
            self._idle_steps += 1
            if self._idle_steps >= self.watchdog_steps:
                raise RuntimeError(
                    f"ServeEngine made no scheduling progress for "
                    f"{self._idle_steps} consecutive steps (no admit / "
                    f"prefill window / decode tick / retirement / "
                    f"preemption while work is pending) — the scheduler "
                    f"is stuck.  State:\n{self.state_dump()}")
        else:
            self._idle_steps = 0
        # Health plane: sample the registry, then judge the series —
        # both are cheap no-ops until their cadence elapses.
        if self.sampler is not None:
            self.sampler.tick()
            if self.alerts is not None:
                self.alerts.tick()
        if self.device is not None:
            self.device.on_step(self.step_index)
        self._last_step_ts = time.monotonic()
        self.step_index += 1
        if prof is not None:
            prof.end()                # closes the bookkeeping phase
        return self._finished

    def run(self, requests: list[Request]) -> list[RequestResult]:
        """Serve ``requests`` to completion; returns each request's
        :class:`~horovod_tpu.serving.RequestResult` in submission order
        (each is a list of the emitted tokens, carrying ``.status``)."""
        ids = [self.submit(r) for r in requests]
        while self.pending():
            self.step()
        return [self.results[i] for i in ids]


# ---------------------------------------------------------------------------
# Throughput measurement (the serve_tokens_per_sec bench metric).
# ---------------------------------------------------------------------------


def measure_throughput(
    params: dict, cfg: llama.LlamaConfig, requests: list[Request], *,
    n_slots: int, max_len: int, chunk: int,
    block_size: int | None = None, n_blocks: int | None = None,
    preempt_after: int | None = None,
) -> dict:
    """Continuous-batching vs fixed-batch throughput on one workload.

    The engine serves the queue with slot recycling; the static baseline
    is plain :func:`llama.generate` over fixed batches of ``n_slots`` in
    submission order — every batch decodes until its LONGEST budget is
    spent and prompts pad to the global maximum (the costs continuous
    batching exists to remove).  Both paths are warmed (compiled) before
    timing; only true emitted tokens count, for both.  Returns
    ``serve_tokens_per_sec``, ``static_tokens_per_sec``,
    ``serve_vs_static_ratio``, ``preemptions`` (timed pass only; nonzero
    only with ``preempt_after`` on an overcommitted ``n_blocks`` pool),
    latency percentiles from the metrics-on pass
    (``serve_ttft_p50_ms`` .. ``serve_e2e_p99_ms``),
    ``serve_metrics_overhead_pct`` (instrumented vs null-registry pass —
    the acceptance bound for the observability layer is < 2 %),
    ``monitor_overhead_pct`` (exporter on and scraped at ~100 Hz),
    ``serve_profiler_overhead_pct`` (phase profiler on — bound < 3 %)
    ``serve_health_overhead_pct`` (time-series sampler + alert
    evaluation in the step loop at 20 Hz — acceptance keeps it within
    2 % of the monitor baseline) and ``serve_trace_overhead_pct``
    (causal span plane at 100 % head sampling vs the None-check
    disabled plane — prices the worst case; disabled is near-free by
    construction) and ``device_telemetry_overhead_pct`` (device
    telemetry plane ON: cost-model dispatch stamping, sync split, and
    per-step gauge refresh — bound < 5 %; its leg also yields
    ``serve_mfu`` — honest ``None`` when no peak is known, i.e. every
    CPU rehearsal — ``serve_model_flops_per_token``,
    ``serve_device_flops_per_s`` and ``serve_overlap_headroom_pct``) —
    all min-of-2 passes against an adjacent min-of-2 metrics-on base,
    so inter-pass drift doesn't masquerade as overhead — with
    ``serve_phase_pct`` / ``serve_phase_mean_ms`` per-phase breakdowns,
    ``serve_goodput``
    (windowed SLO goodput after the timed passes) and workload shape
    fields.
    """
    if not requests:
        raise ValueError("empty workload")

    eng = ServeEngine(params, cfg, n_slots=n_slots, max_len=max_len,
                      chunk=chunk, block_size=block_size,
                      n_blocks=n_blocks, preempt_after=preempt_after,
                      metrics=metrics_mod.NULL)
    warm = eng.run(requests)                 # compiles every program
    assert all(r.ok for r in warm), [r.status for r in warm]
    n_tokens = sum(len(t) for t in warm)
    # timed pass reuses the SAME engine (its jit programs are
    # per-instance): after run() every slot is free, so the pool is in
    # its admission-ready state again.  Metrics ON is the shipping
    # configuration, so it is the primary number; a second pass with
    # the null registry prices the instrumentation itself.
    reg = metrics_mod.MetricsRegistry(event_log=None)
    eng.metrics = reg
    preempt0 = eng.counters["preemptions"]

    def _timed_pass() -> float:
        t0 = time.perf_counter()
        out = eng.run(requests)
        jax.block_until_ready(eng.pcache.k)
        dt = time.perf_counter() - t0
        assert [len(t) for t in out] == [len(t) for t in warm]
        return dt

    t_serve = _timed_pass()
    preemptions = eng.counters["preemptions"] - preempt0
    eng.metrics = metrics_mod.NULL
    t_serve_off = _timed_pass()
    hist = {name: reg.histogram(name)
            for name in ("serve.ttft_s", "serve.tpot_s",
                         "serve.queue_wait_s", "serve.e2e_s")}

    # Overhead arms.  A single pass A/B'd against a single earlier pass
    # is noise-dominated at small shapes (allocator/scheduler drift
    # between passes exceeds the effect being priced), so each arm runs
    # INTERLEAVED with a fresh metrics-on base — base, arm, base, arm —
    # and both sides take their min (the standard drift-robust
    # estimator); the overheads are deltas between those mins.
    mon_reg = metrics_mod.MetricsRegistry(event_log=None)
    mon = monitor_mod.MonitorServer(mon_reg, eng, port=0).start()
    scraping_on = threading.Event()
    stop_scraping = threading.Event()

    def _scrape_loop() -> None:
        import urllib.request
        url = f"http://{mon.host}:{mon.port}/metrics"
        while not stop_scraping.is_set():
            if scraping_on.is_set():
                try:
                    urllib.request.urlopen(url, timeout=1).read()
                except OSError:
                    pass
                stop_scraping.wait(0.01)
            else:
                stop_scraping.wait(0.001)

    scraper = threading.Thread(target=_scrape_loop, daemon=True)
    scraper.start()
    preg = metrics_mod.MetricsRegistry(event_log=None)
    prof = profiler_mod.TickProfiler(preg, timeline=eng.timeline)
    hreg = metrics_mod.MetricsRegistry(event_log=None)
    # 20 Hz sampling is 20x the shipping default — the health arm
    # prices a deliberately aggressive cadence.
    hsampler = timeseries_mod.MetricsSampler(hreg, sample_s=0.05)
    halerts = alerts_mod.AlertManager(hsampler, registry=hreg)
    treg = metrics_mod.MetricsRegistry(event_log=None)
    ttracer = tracing_mod.Tracer(treg)
    dreg = metrics_mod.MetricsRegistry(event_log=None)
    dtel = device_telemetry_mod.DeviceTelemetry(dreg, n_devices=eng.tp_size)
    # Cost-model capture (AOT compiles) happens OUTSIDE the timed
    # passes — it is a construction-time cost in the shipping config
    # too, not a per-tick one.
    eng._device_capture_programs(dtel)
    orig_tracer, orig_fraction = eng.tracer, eng._trace_fraction
    t_base = t_serve_mon = t_serve_prof = float("inf")
    t_serve_health = t_serve_trace = t_serve_dev = float("inf")
    try:
        for _ in range(2):
            # base leg: metrics on, no exporter scrape, no profiler
            eng.metrics = metrics_mod.MetricsRegistry(event_log=None)
            t_base = min(t_base, _timed_pass())
            # monitor leg: exporter ON and actively scraped — a sidecar
            # polling /metrics while the engine serves prices the
            # monitor itself (lock contention + render cost).
            eng.metrics = mon_reg
            scraping_on.set()
            t_serve_mon = min(t_serve_mon, _timed_pass())
            scraping_on.clear()
            # profiler leg: per-tick phase timing ON (acceptance bound
            # < 3 %); its report also says where tick time goes (the
            # BENCH_r06+ breakdown).
            eng.metrics = preg
            eng.prof = prof
            t_serve_prof = min(t_serve_prof, _timed_pass())
            eng.prof = None
            # health leg: time-series sampler + alert evaluation ON in
            # the step loop (acceptance: within 2 % of the monitor
            # baseline).
            eng.metrics = hreg
            eng.sampler = hsampler
            eng.alerts = halerts
            t_serve_health = min(t_serve_health, _timed_pass())
            eng.sampler = None
            eng.alerts = None
            # trace leg: causal span plane ON at 100 % head sampling —
            # every request opens, closes, and tiles its span set.
            # This prices the worst case; the disabled plane is one
            # None-check per request by construction.
            eng.metrics = treg
            eng.tracer = ttracer
            eng._trace_fraction = 1.0
            t_serve_trace = min(t_serve_trace, _timed_pass())
            eng._trace_fraction = orig_fraction
            # device leg: cost-model dispatch stamping + sync split +
            # per-step gauge refresh ON (acceptance bound < 5 %).
            eng.metrics = dreg
            eng.device = dtel
            dev_flops0 = dtel.total_flops
            t_serve_dev = min(t_serve_dev, _timed_pass())
            dev_pass_flops = dtel.total_flops - dev_flops0
            eng.device = None
    finally:
        eng.prof = None
        eng.sampler = None
        eng.alerts = None
        eng.device = None
        eng.tracer = orig_tracer
        eng._trace_fraction = orig_fraction
        stop_scraping.set()
        scraper.join(timeout=5)
        mon.stop()
    prof_report = prof.report()
    dev_report = dtel.report()

    # static baseline: batches of n_slots, one compiled generate per
    # distinct batch budget (compiles excluded by per-batch warmup)
    pad_w = max(len(r.prompt) for r in requests)
    batches = []
    for i in range(0, len(requests), n_slots):
        group = requests[i:i + n_slots]
        while len(group) < n_slots:          # pad rows don't count below
            group.append(group[0])
        toks = np.zeros((n_slots, pad_w), np.int32)
        lens = np.zeros((n_slots,), np.int32)
        for j, r in enumerate(group):
            toks[j, :len(r.prompt)] = r.prompt
            lens[j] = len(r.prompt)
        mn = max(r.max_new_tokens for r in group)
        batches.append((jnp.asarray(toks), jnp.asarray(lens), mn))
    gen_cache: dict[int, Any] = {}
    for _, _, mn in batches:
        if mn not in gen_cache:
            # hvdlint: disable=HVD001 -- bench baseline, one program per token budget
            gen_cache[mn] = jax.jit(partial(
                llama.generate, cfg=cfg, max_new_tokens=mn,
                max_len=max_len))
    for toks, lens, mn in batches:           # warm every batch shape
        jax.block_until_ready(
            gen_cache[mn](params, toks, prompt_lengths=lens))
    t0 = time.perf_counter()
    outs = [gen_cache[mn](params, toks, prompt_lengths=lens)
            for toks, lens, mn in batches]
    jax.block_until_ready(outs)
    t_static = time.perf_counter() - t0

    return {
        "serve_tokens_per_sec": n_tokens / t_serve,
        "static_tokens_per_sec": n_tokens / t_static,
        "serve_vs_static_ratio": t_static / t_serve,
        "preemptions": preemptions,
        "serve_ttft_p50_ms": hist["serve.ttft_s"].percentile(0.5) * 1e3,
        "serve_ttft_p99_ms": hist["serve.ttft_s"].percentile(0.99) * 1e3,
        "serve_tpot_p50_ms": hist["serve.tpot_s"].percentile(0.5) * 1e3,
        "serve_queue_wait_p99_ms":
            hist["serve.queue_wait_s"].percentile(0.99) * 1e3,
        "serve_e2e_p99_ms": hist["serve.e2e_s"].percentile(0.99) * 1e3,
        "serve_metrics_overhead_pct":
            (t_serve - t_serve_off) / t_serve_off * 100.0,
        "monitor_overhead_pct":
            (t_serve_mon - t_base) / t_base * 100.0,
        "serve_profiler_overhead_pct":
            (t_serve_prof - t_base) / t_base * 100.0,
        "serve_health_overhead_pct":
            (t_serve_health - t_base) / t_base * 100.0,
        "serve_trace_overhead_pct":
            (t_serve_trace - t_base) / t_base * 100.0,
        "device_telemetry_overhead_pct":
            (t_serve_dev - t_base) / t_base * 100.0,
        # honest MFU: None on platforms with no known peak (every CPU
        # rehearsal) — consumers must not coerce it to 0.
        "serve_mfu": dev_report["win"]["mfu"],
        "serve_model_flops_per_token": dev_pass_flops / n_tokens,
        "serve_device_flops_per_s": dev_report["win"]["flops_per_s"],
        "serve_overlap_headroom_pct":
            dev_report["win"]["overlap_headroom_pct"],
        "device_peak_flops_known": dev_report["peak_flops_known"],
        "serve_phase_pct": {
            p: prof_report["phases"][p]["pct_of_tick"]
            for p in profiler_mod.PHASES},
        "serve_phase_mean_ms": {
            p: prof_report["phases"][p]["mean_s"] * 1e3
            for p in profiler_mod.PHASES},
        "serve_goodput": eng.slo.goodput(),
        "tokens": n_tokens,
        "n_requests": len(requests),
        "n_slots": n_slots,
        "max_len": max_len,
        "chunk": chunk,
    }


def measure_prefix_throughput(
    params: dict, cfg: llama.LlamaConfig, requests: list[Request], *,
    n_slots: int, max_len: int, chunk: int,
    block_size: int | None = None, n_blocks: int | None = None,
) -> dict:
    """Prefix-cache-on vs cache-off throughput on one workload (the
    ``serve_prefix_*`` bench metrics).

    Both engines serve the same queue; the cache-on engine is warmed by
    a full untimed pass (compiles every program AND populates the radix
    index — the steady state of a server that has seen its system
    prompt before), mirrored by an untimed cache-off warmup, so the
    timed passes compare prefill-skipping against recompute on equal
    footing.  Outputs are asserted token-identical between the two
    engines (the parity guarantee).  Returns
    ``serve_prefix_tokens_per_sec`` (cache on),
    ``serve_prefix_off_tokens_per_sec``, ``serve_prefix_speedup``,
    ``serve_prefix_hit_rate`` (admissions with >= 1 reused block over
    all admissions, timed pass), ``serve_prefix_tokens_skipped`` and
    workload shape fields.
    """
    if not requests:
        raise ValueError("empty workload")
    kw = dict(n_slots=n_slots, max_len=max_len, chunk=chunk,
              block_size=block_size, n_blocks=n_blocks)
    timings: dict[bool, float] = {}
    outputs: dict[bool, list[RequestResult]] = {}
    hit_rate = 0.0
    tokens_skipped = 0
    n_tokens = 0
    for cache_on in (False, True):
        eng = ServeEngine(params, cfg, prefix_cache=cache_on, **kw)
        warm = eng.run(requests)
        assert all(r.ok for r in warm), [r.status for r in warm]
        n_tokens = sum(len(t) for t in warm)
        hits0 = eng.prefix_counters["hits"]
        skip0 = eng.prefix_counters["tokens_skipped"]
        t0 = time.perf_counter()
        out = eng.run(requests)
        jax.block_until_ready(eng.pcache.k)
        timings[cache_on] = time.perf_counter() - t0
        outputs[cache_on] = out
        if cache_on:
            hit_rate = ((eng.prefix_counters["hits"] - hits0)
                        / len(requests))
            tokens_skipped = (eng.prefix_counters["tokens_skipped"]
                              - skip0)
    assert [list(a) for a in outputs[True]] == \
        [list(b) for b in outputs[False]], "prefix-cache parity broken"
    return {
        "serve_prefix_tokens_per_sec": n_tokens / timings[True],
        "serve_prefix_off_tokens_per_sec": n_tokens / timings[False],
        "serve_prefix_speedup": timings[False] / timings[True],
        "serve_prefix_hit_rate": hit_rate,
        "serve_prefix_tokens_skipped": tokens_skipped,
        "tokens": n_tokens,
        "n_requests": len(requests),
        "n_slots": n_slots,
        "max_len": max_len,
        "chunk": chunk,
    }


def measure_spec_throughput(
    params: dict, cfg: llama.LlamaConfig, requests: list[Request], *,
    n_slots: int, max_len: int, chunk: int,
    block_size: int | None = None, n_blocks: int | None = None,
    draft_k: int = 4,
) -> dict:
    """Speculation-on vs plain-decode throughput on one workload (the
    ``serve_spec_*`` bench metrics).

    Both engines serve the same queue; each is warmed by a full untimed
    pass (compiles every program — the spec engine's always-wide
    ``spec_tick`` included), then timed on a second pass.  Outputs are
    asserted token-identical between the two engines — the greedy
    bit-identity guarantee of :func:`llama.spec_verify_paged
    <horovod_tpu.models.llama.spec_verify_paged>` — so the ratio prices
    pure scheduling, never output drift.  Returns
    ``serve_spec_tokens_per_sec`` (spec on),
    ``serve_spec_plain_tokens_per_sec``, ``serve_spec_vs_plain_ratio``,
    ``serve_spec_accepted_per_round`` (mean accepted drafts per
    decoding row per verify round, timed pass),
    ``serve_spec_rounds`` (timed-pass verify ticks), ``draft_k`` and
    workload shape fields.  The ratio beats 1 exactly when acceptance
    buys more rounds than the wider tick costs — lookup-friendly
    (repetitive) workloads win, lookup-hostile (random) ones price the
    overhead floor.
    """
    if not requests:
        raise ValueError("empty workload")
    kw = dict(n_slots=n_slots, max_len=max_len, chunk=chunk,
              block_size=block_size, n_blocks=n_blocks,
              metrics=metrics_mod.NULL)
    timings: dict[bool, float] = {}
    outputs: dict[bool, list[RequestResult]] = {}
    n_tokens = 0
    accepted_per_round = 0.0
    rounds = 0
    for spec_on in (False, True):
        eng = ServeEngine(params, cfg, spec=spec_on, draft_k=draft_k,
                          **kw)
        warm = eng.run(requests)
        assert all(r.ok for r in warm), [r.status for r in warm]
        n_tokens = sum(len(t) for t in warm)
        acc0 = eng.spec_counters["accepted"]
        rr0 = eng.spec_counters["row_rounds"]
        rounds0 = eng.spec_counters["rounds"]
        t0 = time.perf_counter()
        out = eng.run(requests)
        jax.block_until_ready(eng.pcache.k)
        timings[spec_on] = time.perf_counter() - t0
        outputs[spec_on] = out
        if spec_on:
            rr = eng.spec_counters["row_rounds"] - rr0
            accepted_per_round = (
                (eng.spec_counters["accepted"] - acc0) / rr if rr
                else 0.0)
            rounds = eng.spec_counters["rounds"] - rounds0
    assert [list(a) for a in outputs[True]] == \
        [list(b) for b in outputs[False]], "speculation parity broken"
    return {
        "serve_spec_tokens_per_sec": n_tokens / timings[True],
        "serve_spec_plain_tokens_per_sec": n_tokens / timings[False],
        "serve_spec_vs_plain_ratio": timings[False] / timings[True],
        "serve_spec_accepted_per_round": accepted_per_round,
        "serve_spec_rounds": rounds,
        "draft_k": draft_k,
        "tokens": n_tokens,
        "n_requests": len(requests),
        "n_slots": n_slots,
        "max_len": max_len,
        "chunk": chunk,
    }


def measure_tp_throughput(
    params: dict, cfg: llama.LlamaConfig, requests: list[Request], *,
    n_slots: int, max_len: int, chunk: int,
    block_size: int | None = None, n_blocks: int | None = None,
    tp_sizes: tuple[int, ...] = (1, 2, 4),
    prefix_cache: bool = False,
    spec: bool | None = None,
) -> dict:
    """Tensor-parallel throughput sweep on one workload (the
    ``serve_tp_*`` bench metrics).

    One engine per ``tp_size``, each warmed by a full untimed pass
    (compiles every sharded program) and timed on a second pass over
    the same queue.  Outputs are asserted token-identical across every
    tp size (the sharded-parity guarantee), so the ratios price pure
    mesh mechanics.  Returns per-tp ``serve_tp{N}_tokens_per_sec`` and
    ``serve_tp{N}_scaling_eff`` — tokens/s relative to tp=1 divided by
    N, the per-chip scaling efficiency (1.0 = linear; on a faked-CPU
    rehearsal this prices collective overhead only, real ICI numbers
    come from a TPU window) — plus ``serve_tp_sizes`` actually run and
    workload shape fields.  tp entries whose size exceeds the device
    count (or does not divide the head/ffn/vocab axes) are skipped and
    listed under ``serve_tp_skipped``.
    """
    if not requests:
        raise ValueError("empty workload")
    kw = dict(n_slots=n_slots, max_len=max_len, chunk=chunk,
              block_size=block_size, n_blocks=n_blocks,
              prefix_cache=prefix_cache, spec=spec,
              metrics=metrics_mod.NULL)
    timings: dict[int, float] = {}
    outputs: dict[int, list[RequestResult]] = {}
    skipped: list[int] = []
    n_tokens = 0
    for tp in tp_sizes:
        if tp > jax.device_count() or any(
                d % tp for d in (cfg.n_heads, cfg.n_kv_heads, cfg.dim,
                                 cfg.ffn_dim, cfg.vocab_size)):
            skipped.append(tp)
            continue
        eng = ServeEngine(params, cfg, tp_size=tp, **kw)
        warm = eng.run(requests)
        assert all(r.ok for r in warm), [r.status for r in warm]
        n_tokens = sum(len(t) for t in warm)
        t0 = time.perf_counter()
        out = eng.run(requests)
        jax.block_until_ready(eng.pcache.k)
        timings[tp] = time.perf_counter() - t0
        outputs[tp] = out
    ran = sorted(timings)
    if not ran:
        raise ValueError(
            f"no tp size in {tp_sizes} fits {jax.device_count()} "
            f"devices and the model's sharded axes")
    base = ran[0]
    for tp in ran[1:]:
        assert [list(a) for a in outputs[tp]] == \
            [list(b) for b in outputs[base]], \
            f"tensor-parallel parity broken at tp={tp}"
    result: dict[str, Any] = {
        "serve_tp_sizes": ran,
        "serve_tp_skipped": skipped,
        "tokens": n_tokens,
        "n_requests": len(requests),
        "n_slots": n_slots,
        "max_len": max_len,
        "chunk": chunk,
    }
    for tp in ran:
        tps = n_tokens / timings[tp]
        result[f"serve_tp{tp}_tokens_per_sec"] = tps
        result[f"serve_tp{tp}_scaling_eff"] = (
            tps / (n_tokens / timings[base])) / (tp / base)
    return result
