"""Pluggable scheduler policies for :class:`ServeEngine`.

Until PR 8 admission order and preemption victim choice were hardcoded
— FIFO over the queue, evict the youngest decoding row.  Both are
*policy*, not correctness: scheduler invariant 2 (row independence +
greedy determinism) pins every request's output bit-identical to its
solo run regardless of who runs first or who gets preempted, so the
scheduler is free to reorder waiting and to pick preemption victims by
regret rather than by age.  This module is that seam, in the shape
production schedulers grew it (vLLM's ``--scheduling-policy
{fcfs,priority}``, Sarathi/DistServe-style SLO-aware variants):

* :class:`FifoPolicy` — submission order, evict the youngest.  The
  default, and **bit-compatible** with the pre-policy engine: every
  decision it returns is exactly what the hardcoded code chose.
* :class:`PriorityPolicy` — higher ``Request.priority`` admits first,
  lowest-priority rows are preempted first; a step-counted starvation
  guard promotes entries stuck longer than ``starvation_steps`` to the
  front (in FIFO order among themselves) so low priority means *later*,
  never *never*.
* :class:`EdfPolicy` — earliest-deadline-first over the absolute SLO
  deadline (``enqueue + Request.slo_s``; requests without an SLO sort
  last, FIFO among themselves).  Preemption evicts the
  **slack-richest** row — the one with the most time left to its
  deadline, i.e. the least-regretted victim — instead of the youngest.

A policy sees the engine's own queue entries and slot records
(duck-typed: ``req``, ``queued_steps``, ``slo_deadline``,
``admit_seq``) and returns *orderings and choices only* — it never
mutates scheduler state, allocates blocks, or touches device programs,
so a policy never adds (or retraces) a jit signature.

Select with ``ServeEngine(policy=...)`` — an instance, a name, or
``None`` to read the ``HVD_TPU_SCHED_POLICY`` env knob (default
``fifo``).
"""

from __future__ import annotations

import math
import os
from typing import Any, Sequence


def _slo_deadline(x: Any) -> float:
    """Absolute SLO deadline of a queue entry or slot; no-SLO requests
    sort as infinitely slack."""
    d = x.slo_deadline
    return math.inf if d is None else d


def _priority(x: Any) -> int:
    return x.req.priority if x.req is not None else 0


class SchedulerPolicy:
    """Admission order + preemption victim selection.

    ``admission_order(queue)`` returns the queue's entries in the order
    admission should consider them (a permutation — never add or drop
    entries).  Head-of-line blocking applies to the first block-starved
    candidate in that order, which is what feeds the preemption
    trigger, so the order decides who waits under pressure.

    ``victim(candidates)`` picks the slot index to preempt from a
    non-empty ``[(slot_index, slot), ...]`` list of replayable decoding
    rows.  The preempted request replays bit-identically, so this is a
    pure latency/regret decision."""

    name = "base"

    def admission_order(self, queue: Sequence[Any]) -> list[Any]:
        raise NotImplementedError

    def victim(self, candidates: Sequence[tuple[int, Any]]) -> int:
        raise NotImplementedError


class FifoPolicy(SchedulerPolicy):
    """Submission order in, youngest row out — the bit-compatible
    default (exactly the pre-policy hardcoded behavior)."""

    name = "fifo"

    def admission_order(self, queue: Sequence[Any]) -> list[Any]:
        return list(queue)

    def victim(self, candidates: Sequence[tuple[int, Any]]) -> int:
        return max(candidates, key=lambda c: c[1].admit_seq)[0]


class PriorityPolicy(SchedulerPolicy):
    """Strict priority with a step-counted starvation guard.

    Entries queued ``starvation_steps`` or longer jump to the front in
    FIFO order among themselves; the rest sort by descending
    ``Request.priority`` (stable, so equal priorities stay FIFO).
    Preemption evicts the lowest-priority row, youngest on ties."""

    name = "priority"

    def __init__(self, starvation_steps: int = 64):
        if starvation_steps < 1:
            raise ValueError(f"starvation_steps must be >= 1, got "
                             f"{starvation_steps}")
        self.starvation_steps = starvation_steps

    def admission_order(self, queue: Sequence[Any]) -> list[Any]:
        starved = [e for e in queue
                   if e.queued_steps >= self.starvation_steps]
        fresh = sorted((e for e in queue
                        if e.queued_steps < self.starvation_steps),
                       key=lambda e: -_priority(e))
        return starved + fresh

    def victim(self, candidates: Sequence[tuple[int, Any]]) -> int:
        return max(candidates,
                   key=lambda c: (-_priority(c[1]), c[1].admit_seq))[0]


class EdfPolicy(SchedulerPolicy):
    """Earliest-deadline-first over ``enqueue + Request.slo_s``.

    Admission runs the most urgent deadline first (no-SLO entries last,
    FIFO among themselves — ``sorted`` is stable); preemption evicts
    the slack-richest row (latest deadline, youngest on ties) — the
    victim whose SLO the replay detour hurts least."""

    name = "edf"

    def admission_order(self, queue: Sequence[Any]) -> list[Any]:
        return sorted(queue, key=_slo_deadline)

    def victim(self, candidates: Sequence[tuple[int, Any]]) -> int:
        return max(candidates,
                   key=lambda c: (_slo_deadline(c[1]),
                                  c[1].admit_seq))[0]


POLICIES: dict[str, type[SchedulerPolicy]] = {
    "fifo": FifoPolicy,
    "priority": PriorityPolicy,
    "edf": EdfPolicy,
}


def resolve_policy(
    policy: "SchedulerPolicy | str | None" = None,
) -> SchedulerPolicy:
    """An instance passes through; a name constructs; ``None`` reads
    ``HVD_TPU_SCHED_POLICY`` (unset/empty → ``fifo``)."""
    if isinstance(policy, SchedulerPolicy):
        return policy
    name = policy or os.environ.get("HVD_TPU_SCHED_POLICY", "") or "fifo"
    cls = POLICIES.get(name)
    if cls is None:
        raise ValueError(
            f"unknown scheduler policy {name!r}; choose from "
            f"{sorted(POLICIES)}")
    return cls()
