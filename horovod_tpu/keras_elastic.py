"""Elastic training for the Keras-3 frontend — ``hvd.elastic.KerasState``
parity (Horovod 0.20+ grew ``KerasState``; the 0.15.1 reference has no
elastic at all).

``KerasState`` mirrors the torch design (torch_elastic.py): it tracks a
live keras ``model`` (weights restored IN PLACE via
``get_weights``/``set_weights``, optimizer slot variables pairwise) plus
named scalar progress fields, and plugs into the shared
:func:`horovod_tpu.elastic.run` retry loop (reinit → restore → replay on
:class:`~horovod_tpu.basics.HorovodInternalError`).

Durability follows the same conventions: rank 0 writes ``step_N.npz``
atomically (tmp + fsync + rename — a renamed file is a complete file).
``.npz`` is a zip, so the restore walk keeps the torch path's torn-write
discrimination verbatim: a file that fails ``zipfile.is_zipfile`` is a
mid-write kill and the walk falls back LOUDLY; a structurally intact
file whose payload fails to deserialize hard-fails every rank (silent
rollback would renumber later commits over the newer file).

Usage::

    import horovod_tpu.keras as hvd

    model.compile(optimizer=hvd.DistributedOptimizer(opt), loss=...)
    state = hvd.elastic.KerasState(model, ckpt_dir="/ckpts/run1", epoch=0)

    @hvd.elastic.run
    def train(state):
        while state.epoch < epochs:
            model.fit(..., initial_epoch=state.epoch, epochs=state.epoch + 1)
            state.epoch += 1
            state.commit()

    train(state)
"""

from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

from horovod_tpu import elastic as _elastic
from horovod_tpu.basics import HorovodInternalError  # noqa: F401 (re-export)

__all__ = ["KerasState", "run", "HorovodInternalError"]

run = _elastic.run          # the retry loop is frontend-agnostic
BaseState = _elastic.BaseState


def _hvdk():
    # Function-level import: keras/__init__.py exposes this module as its
    # ``elastic`` attribute, so a module-level import would be circular.
    import horovod_tpu.keras as hvdk

    return hvdk


class KerasState(_elastic.LiveObjectState):
    """Elastic state over a live keras model + scalar progress fields.
    The commit/restore protocol lives in
    :class:`horovod_tpu.elastic.LiveObjectState`; this class supplies
    the npz serializer and the keras model slot."""

    _reserved = ("model",)
    _suffix = "npz"

    def __init__(self, model: Any = None, *, ckpt_dir: str | None = None,
                 **scalars: Any) -> None:
        if model is None and not scalars:
            raise ValueError(
                "KerasState needs a model or at least one scalar field"
            )
        object.__setattr__(self, "model", model)
        self._init_live(ckpt_dir, scalars)

    def _rank0(self) -> bool:
        return _hvdk().rank() == 0

    def _broadcast_obj(self, obj: Any) -> Any:
        import horovod_tpu as hvd

        return hvd.broadcast_object(obj, root_rank=0)

    # -- snapshot plumbing ------------------------------------------------

    def _optimizer(self):
        m = self.model
        opt = getattr(m, "optimizer", None) if m is not None else None
        return opt if (opt is not None and getattr(opt, "built", False)) \
            else None

    def _ensure_built_optimizer(self):
        """The compiled-but-unbuilt optimizer (slot variables are created
        on the first train step) must be BUILT before slot state can be
        restored or broadcast — the canonical relaunch flow runs
        ``restore()`` before any ``fit``, and silently skipping the
        committed slots there would resume momentum/Adam moments from
        zero; a built-ness mismatch across ranks would also diverge
        ``sync()``'s per-index variable broadcast."""
        m = self.model
        opt = getattr(m, "optimizer", None) if m is not None else None
        if opt is None:
            return None
        if not getattr(m, "built", True):
            # A deferred-build model (no Input layer, never called) has
            # ZERO trainable variables right now — building the optimizer
            # over them would permanently pin it to 0 slots and crash the
            # first fit.  Leave both unbuilt; _load_local raises its own
            # clear error if a commit actually needs them.
            return None
        if not getattr(opt, "built", False):
            opt.build(m.trainable_variables)
        return opt

    def _snapshot(self) -> dict:
        opt = self._optimizer()
        return {
            "weights": ([np.asarray(w).copy()
                         for w in self.model.get_weights()]
                        if self.model is not None else None),
            "opt_vars": ([np.asarray(v.numpy()).copy()
                          for v in opt.variables]
                         if opt is not None else None),
            "scalars": dict(object.__getattribute__(self, "_scalars")),
            "commit_step": self.commit_step,
        }

    def _load_local(self, snap: dict) -> None:
        has_payload = (snap.get("weights") is not None
                       or snap.get("opt_vars") is not None)
        if has_payload and self.model is None:
            # Silently restoring only the scalars from a commit that
            # carries weights/slots is the invisible-loss case: training
            # would proceed from fresh random weights with the epoch
            # counter claiming otherwise.
            raise ValueError(
                "commit contains model state but this KerasState has no "
                "model — pass the model to KerasState(...) before "
                "restore()"
            )
        if self.model is not None and snap.get("weights") is not None:
            if not getattr(self.model, "built", True):
                raise ValueError(
                    "commit contains weights but the model is unbuilt — "
                    "build it (add an Input layer, call build(), or run "
                    "one batch) before restore()"
                )
            self.model.set_weights(snap["weights"])
        opt_vars = snap.get("opt_vars")
        opt = (self._ensure_built_optimizer() if opt_vars is not None
               else self._optimizer())
        if opt_vars is not None and opt is None and self.model is not None:
            # The commit carries slot state but the live model has no
            # optimizer (restore() before compile()): silently dropping
            # the moments would be the invisible-loss failure the
            # hard-fail-on-drift contract exists to prevent.
            raise ValueError(
                "commit contains optimizer slot state but the model has "
                "no usable optimizer — compile() (and build) the model "
                "before restore()"
            )
        if opt is not None and opt_vars is not None:
            if len(opt_vars) != len(opt.variables):
                raise ValueError(
                    f"optimizer state drift: commit has {len(opt_vars)} "
                    f"slot variables, live optimizer has "
                    f"{len(opt.variables)} — code/commit mismatch"
                )
            for v, arr in zip(opt.variables, opt_vars):
                v.assign(arr)
        self._adopt_scalars(snap["scalars"])
        object.__setattr__(self, "_commit_step",
                           int(snap.get("commit_step", self.commit_step)))

    # -- commit / sync / restore -----------------------------------------

    def _write_file(self, dst: str, snap: dict) -> None:
        arrays = {}
        for i, w in enumerate(snap["weights"] or []):
            arrays[f"w_{i}"] = w
        for i, v in enumerate(snap["opt_vars"] or []):
            arrays[f"o_{i}"] = v
        arrays["meta"] = np.frombuffer(pickle.dumps({
            "n_w": len(snap["weights"] or []),
            "n_o": len(snap["opt_vars"] or []),
            "has_w": snap["weights"] is not None,
            "has_o": snap["opt_vars"] is not None,
            "scalars": snap["scalars"],
            "commit_step": snap["commit_step"],
        }), np.uint8)
        _elastic.atomic_write(dst, lambda f: np.savez(f, **arrays))

    @staticmethod
    def _read_file(path: str) -> dict:
        with np.load(path, allow_pickle=False) as z:
            meta = pickle.loads(bytes(bytearray(z["meta"])))
            return {
                "weights": ([z[f"w_{i}"] for i in range(meta["n_w"])]
                            if meta["has_w"] else None),
                "opt_vars": ([z[f"o_{i}"] for i in range(meta["n_o"])]
                             if meta["has_o"] else None),
                "scalars": meta["scalars"],
                "commit_step": meta["commit_step"],
            }

    def sync(self) -> None:
        """Fan the root's current state out to every rank."""
        import horovod_tpu as hvd
        from horovod_tpu.keras import _model_variables

        hvdk = _hvdk()
        # Build before broadcasting: a built-ness mismatch across ranks
        # (root restored, others fresh) would diverge the per-index
        # variable list and mismatch the gang's collectives.  Variable
        # collection itself is shared with the broadcast callback
        # (_model_variables) so the two lists cannot drift.
        self._ensure_built_optimizer()
        variables = (_model_variables(self.model)
                     if self.model is not None else [])
        hvdk.broadcast_variables(variables, 0)
        agreed = hvd.broadcast_object(
            {"scalars": dict(object.__getattribute__(self, "_scalars")),
             "commit_step": self.commit_step}, root_rank=0)
        self._adopt_scalars(agreed["scalars"])
        object.__setattr__(self, "_commit_step",
                           int(agreed["commit_step"]))

    # commit()/restore() come from LiveObjectState (one protocol copy).
