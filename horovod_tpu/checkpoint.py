"""Checkpoint / resume conventions.

The reference delegates checkpointing to the frameworks but establishes the
conventions (SURVEY.md §5): rank-0-only writes
(reference examples/tensorflow_mnist.py:106-108, keras_imagenet_resnet50.py:157),
resume = find last checkpoint, broadcast the resume epoch, load on root,
broadcast state to all (keras_imagenet_resnet50.py:66-73,
pytorch_imagenet_resnet50.py:134-142), and ``hvd.load_model`` which re-wraps
the optimizer with ``DistributedOptimizer`` on load
(horovod/keras/__init__.py:115-148).

TPU-native: orbax-backed, with the same conventions as helpers.
"""

from __future__ import annotations

import os
import re
from typing import Any

import jax

from horovod_tpu import basics
from horovod_tpu.optim.distributed_optimizer import (
    DistributedOptimizer,
    _root_process,
    allgather_object,
    broadcast_object,
    broadcast_parameters,
)


def _mp_options(solo: bool):
    """orbax MultiprocessingOptions for a rank-0-only call.

    orbax's Checkpointer.save/restore contract is "called by all hosts" —
    it runs cross-process sync barriers internally.  The reference's
    convention is rank-0-ONLY writes, so the root-only code paths must
    scope those barriers to the calling process (``active_processes``),
    or rank 0 joins a global barrier its peers never reach and the next
    collective on every peer pairs with the wrong message.
    """
    import orbax.checkpoint as ocp

    if not solo or jax.process_count() == 1:
        return ocp.options.MultiprocessingOptions()
    me = jax.process_index()
    return ocp.options.MultiprocessingOptions(
        primary_host=me, active_processes={me}
    )


def _make_ckpt(*, solo: bool):
    import orbax.checkpoint as ocp

    return ocp.Checkpointer(
        ocp.PyTreeCheckpointHandler(),
        multiprocessing_options=_mp_options(solo),
    )


_async_checkpointer = None


def _async_ckpt():
    global _async_checkpointer
    if _async_checkpointer is None:
        import orbax.checkpoint as ocp

        # Only the root process saves (save_checkpoint returns early
        # elsewhere), so the async writer is self-scoped too.
        _async_checkpointer = ocp.AsyncCheckpointer(
            ocp.PyTreeCheckpointHandler(),
            multiprocessing_options=_mp_options(True),
        )
    return _async_checkpointer


def save_checkpoint(
    path: str, state: Any, *, step: int | None = None,
    async_save: bool = False,
) -> str | None:
    """Write a checkpoint from rank 0 only (the reference convention:
    ``if hvd.rank() == 0: saver.save(...)``).  Returns the path written, or
    None on non-root processes.

    ``async_save=True`` returns as soon as the device→host copy is done and
    writes in a background thread (orbax AsyncCheckpointer) so training
    continues during the disk write; call :func:`wait_for_checkpoints`
    before reading the file or exiting.
    """
    basics._require_init()
    # "Rank 0" means the process owning mesh device 0 — the same
    # definition restore_checkpoint's reader uses (_root_process); mesh
    # device order is not guaranteed process-contiguous, and a writer /
    # reader living on different hosts would lose every checkpoint on
    # per-host disks.
    if basics.cross_rank() != _root_process(0):
        return None
    base = os.path.abspath(path)
    target = os.path.join(base, f"step_{step}") if step is not None else base
    if async_save:
        _async_ckpt().save(target, jax.device_get(state), force=True)
        return target
    _make_ckpt(solo=True).save(target, jax.device_get(state), force=True)
    return target


def wait_for_checkpoints() -> None:
    """Block until all pending :func:`save_checkpoint` async writes land."""
    if _async_checkpointer is not None:
        _async_checkpointer.wait_until_finished()


def list_checkpoints(path: str) -> list:
    """All ``step_N`` checkpoints under ``path``, NEWEST FIRST, agreed
    across hosts (root scans its disk; everyone adopts root's view — the
    rank-0 write convention means non-root disks may hold nothing).

    Callers that must survive a torn checkpoint (a gang killed mid-write)
    walk this list and fall back — :meth:`horovod_tpu.elastic.State.restore`
    does; ``restore_checkpoint`` raises in agreement on every rank, so the
    walk stays in lockstep."""
    basics._require_init()
    found: list = []
    if basics.cross_rank() == _root_process(0) and os.path.isdir(path):
        steps = []
        for entry in os.listdir(path):
            m = re.fullmatch(r"step_(\d+)", entry)
            if m:
                steps.append(int(m.group(1)))
        found = [os.path.join(os.path.abspath(path), f"step_{s}")
                 for s in sorted(steps, reverse=True)]
    return broadcast_object(found, root_rank=0)


def latest_checkpoint(path: str) -> str | None:
    """Find the newest ``step_N`` checkpoint under ``path`` (the resume scan
    of reference keras_imagenet_resnet50.py:66-70), agreed across hosts."""
    found = list_checkpoints(path)
    return found[0] if found else None


def restore_checkpoint(path: str, template: Any = None, *, root_rank: int = 0) -> Any:
    """Load on root, broadcast to every process, re-place on the mesh — the
    reference's load-then-``broadcast_parameters`` resume recipe
    (pytorch_imagenet_resnet50.py:134-142) as one call.

    With a ``template``, only the ROOT process reads the file: rank-0-only
    writes mean non-root hosts may not have the checkpoint on their local
    disk at all; they contribute the template's values and the broadcast
    overwrites them with root's.  Without a template every process reads
    (requires a shared filesystem) — the broadcast then guarantees
    bit-identity even across racy reads.
    """
    basics._require_init()
    base = os.path.abspath(path)
    on_root = basics.cross_rank() == _root_process(root_rank)
    state, err = template, None
    if template is not None and any(
        isinstance(l, jax.Array) and not l.is_fully_addressable
        for l in jax.tree.leaves(template)
    ):
        # The broadcast path returns REPLICATED state; a template whose
        # leaves span non-addressable devices (live sharded train state)
        # can't ride it — and would crash only on non-root ranks, deep in
        # the broadcast, stranding the root in the collective.  Fail fast
        # and identically on every rank instead (this check is
        # deterministic across ranks, so no agreement round is needed).
        err = (
            f"process {basics.cross_rank()}: template leaves span "
            "non-addressable devices; pass a host/abstract template "
            "(shapes+dtypes) and re-shard the result, or restore with "
            "sharding-aware orbax directly"
        )
    if err is None:
        try:
            if template is not None and not on_root:
                pass                  # root-only read; broadcast fills values
            elif template is not None:
                # Root-only read: scope orbax's barriers to this process.
                state = _make_ckpt(solo=True).restore(base, item=template)
            else:
                # Every process reads together (shared FS): orbax's global
                # barriers are consistent — all ranks make the same call.
                state = _make_ckpt(solo=False).restore(base)
        except Exception as e:
            err = f"process {basics.cross_rank()}: {type(e).__name__}: {e}"
    # Agree on the outcome BEFORE the value broadcast: a read failure on
    # any process must fail EVERY rank with the same error — otherwise the
    # failed rank never joins broadcast_parameters and the others hang in
    # a collective it will never enter.  allgather_object rides the engine
    # queue, so this cannot misorder against in-flight traffic either.
    if jax.process_count() > 1:
        bad = [e for e in allgather_object(err) if e]
        if bad:
            raise RuntimeError(
                "checkpoint restore failed: " + "; ".join(bad)
            )
    elif err:
        raise RuntimeError("checkpoint restore failed: " + err)
    return broadcast_parameters(state, root_rank)


def load_model(path: str, optimizer, template: Any = None, **dist_kwargs):
    """Restore a training state AND re-wrap its optimizer with
    ``DistributedOptimizer`` — parity with ``hvd.load_model``
    (reference horovod/keras/__init__.py:115-148), which exists so users
    can't accidentally resume with an un-distributed optimizer.

    Returns ``(state, distributed_optimizer)``.
    """
    state = restore_checkpoint(path, template)
    return state, DistributedOptimizer(optimizer, **dist_kwargs)
