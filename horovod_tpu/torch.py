"""``import horovod_tpu.torch as hvd`` — the reference's PyTorch frontend.

The reference is torch-first (reference horovod/torch/__init__.py,
mpi_ops.py); its users hold ONE CPU/GPU tensor per process under
``mpirun``.  This adapter reproduces that surface on the TPU-native
engine: each process's torch tensor becomes this process's row of a
rank-major jax array (``jax.make_array_from_process_local_data``), the
eager engine negotiates over the native TCP control plane and dispatches
the XLA collective, and the result lands back in a torch tensor.

Topology: ONE device per process — exactly the reference's process model
(one rank per accelerator).  ``init()`` raises in single-controller
multi-device worlds, where the JAX-native API (rank-major arrays) is the
right surface instead.

Parity surface (reference horovod/torch/__init__.py):
``init/shutdown/rank/local_rank/size/local_size``, blocking + async +
in-place allreduce/allgather/broadcast, ``poll``/``synchronize``,
``broadcast_parameters``, ``broadcast_optimizer_state``,
``DistributedOptimizer`` (post-accumulate-grad hooks fire async
allreduces during backward; ``step()`` drains), and ``Compression``.
"""

from __future__ import annotations

from typing import Any

import numpy as np

import horovod_tpu as _hvd
from horovod_tpu import basics as _basics
from horovod_tpu.ops import eager as _eager
from horovod_tpu.ops.collective_ops import (  # noqa: F401
    Adasum,
    Average,
    Max,
    Min,
    Product,
    Sum,
)
from horovod_tpu.ops.compression import Compression  # noqa: F401


def _torch():
    import torch

    return torch


def init(*args, **kwargs) -> None:
    _hvd.init(*args, **kwargs)
    import jax

    if jax.local_device_count() != 1 and _basics.size() != 1:
        # Tear the world back down BEFORE raising: the message tells the
        # user to call the JAX-native init() instead, and that call would
        # silently no-op against an already-initialized all-devices world.
        _hvd.shutdown()
        raise RuntimeError(
            "horovod_tpu.torch expects the reference's process model: ONE "
            f"device per process (got {jax.local_device_count()} local "
            "devices).  Launch one process per chip (python -m "
            "horovod_tpu.launch / one process per host with 1 visible "
            "device), or use the JAX-native horovod_tpu API for "
            "single-controller multi-device worlds."
        )


shutdown = _hvd.shutdown
rank = _hvd.rank
local_rank = _hvd.local_rank
size = _hvd.size
local_size = _hvd.local_size
mpi_threads_supported = _hvd.mpi_threads_supported
is_initialized = _hvd.is_initialized


def _torch_to_np(t) -> np.ndarray:
    """torch tensor → numpy, bridging bfloat16 (numpy has no native bf16;
    torch refuses .numpy() on it) through a uint16 view into ml_dtypes —
    bf16 is THE TPU dtype, so the frontend must carry it losslessly."""
    torch = _torch()
    if t.dtype == torch.bfloat16:
        import ml_dtypes

        # int16 view, not uint16: same 2-byte bitcast, but torch.uint16
        # only exists in torch >= 2.3 and would silently raise this
        # module's torch floor.
        raw = t.detach().cpu().contiguous().view(torch.int16).numpy()
        return raw.view(ml_dtypes.bfloat16).reshape(tuple(t.shape))
    # ascontiguousarray promotes 0-dim to 1-dim; reshape restores the true
    # shape so scalars (e.g. BatchNorm's num_batches_tracked in a
    # state_dict broadcast) don't grow a bogus axis.
    return np.ascontiguousarray(t.detach().cpu().numpy()).reshape(
        tuple(t.shape)
    )


def _np_to_torch(a: np.ndarray):
    """numpy → torch, bridging ml_dtypes.bfloat16 the same way."""
    import ml_dtypes

    torch = _torch()
    if a.dtype == ml_dtypes.bfloat16:
        # ascontiguousarray promotes 0-dim to 1-dim; reshape restores it
        # (same footgun as _torch_to_np).  int16 view: see _torch_to_np.
        raw = np.ascontiguousarray(a).view(np.int16)
        return (torch.from_numpy(raw.copy()).view(torch.bfloat16)
                .reshape(tuple(a.shape)))
    return torch.from_numpy(np.array(a))


def _to_rank_major(t) -> Any:
    """This process's torch tensor → its row of the rank-major array."""
    import jax

    local = _torch_to_np(t)
    if local.dtype == np.int64:
        # The wire is int32 (jax x64 is off); a silently wrapped value
        # would corrupt the collective, so reject out-of-range up front.
        if local.size and (local.max() > 0x7FFFFFFF
                           or local.min() < -0x80000000):
            raise ValueError(
                "int64 tensor holds values outside int32 range; the TPU "
                "wire carries int32 (set HOROVOD_TPU_X64=1 for the exact "
                "64-bit allreduce/broadcast path, or split the value)"
            )
    if _basics.size() == 1:
        return jax.device_put(local[None], _basics.rank_sharding())
    return jax.make_array_from_process_local_data(
        _basics.rank_sharding(), local[None]
    )


def _to_torch(arr) -> Any:
    import jax

    return _np_to_torch(np.asarray(jax.device_get(arr)))


# ---------------------------------------------------------------------- ops


def _attach_post(handle: int, **kv) -> None:
    """Merge keys into the handle's post payload (a dict living in the
    HandleManager entry — one atomic update under the manager lock,
    released with the handle)."""
    _eager.update_handle_post(handle, **kv)


def _note_wire_dtype(handle: int, tensor) -> int:
    """The XLA wire narrows int64→int32 / float64→float32 (x64 off);
    remember the caller's dtype so ``synchronize`` hands back a tensor of
    the dtype it was given.  int64 INPUTS are validated to fit int32
    (``_to_rank_major``), so broadcast/gather round-trip exactly; a Sum
    allreduce can still overflow the 32-bit wire across ranks, as it
    would any fixed-width wire.  float64 rides at float32 precision —
    the same loss ``Compression.fp16`` users already opt into."""
    torch = _torch()
    if tensor.dtype in (torch.int64, torch.float64):
        _attach_post(handle, dtype=tensor.dtype)
    return handle


def _x64_enabled() -> bool:
    """``HOROVOD_TPU_X64=1``: exact 64-bit allreduce/broadcast (reference
    parity for MPI_LONG_LONG / MPI_DOUBLE wires, mpi_message.h:32,35 →
    operations.cc:551-558).  Read at call time so tests and applications
    can toggle per-op; parsed by the same rule as every other boolean
    knob."""
    from horovod_tpu.utils.env import _get_bool

    return _get_bool("HOROVOD_TPU_X64")


def _encode64(arr: np.ndarray) -> np.ndarray:
    """int64/float64 payload → one (1, 2·numel) int32 bit-plane row.

    The data plane stays 32-bit (jax x64 off — TPUs have no 64-bit
    hardware path); exactness comes from moving the raw 64-bit bit
    pattern as two little-endian int32 words per element and doing the
    64-bit arithmetic on the host."""
    flat = np.ascontiguousarray(arr.reshape(-1))
    return flat.view(np.int32).reshape(1, -1)


def _decode64(rows: np.ndarray, np_dtype, shape: tuple) -> np.ndarray:
    """(world, 2·numel) int32 bit-planes → (world, *shape) 64-bit values."""
    return (
        np.ascontiguousarray(rows).view(np_dtype)
        .reshape((rows.shape[0],) + tuple(shape))
    )


def _np_dtype64(torch_dtype):
    torch = _torch()
    return np.int64 if torch_dtype == torch.int64 else np.float64


def _allreduce64_async(tensor, op, name, compression) -> int:
    """Exact 64-bit allreduce: allgather the bit-planes through the engine
    (so it negotiates/fuses/orders like every other op), reduce in 64-bit
    on the host at ``synchronize``.  O(world) wire and host memory per
    tensor — the int64-counter / fp64-scalar workloads this exists for
    are small; large-model gradients belong on the 32/16-bit paths.
    int64 Sum wraps mod 2⁶⁴ exactly like MPI's; int64 Average floors."""
    torch = _torch()
    if op not in (Sum, Average, Min, Max, Product):
        raise ValueError(
            f"HOROVOD_TPU_X64 allreduce supports Sum/Average/Min/Max/"
            f"Product, not {op}"
        )
    if compression is not Compression.none:
        raise ValueError(
            "HOROVOD_TPU_X64 is the exact 64-bit path; lossy compression "
            "contradicts it — use the default 32-bit wire instead"
        )
    planes = torch.from_numpy(_encode64(_torch_to_np(tensor)).copy())
    h = _eager.allgather_async(_to_rank_major(planes), name=name)
    _attach_post(
        h, x64_reduce=(op, tensor.dtype, tuple(tensor.shape))
    )
    return h


def allreduce_async(tensor, average=True, name=None, *, op=None,
                    compression=Compression.none) -> int:
    torch = _torch()
    if op is None:
        op = Average if average else Sum
    if tensor.dtype in (torch.int64, torch.float64) and _x64_enabled():
        return _allreduce64_async(tensor, op, name, compression)
    guard_h, tensor = _maybe_int64_guard(tensor, op, name)
    h = _eager.allreduce_async(
        _to_rank_major(tensor), name=name, op=op, compression=compression
    )
    _attach_guard(h, guard_h, op)
    return _note_wire_dtype(h, tensor)


def _maybe_int64_guard(tensor, op, name):
    """Collective int32-wire overflow guard for int64 Sum/Average.

    Inputs that individually fit int32 can still overflow mid-reduce.
    The sound per-rank bound |v| <= int32_max / world is checked
    COLLECTIVELY (a Max allreduce of each rank's |v|max): the values
    differ per rank, so a local raise would diverge — one rank erroring
    while its peers sit in the posted collective until the stall watchdog
    fires.  Every rank enqueues the probe, every rank sees the global
    maximum at synchronize, and all raise (or none do) together.
    Values beyond int32 entirely ride a wire-valid clamped payload whose
    probe always exceeds the bound, so the result is discarded by the
    same symmetric raise.  Single-rank worlds skip the probe: nothing to
    desynchronize, and _to_rank_major's range check covers them.  The
    escape hatch is HOROVOD_TPU_X64.

    Returns ``(guard_handle_or_None, wire_tensor)``."""
    torch = _torch()
    if (tensor.dtype != torch.int64 or op not in (Sum, Average)
            or _basics.size() <= 1):
        return None, tensor
    absmax = 0
    if tensor.numel():
        absmax = max(abs(int(tensor.max())), abs(int(tensor.min())))
    probe = torch.tensor([min(absmax, 0x7FFFFFFF)], dtype=torch.int32)
    guard_h = _eager.allreduce_async(
        _to_rank_major(probe),
        name=f"{name}.x64guard" if name else None,
        op=Max,
    )
    if absmax > 0x7FFFFFFF:
        tensor = tensor.clamp(-0x80000000, 0x7FFFFFFF)
    return guard_h, tensor


def _attach_guard(handle: int, guard_h: int | None, op) -> None:
    if guard_h is not None:
        bound = 0x7FFFFFFF // max(_basics.size(), 1)
        _attach_post(handle, x64_guard=(guard_h, bound, str(op)))


def allreduce(tensor, average=True, name=None, *, op=None,
              compression=Compression.none):
    return synchronize(
        allreduce_async(tensor, average, name, op=op, compression=compression)
    )


def allreduce_(tensor, average=True, name=None, *, op=None,
               compression=Compression.none):
    """In-place variant (reference allreduce_): result copied back."""
    out = allreduce(tensor, average, name, op=op, compression=compression)
    tensor.copy_(out)
    return tensor


def allreduce_async_(tensor, average=True, name=None, *, op=None,
                     compression=Compression.none) -> int:
    """Async in-place (reference allreduce_async_, torch/mpi_ops.py:156-176
    — the call the reference's gradient hooks make): ``synchronize(handle)``
    copies the reduced result into ``tensor`` and returns it.

    Divergence from the reference: there the tensor IS the op's output
    buffer, so after the op completes the data is visible without
    ``synchronize``.  Here the reduced value lands in ``tensor`` only when
    ``synchronize(handle)`` runs — ``poll(handle) == True`` means the
    result is ready to copy, not that it has been copied.  Code that polls
    and then reads ``tensor`` without synchronizing sees the pre-reduce
    values."""
    h = allreduce_async(tensor, average, name, op=op, compression=compression)
    _attach_post(h, inplace_dst=tensor)
    return h


# Post-processing for rank-major results rides the HandleManager entry
# itself (set_handle_post/take_handle_post) — under the manager's lock,
# released with the handle — so an abandoned handle or a raising
# synchronize() cannot leak frontend bookkeeping.  (Ragged allgather
# slicing lives in the ENGINE: allgather_async(sizes=).)


def _negotiate_gather_shapes(tensor, name):
    """Exchange (ndim, dtype, shape) across ranks through the engine
    (the shared :func:`horovod_tpu.ops.eager.negotiate_gather_sizes`).
    Returns the CPU copy of the local tensor and the per-rank dim-0
    sizes; raises the same clean errors as the eager list form for
    trailing-dim/dtype mismatch."""
    local = tensor.detach().cpu()
    sizes = _eager.negotiate_gather_sizes(
        tuple(local.shape), str(local.dtype), name
    )
    return local, sizes


def _pad_and_gather_async(local, sizes, name, orig) -> int:
    """Pad a CPU tensor to the negotiated max dim 0 and enqueue the
    ragged allgather — the one wire path both the single-op and grouped
    allgathers share (the engine slices the concatenation via sizes=)."""
    torch = _torch()
    pad = max(sizes)
    if local.shape[0] != pad:
        padded = torch.zeros((pad,) + tuple(local.shape[1:]),
                             dtype=local.dtype)
        padded[:local.shape[0]] = local
        local = padded
    h = _eager.allgather_async(_to_rank_major(local), name=name,
                               sizes=sizes)
    return _note_wire_dtype(h, orig)


def allgather_async(tensor, name=None) -> int:
    """Async allgather along dim 0; ranks may disagree on dim 0 (the
    reference's unequal-first-dim allgather, operations.cc:841-901).
    Sizes are negotiated through the engine up front; ``synchronize``
    returns the ragged concatenation."""
    local, sizes = _negotiate_gather_shapes(tensor, name)
    return _pad_and_gather_async(local, sizes, name, tensor)


def allgather(tensor, name=None):
    return synchronize(allgather_async(tensor, name))


def alltoall_async(tensor, splits=None, name=None) -> int:
    """Async all-to-all (hvd.alltoall_async, Horovod ≥0.20): this
    process's tensor splits into ``size`` chunks along dim 0;
    ``synchronize`` returns chunk ``rank`` from every process,
    concatenated.  The result is RANK-MAJOR (per-rank rows differ), so
    ``synchronize`` extracts this process's row instead of device_get-ing
    the whole array (which would fail on non-addressable multi-host
    shards) — flagged via the handle's post payload.

    ``splits`` [size]: Horovod's unequal-split form (same parameter
    order as ``horovod.torch.alltoall(tensor, splits=None, name=None)``)
    — entry j is how many dim-0 rows go to rank j (sum = this tensor's
    dim 0; ranks may disagree).  The split matrix is negotiated through
    the engine — with sum-vs-dim0 validation AFTER the exchange, so a
    bad rank errors on every rank instead of deadlocking the rest —
    every chunk pads to the global max on the wire (the ragged-allgather
    pad-to-max strategy), one equal all-to-all moves it, and
    ``synchronize`` slices each sender's true chunk back out."""
    if isinstance(splits, str):
        # pre-parity signature was alltoall(tensor, name); a migrating
        # caller's positional name would otherwise crash deep in the
        # split parse (or worse, iterate the string as split values)
        raise TypeError(
            f"alltoall got a str for splits= ({splits!r}): the "
            "reference-parity signature is alltoall(tensor, splits=None, "
            "name=None) — name is now the third argument, pass it as "
            "name=...")
    if splits is None:
        h = _eager.alltoall_async(_to_rank_major(tensor), name=name)
        _attach_post(h, rank_major=True)
        return _note_wire_dtype(h, tensor)
    torch = _torch()
    n = _basics.size()
    sp = [int(s) for s in (splits.tolist() if hasattr(splits, "tolist")
                           else splits)]
    local = tensor.detach().cpu()
    S = _eager.negotiate_alltoall_splits(sp, local.shape[0],
                                         name=name)   # [n, n]
    maxc = max(1, int(S.max()))
    padded = torch.zeros((n * maxc,) + tuple(local.shape[1:]),
                         dtype=local.dtype)
    off = 0
    for j in range(n):
        padded[j * maxc:j * maxc + sp[j]] = local[off:off + sp[j]]
        off += sp[j]
    h = _eager.alltoall_async(_to_rank_major(padded), name=name)
    _attach_post(h, rank_major=True,
                 a2av=(maxc, [int(c) for c in S[:, _basics.rank()]]))
    return _note_wire_dtype(h, tensor)


def alltoall(tensor, splits=None, name=None):
    return synchronize(alltoall_async(tensor, splits=splits, name=name))


def barrier(name=None) -> None:
    """Process-level barrier (hvd.barrier, Horovod ≥0.23): returns only
    after every process has entered it; also drains this process's
    queued eager ops (they must match before the barrier's own
    collective can)."""
    _eager.barrier(name)


def reducescatter_async(tensor, name=None, *, op=None) -> int:
    """Async reduce-scatter on torch tensors (the hvd.reducescatter API
    Horovod grew in 0.21): ranks' tensors are averaged (Horovod's default)
    or summed, and this process keeps shard ``rank()`` along dim 0.
    Dim 0 must be divisible by ``size()``.  Result extraction rides the
    handle's rank-major post flag, like ``alltoall``.

    64-bit dtypes follow ``allreduce``: the int64 Sum/Average overflow
    guard raises symmetrically across ranks, and ``HOROVOD_TPU_X64``
    routes through the exact bit-plane reduce with the shard sliced at
    ``synchronize``."""
    torch = _torch()
    if op is None:
        op = Average
    if tensor.dtype in (torch.int64, torch.float64) and _x64_enabled():
        n = _basics.size()
        if tensor.dim() < 1 or tensor.shape[0] % n != 0:
            raise ValueError(
                "reducescatter expects dim 0 divisible by "
                f"size={n}; got shape {tuple(tensor.shape)}"
            )
        h = _allreduce64_async(tensor, op, name, Compression.none)
        _attach_post(h, x64_shard=True)
        return h
    guard_h, tensor = _maybe_int64_guard(tensor, op, name)
    h = _eager.reducescatter_async(_to_rank_major(tensor), name=name, op=op)
    _attach_post(h, rank_major=True)
    _attach_guard(h, guard_h, op)
    return _note_wire_dtype(h, tensor)


def reducescatter(tensor, name=None, *, op=None):
    return synchronize(reducescatter_async(tensor, name, op=op))


def join(device: int = -1) -> int:
    """``hvd.join()`` (Horovod ≥0.21 torch API): this process is out of
    data — block until every rank joins, contributing zeros to the
    remaining plain Sum/Average allreduces meanwhile; returns the last
    rank to join.  ``device`` is accepted for signature parity and
    ignored (the TPU runtime owns placement)."""
    del device
    return _eager.join()


def broadcast_async(tensor, root_rank, name=None) -> int:
    torch = _torch()
    if tensor.dtype in (torch.int64, torch.float64) and _x64_enabled():
        # Exact 64-bit broadcast: ship the bit-planes, decode at
        # synchronize.  Lifts the int32-range input validation the
        # narrowed wire needs.
        planes = torch.from_numpy(_encode64(_torch_to_np(tensor)).copy())
        h = _eager.broadcast_async(_to_rank_major(planes), root_rank,
                                   name=name)
        _attach_post(h, x64_bcast=(tensor.dtype, tuple(tensor.shape)))
        return h
    h = _eager.broadcast_async(_to_rank_major(tensor), root_rank, name=name)
    return _note_wire_dtype(h, tensor)


def broadcast(tensor, root_rank, name=None):
    return synchronize(broadcast_async(tensor, root_rank, name))


def broadcast_(tensor, root_rank, name=None):
    out = broadcast(tensor, root_rank, name)
    tensor.copy_(out)
    return tensor


def broadcast_async_(tensor, root_rank, name=None) -> int:
    """Async in-place broadcast (reference broadcast_async_):
    ``synchronize(handle)`` writes the root's values into ``tensor``.
    As with ``allreduce_async_``, the write happens AT ``synchronize`` —
    a completed ``poll`` alone does not update ``tensor``."""
    h = broadcast_async(tensor, root_rank, name)
    _attach_post(h, inplace_dst=tensor)
    return h


def sparse_allreduce_async(tensor, name=None, *, average: bool = False,
                           ratio: float = 0.01, k: int | None = None) -> int:
    """The fork's top-k sparse allreduce on torch tensors (reference
    horovod/torch/__init__.py:46-83: mpi4py Allgatherv of nonzero
    values+indices; here top_k → allgather → scatter-add, compiled)."""
    h = _eager.sparse_allreduce_async(
        _to_rank_major(tensor), name=name, average=average, ratio=ratio, k=k
    )
    return _note_wire_dtype(h, tensor)


def sparse_allreduce(tensor, name=None, *, average: bool = False,
                     ratio: float = 0.01, k: int | None = None):
    return synchronize(
        sparse_allreduce_async(tensor, name, average=average, ratio=ratio,
                               k=k)
    )


def grouped_allreduce(tensors, average=True, *, op=None,
                      compression=Compression.none):
    """Allreduce many tensors as one fusion group (the grouped API later
    Horovod grew in 0.21) — one caller-delimited bucket through the
    engine, deterministic across hosts.

    64-bit tensors take the same paths as ``allreduce``: int64 (and, with
    ``HOROVOD_TPU_X64``, float64) members are split out of the bucket and
    ride the per-tensor path, so the collective overflow guard and the
    exact bit-plane wire apply to grouped calls too — a bucket position
    costs nothing for ops that would otherwise wrap silently mid-wire."""
    torch = _torch()
    if op is None:
        op = Average if average else Sum
    x64 = _x64_enabled()
    routed = {
        i: t for i, t in enumerate(tensors)
        if t.dtype == torch.int64 or (x64 and t.dtype == torch.float64)
    }
    handles = {
        i: allreduce_async(t, name=f"grouped.{i}", op=op,
                           compression=compression)
        for i, t in routed.items()
    }
    bucket = [t for i, t in enumerate(tensors) if i not in routed]
    outs = _eager.grouped_allreduce_eager(
        [_to_rank_major(t) for t in bucket], op=op, compression=compression
    ) if bucket else []
    results: list = []
    it = iter(outs)
    for i in range(len(tensors)):
        if i in handles:
            results.append(synchronize(handles[i]))
        else:
            results.append(_to_torch(next(it)))
    return results


def grouped_allgather(tensors, name=None):
    """Allgather many tensors together (the grouped API Horovod grew in
    0.28): ALL members' shape digests ride one engine negotiation (one
    control-plane round-trip, not one per member), then every async
    enqueues back-to-back — one deterministic engine sequence on every
    rank — and they complete together.  Ragged first dims follow the
    single-op semantics per member."""
    prefix = name or "grouped_allgather"
    locals_ = [t.detach().cpu() for t in tensors]
    sizes_per = _eager.negotiate_gather_sizes_many(
        [tuple(t.shape) for t in locals_],
        [str(t.dtype) for t in locals_],       # same convention as the
        name=prefix,                           # single-op negotiation
    )
    handles = [
        _pad_and_gather_async(local, sizes, f"{prefix}.{i}", tensors[i])
        for i, (local, sizes) in enumerate(zip(locals_, sizes_per))
    ]
    return [synchronize(h) for h in handles]


def grouped_reducescatter(tensors, name=None, *, op=None):
    """Reduce-scatter many tensors together (grouped API, Horovod ≥0.28):
    every member validates BEFORE any enqueues (a bad member can't strand
    earlier members' handles), then back-to-back asyncs complete
    together; each member keeps this process's reduced shard, default
    Average like ``reducescatter``."""
    n = _basics.size()
    for i, t in enumerate(tensors):
        if t.dim() < 1 or t.shape[0] % n != 0:
            raise ValueError(
                f"grouped_reducescatter member {i}: dim 0 must be "
                f"divisible by size={n}; got shape {tuple(t.shape)}")
    prefix = name or "grouped_reducescatter"
    handles = [reducescatter_async(t, name=f"{prefix}.{i}", op=op)
               for i, t in enumerate(tensors)]
    return [synchronize(h) for h in handles]


def poll(handle: int) -> bool:
    return _eager.poll(handle)


def synchronize(handle: int):
    # Detach the post payload BEFORE waiting: if the wait raises, the
    # payload is already off the entry and the entry itself is released by
    # the manager's error path — nothing to leak either way.
    post = _eager.take_handle_post(handle) or {}
    guard = post.get("x64_guard")
    if guard is not None:
        # The collective overflow probe for an int64 Sum/Average on the
        # int32 wire: every rank sees the same global |v|max, so this
        # raise happens on ALL ranks or none — never a divergent hang.
        guard_h, bound, op_name = guard
        gmax = int(np.asarray(_eager.synchronize(guard_h)).max())
        if gmax > bound:
            _eager.release(handle)
            raise ValueError(
                f"int64 {op_name} allreduce may overflow the int32 wire "
                f"(a rank holds |value| {gmax} > bound {bound} for world "
                f"size {_basics.size()}); set HOROVOD_TPU_X64=1 for the "
                "exact 64-bit path"
            )
    raw = _eager.synchronize(handle)
    torch = _torch()
    if post.get("rank_major"):
        local = np.asarray(raw.addressable_shards[0].data)[0]
        out = _np_to_torch(local)
    else:
        out = _to_torch(raw)
    a2av = post.get("a2av")
    if a2av is not None:
        # unequal-split alltoall: row layout is [sender s at s·maxc, its
        # true chunk is the first recv[s] rows of that window]
        maxc, recv = a2av
        parts = [out[s * maxc:s * maxc + c] for s, c in enumerate(recv)]
        out = (torch.cat(parts, 0).clone() if any(recv)
               else out[:0].clone())
    x64r = post.get("x64_reduce")
    if x64r is not None:
        op, want_dtype, shape = x64r
        rows = out.numpy()            # (world, 2·numel) int32 bit-planes
        vals = _decode64(rows, _np_dtype64(want_dtype), shape)
        n = vals.shape[0]
        if op is Sum:
            red = vals.sum(axis=0)
        elif op is Average:
            s = vals.sum(axis=0)
            red = s // n if vals.dtype == np.int64 else s / n
        elif op is Min:
            red = vals.min(axis=0)
        elif op is Max:
            red = vals.max(axis=0)
        else:                         # Product (validated at enqueue)
            red = vals.prod(axis=0)
        out = torch.from_numpy(np.ascontiguousarray(red).reshape(shape))
        if post.get("x64_shard"):
            # reducescatter rides the exact x64 reduce: keep this
            # process's shard of the reduced tensor (dim-0 divisibility
            # validated at enqueue).
            n = _basics.size()
            m = out.shape[0] // n
            out = out[_basics.rank() * m:(_basics.rank() + 1) * m].clone()
    x64b = post.get("x64_bcast")
    if x64b is not None:
        want_dtype, shape = x64b
        rows = out.numpy().reshape(1, -1)
        # np.array: a 0-dim payload indexes out as a numpy scalar, which
        # torch.from_numpy refuses.
        out = torch.from_numpy(
            np.array(_decode64(rows, _np_dtype64(want_dtype), shape)[0])
        )
    want = post.get("dtype")
    if want is not None and out.dtype != want:
        out = out.to(want)
    dst = post.get("inplace_dst")
    if dst is not None:          # the async in-place variants
        dst.copy_(out)
        return dst
    return out


# ------------------------------------------------------------- state sync


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """In-place sync of a ``state_dict()`` or ``named_parameters()``
    iterable from ``root_rank`` (reference torch/__init__.py:270-299)."""
    if hasattr(params, "items"):
        items = list(params.items())
    else:
        items = list(params)
    handles = [
        (t, broadcast_async(t.data, root_rank, name=f"bp.{name}"))
        for name, t in items
    ]
    for t, h in handles:
        t.data.copy_(synchronize(h))


def broadcast_optimizer_state(optimizer, root_rank: int = 0) -> None:
    """Sync a torch optimizer's state from ``root_rank``.

    The reference needs ~100 lines of scalar→tensor wrapping
    (torch/__init__.py:302-418); here the ROOT's state_dict shape is
    authoritative: its skeleton (with per-tensor shape/dtype) rides one
    pickled ``broadcast_object``, then every rank — including workers
    whose local optimizer has no state yet, e.g. fresh processes syncing
    from a restored root — posts exactly the root's tensor count of
    broadcasts, contributing placeholder zeros where it has nothing."""
    torch = _torch()
    sd = optimizer.state_dict()
    tensors: list = []

    def strip(obj):
        if isinstance(obj, torch.Tensor):
            tensors.append(obj)
            return ("__hvd_tensor__", len(tensors) - 1, tuple(obj.shape),
                    str(obj.dtype).removeprefix("torch."))
        if isinstance(obj, dict):
            return {k: strip(v) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            out = [strip(v) for v in obj]
            return out if isinstance(obj, list) else tuple(out)
        return obj

    skeleton = _hvd.broadcast_object(strip(sd), root_rank)

    def placeholders(obj, out):
        if isinstance(obj, tuple) and len(obj) == 4 and obj[0] == "__hvd_tensor__":
            out.append((obj[1], obj[2], obj[3]))
        elif isinstance(obj, dict):
            for v in obj.values():
                placeholders(v, out)
        elif isinstance(obj, (list, tuple)):
            for v in obj:
                placeholders(v, out)

    slots: list = []
    placeholders(skeleton, slots)
    slots.sort()
    handles = []
    for idx, shape, dtype_name in slots:
        local = (
            tensors[idx] if idx < len(tensors) and root_rank == rank()
            else torch.zeros(shape, dtype=getattr(torch, dtype_name))
        )
        handles.append(broadcast_async(local, root_rank, name=f"bos.{idx}"))
    synced = [synchronize(h) for h in handles]

    def rebuild(obj):
        if isinstance(obj, tuple) and len(obj) == 4 and obj[0] == "__hvd_tensor__":
            return synced[obj[1]]
        if isinstance(obj, dict):
            return {k: rebuild(v) for k, v in obj.items()}
        if isinstance(obj, list):
            return [rebuild(v) for v in obj]
        if isinstance(obj, tuple):
            return tuple(rebuild(v) for v in obj)
        return obj

    optimizer.load_state_dict(rebuild(skeleton))


def broadcast_object(obj, root_rank: int = 0):
    return _hvd.broadcast_object(obj, root_rank)


def allgather_object(obj):
    """One picklable object per process -> size()-long list ordered by
    RANK (hvd.allgather_object, Horovod >=0.21).

    The engine-level allgather_object orders by process index, but the
    torch frontend's rank() is mesh-device order — and mesh order is not
    guaranteed process-contiguous on multi-host pods.  Each entry is
    therefore tagged with its sender's rank and the result re-sorted, so
    ``out[hvd.rank()]`` is always this rank's object."""
    tagged = _hvd.allgather_object((rank(), obj))
    return [o for _, o in sorted(tagged, key=lambda t: t[0])]


# --------------------------------------------------------------- optimizer


class _DistributedOptimizer:
    """Hook-based wrapper (reference torch/__init__.py:86-267): each
    parameter's post-accumulate-grad hook fires an async allreduce as the
    gradient is produced; ``step()`` drains every handle, installs the
    reduced gradients, and runs the base optimizer."""

    def __init__(self, optimizer, named_parameters=None, *,
                 compression=Compression.none, op=None,
                 backward_passes_per_step: int = 1):
        self._opt = optimizer
        self._compression = compression
        self._op = op if op is not None else Average
        self._bpps = backward_passes_per_step
        if named_parameters is None:
            named_parameters = [
                (f"param.{gi}.{pi}", p)
                for gi, group in enumerate(optimizer.param_groups)
                for pi, p in enumerate(group["params"])
            ]
        else:
            named_parameters = list(named_parameters)
        self._named = named_parameters
        self._handles: dict = {}
        self._passes: dict = {}
        self._hooks = []
        for name, p in self._named:
            if p.requires_grad:
                self._hooks.append(p.register_post_accumulate_grad_hook(
                    self._make_hook(name)
                ))

    def _make_hook(self, name):
        def hook(p):
            n = self._passes.get(name, 0) + 1
            self._passes[name] = n
            if n % self._bpps != 0:
                return      # keep accumulating locally (reference :115)
            self._handles[name] = (p, allreduce_async(
                p.grad, name=f"grad.{name}", op=self._op,
                compression=self._compression,
            ))
        return hook

    def synchronize(self) -> None:
        torch = _torch()
        # Force-allreduce parameters whose hooks never fired this step
        # (frozen/conditional branches): ranks can DISAGREE on which grads
        # materialized, and a rank that skips the collective would deadlock
        # the ranks that posted it — the reference enqueues missing params
        # in synchronize() for exactly this reason (torch/__init__.py:
        # 190-197; its test_force_allreduce pins the two-headed-net case).
        for name, p in self._named:
            if not p.requires_grad or name in self._handles:
                continue
            if p.grad is None:
                p.grad = torch.zeros_like(p)
            self._handles[name] = (p, allreduce_async(
                p.grad, name=f"grad.{name}", op=self._op,
                compression=self._compression,
            ))
        for name, (p, h) in list(self._handles.items()):
            p.grad.copy_(synchronize(h))
        self._handles.clear()

    def step(self, closure=None):
        self.synchronize()
        return self._opt.step(closure)

    def zero_grad(self, *a, **k):
        return self._opt.zero_grad(*a, **k)

    def __getattr__(self, item):
        # Only reached when normal lookup fails.  Guard _opt itself: during
        # unpickling/copy __init__ hasn't run, and delegating would recurse
        # (self._opt → __getattr__("_opt") → ...) into RecursionError
        # instead of the AttributeError pickle expects.
        if item == "_opt":
            raise AttributeError(item)
        return getattr(self._opt, item)

    @property
    def param_groups(self):
        return self._opt.param_groups

    def state_dict(self):
        return self._opt.state_dict()

    def load_state_dict(self, sd):
        return self._opt.load_state_dict(sd)


def DistributedOptimizer(optimizer, named_parameters=None, *,
                         compression=Compression.none, op=None,
                         backward_passes_per_step: int = 1):
    return _DistributedOptimizer(
        optimizer, named_parameters, compression=compression, op=op,
        backward_passes_per_step=backward_passes_per_step,
    )


# ----------------------------------------------------------------- elastic
# hvd.elastic.TorchState / hvd.elastic.run — horovod.torch.elastic parity
# (Horovod 0.20+; see horovod_tpu/torch_elastic.py).
from horovod_tpu import torch_elastic as elastic  # noqa: E402,F401
