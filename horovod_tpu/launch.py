"""``python -m horovod_tpu.launch`` — the multi-process launcher.

The reference launches with plain ``mpirun -np N python train.py``
(reference docs/running.md; no custom launcher).  On TPU there is no MPI;
this is the torchrun-shaped equivalent for the cases that need one process
per host (or per simulated worker): it spawns N copies of the script with
the coordination environment set, prefixes their output by rank, and
propagates the first failure.

    # 2-process CPU simulation of a 2-host job, eager TCP control plane:
    python -m horovod_tpu.launch --nproc 2 -- python train.py --epochs 1

On a real pod slice you usually do NOT need this: one process per host is
started by the platform (GKE/queued resources), and ``hvd.init()`` reads
``HOROVOD_TPU_COORDINATOR/NUM_PROCESSES/PROCESS_ID`` which the platform or
this launcher sets.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _stream(rank: int, pipe, out) -> None:
    for line in iter(pipe.readline, ""):
        out.write(f"[rank {rank}] {line}")
        out.flush()
    pipe.close()


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.launch",
        description="Spawn N coordinated worker processes on this host.",
    )
    p.add_argument("--nproc", type=int, required=True,
                   help="worker processes on THIS host")
    p.add_argument("--nnodes", type=int, default=1,
                   help="total hosts in the job (world = nnodes * nproc)")
    p.add_argument("--node-rank", type=int, default=0,
                   help="this host's index in [0, nnodes)")
    p.add_argument("--coordinator", default=None,
                   help="host:port of process 0 (default: 127.0.0.1:auto; "
                        "REQUIRED when nnodes > 1 — every host must name "
                        "node 0's address)")
    p.add_argument("--controller-transport", default=None,
                   help="native control plane, e.g. tcp:<node0>:9876 "
                        "(default: tcp on an auto local port; REQUIRED when "
                        "nnodes > 1)")
    p.add_argument("--cpu", action="store_true",
                   help="pin workers to the CPU backend (simulation)")
    p.add_argument("--restarts", type=int, default=0,
                   help="relaunch the whole gang up to N times after a "
                        "failure (fault tolerance without in-job world "
                        "resize: workers resume via latest_checkpoint() + "
                        "restore_checkpoint() at startup — see "
                        "docs/running.md, 'The launcher')")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="-- command to run (e.g. -- python train.py)")
    args = p.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        p.error("no command given; usage: ... --nproc 2 -- python train.py")
    if args.nproc < 1:
        p.error(f"--nproc must be >= 1, got {args.nproc}")
    if not 0 <= args.node_rank < args.nnodes:
        p.error(f"--node-rank {args.node_rank} outside [0, {args.nnodes})")
    if args.nnodes > 1 and not (args.coordinator and args.controller_transport):
        p.error(
            "nnodes > 1 requires explicit --coordinator and "
            "--controller-transport (auto-picked local ports would differ "
            "per host)"
        )

    if args.restarts < 0:
        p.error(f"--restarts must be >= 0, got {args.restarts}")
    if args.restarts and args.nnodes > 1:
        p.error(
            "--restarts only coordinates a single-host gang; multi-host "
            "restart needs an external supervisor on every node"
        )
    if args.restarts and (args.coordinator or args.controller_transport):
        print(
            "horovod_tpu.launch: warning: --restarts with explicit "
            "--coordinator/--controller-transport rebinds the SAME ports "
            "every attempt; a relaunch can fail to bind while the dead "
            "gang's connections sit in TIME_WAIT.  Prefer auto ports "
            "(omit the flags) for restartable single-host gangs.",
            file=sys.stderr,
        )

    world = args.nnodes * args.nproc
    for attempt in range(args.restarts + 1):
        # Fresh auto ports per attempt: the dead gang's coordinator/
        # controller listeners may linger in TIME_WAIT.
        coordinator = args.coordinator or f"127.0.0.1:{_free_port()}"
        transport = (
            args.controller_transport or f"tcp:127.0.0.1:{_free_port()}"
        )
        rc = _run_gang(args, cmd, world, coordinator, transport)
        if rc == 0 or rc == 130 or attempt == args.restarts:
            return rc
        print(
            f"horovod_tpu.launch: gang failed (rc={rc}); restarting "
            f"({attempt + 1}/{args.restarts}) — workers resume from their "
            "latest checkpoint",
            file=sys.stderr,
        )
    raise AssertionError("unreachable: the loop returns on its last pass")


def _run_gang(args, cmd, world: int, coordinator: str,
              transport: str) -> int:
    procs: list[subprocess.Popen] = []
    streams: list[threading.Thread] = []
    for i in range(args.nproc):
        pid = args.node_rank * args.nproc + i
        env = dict(os.environ)
        env.update(
            HOROVOD_TPU_COORDINATOR=coordinator,
            HOROVOD_TPU_NUM_PROCESSES=str(world),
            HOROVOD_TPU_PROCESS_ID=str(pid),
            HOROVOD_TPU_CONTROLLER_TRANSPORT=transport,
            # Per-host topology (reference MPI_COMM_TYPE_SHARED split,
            # operations.cc:1558-1590): the launcher spawned exactly
            # --nproc workers on this host, so it is the authority.
            HOROVOD_TPU_LOCAL_RANK=str(i),
            HOROVOD_TPU_LOCAL_SIZE=str(args.nproc),
        )
        if args.cpu:
            env["JAX_PLATFORMS"] = "cpu"
            # The env var alone loses to sitecustomize-forced platform
            # config; hvd.init() re-asserts THIS launcher-owned variable.
            env["HOROVOD_TPU_FORCE_PLATFORM"] = "cpu"
            env.pop("XLA_FLAGS", None)
        proc = subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )
        procs.append(proc)
        t = threading.Thread(
            target=_stream, args=(pid, proc.stdout, sys.stdout), daemon=True
        )
        t.start()
        streams.append(t)

    rc = 0
    first_failed = None
    try:
        # Gang semantics (mpirun/torchrun): the first worker failure tears
        # the rest down — survivors would otherwise block forever inside a
        # collective waiting for the dead rank.  terminate() escalates to
        # kill() after a grace period for workers that trap SIGTERM.
        import time as _time

        live = set(range(len(procs)))
        terminated_at = None
        while live:
            for i in sorted(live):
                code = procs[i].poll()
                if code is None:
                    continue
                live.discard(i)
                if code != 0 and rc == 0 and terminated_at is None:
                    rc, first_failed = code, i
                    print(
                        f"horovod_tpu.launch: worker {i} exited rc={code}; "
                        "terminating the remaining workers",
                        file=sys.stderr,
                    )
                    terminated_at = _time.monotonic()
                    for j in live:
                        if procs[j].poll() is None:
                            procs[j].terminate()
            if live:
                if (terminated_at is not None
                        and _time.monotonic() - terminated_at > 15.0):
                    for j in live:
                        if procs[j].poll() is None:
                            procs[j].kill()
                    terminated_at = float("inf")  # escalate once
                _time.sleep(0.2)
    except KeyboardInterrupt:
        rc = 130
        for proc in procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGINT)
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        for t in streams:
            t.join(timeout=5)
    if rc:
        # Report only genuine failures — not survivors the launcher itself
        # SIGTERM/SIGKILLed (negative returncode) or never waited on.
        failed = [i for i, pr in enumerate(procs)
                  if pr.returncode is not None and pr.returncode > 0]
        if first_failed is not None and first_failed not in failed:
            failed.append(first_failed)
        print(f"horovod_tpu.launch: worker(s) {sorted(failed)} failed "
              f"(rc={rc})", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
