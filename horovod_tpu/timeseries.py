"""Bounded in-process time series over the metrics registry.

PR 4 gave every process a :class:`~horovod_tpu.metrics.MetricsRegistry`
and PR 5 a fleet merge — but both answer "what is the value *now*";
nothing in the stack remembers a metric from one moment to the next, so
"is goodput sagging?" or "is p99 TTFT drifting?" needed an offline
bench run.  This module is the memory: a :class:`MetricsSampler` that
is *ticked* by loops the stack already runs (``ServeEngine.step()``
bookkeeping, the router poller — no new threads) and samples the
registry into fixed-size ring-buffer series, Monarch-style (bounded
in-memory series with local aggregation; Adams et al., VLDB 2020):

* **Tiers** — every sample lands in the ``raw`` ring (one point per
  ``sample_s``), and folds into time-aligned ``10s`` and ``60s``
  downsample rings whose bucket timestamps are ``floor(t / step) *
  step`` — aligned buckets are what makes cross-rank merge exact.

* **Counters are stored as rates** — each point carries the increment
  over the sample interval and the derived per-second rate, with the
  delta clamped at zero so a counter that *reset* (a replica respawn)
  yields a zero-rate sample, never a negative one.

* **Histograms are stored as bucket deltas** — each point carries the
  per-bucket count increments for its interval, so any window's
  p50/p90/p99 is recomputed *exactly* (at the fixed bucket resolution)
  by summing deltas and running the very same
  :func:`~horovod_tpu.metrics.percentile_from_buckets` code path the
  live registry and the PR-5 fleet merge use.

* **Gauges keep last/min/max/mean** per point, so downsampled tiers
  don't hide a spike between samples.

:func:`merge_series` merges per-rank :meth:`MetricsSampler.report`
payloads bucket-for-bucket (rates sum, gauge envelopes combine,
histogram deltas sum) — the series counterpart of
:func:`horovod_tpu.monitor.merge_snapshots`, which calls it when the
snapshots it merges carry a ``timeseries`` section.  A rank missing
from one bucket merges from the ranks that have it (a torn or partial
snapshot degrades coverage, never correctness).

Everything is standard library; only :mod:`horovod_tpu.metrics` and
the tolerant env parsing from :mod:`horovod_tpu.monitor` are imported.
The sampler is the sensor half of ROADMAP item 2 (elastic
autoscaling); :mod:`horovod_tpu.alerts` evaluates rules over these
series and folds them into capacity advice.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Iterable

from horovod_tpu import metrics as metrics_mod
from horovod_tpu.monitor import env_float

#: Downsample tiers: name -> bucket step in seconds (``None`` = the
#: raw sampling cadence itself).  Order matters: finest first.
TIERS: tuple[tuple[str, float | None], ...] = (
    ("raw", None), ("10s", 10.0), ("60s", 60.0))


def _clamp0(x: float) -> float:
    return x if x > 0 else 0.0


class _Ring:
    """One metric's bounded point ring for one tier."""

    __slots__ = ("kind", "bounds", "points")

    def __init__(self, kind: str, maxlen: int,
                 bounds: list[float] | None = None):
        self.kind = kind                  # "counter" | "gauge" | "histogram"
        self.bounds = bounds              # histogram bucket upper edges
        self.points: collections.deque[dict] = collections.deque(
            maxlen=maxlen)


class _Agg:
    """A tier's in-progress aligned bucket for one metric."""

    __slots__ = ("t", "n", "delta", "dt", "last", "mn", "mx", "total",
                 "count", "sum", "buckets")

    def __init__(self, t: float):
        self.t = t
        self.n = 0
        self.delta = 0.0      # counter increment
        self.dt = 0.0         # counter covered seconds
        self.last = 0.0       # gauge last value
        self.mn = float("inf")
        self.mx = float("-inf")
        self.total = 0.0      # gauge sum (for the mean)
        self.count = 0        # histogram observations
        self.sum = 0.0        # histogram value sum
        self.buckets: list[int] | None = None


class MetricsSampler:
    """Samples a registry into tiered ring-buffer series on ``tick()``.

    ``tick()`` is designed for a hot loop: a clock read and one float
    compare until ``sample_s`` has elapsed, then a single registry
    ``snapshot()`` pass.  It is called by ``ServeEngine.step()`` and by
    ``RouterServer.poll_now()`` — never by a thread of its own.

    ``clock`` defaults to ``time.time`` (wall clock) because the tier
    bucket timestamps must align ACROSS ranks for :func:`merge_series`;
    tests drive a virtual clock through the same parameter.
    """

    _GUARDED_BY_LOCK = ("_series", "_aggs", "_prev_counters",
                        "_prev_hists", "_last_sample")

    def __init__(self,
                 registry: metrics_mod.MetricsRegistry | None = None,
                 *, sample_s: float | None = None,
                 clock: Callable[[], float] | None = None,
                 raw_points: int = 120, mid_points: int = 180,
                 top_points: int = 360):
        self.registry = (registry if registry is not None
                         else metrics_mod.DEFAULT)
        self.sample_s = max(
            sample_s if sample_s is not None
            else env_float("HVD_TPU_SAMPLE_S", 1.0), 1e-9)
        self.clock = clock if clock is not None else time.time
        self._maxlens = {"raw": raw_points, "10s": mid_points,
                         "60s": top_points}
        self._lock = threading.Lock()
        # tier -> metric name -> ring; tier -> metric name -> open bucket
        self._series: dict[str, dict[str, _Ring]] = {
            name: {} for name, _ in TIERS}
        self._aggs: dict[str, dict[str, _Agg]] = {
            name: {} for name, _ in TIERS if name != "raw"}
        self._prev_counters: dict[str, tuple[float, float]] = {}
        self._prev_hists: dict[str, dict] = {}
        self._last_sample = float("-inf")
        # Registered up front (literal names — the HVD005 contract).
        self._samples = self.registry.counter("ts.samples")
        self._n_series = self.registry.gauge("ts.series")

    # -- ingestion ---------------------------------------------------------

    def tick(self, now: float | None = None) -> bool:
        """Sample the registry if ``sample_s`` has elapsed; returns
        whether a sample was taken.  Cheap when it wasn't."""
        now = self.clock() if now is None else now
        if now - self._last_sample < self.sample_s:
            return False
        # Snapshot OUTSIDE our lock (it takes the registry's).
        snap = self.registry.snapshot()
        return self.ingest(now, snap)

    def ingest(self, now: float, snap: dict) -> bool:
        """Fold one registry ``snapshot()`` dict into the series.  The
        public seam ``tick()`` uses — tests (and replayers) feed
        synthetic or degraded snapshots here directly.  Tolerant of
        partial snapshots: missing sections or malformed histogram
        entries are skipped, never fatal."""
        if not isinstance(snap, dict):
            return False
        with self._lock:
            if now - self._last_sample < self.sample_s:
                return False
            self._last_sample = now
            self._ingest_locked(now, snap)
        self._samples.inc()
        return True

    def _ingest_locked(self, now: float, snap: dict) -> None:
        counters = snap.get("counters") or {}
        gauges = snap.get("gauges") or {}
        hists = snap.get("histograms") or {}
        for name, v in counters.items():
            if not isinstance(v, (int, float)):
                continue
            prev = self._prev_counters.get(name)
            self._prev_counters[name] = (now, float(v))
            if prev is None:
                continue                      # no rate from one sample
            t0, v0 = prev
            dt = now - t0
            if dt <= 0:
                continue
            delta = _clamp0(float(v) - v0)    # reset clamps at 0
            self._point(name, "counter", now,
                        {"t": now, "rate": delta / dt,
                         "delta": delta, "dt": dt})
        for name, v in gauges.items():
            if not isinstance(v, (int, float)):
                continue
            v = float(v)
            self._point(name, "gauge", now,
                        {"t": now, "last": v, "min": v, "max": v,
                         "mean": v, "n": 1})
        for name, h in hists.items():
            if not isinstance(h, dict) or "buckets" not in h:
                continue                      # torn/partial snapshot
            buckets = h.get("buckets")
            bounds = h.get("bounds")
            if not isinstance(buckets, list) or not isinstance(
                    bounds, list):
                continue
            prev = self._prev_hists.get(name)
            self._prev_hists[name] = {
                "count": h.get("count", 0), "sum": h.get("sum", 0.0),
                "buckets": list(buckets), "bounds": list(bounds)}
            if prev is None or prev["bounds"] != list(bounds):
                continue
            db = [max(int(b) - int(a), 0)
                  for a, b in zip(prev["buckets"], buckets)]
            self._point(name, "histogram", now,
                        {"t": now,
                         "count": _clamp0(h.get("count", 0)
                                          - prev["count"]),
                         "sum": _clamp0(h.get("sum", 0.0)
                                        - prev["sum"]),
                         "buckets": db},
                        bounds=list(bounds))
        n = sum(len(tier) for tier in self._series.values())
        self._n_series.set(n)

    def _ring(self, tier: str, name: str, kind: str,
              bounds: list[float] | None) -> _Ring:
        ring = self._series[tier].get(name)
        if ring is None:
            ring = self._series[tier][name] = _Ring(
                kind, self._maxlens[tier], bounds)
        return ring

    def _point(self, name: str, kind: str, now: float, pt: dict,
               bounds: list[float] | None = None) -> None:
        self._ring("raw", name, kind, bounds).points.append(pt)
        for tier, step in TIERS:
            if step is None:
                continue
            bucket_t = (now // step) * step
            agg = self._aggs[tier].get(name)
            if agg is not None and bucket_t > agg.t:
                self._flush_agg(tier, name, kind, agg, bounds)
                agg = None
            if agg is None:
                agg = self._aggs[tier][name] = _Agg(bucket_t)
            agg.n += 1
            if kind == "counter":
                agg.delta += pt["delta"]
                agg.dt += pt["dt"]
            elif kind == "gauge":
                agg.last = pt["last"]
                agg.mn = min(agg.mn, pt["min"])
                agg.mx = max(agg.mx, pt["max"])
                agg.total += pt["mean"]
            else:
                agg.count += pt["count"]
                agg.sum += pt["sum"]
                if agg.buckets is None:
                    agg.buckets = list(pt["buckets"])
                else:
                    agg.buckets = [a + b for a, b in
                                   zip(agg.buckets, pt["buckets"])]

    def _flush_agg(self, tier: str, name: str, kind: str, agg: _Agg,
                   bounds: list[float] | None) -> None:
        if kind == "counter":
            pt = {"t": agg.t, "rate": (agg.delta / agg.dt
                                       if agg.dt > 0 else 0.0),
                  "delta": agg.delta, "dt": agg.dt}
        elif kind == "gauge":
            pt = {"t": agg.t, "last": agg.last, "min": agg.mn,
                  "max": agg.mx, "mean": agg.total / max(agg.n, 1),
                  "n": agg.n}
        else:
            pt = {"t": agg.t, "count": agg.count, "sum": agg.sum,
                  "buckets": agg.buckets or []}
        self._ring(tier, name, kind, bounds).points.append(pt)

    # -- queries -----------------------------------------------------------

    def window(self, name: str, window_s: float, *,
               now: float | None = None,
               end_offset_s: float = 0.0) -> list[dict]:
        """Points for ``name`` in ``[now - end_offset_s - window_s,
        now - end_offset_s]``, from the finest tier whose ring still
        reaches back to the window start; when no tier reaches that
        far, the one reaching furthest back.  Coverage is judged from
        the stored points, not ``sample_s`` — a sampler ticked slower
        than its nominal cadence (e.g. once per engine step) holds far
        more wall time in its raw ring than ``raw_points * sample_s``.
        Empty list when the metric was never sampled."""
        now = self.clock() if now is None else now
        hi = now - end_offset_s
        lo = hi - window_s
        with self._lock:
            chosen = None
            for tier, _ in TIERS:
                ring = self._series[tier].get(name)
                if ring is None or not ring.points:
                    continue
                # A ring that never evicted holds the series' complete
                # history — it reaches as far back as any tier can.
                if (ring.points[0]["t"] <= lo
                        or len(ring.points) < ring.points.maxlen):
                    chosen = ring
                    break
                if chosen is None or \
                        ring.points[0]["t"] < chosen.points[0]["t"]:
                    chosen = ring
            if chosen is None:
                return []
            return [p for p in chosen.points if lo <= p["t"] <= hi]

    def gauge_stats(self, name: str, window_s: float, *,
                    now: float | None = None) -> dict:
        """``{n, mean, min, max, last}`` of a gauge over the window."""
        pts = self.window(name, window_s, now=now)
        pts = [p for p in pts if "mean" in p]
        if not pts:
            return {"n": 0, "mean": 0.0, "min": 0.0, "max": 0.0,
                    "last": 0.0}
        return {
            "n": len(pts),
            "mean": sum(p["mean"] for p in pts) / len(pts),
            "min": min(p["min"] for p in pts),
            "max": max(p["max"] for p in pts),
            "last": pts[-1]["last"],
        }

    def counter_rate(self, name: str, window_s: float, *,
                     now: float | None = None) -> dict:
        """``{n, rate, delta}`` of a counter over the window — ``rate``
        is total increment over covered seconds (never negative)."""
        pts = [p for p in self.window(name, window_s, now=now)
               if "delta" in p]
        delta = sum(p["delta"] for p in pts)
        dt = sum(p["dt"] for p in pts)
        return {"n": len(pts), "delta": delta,
                "rate": delta / dt if dt > 0 else 0.0}

    def hist_window(self, name: str, window_s: float, *,
                    now: float | None = None,
                    end_offset_s: float = 0.0) -> dict | None:
        """Summed bucket deltas over the window, in the mergeable
        histogram-snapshot shape, or None without data."""
        pts = [p for p in self.window(name, window_s, now=now,
                                      end_offset_s=end_offset_s)
               if "buckets" in p]
        if not pts:
            return None
        with self._lock:
            ring = (self._series["raw"].get(name)
                    or self._series["10s"].get(name))
            bounds = ring.bounds if ring is not None else None
        if bounds is None:
            return None
        buckets = [0] * len(pts[0]["buckets"])
        for p in pts:
            buckets = [a + b for a, b in zip(buckets, p["buckets"])]
        return {"count": int(sum(p["count"] for p in pts)),
                "sum": sum(p["sum"] for p in pts),
                "buckets": buckets, "bounds": list(bounds)}

    def hist_percentile(self, name: str, window_s: float, q: float, *,
                        now: float | None = None,
                        end_offset_s: float = 0.0) -> float | None:
        """The ``q``-quantile of a histogram over the window, exact at
        bucket resolution via ``percentile_from_buckets`` (the same
        path the live registry and the fleet merge use); None without
        data in the window."""
        h = self.hist_window(name, window_s, now=now,
                             end_offset_s=end_offset_s)
        if h is None or h["count"] == 0:
            return None
        mn, mx = _bucket_envelope(h["bounds"], h["buckets"])
        return metrics_mod.percentile_from_buckets(
            h["bounds"], h["buckets"], h["count"], mn, mx, q)

    def slope_per_s(self, name: str, window_s: float, *,
                    now: float | None = None) -> float | None:
        """Least-squares slope (value/sec) of a gauge over the window;
        None with fewer than 3 points."""
        pts = [p for p in self.window(name, window_s, now=now)
               if "mean" in p]
        if len(pts) < 3:
            return None
        n = len(pts)
        t0 = pts[0]["t"]
        xs = [p["t"] - t0 for p in pts]
        ys = [p["mean"] for p in pts]
        mx = sum(xs) / n
        my = sum(ys) / n
        den = sum((x - mx) ** 2 for x in xs)
        if den <= 0:
            return None
        return sum((x - mx) * (y - my)
                   for x, y in zip(xs, ys)) / den

    # -- export ------------------------------------------------------------

    def report(self, *, points: int | None = None) -> dict:
        """JSON-serializable series dump (the ``/timeseries`` payload
        and the ``timeseries`` section of ``metrics_snapshot()``).
        ``points`` bounds how many trailing points each series carries
        (None = everything in the rings)."""
        with self._lock:
            tiers: dict[str, Any] = {}
            for tier, step in TIERS:
                series = {}
                for name, ring in sorted(self._series[tier].items()):
                    pts = list(ring.points)
                    if points is not None:
                        pts = pts[-points:]
                    entry: dict[str, Any] = {"kind": ring.kind,
                                             "points": pts}
                    if ring.bounds is not None:
                        entry["bounds"] = list(ring.bounds)
                    series[name] = entry
                tiers[tier] = {
                    "step_s": step if step is not None else self.sample_s,
                    "series": series}
            return {"sample_s": self.sample_s,
                    "now": self._last_sample,
                    "tiers": tiers}


def _bucket_envelope(bounds: list[float],
                     buckets: list[int]) -> tuple[float, float]:
    """(min, max) clamp envelope implied by nonzero buckets — windowed
    deltas don't carry observed min/max, so the quantile clamps to the
    resolved buckets' edges instead."""
    lo_i = next((i for i, c in enumerate(buckets) if c), None)
    hi_i = next((i for i in range(len(buckets) - 1, -1, -1)
                 if buckets[i]), None)
    if lo_i is None or hi_i is None:
        return 0.0, 0.0
    mn = bounds[lo_i - 1] if lo_i > 0 else 0.0
    mx = bounds[hi_i] if hi_i < len(bounds) else bounds[-1]
    return mn, mx


def merge_series(reports: Iterable[dict],
                 ranks: Iterable[int] | None = None) -> dict:
    """Merge per-rank :meth:`MetricsSampler.report` payloads into one
    fleet view, bucket-for-bucket on the time-aligned tiers.

    Counter rates/deltas SUM; gauge envelopes combine (min of mins,
    max of maxes, mean of means, last = any rank's last); histogram
    bucket deltas SUM with windowed percentiles recomputable downstream
    via :func:`~horovod_tpu.metrics.percentile_from_buckets`.  A rank
    missing a bucket (torn snapshot, dead rank) merges from the ranks
    that have it — degraded coverage, not an error."""
    reports = [r for r in reports if isinstance(r, dict)
               and "tiers" in r]
    rank_ids = (list(ranks) if ranks is not None
                else list(range(len(reports))))
    out_tiers: dict[str, Any] = {}
    for tier, step in TIERS:
        step_s = step
        if step_s is None:
            step_s = max((r.get("sample_s", 1.0) for r in reports),
                         default=1.0)
        merged: dict[str, dict] = {}
        for r in reports:
            series = (r.get("tiers", {}).get(tier, {})
                      .get("series", {}))
            if not isinstance(series, dict):
                continue
            for name, entry in series.items():
                kind = entry.get("kind")
                dst = merged.setdefault(
                    name, {"kind": kind, "bounds": entry.get("bounds"),
                           "buckets_by_t": {}})
                for pt in entry.get("points", ()):
                    if "t" not in pt:
                        continue
                    key = (pt["t"] // step_s) * step_s
                    cell = dst["buckets_by_t"].get(key)
                    if cell is None:
                        dst["buckets_by_t"][key] = dict(pt, t=key,
                                                        ranks=1)
                        continue
                    cell["ranks"] += 1
                    if kind == "counter":
                        cell["rate"] += pt.get("rate", 0.0)
                        cell["delta"] += pt.get("delta", 0.0)
                        cell["dt"] = max(cell.get("dt", 0.0),
                                         pt.get("dt", 0.0))
                    elif kind == "gauge":
                        cell["min"] = min(cell["min"], pt["min"])
                        cell["max"] = max(cell["max"], pt["max"])
                        n0, n1 = cell.get("n", 1), pt.get("n", 1)
                        cell["mean"] = ((cell["mean"] * n0
                                         + pt["mean"] * n1)
                                        / max(n0 + n1, 1))
                        cell["n"] = n0 + n1
                        cell["last"] = pt["last"]
                    elif "buckets" in pt and "buckets" in cell:
                        cell["count"] += pt.get("count", 0)
                        cell["sum"] += pt.get("sum", 0.0)
                        cell["buckets"] = [
                            a + b for a, b in zip(cell["buckets"],
                                                  pt["buckets"])]
        series_out = {}
        for name, dst in sorted(merged.items()):
            pts = [dst["buckets_by_t"][t]
                   for t in sorted(dst["buckets_by_t"])]
            entry = {"kind": dst["kind"], "points": pts}
            if dst.get("bounds") is not None:
                entry["bounds"] = dst["bounds"]
            series_out[name] = entry
        out_tiers[tier] = {"step_s": step_s, "series": series_out}
    return {"ranks": [int(r) for r in rank_ids[:len(reports)]],
            "tiers": out_tiers}


def maybe_sampler(registry: metrics_mod.MetricsRegistry | None = None,
                  ) -> MetricsSampler | None:
    """A sampler per the env contract: ``HVD_TPU_SAMPLE_S`` (default
    1.0) is the cadence, ``<= 0`` disables.  A
    :class:`~horovod_tpu.metrics.NullRegistry` gets no sampler —
    there's nothing to remember (and the bench's null arm must not pay
    for one)."""
    if isinstance(registry, metrics_mod.NullRegistry):
        return None
    sample_s = env_float("HVD_TPU_SAMPLE_S", 1.0)
    if sample_s <= 0:
        return None
    return MetricsSampler(registry, sample_s=sample_s)
