"""Training-loop callbacks and LR schedules.

Parity with the reference Keras callback set
(reference: horovod/_keras/callbacks.py:1-168 and the public wrappers in
horovod/keras/callbacks.py / horovod/tensorflow/keras/callbacks.py):

* ``BroadcastGlobalVariablesCallback``  — state sync at train begin
* ``MetricAverageCallback``             — epoch-end metric allreduce
* ``LearningRateScheduleCallback``      — multiplier schedule (staircase or
  smooth) with momentum correction
* ``LearningRateWarmupCallback``        — gradual ``lr → lr·size`` ramp

TPU-native design: schedules are *pure functions of the step* so they can
live inside the compiled train step — exposed both as optax schedules
(:func:`warmup_schedule`, :func:`multiplier_schedule`) and as callback
objects with the reference's epoch-driven API for eager-style loops.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Mapping

import jax.numpy as jnp
import optax

from horovod_tpu import basics
from horovod_tpu.ops import eager as eager_ops
from horovod_tpu.optim.distributed_optimizer import broadcast_parameters


# ---------------------------------------------------------------------------
# optax schedules (the compiled-path form).
# ---------------------------------------------------------------------------


def warmup_schedule(
    base_lr: float,
    *,
    size: int | None = None,
    warmup_epochs: float = 5.0,
    steps_per_epoch: int,
    verbose: bool = False,
) -> optax.Schedule:
    """Gradual ``lr → lr·size`` warm-up ramp.

    Reference formula (``_keras/callbacks.py:149-168``):
    ``lr = base_lr · size · (epoch·(size-1)/warmup + 1) / size`` — i.e. a
    linear interpolation from ``base_lr`` at epoch 0 to ``base_lr·size``
    after ``warmup_epochs``.  Returns an optax schedule over *steps*.
    """
    del verbose
    n = size if size is not None else basics.size()

    def schedule(step):
        epoch = step / steps_per_epoch
        ramp = jnp.minimum(epoch / warmup_epochs, 1.0)
        return base_lr * (1.0 + ramp * (n - 1))

    return schedule


def multiplier_schedule(
    base_lr: float,
    multiplier: Callable[[float], float] | float,
    *,
    start_epoch: float = 0.0,
    end_epoch: float | None = None,
    steps_per_epoch: int,
    staircase: bool = True,
) -> optax.Schedule:
    """Epoch-window multiplier schedule
    (reference ``LearningRateScheduleCallbackImpl``, _keras/callbacks.py:70-146).

    ``multiplier`` is a constant or a function of epoch; ``staircase`` feeds
    it integer epochs, otherwise smooth fractional epochs (reference
    :103-116).  Composable: sum several windows with optax.join_schedules.
    """

    def schedule(step):
        epoch = step / steps_per_epoch
        if staircase:
            epoch = jnp.floor(epoch)
        if callable(multiplier):
            m = multiplier(epoch)
        else:
            m = multiplier
        # `is None`, not truthiness: end_epoch=0 is a real (empty) window,
        # and `0 or inf` would silently unbound it.
        end = math.inf if end_epoch is None else end_epoch
        in_window = (epoch >= start_epoch) & (epoch < end)
        return jnp.where(in_window, base_lr * m, base_lr)

    return schedule


# ---------------------------------------------------------------------------
# Callback objects (the eager/epoch-driven form, reference API shape).
# ---------------------------------------------------------------------------


class Callback:
    """Minimal callback protocol for eager training loops (the shape of
    keras.callbacks.Callback that the reference builds on)."""

    def on_train_begin(self, state: Any) -> Any:
        return state

    def on_epoch_begin(self, epoch: int, state: Any) -> Any:
        return state

    def on_batch_begin(self, batch: int, state: Any) -> Any:
        return state

    def on_epoch_end(self, epoch: int, state: Any, metrics: dict) -> dict:
        return metrics


class BroadcastGlobalVariablesCallback(Callback):
    """Sync all state from root at train begin
    (reference _keras/callbacks.py:20-30)."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank

    def on_train_begin(self, state):
        return broadcast_parameters(state, self.root_rank)


class MetricAverageCallback(Callback):
    """Average epoch metrics over ranks (reference _keras/callbacks.py:33-67).

    Works on rank-major metric arrays (eager) or plain scalars in
    single-host jobs (already global)."""

    def on_epoch_end(self, epoch, state, metrics):
        del epoch, state
        return average_metrics(metrics)


def average_metrics(metrics: Mapping[str, Any]) -> dict:
    """Eager allreduce-average of a metrics dict; rank-major values are
    averaged over ranks, plain scalars pass through replicated."""
    out = {}
    n = basics.size()
    for k, v in metrics.items():
        arr = jnp.asarray(v)
        if arr.ndim >= 1 and arr.shape[0] == n:
            out[k] = eager_ops.allreduce(arr, average=True, name=f"metric.{k}")
        else:
            out[k] = arr
    return out


class LearningRateWarmupCallback(Callback):
    """Epoch-driven warm-up mirror of :func:`warmup_schedule`
    (reference _keras/callbacks.py:149-168).  Mutates a ``state.lr`` field
    via ``set_lr`` if provided, else returns the LR from ``current_lr``."""

    def __init__(self, base_lr: float, warmup_epochs: float = 5.0,
                 size: int | None = None, set_lr=None, verbose: bool = False):
        self.base_lr = base_lr
        self.warmup_epochs = warmup_epochs
        self.size = size if size is not None else basics.size()
        self.set_lr = set_lr
        self.verbose = verbose

    def current_lr(self, epoch: float) -> float:
        ramp = min(epoch / self.warmup_epochs, 1.0)
        return self.base_lr * (1.0 + ramp * (self.size - 1))

    def on_epoch_begin(self, epoch, state):
        if epoch > self.warmup_epochs:
            # Outside the warm-up window the callback must NO-OP so stacked
            # schedule callbacks can own the LR (the reference warmup is a
            # windowed schedule ending at warmup_epochs, _keras/callbacks.py
            # :149-168).
            return state
        lr = self.current_lr(epoch)
        if self.verbose and basics.rank() == 0:
            print(f"Epoch {epoch}: LearningRateWarmup sets lr={lr:.6g}")
        if self.set_lr is not None:
            state = self.set_lr(state, lr)
        return state


class LearningRateScheduleCallback(Callback):
    """Epoch-window multiplier (reference _keras/callbacks.py:70-146), with
    the momentum-correction knob: when LR changes by factor f, rescale
    momentum buffers by f so accumulated velocity stays consistent
    (reference :126-138)."""

    def __init__(self, base_lr: float, multiplier, start_epoch: float = 0.0,
                 end_epoch: float | None = None, staircase: bool = True,
                 momentum_correction: bool = True, set_lr=None,
                 scale_momentum=None):
        self.base_lr = base_lr
        self.multiplier = multiplier
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.set_lr = set_lr
        self.scale_momentum = scale_momentum
        self._last_lr: float | None = None

    def current_lr(self, epoch: float) -> float | None:
        """LR inside the window; None outside (callback must no-op there so
        stacked windowed callbacks don't clobber each other — the reference
        impl returns early when out of window, _keras/callbacks.py:98-101)."""
        e = math.floor(epoch) if self.staircase else epoch
        in_window = e >= self.start_epoch and (
            self.end_epoch is None or e < self.end_epoch
        )
        if not in_window:
            return None
        m = self.multiplier(e) if callable(self.multiplier) else self.multiplier
        return self.base_lr * m

    def on_epoch_begin(self, epoch, state):
        lr = self.current_lr(epoch)
        if lr is None:
            return state
        if self.set_lr is not None:
            state = self.set_lr(state, lr)
        if (
            self.momentum_correction
            and self.scale_momentum is not None
            and self._last_lr not in (None, lr)
        ):
            state = self.scale_momentum(state, lr / self._last_lr)
        self._last_lr = lr
        return state


class ModelCheckpointCallback(Callback):
    """Rank-0 periodic checkpointing from inside ``fit`` — the reference's
    ``keras.callbacks.ModelCheckpoint`` slot in its canonical callback
    stack (reference examples/keras_imagenet_resnet50.py:155-158: appended
    on rank 0 only; here the rank gate lives in ``save_checkpoint``).

    Writes ``<path>/step_<epoch>`` every ``every_epochs``; ``async_save``
    uses the background orbax writer so the epoch loop never blocks on
    disk.  Resume with ``latest_checkpoint`` + ``restore_checkpoint``.
    """

    def __init__(self, path: str, *, every_epochs: int = 1,
                 async_save: bool = False):
        if every_epochs < 1:
            raise ValueError(f"every_epochs must be >= 1, got {every_epochs}")
        self.path = path
        self.every_epochs = every_epochs
        self.async_save = async_save

    def on_epoch_end(self, epoch, state, metrics):
        if (epoch + 1) % self.every_epochs == 0:
            from horovod_tpu.checkpoint import save_checkpoint

            save_checkpoint(self.path, state, step=epoch,
                            async_save=self.async_save)
        return metrics
