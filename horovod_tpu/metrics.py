"""Process-local metrics registry + structured event log for the stack.

The reference ships exactly one observability surface — the Chrome-trace
timeline (timeline.h/.cc, :mod:`horovod_tpu.timeline`) — which is
rank-0-only, file-based, and made for eyeballs, not machines.  A
production engine needs the request-level latency decomposition that
Dapper (Sigelman et al. 2010) made standard and vLLM-class servers
expose as first-class metrics: TTFT, per-output-token latency, queue
wait, preemption/retry cost — as queryable numbers.  This module is
that layer, shared by training and serving:

* :class:`MetricsRegistry` — a thread-safe, process-local registry of
  monotonically increasing :class:`Counter`\\ s, last-value
  :class:`Gauge`\\ s, and fixed-log-bucket :class:`Histogram`\\ s.
  ``snapshot()`` returns a plain nested dict (with p50/p90/p99 per
  histogram) and ``to_prometheus()`` renders the standard Prometheus
  text exposition, so a serving sidecar can scrape the engine with
  zero extra dependencies.

* :class:`EventLog` — an optional JSONL structured event log.  Setting
  ``HVD_TPU_EVENT_LOG=<path>`` makes every registry created with the
  default ``event_log="auto"`` append one JSON object per event —
  request state transitions, fault-site hits, preemptions, prefix-cache
  evictions — each stamped with wall-clock time and (when the emitter
  has one) the engine step.  The log is the replayable ground truth:
  ``tests/test_metrics.py`` pins that replaying a serve run's lines
  reproduces the engine's lifecycle counters exactly.

* :class:`Trace` — the per-request span threaded through
  :class:`~horovod_tpu.serving_scheduler.ServeEngine` and surfaced on
  ``RequestResult.trace``: enqueue/admit/first-token/terminal stamps
  (``time.monotonic`` seconds, comparable within a process), plus
  prefill-chunk / preemption / retry / prefix-reuse odometers.

* Canonical name tables (:data:`TIMELINE_COUNTER_SERIES`,
  :data:`FAULT_SITES`, :data:`LIFECYCLE_EVENT_COUNTERS`) — the single
  source of truth ``tools/check_counter_names.py`` lints the codebase
  against, so dashboards built on these names cannot silently drift
  from the code.

Everything here is standard library only and imports nothing else from
``horovod_tpu`` — any module (``basics``, ``ops.eager``, ``faults``,
``serving_scheduler``) can instrument itself without import cycles.
The module-level :data:`DEFAULT` registry is the shared venue: the
eager collectives engine and a default-constructed ``ServeEngine``
both feed it, so one scrape sees training and serving side by side.
:data:`NULL` is the no-op twin for measuring instrumentation overhead
(``bench.py`` records the on-vs-off delta in its serve arm extras).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import sys
import threading
import time
from bisect import bisect_left
from typing import Any, IO


# ---------------------------------------------------------------------------
# Canonical name tables (linted by tools/check_counter_names.py).
# ---------------------------------------------------------------------------

#: Every Chrome-trace counter (``ph: "C"``) activity the codebase emits,
#: mapped to the exact series keys its ``values`` dict carries.  A new
#: timeline counter MUST be registered here or the lint fails the suite.
TIMELINE_COUNTER_SERIES: dict[str, tuple[str, ...]] = {
    # serving_scheduler.ServeEngine, per step
    "SCHED": ("queued", "decoding", "prefilling", "free_blocks"),
    "LIFECYCLE": ("preemptions", "timeouts", "cancellations",
                  "rejections", "retries", "failures"),
    "PREFIX": ("hits", "blocks_reused", "tokens_skipped", "evictions"),
    # serving_scheduler.ServeEngine with spec=True, per step
    "SPEC": ("rounds", "row_rounds", "proposed", "accepted"),
    # serving.speculative_generate, per verify round
    "ACCEPT": ("accepted", "rows"),
}

#: Every named fault-injection site wired through
#: :meth:`horovod_tpu.faults.FaultRegistry.check`.
FAULT_SITES: tuple[str, ...] = (
    "serve.admit",
    "serve.prefill",
    "serve.tick",
    "serve.cache",
    "serve.draft",
    "serve.router",
    "serve.supervisor",
    "serve.autoscale",
    "router.journal",
    "data.producer",
)

#: Event-log ``kind`` → ``ServeEngine.counters`` key.  Replaying a JSONL
#: event log by counting these kinds reproduces the engine's lifecycle
#: counters exactly (pinned by tests/test_metrics.py).
LIFECYCLE_EVENT_COUNTERS: dict[str, str] = {
    "serve.preempt": "preemptions",
    "serve.timeout": "timeouts",
    "serve.cancel": "cancellations",
    "serve.reject": "rejections",
    "serve.retry": "retries",
    "serve.fail": "failures",
}

#: Declared bit-identity replay surfaces: code paths whose output must
#: be byte-for-byte reproducible from their inputs (journal entries, a
#: seed, a snapshot) because something downstream replays or diffs it.
#: ``tools/hvdlint`` (HVD010) walks each ``(surface, path, qualname,
#: note)`` row's same-file call closure and flags wall-clock reads,
#: unseeded entropy, and set-iteration-order dependence.  A new replay
#: path MUST be registered here to get that protection.
DETERMINISM_SURFACES: tuple = (
    ("journal-replay", "horovod_tpu/router.py", "load_journal",
     "journal parse feeding exactly-once accept/terminal state"),
    ("journal-replay", "horovod_tpu/router.py",
     "RouterServer.replay_journal",
     "re-submission of non-terminal journal entries on restart"),
    ("journal-replay", "horovod_tpu/router.py", "compact_journal",
     "rewrite of the journal file from replayed state"),
    ("failover-replay", "horovod_tpu/router.py", "RouterServer._on_done",
     "terminal results recorded for dedupe/journal on completion"),
    ("failover-replay", "horovod_tpu/supervisor.py", "clone_engine",
     "respawned engine must be bit-identical to the dead one"),
    ("chaos-oracle", "horovod_tpu/chaos.py", "ChaosSchedule.generate",
     "seeded fault schedule replayed across campaign runs"),
    ("sim-fleet", "horovod_tpu/simfleet.py", "SimFleet.run",
     "virtual-time fleet driver replayed bit-identically from seed"),
    ("sim-campaign", "horovod_tpu/simfleet.py", "run_sim_campaign",
     "seeded chaos-at-scale campaign diffed by the --compare gate"),
    ("trace-sampling", "horovod_tpu/tracing.py", "sampled",
     "head-sampling decision is a pure function of (seed, request id)"),
    ("device-replay", "horovod_tpu/device_telemetry.py",
     "report_from_events",
     "device report rebuilt from the event log must match the live scrape"),
)

#: Canonical one-line descriptions for every registry metric the codebase
#: emits by literal name — ``to_prometheus()`` renders these as ``# HELP``
#: lines, and ``tools/check_counter_names.py`` lints call sites against
#: this table both directions (a new literal metric name MUST land here).
#: Dynamic families (``"serve." + key`` mirrors of ``ServeEngine.counters``,
#: ``"prefix." + key`` mirrors of the prefix-cache counters) are covered by
#: the ``serve.<lifecycle>`` / ``prefix.<series>`` entries below.
METRIC_HELP: dict[str, str] = {
    # hvd.* — collectives / negotiation / cross-rank step health
    "hvd.allreduce_bytes": "Per-rank eager allreduce payload bytes dispatched",
    "hvd.negotiate_polls": "KV-store poll iterations spent negotiating collective readiness",
    "hvd.negotiate_timeouts": "Negotiation rounds abandoned after the stall timeout",
    "hvd.negotiate_s": "Seconds from eager-op enqueue to negotiated dispatch",
    "hvd.step_s": "Per-rank engine/training step wall time in seconds",
    "hvd.step_skew_s": "Slowest-minus-median rank step time over the straggler window",
    # serve.* — ServeEngine request latencies and occupancy
    "serve.queue_wait_s": "Seconds a request waited from submit to first admission",
    "serve.ttft_s": "Seconds from submit to first emitted token",
    "serve.e2e_s": "Seconds from submit to terminal status",
    "serve.tpot_s": "Seconds per output token after the first (decode cadence)",
    "serve.steps": "Engine scheduler steps executed",
    "serve.queue_depth": "Requests waiting for admission",
    "serve.decoding": "Slots actively decoding",
    "serve.prefilling": "Slots mid-prefill",
    "serve.free_blocks": "Free KV-cache pages",
    "serve.cached_blocks": "KV-cache pages retained by the prefix cache",
    "serve.goodput": "Fraction of windowed terminal requests that finished OK within SLO",
    # serve.* lifecycle counters mirrored from ServeEngine.counters
    "serve.requests_submitted": "Requests accepted by submit()",
    "serve.requests_completed": "Requests reaching a terminal status",
    "serve.tokens_emitted": "Output tokens emitted across all requests",
    "serve.preemptions": "Scheduler preemptions (victim returned to queue)",
    "serve.timeouts": "Requests terminated by deadline expiry",
    "serve.cancellations": "Requests cancelled by the caller",
    "serve.rejections": "Requests load-shed after max_queue_steps",
    "serve.retries": "Fault-triggered replays of a request",
    "serve.failures": "Requests terminated FAILED after exhausting retries",
    "serve.prefix_indexed_blocks": "KV pages indexed by the radix prefix cache",
    "serve.retrace": "Jit cache growths detected mid-serve by the retrace sentry",
    # serve.spec.* — self-drafting speculation (spec=True engines)
    "serve.spec.rounds": "Speculative verify ticks executed (>= 1 decoding row)",
    "serve.spec.row_rounds": "Per-row verify rounds (decoding rows summed over spec ticks)",
    "serve.spec.proposed": "Draft tokens proposed by the prompt-lookup drafter",
    "serve.spec.accepted": "Draft tokens accepted by greedy longest-prefix verification",
    "serve.spec.accepted_per_round": "Accepted draft tokens per decoding row per verify round",
    "serve.spec.draft_faults": "Drafter faults degraded to plain decode (row unaffected)",
    # serve.phase.* — TickProfiler per-tick phase histograms (seconds);
    # the top-level phases tile step() wall time, the admit_* sub-phases
    # nest inside admit, and tick_s is the whole step.
    "serve.phase.expire_s": "Tick phase: deadline expiry + queue bookkeeping",
    "serve.phase.admit_s": "Tick phase: admission, preemption, and prefill windows",
    "serve.phase.admit_cache_acquire_s": "Admit sub-phase: prefix-cache longest-prefix acquire",
    "serve.phase.admit_prefill_dispatch_s": "Admit sub-phase: chunked-prefill window dispatch",
    "serve.phase.draft_s": "Tick phase: prompt-lookup draft proposal (spec engines)",
    "serve.phase.decode_dispatch_s": "Tick phase: host time dispatching the decode tick",
    "serve.phase.device_sync_s": "Tick phase: blocking token readback (device wait)",
    "serve.phase.device_sync_compute_est_s": "Device-sync sub-phase: cost-model-predicted device compute share",
    "serve.phase.device_sync_host_stall_s": "Device-sync sub-phase: readback wait beyond predicted device time",
    "serve.phase.verify_s": "Tick phase: acceptance + token emission (spec engines)",
    "serve.phase.sample_postprocess_s": "Tick phase: per-slot token handling and retirement",
    "serve.phase.bookkeeping_s": "Tick phase: counters, gauges, sentry, watchdog",
    "serve.phase.tick_s": "Whole engine step wall time as the profiler measures it",
    # kv.* — paged KV pool accounting in blocks AND bytes (bytes derive
    # from the llama cache dtype/shape: k+v for one block).
    "kv.free_blocks": "KV pool blocks on the free list",
    "kv.free_bytes": "KV pool bytes on the free list",
    "kv.referenced_blocks": "KV pool blocks mapped by live rows",
    "kv.referenced_bytes": "KV pool bytes mapped by live rows",
    "kv.cached_blocks": "Zero-ref KV pool blocks parked in the prefix cache",
    "kv.cached_bytes": "Zero-ref KV pool bytes parked in the prefix cache",
    "kv.block_bytes": "Device bytes one KV block holds (k+v, all layers)",
    "kv.total_bytes": "Device bytes of the whole paged KV pool (incl. trash)",
    # kv.shard_* / tp.* — per-chip view of the same pool under
    # tensor-parallel serving (logical bytes / tp.size: the pool is
    # head-split, block counts are per-chip already).  Always emitted;
    # equal to the logical kv.* bytes at tp.size = 1.
    "kv.shard_block_bytes": "Per-chip device bytes of one KV block (logical / tp.size)",
    "kv.shard_total_bytes": "Per-chip device bytes of the paged KV pool (logical / tp.size)",
    "kv.shard_free_bytes": "Per-chip KV pool bytes on the free list",
    "kv.shard_referenced_bytes": "Per-chip KV pool bytes mapped by live rows",
    "kv.shard_cached_bytes": "Per-chip KV pool bytes parked in the prefix cache",
    "tp.size": "Tensor-parallel degree of the serving engine (chips per replica)",
    # mem.* — host-side observability footprint (approximate)
    "mem.registry_bytes": "Approximate host bytes held by the metrics registry",
    "mem.trace_ring_bytes": "Approximate host bytes of live traces + the SLO ring",
    "mem.event_log_bytes": "Bytes written to the JSONL event log so far",
    "mem.prefix_index_bytes": "Approximate host bytes of the radix prefix index",
    # prefix.* — RadixPrefixCache counters mirrored from prefix_counters
    "prefix.hits": "Admissions that reused prefix-cache blocks",
    "prefix.blocks_reused": "KV pages spliced from the prefix cache",
    "prefix.tokens_skipped": "Prompt tokens skipped via prefix reuse",
    "prefix.evictions": "Prefix-cache pages evicted under pressure",
    # monitor.* — the cross-rank observability layer itself
    "monitor.scrapes": "HTTP requests served by the /metrics exporter",
    "monitor.aggregations": "Cross-rank aggregate_snapshots() rounds completed",
    "monitor.scrape_s": "Seconds serving one exporter request, per endpoint (monitor.scrape_s.<endpoint>)",
    "monitor.scrape_errors": "Exporter requests that raised or returned 5xx, per endpoint",
    # ts.* — the in-process time-series sampler (horovod_tpu.timeseries)
    "ts.samples": "Registry snapshots folded into the ring-buffer series",
    "ts.series": "Distinct metric series held across all downsample tiers",
    # alert.* — declarative rule evaluation (horovod_tpu.alerts)
    "alert.evals": "ALERT_RULES evaluation passes executed",
    "alert.fired": "Alert transitions into the firing state",
    "alert.resolved": "Firing alerts that resolved after sustained recovery",
    "alert.firing": "Rules currently in the firing state",
    "alert.pending": "Rules currently pending (condition true, not yet sustained)",
    # advisor.* — the capacity advisor (horovod_tpu.alerts)
    "advisor.recommendations": "Capacity recommendation records emitted",
    "advisor.target_delta": "Signed replica delta of the last recommendation (+grow/-shrink)",
    # router.* — the multi-replica front door (horovod_tpu.router)
    "router.requests": "Requests received at the router front door",
    "router.routed.round_robin": "Requests placed by the round_robin policy",
    "router.routed.least_loaded": "Requests placed by the least_loaded policy",
    "router.routed.prefix_affinity": "Requests placed by the prefix_affinity policy",
    "router.affinity_hit_tokens": "Tokens of shadow-index prefix shared with the chosen replica",
    "router.affinity_fallbacks": "Prefix-affinity choices overridden by the load-imbalance fallback",
    "router.sheds": "Requests REJECTED by router admission control (goodput / free-KV floors)",
    "router.failovers": "In-flight requests re-enqueued to survivors after a replica loss",
    "router.replica_deaths": "Replica healthy-to-dead transitions observed by the router",
    "router.replica_revives": "Dead HTTP replicas returned to routing after healthy probes",
    "router.replicas_healthy": "Replicas currently accepting routed requests",
    "router.inflight": "Routed requests not yet terminal, fleet-wide",
    "router.shadow_index_bytes": "Approximate host bytes of the per-replica shadow prefix indexes",
    "router.journal_appends": "Records durably appended to the request-journal WAL",
    "router.journal_errors": "Journal appends lost to a write fault (request still served)",
    "router.journal_replays": "Incomplete journaled requests re-submitted after a router restart",
    "router.journal_dedups": "Duplicate idempotency keys answered from the journaled result",
    "router.route_decision_s": "Seconds the routing policy spent choosing and booking a replica",
    "router.admission_s": "Seconds spent in router admission control per accepted-or-shed request",
    "router.journal_append_s": "Seconds appending the durable accept record to the journal WAL",
    "router.replica_queue_s": "Seconds between router submit and engine enqueue (replica inbox wait)",
    "router.e2e_s": "Seconds from router receive to terminal result, as the client observes",
    "router.failover_hops": "Failover replays one request took before reaching a terminal result",
    "router.poll_s": "Wall seconds one full poller pass took, probes through ticket reaping",
    "router.fleet_size": "Replicas currently in the routing candidate set, any health",
    "router.shadow_evictions": "Shadow-index digests evicted to honor the fleet-wide byte ceiling",
    # supervisor.* — the self-healing layer (horovod_tpu.supervisor)
    "supervisor.respawns": "Dead replicas respawned by the supervisor",
    "supervisor.respawn_failures": "Respawn attempts that failed (fault or factory error)",
    "supervisor.permanent_deaths": "Replicas circuit-broken to permanent-dead after exhausting restarts",
    "supervisor.warm_prefixes": "Hot prompts replayed into a fresh engine to rewarm its prefix cache",
    # autoscaler.* — the advisor-driven elastic actuator (horovod_tpu.autoscaler)
    "autoscaler.epoch": "Fleet membership generation (bumped on every join/leave)",
    "autoscaler.actions": "Actuations initiated (scale-up joins plus scale-down cordons)",
    "autoscaler.scale_ups": "Replicas added to the fleet by the autoscaler",
    "autoscaler.scale_downs": "Replicas retired from the fleet after a zero-drop drain",
    "autoscaler.holds": "Recommendations not actuated (hold advice, guards, or a degraded action)",
    "autoscaler.hold_faults": "Actuations degraded to hold by a serve.autoscale fault",
    "autoscaler.cordons": "Replicas cordoned out of routing pending drain",
    "autoscaler.draining": "Replicas currently cordoned and draining in-flight work",
    "autoscaler.replicas_target": "Fleet size the last actuation drove toward",
    # trace.* — the causal span-tree plane (horovod_tpu.tracing)
    "trace.sampled": "Requests head-sampled into the tracing plane at a root",
    "trace.spans": "Closed trace.span records emitted to the event log",
    # serve.mfu / device.* — the device telemetry plane
    # (horovod_tpu.device_telemetry): XLA cost model, compile ledger,
    # HBM polling, and the transfer/dispatch split.  The conditional
    # gauges (serve.mfu, device.bytes_in_use, ...) are minted only when
    # their value is honestly known — absent beats a fabricated zero.
    "serve.mfu": "Windowed achieved model FLOPs over the platform peak (absent when no peak is known)",
    "serve.arithmetic_intensity": "Windowed cost-model FLOPs per byte accessed across dispatched programs",
    "device.compiles": "XLA program compilations observed (AOT captures plus sentry-detected retraces)",
    "device.compile_s": "Seconds one XLA program compilation took (AOT capture wall time)",
    "device.model_flops": "Cost-model FLOPs dispatched to the device across all pinned programs",
    "device.h2d_bytes": "Host-to-device bytes of per-call program arguments stamped at dispatch",
    "device.d2h_bytes": "Device-to-host bytes read back at the device_sync boundary",
    "device.bytes_in_use": "Device memory in use per memory_stats() (absent when the backend has none)",
    "device.peak_bytes_in_use": "High-water device memory per memory_stats() (absent when the backend has none)",
    "device.hbm_used_fraction": "bytes_in_use over bytes_limit (absent without a device memory limit)",
    "device.overlap_headroom_pct": "Windowed predicted device-compute share of wall time (the double-buffering ceiling)",
    "device.peak_flops_known": "1 when the platform peak-FLOPs table (or override) knows this device, else 0",
}


# ---------------------------------------------------------------------------
# Instruments.
# ---------------------------------------------------------------------------


class _Gen:
    """A shared mutation-generation cell: every instrument write bumps
    ``n`` (under the instrument's lock), so a renderer can cache its
    output keyed on the generation it rendered and serve the cached text
    until ANY instrument changes.  Registry-created instruments share
    the registry's cell; standalone instruments get a private one."""

    __slots__ = ("n",)

    def __init__(self) -> None:
        self.n = 0


class Counter:
    """A monotonically increasing integer (Prometheus ``counter``)."""

    __slots__ = ("name", "_lock", "_gen", "_value")
    _GUARDED_BY_LOCK = ("_value",)

    def __init__(self, name: str, lock: threading.Lock,
                 gen: _Gen | None = None):
        self.name = name
        self._lock = lock
        self._gen = gen if gen is not None else _Gen()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (n={n})")
        with self._lock:
            self._value += n
            self._gen.n += 1

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """A last-value-wins float (Prometheus ``gauge``)."""

    __slots__ = ("name", "_lock", "_gen", "_value")
    _GUARDED_BY_LOCK = ("_value",)

    def __init__(self, name: str, lock: threading.Lock,
                 gen: _Gen | None = None):
        self.name = name
        self._lock = lock
        self._gen = gen if gen is not None else _Gen()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)
            self._gen.n += 1

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


def log_bucket_bounds(lo: float = 1e-6, hi: float = 1e3,
                      per_decade: int = 3) -> tuple[float, ...]:
    """Fixed log-spaced bucket upper bounds: ``per_decade`` buckets per
    decade from ``lo`` to ``hi`` inclusive.  The default (1 µs → 1000 s,
    3/decade → 28 bounds) bounds every latency this stack measures with
    <= 10^(1/3) ≈ 2.15x relative quantile error — coarse, but fixed:
    histograms from any two processes/runs merge bucket-for-bucket."""
    if not (0 < lo < hi):
        raise ValueError(f"need 0 < lo < hi, got {lo}, {hi}")
    n = int(round(math.log10(hi / lo) * per_decade))
    return tuple(lo * 10 ** (i / per_decade) for i in range(n + 1))


def percentile_from_buckets(bounds: tuple[float, ...] | list[float],
                            counts: list[int], count: int,
                            mn: float, mx: float, q: float) -> float:
    """Estimate the ``q``-quantile from fixed-bucket counts; 0.0 when
    empty.  This is THE quantile code path — :class:`Histogram` and
    :func:`horovod_tpu.monitor.merge_snapshots` both call it, which is
    what makes a merged fleet histogram's p50/p90/p99 bit-identical to a
    single-process histogram over the union of observations."""
    if count == 0:
        return 0.0
    rank = q * count
    cum = 0.0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        if cum + c >= rank:
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i] if i < len(bounds) else mx
            frac = (rank - cum) / c
            est = lo + (hi - lo) * max(frac, 0.0)
            return min(max(est, mn), mx)
        cum += c
    return mx


class Histogram:
    """Fixed-log-bucket histogram with quantile estimation.

    ``bounds`` are bucket *upper* edges (ascending); one implicit
    overflow bucket catches everything above the last edge.  Quantiles
    interpolate linearly inside the resolved bucket and clamp to the
    exact observed min/max, so single-sample and narrow distributions
    report true values instead of bucket edges.
    """

    __slots__ = ("name", "bounds", "_lock", "_gen", "_counts", "_count",
                 "_sum", "_min", "_max", "_exemplars")
    _GUARDED_BY_LOCK = ("_counts", "_count", "_sum", "_min", "_max",
                        "_exemplars")

    def __init__(self, name: str, lock: threading.Lock,
                 bounds: tuple[float, ...] | None = None,
                 gen: _Gen | None = None):
        self.name = name
        self.bounds = tuple(bounds) if bounds else log_bucket_bounds()
        if list(self.bounds) != sorted(self.bounds):
            raise ValueError(f"histogram {name} bounds must ascend")
        self._lock = lock
        self._gen = gen if gen is not None else _Gen()
        self._counts = [0] * (len(self.bounds) + 1)
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        # bucket index -> (trace_id, value): the OpenMetrics-style
        # exemplar store, lazily created so untraced histograms pay
        # nothing.  Last-write-wins per bucket — the p99 bucket always
        # links to the most recent trace that landed there.
        self._exemplars: dict[int, tuple[str, float]] | None = None

    def observe(self, v: float, exemplar: str | None = None) -> None:
        v = float(v)
        with self._lock:
            idx = bisect_left(self.bounds, v)
            self._counts[idx] += 1
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            if exemplar is not None:
                if self._exemplars is None:
                    self._exemplars = {}
                self._exemplars[idx] = (exemplar, v)
            self._gen.n += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def percentile(self, q: float) -> float:
        """Estimate the ``q``-quantile (``q`` in [0, 1]) from the bucket
        counts; 0.0 when empty."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        with self._lock:
            return self._percentile_locked(q)

    def _percentile_locked(self, q: float) -> float:
        return percentile_from_buckets(self.bounds, self._counts,
                                       self._count, self._min, self._max, q)

    def snapshot(self) -> dict:
        """Schema-stable summary: count/sum/min/max + p50/p90/p99, plus
        the raw ``buckets`` counts and their ``bounds`` — the mergeable
        form :func:`horovod_tpu.monitor.merge_snapshots` sums exactly
        (one extra slot past ``bounds`` is the overflow bucket)."""
        with self._lock:
            return self._snapshot_locked()

    def _snapshot_locked(self) -> dict:
        # The registry calls this directly inside ITS lock pass — the
        # instrument lock IS the registry lock there, and a plain Lock
        # re-taken would wedge.
        if self._count == 0:
            return {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
                    "p50": 0.0, "p90": 0.0, "p99": 0.0,
                    "buckets": list(self._counts),
                    "bounds": list(self.bounds)}
        snap = {
            "count": self._count,
            "sum": self._sum,
            "min": self._min,
            "max": self._max,
            "p50": self._percentile_locked(0.50),
            "p90": self._percentile_locked(0.90),
            "p99": self._percentile_locked(0.99),
            "buckets": list(self._counts),
            "bounds": list(self.bounds),
        }
        if self._exemplars:
            # keyed by the bucket's le edge label ("+Inf" for overflow)
            # so readers need no index arithmetic; absent entirely when
            # no traced observation ever landed (schema-stable default).
            snap["exemplars"] = {
                (f"{self.bounds[i]:g}" if i < len(self.bounds)
                 else "+Inf"): {"trace_id": tid, "value": v}
                for i, (tid, v) in sorted(self._exemplars.items())}
        return snap


# ---------------------------------------------------------------------------
# Rank identity (stamped onto event-log records and state dumps).
# ---------------------------------------------------------------------------

# This module imports nothing from horovod_tpu, so the rank arrives by
# push: ``basics.init()`` calls ``set_rank()`` once the mesh is up.
# Before that (or in single-process tests) the launcher env var is the
# best available answer, matching jax.distributed's process index.
_RANK_LOCK = threading.Lock()
_RANK: int | None = None


def set_rank(r: int | None) -> None:
    """Pin the rank stamped on event-log records (``basics.init()`` /
    ``shutdown()`` call this; tests may too)."""
    global _RANK
    with _RANK_LOCK:
        _RANK = None if r is None else int(r)


def current_rank() -> int:
    """The rank identity for log attribution: the value ``set_rank()``
    pinned, else ``HOROVOD_TPU_PROCESS_ID`` from the launcher, else 0."""
    with _RANK_LOCK:
        if _RANK is not None:
            return _RANK
    try:
        return int(os.environ.get("HOROVOD_TPU_PROCESS_ID", "0"))
    except ValueError:
        return 0


# ---------------------------------------------------------------------------
# Structured event log (JSONL).
# ---------------------------------------------------------------------------


class EventLog:
    """Append-only JSONL event sink: one JSON object per line, each
    stamped with the ``(wall_s, mono_s)`` clock pair (``ts`` is the
    wall-clock half, kept under its original key; ``mono_s`` is
    ``time.monotonic()`` so cross-rank tools can align on monotonic
    deltas when wall clocks skew) plus ``kind`` and the emitter's
    fields.  Flushed per line — a crashed process leaves a readable log
    up to its last event (the postmortem property the engine watchdog
    counts on).  Thread-safe.

    The sink is size-bounded: past ``max_mb`` (default from
    ``HVD_TPU_EVENT_LOG_MAX_MB``; unset/0 = unbounded) the file rotates
    to ``<path>.1``, keeping one generation.  :meth:`read` spans the
    rotation boundary and stays torn-line tolerant in both
    generations."""

    _GUARDED_BY_LOCK = ("_file", "_bytes")

    def __init__(self, path: str, max_mb: float | None = None):
        self.path = path
        if max_mb is None:
            raw = os.environ.get("HVD_TPU_EVENT_LOG_MAX_MB", "")
            try:
                max_mb = float(raw) if raw else 0.0
            except ValueError:
                max_mb = 0.0
        self.max_bytes = int(max_mb * 1024 * 1024) if max_mb > 0 else 0
        self._lock = threading.Lock()
        dirname = os.path.dirname(path)
        if dirname:
            os.makedirs(dirname, exist_ok=True)
        self._file: IO[str] | None = open(path, "a")
        try:
            self._bytes = os.path.getsize(path)
        except OSError:
            self._bytes = 0

    def emit(self, kind: str, **fields: Any) -> None:
        line = json.dumps({"ts": time.time(),
                           "mono_s": time.monotonic(), "kind": kind,
                           "rank": current_rank(), "pid": os.getpid(),
                           **fields})
        with self._lock:
            if self._file is None:
                return
            self._file.write(line + "\n")
            self._file.flush()
            self._bytes += len(line) + 1
            if self.max_bytes and self._bytes > self.max_bytes:
                self._rotate_locked()

    def _rotate_locked(self) -> None:
        """Roll the current file to ``<path>.1`` (replacing any prior
        generation) and start fresh.  Best-effort: a failed rename
        keeps appending to the oversized file rather than losing
        events."""
        assert self._file is not None
        self._file.close()
        try:
            os.replace(self.path, self.path + ".1")
            self._bytes = 0
        except OSError:
            pass
        self._file = open(self.path, "a")

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None

    @staticmethod
    def read(path: str) -> list[dict]:
        """Parse a JSONL event log (test/replay helper), including the
        rotated ``<path>.1`` generation when present (oldest first).
        A torn line (writer died mid-write, or mid-rotation) is
        dropped, not fatal."""
        out = []
        for p in (path + ".1", path):
            if p.endswith(".1") and not os.path.exists(p):
                continue
            with open(p) as f:
                for ln in f:
                    ln = ln.strip()
                    if not ln:
                        continue
                    try:
                        out.append(json.loads(ln))
                    except json.JSONDecodeError:
                        continue
        return out


_ENV_LOG_LOCK = threading.Lock()
_ENV_LOGS: dict[str, EventLog] = {}


def env_event_log() -> EventLog | None:
    """The shared ``HVD_TPU_EVENT_LOG`` sink, or None when unset.  One
    :class:`EventLog` per path for the process lifetime, shared by every
    registry resolving ``event_log="auto"`` — so concurrent emitters
    serialize on one lock instead of interleaving file appends."""
    path = os.environ.get("HVD_TPU_EVENT_LOG")
    if not path:
        return None
    with _ENV_LOG_LOCK:
        log = _ENV_LOGS.get(path)
        if log is None:
            log = _ENV_LOGS[path] = EventLog(path)
        return log


# ---------------------------------------------------------------------------
# The registry.
# ---------------------------------------------------------------------------


def _prom_name(name: str) -> str:
    """Prometheus metric names allow [a-zA-Z0-9_:] — dots become
    underscores (``serve.ttft_s`` → ``serve_ttft_s``)."""
    return "".join(c if (c.isalnum() or c in "_:") else "_" for c in name)


def escape_label_value(v: str) -> str:
    """Escape a label VALUE per the Prometheus 0.0.4 exposition spec:
    backslash, double-quote, and line-feed must be escaped inside the
    ``name="value"`` quotes."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    """Escape a ``# HELP`` docstring per the 0.0.4 spec: backslash and
    line-feed only (quotes are legal in help text)."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


class MetricsRegistry:
    """Thread-safe, process-local home for counters/gauges/histograms.

    Instruments are get-or-create by name (a name is permanently one
    type; reusing it as another raises).  ``event_log`` controls the
    structured-event sink: the default ``"auto"`` resolves
    ``HVD_TPU_EVENT_LOG`` at each emit (so tests can monkeypatch the
    env mid-process), ``None`` disables events, and an explicit
    :class:`EventLog` pins one.
    """

    _GUARDED_BY_LOCK = ("_counters", "_gauges", "_histograms",
                        "_prom_cache", "_prom_gen")

    def __init__(self, event_log: "EventLog | None | str" = "auto"):
        # ONE lock and ONE generation cell shared by every instrument
        # this registry creates: snapshot()/to_prometheus() take a
        # single lock pass over a frozen registry instead of one
        # acquisition per metric, and any instrument write bumps the
        # shared generation, invalidating the cached Prometheus text.
        self._lock = threading.Lock()
        self._gen = _Gen()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}
        self._prom_cache: str | None = None
        self._prom_gen = -1
        self._event_log = event_log

    def _get(self, table: dict, name: str, factory) -> Any:
        with self._lock:
            inst = None
            for t in (self._counters, self._gauges, self._histograms):
                if name in t:
                    inst = t[name]
                    break
            if inst is None:
                inst = table[name] = factory()
            elif table.get(name) is not inst:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}")
            return inst

    def counter(self, name: str) -> Counter:
        return self._get(self._counters, name,
                         lambda: Counter(name, self._lock, self._gen))

    def gauge(self, name: str) -> Gauge:
        return self._get(self._gauges, name,
                         lambda: Gauge(name, self._lock, self._gen))

    def histogram(self, name: str,
                  bounds: tuple[float, ...] | None = None) -> Histogram:
        return self._get(
            self._histograms, name,
            lambda: Histogram(name, self._lock, bounds, self._gen))

    # -- events ------------------------------------------------------------

    def active_event_log(self) -> "EventLog | None":
        """The sink ``event()`` would write to right now (resolving the
        ``"auto"`` env indirection), or None."""
        log = self._event_log
        if log == "auto":
            log = env_event_log()
        return log

    def event(self, kind: str, **fields: Any) -> None:
        """Emit one structured event to the configured sink (no-op when
        no sink is configured)."""
        log = self.active_event_log()
        if log is not None:
            log.emit(kind, **fields)

    # -- export ------------------------------------------------------------

    def snapshot(self) -> dict:
        """Plain nested dict of every instrument — JSON-serializable,
        schema-stable (``counters`` / ``gauges`` / ``histograms`` with
        count/sum/min/max/p50/p90/p99 each).  One lock pass: instruments
        share the registry lock, so holding it freezes the whole
        registry and the fields are read directly."""
        with self._lock:
            return {
                "counters": {n: c._value
                             for n, c in sorted(self._counters.items())},
                "gauges": {n: g._value
                           for n, g in sorted(self._gauges.items())},
                "histograms": {n: h._snapshot_locked()
                               for n, h in sorted(self._histograms.items())},
            }

    def to_prometheus(self) -> str:
        """Prometheus text exposition format, version 0.0.4: ``# HELP``
        (from :data:`METRIC_HELP`) and ``# TYPE`` lines plus samples;
        histograms render cumulative ``_bucket`` series with ``le``
        labels, ``_sum`` and ``_count``.  Label values are escaped per
        the spec via :func:`escape_label_value`.

        The rendered text is cached keyed on the registry's mutation
        generation: consecutive scrapes of an unchanged registry return
        the same string with zero render work (the monitor-overhead
        fix).  The shared lock makes the pairing exact — no instrument
        can move while the render reads it."""
        with self._lock:
            if (self._prom_cache is not None
                    and self._prom_gen == self._gen.n):
                return self._prom_cache
            lines: list[str] = []

            def _head(name: str, pn: str, kind: str) -> None:
                help_text = METRIC_HELP.get(name)
                if help_text:
                    lines.append(f"# HELP {pn} {_escape_help(help_text)}")
                lines.append(f"# TYPE {pn} {kind}")

            for name, c in sorted(self._counters.items()):
                pn = _prom_name(name)
                _head(name, pn, "counter")
                lines.append(f"{pn} {c._value}")
            for name, g in sorted(self._gauges.items()):
                pn = _prom_name(name)
                _head(name, pn, "gauge")
                lines.append(f"{pn} {g._value:g}")
            for name, h in sorted(self._histograms.items()):
                pn = _prom_name(name)
                _head(name, pn, "histogram")
                cum = 0
                ex = h._exemplars or {}
                for i, (edge, c) in enumerate(zip(h.bounds, h._counts)):
                    cum += c
                    le = escape_label_value(f"{edge:g}")
                    line = f'{pn}_bucket{{le="{le}"}} {cum}'
                    if i in ex:
                        tid, v = ex[i]
                        line += (f' # {{trace_id="'
                                 f'{escape_label_value(tid)}"}} {v:g}')
                    lines.append(line)
                line = f'{pn}_bucket{{le="+Inf"}} {h._count}'
                if len(h.bounds) in ex:
                    tid, v = ex[len(h.bounds)]
                    line += (f' # {{trace_id="'
                             f'{escape_label_value(tid)}"}} {v:g}')
                lines.append(line)
                lines.append(f"{pn}_sum {h._sum:g}")
                lines.append(f"{pn}_count {h._count}")
            text = "\n".join(lines) + "\n"
            self._prom_cache = text
            self._prom_gen = self._gen.n
            return text

    def approx_footprint_bytes(self) -> int:
        """Approximate host memory the registry itself holds (the
        ``mem.registry_bytes`` gauge): instruments, their name strings,
        and histogram bucket arrays — shallow ``sys.getsizeof`` sums, an
        accounting estimate rather than a deep audit."""
        with self._lock:
            total = (sys.getsizeof(self._counters)
                     + sys.getsizeof(self._gauges)
                     + sys.getsizeof(self._histograms))
            for c in self._counters.values():
                total += sys.getsizeof(c) + sys.getsizeof(c.name)
            for g in self._gauges.values():
                total += sys.getsizeof(g) + sys.getsizeof(g.name)
            for h in self._histograms.values():
                total += (sys.getsizeof(h) + sys.getsizeof(h.name)
                          + sys.getsizeof(h.bounds)
                          + sys.getsizeof(h._counts)
                          + 28 * len(h._counts))   # the int cells
            if self._prom_cache is not None:
                total += sys.getsizeof(self._prom_cache)
            return total


class _NullCounter(Counter):
    __slots__ = ()

    def inc(self, n: int = 1) -> None:
        pass


class _NullGauge(Gauge):
    __slots__ = ()

    def set(self, v: float) -> None:
        pass


class _NullHistogram(Histogram):
    __slots__ = ()

    def observe(self, v: float, exemplar: str | None = None) -> None:
        pass


class NullRegistry(MetricsRegistry):
    """A registry whose instruments discard everything — attach it to
    measure the cost of instrumentation itself (the bench's metrics-off
    arm), or to silence a hot path without if-guards at every site."""

    def __init__(self):
        super().__init__(event_log=None)

    def counter(self, name: str) -> Counter:
        return self._get(self._counters, name,
                         lambda: _NullCounter(name, self._lock, self._gen))

    def gauge(self, name: str) -> Gauge:
        return self._get(self._gauges, name,
                         lambda: _NullGauge(name, self._lock, self._gen))

    def histogram(self, name: str,
                  bounds: tuple[float, ...] | None = None) -> Histogram:
        return self._get(
            self._histograms, name,
            lambda: _NullHistogram(name, self._lock, bounds, self._gen))

    def event(self, kind: str, **fields: Any) -> None:
        pass


#: The shared process-local registry: the eager collectives engine,
#: ``basics`` negotiation, and default-constructed ServeEngines all feed
#: this one, so a single scrape sees training and serving together.
DEFAULT = MetricsRegistry()

#: The no-op twin (overhead measurement / explicit opt-out).
NULL = NullRegistry()


# ---------------------------------------------------------------------------
# Per-request tracing.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Trace:
    """One request's span through the serving stack, surfaced on
    ``RequestResult.trace``.  Timestamps are ``time.monotonic`` seconds
    (comparable within the process; durations exact); ``*_step`` fields
    are engine step indices.  ``None`` timestamp = the request never
    reached that state (e.g. ``admit_ts`` stays None on a queue-side
    REJECTED/TIMEOUT result)."""

    rid: int
    enqueue_ts: float
    enqueue_step: int
    admit_ts: float | None = None
    admit_step: int | None = None
    first_token_ts: float | None = None
    terminal_ts: float | None = None
    terminal_step: int | None = None
    status: str | None = None
    n_tokens: int = 0
    prefill_chunks: int = 0
    preemptions: int = 0
    retries: int = 0
    prefix_tokens_skipped: int = 0
    queue_steps: int = 0
    # Causal-tracing identity (None on unsampled requests): the trace
    # this request belongs to, its own serve.request span, and the
    # propagated parent (a router replica.attempt span, or None on an
    # engine-origin root).  See horovod_tpu.tracing.
    trace_id: str | None = None
    span_id: str | None = None
    parent_span_id: str | None = None

    @property
    def queue_wait_s(self) -> float | None:
        """Enqueue → first admission (None while queued)."""
        if self.admit_ts is None:
            return None
        return self.admit_ts - self.enqueue_ts

    @property
    def ttft_s(self) -> float | None:
        """Enqueue → first emitted token (None if none was emitted)."""
        if self.first_token_ts is None:
            return None
        return self.first_token_ts - self.enqueue_ts

    @property
    def e2e_s(self) -> float | None:
        """Enqueue → terminal state (None while live)."""
        if self.terminal_ts is None:
            return None
        return self.terminal_ts - self.enqueue_ts

    @property
    def tpot_s(self) -> float | None:
        """Time per output token after the first (decode cadence);
        None until the request terminates with >= 2 tokens."""
        if (self.terminal_ts is None or self.first_token_ts is None
                or self.n_tokens < 2):
            return None
        return ((self.terminal_ts - self.first_token_ts)
                / (self.n_tokens - 1))

    def to_dict(self) -> dict:
        """JSON-serializable form: every field plus the derived
        latencies (the shape the event log and dashboards consume)."""
        d = dataclasses.asdict(self)
        d.update(queue_wait_s=self.queue_wait_s, ttft_s=self.ttft_s,
                 e2e_s=self.e2e_s, tpot_s=self.tpot_s)
        return d
