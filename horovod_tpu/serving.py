"""Continuous-batching serving loop for the llama inference stack.

The reference has no serving story (its zoo is ResNet/MNIST-era,
SURVEY.md §2.3); this is capability extension on the TPU-first side,
built from the ragged KV-cache primitives in :mod:`horovod_tpu.models.llama`:

* a fixed pool of **slots** (the compiled batch dimension — shapes never
  change, so the decode step is one cached XLA program for the life of
  the server);
* **admission** of a new request into a free slot mid-stream: a B=1
  ragged ``prefill`` (padded to one static width so every admission hits
  the same compiled program) whose K/V window is spliced into the pool
  cache at the slot row;
* a **decode tick** advancing every slot one token (per-row cache
  positions and masks do the isolation — a freshly admitted short prompt
  and a slot 900 tokens into its answer share the same batched matvecs);
* host-side orchestration only at the boundaries (which slot is free,
  which request is done) — the standard serving-engine split: control
  flow on the host, one compiled program per phase on the device.

Isolation is exact: rows are independent in attention, so each request's
greedy continuation is bit-identical to running it alone (pinned by
``tests/test_serving.py``).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from horovod_tpu.models import llama
from horovod_tpu.models.llama import KVCache


@dataclasses.dataclass
class Request:
    """One generation request: prompt token ids + a new-token budget.

    ``sample_key``: PRNG key for sampled decoding (required when the
    batcher's ``temperature > 0``).  The slot replays exactly the key
    schedule solo ``generate(key=sample_key)`` uses — ``split(key,
    max_new_tokens)[i]`` for the i-th new token — so a sampled request's
    tokens equal its solo run draw for draw.

    ``prefix``: a :class:`PrefixCache` (shared system prompt) this
    request continues from; ``prompt`` is then just the suffix (the user
    turn) and the prefix's K/V are spliced instead of recomputed.  This
    explicit-handle splice is :class:`ContinuousBatcher`-only;
    :class:`~horovod_tpu.serving_scheduler.ServeEngine` instead reuses
    prefixes transparently (``prefix_cache=True``: radix-indexed,
    ref-counted paged blocks — see :mod:`horovod_tpu.prefix_cache`), so
    engine requests always carry the full prompt.

    ``temperature``: per-request override of the pool temperature.  A
    sampling pool serves greedy requests via 0.0; the reverse is not
    possible — a greedy pool compiles no sampling tick, so overrides > 0
    require a sampling pool.  ``None`` inherits the pool setting.

    Lifecycle fields (honored by
    :class:`~horovod_tpu.serving_scheduler.ServeEngine`; the simpler
    :class:`ContinuousBatcher` ignores them):

    ``deadline_s``: wall-clock budget from ``submit()`` — a request
    still queued or in flight when it expires terminates with a
    ``TIMEOUT`` result carrying its tokens-so-far.

    ``max_queue_steps``: admission budget in ENGINE STEPS — a request
    still queued after this many steps (per queue stint; a preempted
    request's replay restarts the count) is load-shed with a
    ``REJECTED`` result.  Step-counted so tests never sleep.

    ``slo_s``: SOFT end-to-end latency target for SLO accounting — a
    request finishing OK but slower than this counts against the
    engine's windowed ``serve.goodput``
    (:class:`~horovod_tpu.monitor.SLOWindow`).  Under the engine's
    ``edf`` scheduler policy (:mod:`horovod_tpu.scheduling`) the
    derived absolute deadline ALSO orders admission and picks
    preemption victims; with the default ``fifo`` policy it never
    changes scheduling or the result: the request still completes and
    returns its tokens.

    ``priority``: scheduling weight for the engine's ``priority``
    policy (higher admits first, lower is preempted first; 0 default).
    Like ``slo_s`` it never affects any request's *output* — scheduler
    policies reorder waiting, not tokens."""

    prompt: list[int]
    max_new_tokens: int
    eos_id: int | None = None
    sample_key: Any = None
    prefix: "PrefixCache | None" = None
    temperature: float | None = None
    deadline_s: float | None = None
    max_queue_steps: int | None = None
    slo_s: float | None = None
    priority: int = 0
    # Causal-trace context (horovod_tpu.tracing.TraceContext) stamped by
    # whoever minted or propagated the trace — the router sets it per
    # delivery attempt so engine spans parent under the right hop; None
    # (the default) means unsampled and costs one attribute test.
    # Excluded from the JSON wire schema's REQUIRED fields: it rides
    # request_to_json/request_from_json as an optional "trace" dict.
    trace_ctx: Any = None


# Terminal request statuses (ServeEngine request lifecycle).
OK = "OK"
TIMEOUT = "TIMEOUT"
CANCELLED = "CANCELLED"
FAILED = "FAILED"
REJECTED = "REJECTED"


class RequestResult(list):
    """Terminal result of one engine request: the emitted tokens plus a
    lifecycle status.

    Subclasses ``list`` so every pre-lifecycle consumer — parity
    asserts, ``len()``, ``np.asarray`` — keeps working on the tokens
    unchanged; the lifecycle layer reads ``status`` (one of ``OK /
    TIMEOUT / CANCELLED / FAILED / REJECTED``) and, for ``FAILED``,
    ``error`` (the exception that condemned the request).  Non-``OK``
    results carry tokens-so-far: everything emitted before the request
    terminated (greedy determinism makes that a prefix of the solo run).

    ``trace`` is the request's :class:`horovod_tpu.metrics.Trace` —
    enqueue/admit/first-token/terminal timestamps plus prefill-chunk /
    preemption / retry / prefix-reuse odometers.  The ServeEngine
    populates it for EVERY terminal state (a rejected request still has
    its enqueue and terminal stamps); simpler producers leave it None.
    """

    def __init__(self, tokens=(), status: str = OK,
                 error: BaseException | None = None,
                 trace: Any = None):
        super().__init__(tokens)
        self.status = status
        self.error = error
        self.trace = trace

    @property
    def tokens(self) -> list[int]:
        return list(self)

    @property
    def ok(self) -> bool:
        return self.status == OK

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        err = f", error={self.error!r}" if self.error is not None else ""
        return (f"RequestResult(status={self.status}, "
                f"tokens={list(self)}{err})")


class PrefixCache:
    """Precomputed K/V of a shared prompt prefix (the system-prompt
    pattern): prefill once, splice into every admission that carries it —
    the prefix's FLOPs are paid once per server, not once per request.

    Storage: [n_layers, 1, P, KVH, Dh] K/V plus the prefix token count.
    """

    def __init__(self, k: jax.Array, v: jax.Array, length: int):
        self.k, self.v, self.length = k, v, int(length)


def precompute_prefix(params: dict, cfg: llama.LlamaConfig,
                      tokens: list[int], *,
                      window: int | None = None) -> PrefixCache:
    """Prefill a shared prefix once → a splice-ready :class:`PrefixCache`.

    ``window``: chunk the prefill (``llama.prefill_chunked``) so a
    multi-thousand-token system prompt doesn't spike O(P²) activation
    memory at server setup — the same bound the batcher's admissions
    use.  The K/V buffer pads to a window multiple; ``length`` stays the
    true token count (the pad tail is masked/overwritten downstream).
    """
    if not tokens:
        raise ValueError("empty prefix")
    p = len(tokens)
    if window is None:
        t = jnp.asarray([tokens], jnp.int32)
        cache = llama.init_cache(cfg, 1, p)
        _, cache = llama.prefill(params, t, cfg, cache)
        return PrefixCache(cache.k, cache.v, p)
    pad = -(-p // window) * window
    t = np.zeros((1, pad), np.int32)
    t[0, :p] = tokens
    cache = llama.init_cache(cfg, 1, pad)
    cache = cache._replace(length=jnp.zeros((1,), jnp.int32))
    _, cache = llama.prefill_chunked(
        params, jnp.asarray(t), cfg, cache, window=window,
        lengths=jnp.asarray([p], jnp.int32))
    return PrefixCache(cache.k, cache.v, p)


# hvdlint: disable=HVD001 -- module-level splice shared by every ContinuousBatcher; one program per padded prompt width by construction, counted indirectly by the batcher's prefill cache sizes
@partial(jax.jit, donate_argnums=(0,))
def _splice(cache: KVCache, k_new: jax.Array, v_new: jax.Array,
            slot: jax.Array, length: jax.Array) -> KVCache:
    """Write a B=1 prefill's K/V window into slot ``slot`` of the pool.

    k_new/v_new: [n_layers, 1, W, KVH, Dh] where W is the padded prompt
    width (a multiple of the admission window; one compiled program per
    distinct W).  Only the first W positions of the slot row are
    written; ``length`` is the row's true prompt length, and positions
    beyond it are unreadable until rewritten (write-before-read).
    """
    k = lax.dynamic_update_slice(cache.k, k_new, (0, slot, 0, 0, 0))
    v = lax.dynamic_update_slice(cache.v, v_new, (0, slot, 0, 0, 0))
    return KVCache(k=k, v=v, length=cache.length.at[slot].set(length))


class ContinuousBatcher:
    """Serve mixed-length requests through a fixed slot pool.

    ``n_slots`` is the compiled batch size; ``max_len`` bounds prompt +
    generation per request; ``admit_width`` is the admission window —
    prompts chunk in at this width (up to the pool depth), so it sets
    the admission activation-memory bound and the compiled-program
    granularity, not a prompt-length limit.

    ``temperature``/``top_k``/``top_p`` are pool-level sampling knobs
    (one compiled tick for every slot).  With ``temperature > 0`` each
    request carries its own ``sample_key`` and every slot draws from its
    own PRNG stream on solo ``generate``'s exact key schedule — sampled
    results stay draw-for-draw equal to running each request alone.
    """

    def __init__(self, params: dict, cfg: llama.LlamaConfig, *,
                 n_slots: int, max_len: int, admit_width: int,
                 temperature: float = 0.0, top_k: int | None = None,
                 top_p: float | None = None):
        if admit_width > max_len:
            raise ValueError(
                f"admit_width {admit_width} > max_len {max_len}: the "
                f"admission window must fit inside the pool cache")
        self.params = params
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_len = max_len
        self.admit_width = admit_width
        self.temperature = float(temperature)
        self.cache = llama.init_cache(cfg, n_slots, max_len)
        # ragged from birth: every row owns its position
        self.cache = self.cache._replace(
            length=jnp.zeros((n_slots,), jnp.int32))
        self.last_logits = jnp.zeros((n_slots, cfg.vocab_size), jnp.float32)
        # host-side slot state
        self._busy = [False] * n_slots
        self._budget = [0] * n_slots
        self._eos = [None] * n_slots
        self._out: list[list[int]] = [[] for _ in range(n_slots)]
        # per-slot key schedules (sampling): slot s's next draw uses
        # _keys[s][len(_out[s])] — exactly solo generate's split schedule.
        # All schedules are canonicalized to typed keys at admit, so the
        # free-slot dummy always stacks with them.
        self._keys: list[Any] = [None] * n_slots
        self._temps = [0.0] * n_slots
        self._dummy_key = jax.random.key(0)
        self._greedy_keys = jnp.stack([self._dummy_key] * n_slots)
        self._zero_temps = jnp.zeros((n_slots,), jnp.float32)

        @jax.jit
        def _prefill_one(params, tokens, length):
            # Chunked at the admission width: prompts up to the pool
            # depth admit through fixed admit_width windows, so
            # activation memory never spikes past O(admit_width·depth)
            # and there are at most max_len/admit_width admission
            # programs (one per window count).  The B=1 cache is sized
            # to the padded prompt (tokens.shape[1]), so the splice
            # moves only the K/V the prefill produced — the slot row's
            # tail keeps the previous occupant's bytes, which the
            # write-before-read invariant makes unreadable.
            cache = llama.init_cache(cfg, 1, tokens.shape[1])
            cache = cache._replace(length=jnp.zeros((1,), jnp.int32))
            logits, cache = llama.prefill_chunked(
                params, tokens, cfg, cache, window=admit_width,
                lengths=length)
            return logits[0], cache.k, cache.v

        @jax.jit
        def _prefill_suffix(params, pk, pv, plen, tokens, length):
            # continue from a spliced prefix: the B=1 cache starts with
            # the prefix K/V at [0, P) and the suffix chunk-prefills
            # from base position P (prefill_chunked's nonzero-base path).
            # The prefix rides along in the admission window — one extra
            # copy of its K/V per admission (suffix attention NEEDS the
            # prefix keys in context, so a prefix-free B=1 cache can't
            # work), still orders of magnitude below recomputing the
            # prefill.  One compiled program per distinct (prefix width,
            # window count) pair — servers hold few distinct prefixes.
            w_total = pk.shape[2] + tokens.shape[1]
            cache = llama.init_cache(cfg, 1, w_total)
            cache = KVCache(
                k=lax.dynamic_update_slice(cache.k, pk, (0, 0, 0, 0, 0)),
                v=lax.dynamic_update_slice(cache.v, pv, (0, 0, 0, 0, 0)),
                length=plen,
            )
            logits, cache = llama.prefill_chunked(
                params, tokens, cfg, cache, window=admit_width,
                lengths=length)
            return logits[0], cache.k, cache.v

        @partial(jax.jit, donate_argnums=(1, 2))
        def _tick(params, cache, last_logits, keys, temps):
            # donation matters here: without it every tick copies the
            # whole pool K/V (decode's cost IS cache traffic)
            if temperature > 0.0:
                # per-row [1, V] sampling with that row's own key and
                # (possibly overridden) temperature — the same math
                # solo generate's sample_logits computes, via the shared
                # filtered_logits, so draws are bit-identical per row;
                # temp <= 0 rows take the greedy branch
                def row(l, k, t):
                    # safe divisor ONLY on the greedy branch (t <= 0);
                    # every positive t divides exactly as solo generate
                    # does, keeping bit-parity at any magnitude
                    sampled = jax.random.categorical(
                        k, llama.filtered_logits(
                            l[None], jnp.where(t > 0.0, t, 1.0),
                            top_k=top_k, top_p=top_p), axis=-1)[0]
                    return jnp.where(t > 0.0, sampled,
                                     jnp.argmax(l, axis=-1))

                tok = jax.vmap(row)(last_logits, keys,
                                    temps).astype(jnp.int32)
            else:
                tok = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
            logits, cache = llama.decode_step(params, tok, cfg, cache)
            return tok, logits, cache

        self._prefill_one = _prefill_one
        self._prefill_suffix = _prefill_suffix
        self._tick = _tick

    def compile_cache_sizes(self) -> dict[str, int]:
        """Compile counts of the batcher's device programs.  The decode
        tick must hold ONE signature for the pool's life; prefill
        programs are one per distinct padded prompt width (a multiple of
        ``admit_width``).  Tests snapshot this dict and assert it stays
        flat across steady-state serving."""
        return {
            "prefill_one": self._prefill_one._cache_size(),
            "prefill_suffix": self._prefill_suffix._cache_size(),
            "tick": self._tick._cache_size(),
        }

    # -- admission ---------------------------------------------------------

    def free_slots(self) -> list[int]:
        return [i for i, b in enumerate(self._busy) if not b]

    def admit(self, req: Request) -> int:
        """Prefill ``req`` into a free slot (chunked at ``admit_width``
        for prompts longer than one window); returns the slot index."""
        L = len(req.prompt)
        if L < 1:
            raise ValueError("empty prompt")
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        eff_temp = (self.temperature if req.temperature is None
                    else float(req.temperature))
        # validated BEFORE any state changes: a rejected admission must
        # not leave the slot busy or spliced
        if eff_temp > 0.0 and self.temperature <= 0.0:
            # the unfixable problem first: no sample_key can make a
            # greedy pool serve a sampled request
            raise ValueError(
                "a greedy pool compiles no sampling tick; construct the "
                "ContinuousBatcher with temperature > 0 to serve sampled "
                "requests (per-request temperature can still be 0)")
        if eff_temp > 0.0 and req.sample_key is None:
            raise ValueError(
                "sampled request (temperature > 0) needs a sample_key")
        P = req.prefix.length if req.prefix is not None else 0
        p_pad = int(req.prefix.k.shape[2]) if req.prefix is not None else 0
        if P + L + req.max_new_tokens > self.max_len:
            raise ValueError(
                f"prefix {P} + prompt {L} + max_new_tokens "
                f"{req.max_new_tokens} exceeds max_len {self.max_len}")
        w = self.admit_width
        n_win = -(-L // w)
        if p_pad + n_win * w > self.max_len:
            raise ValueError(
                f"prefix buffer {p_pad} + prompt {L} padded to "
                f"{n_win * w} admission windows exceeds max_len "
                f"{self.max_len}")
        free = self.free_slots()
        if not free:
            raise RuntimeError("no free slot; call step() until one opens")
        slot = free[0]
        padded = np.zeros((1, n_win * w), np.int32)
        padded[0, :L] = req.prompt
        if req.prefix is not None:
            logits, k_new, v_new = self._prefill_suffix(
                self.params, req.prefix.k, req.prefix.v,
                jnp.asarray([P], jnp.int32), jnp.asarray(padded),
                jnp.asarray([L], jnp.int32))
        else:
            logits, k_new, v_new = self._prefill_one(
                self.params, jnp.asarray(padded),
                jnp.asarray([L], jnp.int32))
        self.cache = _splice(self.cache, k_new, v_new,
                             jnp.asarray(slot, jnp.int32),
                             jnp.asarray(P + L, jnp.int32))
        self.last_logits = self.last_logits.at[slot].set(logits)
        self._busy[slot] = True
        self._budget[slot] = req.max_new_tokens
        self._eos[slot] = req.eos_id
        self._out[slot] = []
        self._temps[slot] = eff_temp
        if eff_temp > 0.0:
            # canonicalize legacy uint32 [2] keys to typed (same key
            # data → same split children → same draws), so per-slot
            # schedules and the free-slot dummy always stack together
            key = req.sample_key
            if not jax.dtypes.issubdtype(
                    getattr(key, "dtype", None), jax.dtypes.prng_key):
                key = jax.random.wrap_key_data(
                    jnp.asarray(key, jnp.uint32))
            # solo generate's schedule: one split per prospective token
            self._keys[slot] = jax.random.split(key, req.max_new_tokens)
        else:
            self._keys[slot] = None
        return slot

    # -- decode ------------------------------------------------------------

    def step(self) -> dict[int, list[int]]:
        """Advance every slot one token; returns {slot: tokens} for
        requests that finished on this tick."""
        if self.temperature > 0.0:
            keys = jnp.stack([
                self._keys[s][len(self._out[s])]
                if (self._busy[s] and self._keys[s] is not None
                    and len(self._out[s]) < len(self._keys[s]))
                else self._dummy_key
                for s in range(self.n_slots)
            ])
            temps = jnp.asarray([
                self._temps[s] if self._busy[s] else 0.0
                for s in range(self.n_slots)
            ], jnp.float32)
        else:
            keys = self._greedy_keys      # constants; _tick ignores
            temps = self._zero_temps      # them on the greedy path
        tok, self.last_logits, self.cache = self._tick(
            self.params, self.cache, self.last_logits, keys, temps)
        done: dict[int, list[int]] = {}
        tok_host = np.asarray(tok)
        for slot in range(self.n_slots):
            if not self._busy[slot]:
                continue
            t = int(tok_host[slot])
            self._out[slot].append(t)
            self._budget[slot] -= 1
            if self._budget[slot] <= 0 or t == self._eos[slot]:
                done[slot] = self._out[slot]
                self._busy[slot] = False
                # Rewind the row to 0.  Free rows still tick with the
                # batch (one compiled program for all slots), so the
                # position resumes advancing and scatters garbage K/V
                # from 0 upward — which is safe because every occupant
                # WRITES positions before attending to them: admission
                # splices [0, L) and each decode step writes pos before
                # reading [0, pos].  The rewind's only job is keeping
                # the write position in bounds on long-idle slots.
                # (Anything that reads cache rows it didn't write —
                # e.g. a future speculative-decode path — must re-splice
                # or re-validate the row first.)
                self.cache = self.cache._replace(
                    length=self.cache.length.at[slot].set(0))
        return done

    # -- convenience -------------------------------------------------------

    def run(self, requests: list[Request]) -> list[list[int]]:
        """Serve ``requests`` to completion (admission order, slots
        recycled as they free up); returns each request's tokens."""
        results: list[list[int] | None] = [None] * len(requests)
        slot_owner: dict[int, int] = {}
        pending = list(enumerate(requests))
        while pending or slot_owner:
            while pending and self.free_slots():
                idx, req = pending.pop(0)
                slot_owner[self.admit(req)] = idx
            for slot, toks in self.step().items():
                results[slot_owner.pop(slot)] = toks
        assert all(r is not None for r in results)
        return results  # type: ignore[return-value]


# ---------------------------------------------------------------------------
# Speculative decoding (draft-and-verify), greedy acceptance.
# ---------------------------------------------------------------------------


import functools


@functools.lru_cache(maxsize=32)
def _spec_programs(cfg: llama.LlamaConfig, draft_cfg: llama.LlamaConfig,
                   draft_k: int):
    """Compiled draft/verify programs, cached per (configs, draft_k) so
    repeated speculative_generate calls reuse one XLA compile (the same
    lifetime pattern as ContinuousBatcher's held closures)."""

    # hvdlint: disable=HVD001 -- held by the lru_cache: one program per config triple
    @jax.jit
    def draft_round(dparams, dcache, first_tok):
        """draft_k proposals from first_tok, in draft_k + 1 decode steps:
        the extra step consumes the LAST proposal so its K/V is in the
        draft cache — when a round accepts all draft_k proposals the
        frontier advances past that position, and a hole there would
        poison every later draft.  The extra step's own token is
        discarded (it was never verified)."""
        def step(carry, _):
            tok, cache = carry
            logits, cache = llama.decode_step(dparams, tok, draft_cfg,
                                              cache)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (nxt, cache), nxt

        (_, dcache), drafts = lax.scan(
            step, (first_tok, dcache), None, length=draft_k + 1)
        return jnp.moveaxis(drafts, 0, 1)[:, :draft_k], dcache

    # hvdlint: disable=HVD001 -- held by the lru_cache: one program per config triple
    @jax.jit
    def verify_round(params_, tcache, chunk):
        logits, tcache = llama.decode_chunk(params_, chunk, cfg, tcache)
        preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, K+1]
        return logits, preds, tcache

    return draft_round, verify_round


def speculative_generate(
    params: dict,
    cfg: llama.LlamaConfig,
    draft_params: dict,
    draft_cfg: llama.LlamaConfig,
    prompt: jax.Array,
    *,
    max_new_tokens: int,
    draft_k: int = 4,
    max_len: int | None = None,
    prompt_lengths: jax.Array | None = None,
    stats: dict | None = None,
    timeline: Any = None,
) -> jax.Array:
    """Greedy speculative decoding: a small draft model proposes
    ``draft_k`` tokens per round, the target verifies the full
    ``(draft_k + 1)``-wide chunk ``[cur, d_1..d_k]`` in ONE
    :func:`~horovod_tpu.models.llama.decode_chunk` pass, and the longest
    matching prefix is accepted — so a round can accept all ``draft_k``
    proposals, with position ``draft_k`` of the verify logits supplying
    the target's own follow-on token (emitted as the next round's
    ``cur``).  No draft decode is ever wasted.

    With greedy acceptance the output is **bit-identical to the target's
    own greedy** ``generate`` — the draft only changes how many target
    passes it takes (1 + accepted per round instead of 1 per token), so
    any draft, however bad, is safe (pinned by ``tests/test_serving.py``).

    Batched with PER-ROW acceptance: rows accept different prefix lengths
    each round, which makes every cache ragged — the [B] ``length``
    vector IS the rewind (stale K/V beyond it is masked and rewritten
    before any read, the same write-before-read invariant the slot pool
    relies on).  Rows that hit their token budget freeze their length
    (clamped to prompt + max_new_tokens - 1) while slower rows continue,
    keeping every cache write in bounds by construction rather than by
    scatter-drop semantics.  Returns [B, max_new_tokens].

    ``stats``: optional dict filled with observability counters —
    ``rounds``, ``accepted_per_round`` (list of [B] int arrays) and
    ``max_length_seen`` (max cache length across rounds).  ``timeline``:
    optional :class:`horovod_tpu.timeline.Timeline` receiving a
    per-round acceptance counter event.
    """
    b, l = prompt.shape
    max_len = max_len or (l + max_new_tokens + draft_k + 1)
    if max_len < l + max_new_tokens + draft_k + 1:
        raise ValueError(
            f"max_len={max_len} < prompt {l} + max_new_tokens "
            f"{max_new_tokens} + draft_k {draft_k} + 1 (verification "
            f"overshoot needs the slack)")

    tcache = llama.init_cache(cfg, b, max_len)
    dcache = llama.init_cache(draft_cfg, b, max_len)
    lengths = (jnp.full((b,), l, jnp.int32) if prompt_lengths is None
               else jnp.asarray(prompt_lengths, jnp.int32))
    tlog, tcache = llama.prefill(params, prompt, cfg, tcache,
                                 lengths=lengths)
    _, dcache = llama.prefill(draft_params, prompt, draft_cfg, dcache,
                              lengths=lengths)

    draft_round, verify_round = _spec_programs(cfg, draft_cfg, draft_k)

    out = np.zeros((b, max_new_tokens), np.int32)
    emitted = np.zeros(b, np.int32)
    rows = np.arange(b)
    # finished rows freeze here: the largest length any row ever needs
    # is its last emitted token's position (prompt + max_new - 1), and
    # clamping to it bounds every later garbage write of the frozen row
    # to <= len_cap + draft_k < max_len — in bounds by arithmetic, not
    # by the scatter dropping out-of-range indices
    len_cap = np.asarray(lengths) + max_new_tokens - 1
    if stats is not None:
        stats["rounds"] = 0
        stats["accepted_per_round"] = []
        stats["max_length_seen"] = int(np.asarray(lengths).max())

    def emit(row, tok):
        if emitted[row] < max_new_tokens:
            out[row, emitted[row]] = tok
            emitted[row] += 1

    while (emitted < max_new_tokens).any():
        cur = jnp.argmax(tlog, axis=-1).astype(jnp.int32)     # [B]
        cur_host = np.asarray(cur)
        for r in rows:
            emit(r, int(cur_host[r]))
        # draft proposes cur's continuations: d_1..d_k
        drafts, dcache = draft_round(draft_params, dcache, cur)
        # target consumes the FULL [cur, d_1..d_k] chunk; preds[:, i] is
        # the target's greedy token after chunk[:, :i+1], so preds[:, k]
        # (the +1 width) is the follow-on token when everything accepts
        chunk = jnp.concatenate([cur[:, None], drafts], axis=1)
        logits, preds, tcache = verify_round(params, tcache, chunk)
        # per-row longest accepted prefix: d_i accepted while == preds_i-1
        d_host = np.asarray(drafts)
        p_host = np.asarray(preds)
        accept = np.zeros(b, np.int32)
        for r in rows:
            a = 0
            while a < draft_k and d_host[r, a] == p_host[r, a]:
                emit(r, int(d_host[r, a]))
                a += 1
            accept[r] = a
        # rewind both caches to the true accepted frontier (clamped for
        # rows that just finished) and pick the logits that follow each
        # row's last accepted token
        new_len = np.minimum(np.asarray(lengths) + 1 + accept, len_cap)
        lengths = jnp.asarray(new_len, jnp.int32)
        tcache = tcache._replace(length=lengths)
        dcache = dcache._replace(length=lengths)
        tlog = logits[jnp.arange(b), jnp.asarray(accept)]      # [B, V]
        if stats is not None:
            stats["rounds"] += 1
            stats["accepted_per_round"].append(accept.copy())
            stats["max_length_seen"] = max(stats["max_length_seen"],
                                           int(new_len.max()))
        if timeline is not None:
            timeline.counter(
                "serving.speculative", "ACCEPT",
                {"accepted": int(accept.sum()), "rows": b})

    return jnp.asarray(out)
