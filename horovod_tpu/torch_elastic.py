"""Elastic training for the torch frontend — ``horovod.torch.elastic``
parity (Horovod 0.20+; the 0.15.1 reference has no elastic at all).

``TorchState`` mirrors Horovod's: it tracks a torch ``model`` and/or
``optimizer`` IN PLACE (restore loads state_dicts back into the live
objects) plus named scalar progress fields, and plugs into the shared
:func:`horovod_tpu.elastic.run` retry loop (reinit → restore → replay on
:class:`~horovod_tpu.basics.HorovodInternalError`).

Durability follows the torch-frontend conventions
(examples/pytorch_imagenet_resnet50.py): rank 0 ``torch.save``s the
state_dicts; a resume loads on root and fans out through
``broadcast_parameters`` / ``broadcast_optimizer_state`` — non-root
disks never need the checkpoint file.  Writes are atomic
(tmp + ``os.replace``), so a gang killed mid-write leaves no torn
``step_N.pt``; the restore walk still skips unreadable files for
belt-and-braces.

Usage::

    import horovod_tpu.torch as hvd

    state = hvd.elastic.TorchState(model=model, optimizer=optimizer,
                                   ckpt_dir="/ckpts/run1", epoch=0)

    @hvd.elastic.run
    def train(state):
        while state.epoch < epochs:
            train_one_epoch(state.model, state.optimizer)
            state.epoch += 1
            state.commit()

    train(state)
"""

from __future__ import annotations

import copy
import os
from typing import Any

from horovod_tpu import elastic as _elastic
from horovod_tpu.basics import HorovodInternalError  # noqa: F401 (re-export)

__all__ = ["TorchState", "run", "HorovodInternalError"]

run = _elastic.run          # the retry loop is frontend-agnostic
BaseState = _elastic.BaseState


def _hvdt():
    # Function-level import: torch.py exposes this module as its
    # ``elastic`` attribute, so a module-level import would be circular.
    import horovod_tpu.torch as hvdt

    return hvdt


class TorchState(_elastic.LiveObjectState):
    """Elastic state over live torch objects + scalar progress fields.
    The commit/restore protocol (scalar guards, atomic rank-0 writes,
    durable walk, outcome agreement) lives in
    :class:`horovod_tpu.elastic.LiveObjectState`; this class supplies
    the torch serializer and the model/optimizer slots."""

    _reserved = ("model", "optimizer")
    _suffix = "pt"

    def __init__(self, model: Any = None, optimizer: Any = None, *,
                 ckpt_dir: str | None = None, **scalars: Any) -> None:
        if model is None and optimizer is None and not scalars:
            raise ValueError("TorchState needs a model, an optimizer, or "
                             "at least one scalar field")
        object.__setattr__(self, "model", model)
        object.__setattr__(self, "optimizer", optimizer)
        self._init_live(ckpt_dir, scalars)

    def _rank0(self) -> bool:
        return _hvdt().rank() == 0

    def _broadcast_obj(self, obj: Any) -> Any:
        return _hvdt().broadcast_object(obj, root_rank=0)

    def _write_file(self, dst: str, snap: dict) -> None:
        import torch

        _elastic.atomic_write(dst, lambda f: torch.save(snap, f))

    def _read_file(self, path: str) -> dict:
        import torch

        return torch.load(path, map_location="cpu", weights_only=False)

    def _snapshot(self) -> dict:
        return {
            "model": (copy.deepcopy(self.model.state_dict())
                      if self.model is not None else None),
            "optimizer": (copy.deepcopy(self.optimizer.state_dict())
                          if self.optimizer is not None else None),
            "scalars": dict(object.__getattribute__(self, "_scalars")),
            "commit_step": self.commit_step,
        }

    def _load_local(self, snap: dict) -> None:
        if self.model is not None and snap.get("model") is not None:
            self.model.load_state_dict(snap["model"])
        if self.optimizer is not None and snap.get("optimizer") is not None:
            self.optimizer.load_state_dict(snap["optimizer"])
        self._adopt_scalars(snap["scalars"])
        object.__setattr__(self, "_commit_step",
                           int(snap.get("commit_step", self.commit_step)))

    def sync(self) -> None:
        """Fan the root's current state out to every rank (the reference
        resume recipe, pytorch_imagenet_resnet50.py:134-142)."""
        hvdt = _hvdt()
        if self.model is not None:
            hvdt.broadcast_parameters(self.model.state_dict(), root_rank=0)
        if self.optimizer is not None:
            hvdt.broadcast_optimizer_state(self.optimizer, root_rank=0)
        agreed = hvdt.broadcast_object(
            {"scalars": dict(object.__getattribute__(self, "_scalars")),
             "commit_step": self.commit_step}, root_rank=0)
        self._adopt_scalars(agreed["scalars"])
        object.__setattr__(self, "_commit_step",
                           int(agreed["commit_step"]))

    # commit()/restore() come from LiveObjectState (one protocol copy).
