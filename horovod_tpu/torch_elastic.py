"""Elastic training for the torch frontend — ``horovod.torch.elastic``
parity (Horovod 0.20+; the 0.15.1 reference has no elastic at all).

``TorchState`` mirrors Horovod's: it tracks a torch ``model`` and/or
``optimizer`` IN PLACE (restore loads state_dicts back into the live
objects) plus named scalar progress fields, and plugs into the shared
:func:`horovod_tpu.elastic.run` retry loop (reinit → restore → replay on
:class:`~horovod_tpu.basics.HorovodInternalError`).

Durability follows the torch-frontend conventions
(examples/pytorch_imagenet_resnet50.py): rank 0 ``torch.save``s the
state_dicts; a resume loads on root and fans out through
``broadcast_parameters`` / ``broadcast_optimizer_state`` — non-root
disks never need the checkpoint file.  Writes are atomic
(tmp + ``os.replace``), so a gang killed mid-write leaves no torn
``step_N.pt``; the restore walk still skips unreadable files for
belt-and-braces.

Usage::

    import horovod_tpu.torch as hvd

    state = hvd.elastic.TorchState(model=model, optimizer=optimizer,
                                   ckpt_dir="/ckpts/run1", epoch=0)

    @hvd.elastic.run
    def train(state):
        while state.epoch < epochs:
            train_one_epoch(state.model, state.optimizer)
            state.epoch += 1
            state.commit()

    train(state)
"""

from __future__ import annotations

import copy
import os
from typing import Any

from horovod_tpu import elastic as _elastic
from horovod_tpu.basics import HorovodInternalError  # noqa: F401 (re-export)

__all__ = ["TorchState", "run", "HorovodInternalError"]

run = _elastic.run          # the retry loop is frontend-agnostic
BaseState = _elastic.BaseState


def _hvdt():
    # Function-level import: torch.py exposes this module as its
    # ``elastic`` attribute, so a module-level import would be circular.
    import horovod_tpu.torch as hvdt

    return hvdt


class TorchState(BaseState):
    """Elastic state over live torch objects + scalar progress fields."""

    def __init__(self, model: Any = None, optimizer: Any = None, *,
                 ckpt_dir: str | None = None, **scalars: Any) -> None:
        if model is None and optimizer is None and not scalars:
            raise ValueError("TorchState needs a model, an optimizer, or "
                             "at least one scalar field")
        for k in scalars:
            if k.startswith("_") or k in ("model", "optimizer"):
                raise ValueError(f"reserved field name: {k!r}")
        object.__setattr__(self, "model", model)
        object.__setattr__(self, "optimizer", optimizer)
        object.__setattr__(self, "_scalars", dict(scalars))
        object.__setattr__(self, "_ckpt_dir",
                           os.path.abspath(ckpt_dir) if ckpt_dir else None)
        object.__setattr__(self, "_mem_commit", None)
        object.__setattr__(self, "_commit_step", 0)

    def __getattr__(self, name: str) -> Any:
        scalars = object.__getattribute__(self, "_scalars")
        if name in scalars:
            return scalars[name]
        raise AttributeError(name)

    def __setattr__(self, name: str, value: Any) -> None:
        if name in ("model", "optimizer") or name.startswith("_"):
            object.__setattr__(self, name, value)
            return
        scalars = object.__getattribute__(self, "_scalars")
        if name in scalars:
            scalars[name] = value
        else:
            raise AttributeError(
                f"unknown state field {name!r}; declare every scalar in "
                f"TorchState(...) so commits stay complete")

    @property
    def commit_step(self) -> int:
        return object.__getattribute__(self, "_commit_step")

    def _snapshot(self) -> dict:
        return {
            "model": (copy.deepcopy(self.model.state_dict())
                      if self.model is not None else None),
            "optimizer": (copy.deepcopy(self.optimizer.state_dict())
                          if self.optimizer is not None else None),
            "scalars": dict(object.__getattribute__(self, "_scalars")),
            "commit_step": self.commit_step,
        }

    def commit(self) -> None:
        """Snapshot in host memory; rank 0 additionally ``torch.save``s
        ``step_N.pt`` atomically (tmp + rename — no torn files)."""
        import torch

        object.__setattr__(self, "_commit_step", self.commit_step + 1)
        snap = self._snapshot()
        object.__setattr__(self, "_mem_commit", snap)
        ckpt_dir = object.__getattribute__(self, "_ckpt_dir")
        if ckpt_dir and _hvdt().rank() == 0:
            os.makedirs(ckpt_dir, exist_ok=True)
            dst = os.path.join(ckpt_dir, f"step_{self.commit_step}.pt")
            _elastic.atomic_write(dst, lambda f: torch.save(snap, f))

    def _load_local(self, snap: dict) -> None:
        if self.model is not None and snap.get("model") is not None:
            self.model.load_state_dict(snap["model"])
        if self.optimizer is not None and snap.get("optimizer") is not None:
            self.optimizer.load_state_dict(snap["optimizer"])
        self._adopt_scalars(snap["scalars"])
        object.__setattr__(self, "_commit_step",
                           int(snap.get("commit_step", self.commit_step)))

    def _adopt_scalars(self, incoming: dict) -> None:
        # Only DECLARED fields are adopted (same contract as the JAX-side
        # State._adopt): a commit from an older code revision must not
        # inject undeclared keys past the __setattr__ completeness guard,
        # nor silently leave a renamed field at its initial value without
        # the reader noticing the mismatch in what restore() returns.
        scalars = object.__getattribute__(self, "_scalars")
        for k in scalars:
            if k in incoming:
                scalars[k] = incoming[k]

    def sync(self) -> None:
        """Fan the root's current state out to every rank (the reference
        resume recipe, pytorch_imagenet_resnet50.py:134-142)."""
        hvdt = _hvdt()
        if self.model is not None:
            hvdt.broadcast_parameters(self.model.state_dict(), root_rank=0)
        if self.optimizer is not None:
            hvdt.broadcast_optimizer_state(self.optimizer, root_rank=0)
        agreed = hvdt.broadcast_object(
            {"scalars": dict(object.__getattribute__(self, "_scalars")),
             "commit_step": self.commit_step}, root_rank=0)
        self._adopt_scalars(agreed["scalars"])
        object.__setattr__(self, "_commit_step",
                           int(agreed["commit_step"]))

    def restore(self) -> None:
        """Adopt the newest commit: durable ``step_N.pt`` (root reads,
        everyone receives via sync) → in-memory snapshot → plain sync of
        the initial values."""
        import torch

        hvdt = _hvdt()
        ckpt_dir = object.__getattribute__(self, "_ckpt_dir")
        if ckpt_dir:
            # The walk, the torn-vs-intact discrimination, and the
            # outcome-agreement protocol live in
            # elastic.restore_newest_commit (shared with KerasState).
            outcome = _elastic.restore_newest_commit(
                ckpt_dir, "pt",
                read_file=lambda p: torch.load(p, map_location="cpu",
                                               weights_only=False),
                load_local=self._load_local,
                is_root=hvdt.rank() == 0,
                broadcast_obj=lambda o: hvdt.broadcast_object(
                    o, root_rank=0),
            )
            if outcome == "ok":
                self.sync()           # root's loaded values fan out
                return
            if outcome is not None:
                raise RuntimeError(
                    f"elastic restore failed on root: {outcome}")
        mem = object.__getattribute__(self, "_mem_commit")
        if mem is not None:
            self._load_local(mem)
        self.sync()
