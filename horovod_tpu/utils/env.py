"""Environment-variable configuration, read once at engine start.

TPU-native re-design of the reference's env knob system
(reference: horovod/common/operations.h:52-59, parsed in
horovod/common/operations.cc:1614-1685).  The same knob names are kept so a
Horovod user can bring their launch scripts across unchanged; TPU-specific
knobs use the ``HOROVOD_TPU_`` prefix.
"""

from __future__ import annotations

import dataclasses
import os

# Knob names kept for parity with the reference.
HOROVOD_TIMELINE = "HOROVOD_TIMELINE"
HOROVOD_FUSION_THRESHOLD = "HOROVOD_FUSION_THRESHOLD"
HOROVOD_CYCLE_TIME = "HOROVOD_CYCLE_TIME"
HOROVOD_STALL_CHECK_DISABLE = "HOROVOD_STALL_CHECK_DISABLE"
HOROVOD_HIERARCHICAL_ALLREDUCE = "HOROVOD_HIERARCHICAL_ALLREDUCE"
HOROVOD_SPARSE_ALLREDUCE = "HOROVOD_SPARSE_ALLREDUCE"
# Autotune knob names shared with later Horovod releases, which grew an
# online tuner for the same two knobs (threshold/cycle); see autotune.py.
HOROVOD_AUTOTUNE = "HOROVOD_AUTOTUNE"
HOROVOD_AUTOTUNE_LOG = "HOROVOD_AUTOTUNE_LOG"
HOROVOD_AUTOTUNE_WARMUP_SAMPLES = "HOROVOD_AUTOTUNE_WARMUP_SAMPLES"
HOROVOD_AUTOTUNE_STEADY_STATE_SAMPLES = "HOROVOD_AUTOTUNE_STEADY_STATE_SAMPLES"
HOROVOD_TPU_SERIALIZE_DISPATCH = "HOROVOD_TPU_SERIALIZE_DISPATCH"

# Defaults mirror reference horovod/common/operations.cc:151 (64 MiB fusion
# buffer), :155 (5 ms cycle) and :273 (60 s stall warning).
DEFAULT_FUSION_THRESHOLD_BYTES = 64 * 1024 * 1024
DEFAULT_CYCLE_TIME_MS = 5.0
DEFAULT_STALL_WARNING_TIME_S = 60.0


def _get_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        return default


def _get_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def _get_bool(name: str, default: bool = False) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in ("", "0", "false", "no", "off")


def _get_tristate(name: str) -> str:
    """on/off/auto knob, accepting the same truthy/falsy spellings as
    ``_get_bool`` (so ``=1`` forces on, like every other knob); an
    unrecognized value warns and falls back to auto instead of silently
    misconfiguring."""
    raw = os.environ.get(name, "auto").strip().lower()
    if raw in ("on", "1", "true", "yes"):
        return "on"
    if raw in ("off", "0", "false", "no"):
        return "off"
    if raw in ("auto", ""):
        return "auto"
    import warnings

    warnings.warn(
        f"{name}={raw!r} not recognized (want on/off/auto); using auto",
        RuntimeWarning,
        stacklevel=2,
    )
    return "auto"


@dataclasses.dataclass
class EngineConfig:
    """Snapshot of all engine knobs, taken once when the engine starts.

    Mirrors the one-shot parse at background-thread startup in the reference
    (horovod/common/operations.cc:1614-1685).
    """

    timeline_file: str | None = None
    fusion_threshold_bytes: int = DEFAULT_FUSION_THRESHOLD_BYTES
    cycle_time_ms: float = DEFAULT_CYCLE_TIME_MS
    stall_check_enabled: bool = True
    stall_warning_time_s: float = DEFAULT_STALL_WARNING_TIME_S
    hierarchical_allreduce: bool = False
    # Inner (ici) extent of the hierarchical dispatch mesh; None → this
    # process's local device count (the reference's local/cross comm split
    # by MPI_COMM_TYPE_SHARED, operations.cc:1558-1590).  Settable for
    # tests via HOROVOD_TPU_HIERARCHY_LOCAL_SIZE.
    hierarchy_local_size: int | None = None
    sparse_allreduce: bool = False
    # Native coordination engine (native/src/): "auto" enables it for
    # multi-controller jobs when libhvdtpu builds; "on" forces it (tests,
    # single-host soak); "off" keeps pure-Python coordination.
    native_controller: str = "auto"
    # Transport spec for the native control plane: "tcp:<host>:<port>"
    # (multi-host; rank 0 binds) or "local:<world>" (in-process).
    controller_transport: str | None = None
    # Online (threshold, cycle-time) tuning — horovod_tpu/autotune.py.
    # These two knobs are the only MUTABLE config fields: the autotuner
    # rewrites them mid-run and the engine re-reads both every tick.
    autotune: bool = False
    autotune_log: str | None = None
    autotune_warmup_samples: int = 3
    autotune_steady_state_samples: int = 10
    # Dispatch serialization: "auto" blocks per launch on the CPU backend
    # only (multi-controller CPU collectives are matched by arrival order
    # — concurrent launches can pair mismatched messages); "off" keeps the
    # TPU-style async pipeline everywhere (safe single-process, where one
    # launch covers all ranks); "on" forces depth-1 even on TPU.
    serialize_dispatch: str = "auto"

    @classmethod
    def from_env(cls) -> "EngineConfig":
        return cls(
            timeline_file=os.environ.get(HOROVOD_TIMELINE) or None,
            fusion_threshold_bytes=_get_int(
                HOROVOD_FUSION_THRESHOLD, DEFAULT_FUSION_THRESHOLD_BYTES
            ),
            cycle_time_ms=_get_float(HOROVOD_CYCLE_TIME, DEFAULT_CYCLE_TIME_MS),
            stall_check_enabled=not _get_bool(HOROVOD_STALL_CHECK_DISABLE),
            stall_warning_time_s=_get_float(
                "HOROVOD_STALL_CHECK_TIME", DEFAULT_STALL_WARNING_TIME_S
            ),
            hierarchical_allreduce=_get_bool(HOROVOD_HIERARCHICAL_ALLREDUCE),
            hierarchy_local_size=(
                _get_int("HOROVOD_TPU_HIERARCHY_LOCAL_SIZE", 0) or None
            ),
            sparse_allreduce=_get_bool(HOROVOD_SPARSE_ALLREDUCE),
            native_controller=os.environ.get(
                "HOROVOD_TPU_NATIVE_CONTROLLER", "auto"
            ).strip().lower(),
            controller_transport=os.environ.get(
                "HOROVOD_TPU_CONTROLLER_TRANSPORT"
            ) or None,
            autotune=_get_bool(HOROVOD_AUTOTUNE),
            autotune_log=os.environ.get(HOROVOD_AUTOTUNE_LOG) or None,
            autotune_warmup_samples=_get_int(HOROVOD_AUTOTUNE_WARMUP_SAMPLES, 3),
            serialize_dispatch=_get_tristate(HOROVOD_TPU_SERIALIZE_DISPATCH),
            autotune_steady_state_samples=_get_int(
                HOROVOD_AUTOTUNE_STEADY_STATE_SAMPLES, 10
            ),
        )


def enable_persistent_compile_cache(
    default_dir: str | None = None, platform: str | None = None,
    allow_cpu_aot: bool = False,
) -> None:
    """Point jax's persistent compilation cache at ``HVD_TPU_BENCH_CACHE``
    (or ``default_dir``) so compile work survives across processes — the
    bench orchestrator's workers, rehearsals, the driver's entry-point
    checks, and the perf-sweep tools all share one cache (entries are
    keyed by computation + backend, so CPU and TPU entries coexist).

    Must run before the first compilation; safe to call repeatedly.  A jax
    without the knob (or a read-only path) degrades to per-process
    compiles with a one-line ``RuntimeWarning`` breadcrumb — callers never
    depend on the cache for correctness.

    ``platform`` is the backend this process is pinned to, when the
    caller knows it; ``None`` reads the pin from
    ``jax.config.jax_platforms`` (set by the test conftest, the dryrun's
    CPU-mesh forcing, and the bench CPU worker).  **A CPU pin refuses the
    cache** — and actively clears any cache dir enabled earlier in the
    process: XLA:CPU serialized executables are AOT blobs whose
    compile-feature list includes XLA-injected pseudo-features
    (``+prefer-no-gather``/``+prefer-no-scatter``) that the loader's host
    feature check can NEVER match, so every reload — even same-host,
    same-process — logs "could lead to execution errors such as SIGILL",
    and a cross-host load can actually SIGILL (observed as the
    MULTICHIP_r04 error wall).  TPU executables have no such loader, so
    the cache stays on where it pays (window compile reuse).

    ``allow_cpu_aot=True`` overrides the refusal for callers that accept
    the same-host loader noise in exchange for warm compiles (the bench
    CPU-fallback worker, whose time reserve depends on them; cross-host
    loads stay guarded by the host-fingerprint subdir).  Residual gap,
    accepted: a process with NO platform pin that happens to resolve to
    the CPU backend (e.g. a manual sweep smoke on a TPU-less host) still
    enables the cache — refusing on an unknown platform would disable
    the cache for every TPU claim (the ambient env is unpinned exactly
    there), and probing the backend here could hang on a down tunnel.
    """
    try:
        import jax

        if platform is None:
            try:
                raw = jax.config.jax_platforms or ""
                platform = raw.split(",")[0].strip() or None
            except Exception:
                platform = None
        if platform == "cpu" and not allow_cpu_aot:
            # The refusal does not depend on a cache path being
            # configured: clear any dir enabled earlier in the process
            # (the entry()-then-dryrun single-process flow).
            try:
                jax.config.update("jax_compilation_cache_dir", None)
            except Exception:
                pass
            return
    except Exception:
        pass
    path = os.environ.get("HVD_TPU_BENCH_CACHE") or default_dir
    if not path:
        return
    try:
        import hashlib
        import platform as platform_mod

        import jax

        # Sub-directory keyed by a host fingerprint: XLA:CPU AOT blobs
        # bake in the compile machine's features, and loading them on a
        # different host can SIGILL (the loader warns exactly this).  The
        # persistent dir can outlive the machine (it sits in the repo), so
        # never let one host's blobs reach another's loader.
        try:
            from pathlib import Path

            cpu = Path("/proc/cpuinfo").read_text()
            # x86 lists "flags", aarch64 lists "Features"; hash whichever
            # is present (an empty fallback would give every host of an
            # architecture the same key and defeat the guard).
            flags = next(
                (ln for ln in cpu.splitlines()
                 if ln.startswith(("flags", "Features"))),
                platform_mod.processor() or cpu[:512],
            )
        except OSError:
            flags = platform_mod.processor() or platform_mod.platform()
        # jaxlib in the key too: XLA injects target features beyond
        # cpuinfo's (+prefer-no-scatter/gather and friends) that change
        # across jaxlib builds — an AOT blob from another jaxlib on the
        # SAME host trips the loader's feature check ("could lead to
        # SIGILL") even though the cpuinfo fingerprint matches.
        import jaxlib

        jl = getattr(jaxlib, "__version__", "?")
        host_key = hashlib.sha1(
            (platform_mod.machine() + ":" + jl + ":" + flags).encode()
        ).hexdigest()[:10]
        jax.config.update(
            "jax_compilation_cache_dir", os.path.join(path, host_key))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # pragma: no cover - depends on jax version
        # Read-only paths degrade silently by design, but a renamed jax
        # config knob would ALSO land here and quietly disable the shared
        # cache — leave one breadcrumb instead of nothing.
        import warnings

        warnings.warn(
            f"persistent compile cache disabled ({type(e).__name__}: {e})",
            RuntimeWarning,
            stacklevel=2,
        )
