"""Version shims for the supported jax range.

``shard_map`` graduated from ``jax.experimental.shard_map`` (keyword
``check_rep``) to top-level ``jax.shard_map`` (keyword ``check_vma``)
around jax 0.6; the library runs on both sides of that move.  Call
:func:`shard_map` here with the NEW spelling — on an older jax the
``check_vma`` keyword is translated to ``check_rep``.
"""

from __future__ import annotations

try:
    from jax import shard_map as _shard_map

    _CHECK_KW = "check_vma"
except ImportError:  # jax < 0.6: experimental home, older keyword
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f=None, **kwargs):
    """`jax.shard_map` with the installed version's check keyword."""
    if _CHECK_KW == "check_rep" and "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    if f is None:
        return lambda g: _shard_map(g, **kwargs)
    return _shard_map(f, **kwargs)
