"""horovod_tpu — a TPU-native distributed training framework.

A ground-up re-design of Horovod 0.15.1 (the shyhuai fork, with sparse/top-k
allreduce) for TPU: the data plane is XLA collectives over the ICI/DCN mesh
(``psum`` / ``all_gather`` / collective-permute emitted from ``shard_map`` /
``pjit``), the eager frontend is an async-handle engine with Horovod's
fusion/cycle/stall-check/timeline semantics, and the optimizer wrappers are
optax/flax-native (plus a torch frontend for API parity).

Two ways to use it, mirroring the reference's two frontends:

* **Compiled SPMD** (the TF-graph analogue, and the fast path): call
  ``horovod_tpu.ops.allreduce(...)`` — or just use ``DistributedOptimizer``
  — inside your jitted step function over the ``"hvd"`` mesh axis.
* **Eager** (the PyTorch analogue): ``hvd.allreduce / allgather / broadcast``
  on rank-major arrays, with ``*_async`` + ``poll`` / ``synchronize``
  handles, background fusion cycles, and the Chrome-trace timeline.

Quick start (the reference's canonical recipe, examples/pytorch_mnist.py)::

    import horovod_tpu as hvd
    hvd.init()
    tx = hvd.DistributedOptimizer(optax.adam(1e-3 * hvd.size()))
    params = hvd.broadcast_parameters(params, root_rank=0)
    step = hvd.make_train_step(loss_fn, tx)   # compiled SPMD over the mesh
    params, opt_state, loss = step(params, opt_state, batch)  # batch rank-major
"""

from horovod_tpu.basics import (  # noqa: F401
    AXIS_NAME,
    CPU_DEVICE_ID,
    NotInitializedError,
    axis_rank,
    cross_rank,
    cross_size,
    from_per_rank,
    init,
    is_initialized,
    local_rank,
    local_size,
    mesh,
    mpi_threads_supported,
    per_rank,
    rank,
    rank_sharding,
    replicated_sharding,
    shutdown,
    size,
)
from horovod_tpu.ops.collective_ops import (  # noqa: F401
    Adasum,
    Average,
    Max,
    Min,
    ProcessSet,
    Product,
    Sum,
)
from horovod_tpu.ops.compression import Compression  # noqa: F401
from horovod_tpu.ops.powersgd import (  # noqa: F401
    ErrorFeedback,
    PowerSGDCompressor,
)
from horovod_tpu.ops.eager import (  # noqa: F401
    allgather,
    allgather_async,
    allreduce,
    allreduce_async,
    alltoall,
    alltoall_async,
    barrier,
    broadcast,
    broadcast_async,
    engine_stats,
    grouped_allreduce_eager,
    join,
    poll,
    reducescatter,
    reducescatter_async,
    sparse_allreduce,
    sparse_allreduce_async,
    synchronize,
)
from horovod_tpu.optim.distributed_optimizer import (  # noqa: F401
    DistributedOptimizer,
    TrainStepResult,
    allgather_object,
    allreduce_gradients,
    broadcast_object,
    broadcast_optimizer_state,
    broadcast_parameters,
    make_train_step,
)
from horovod_tpu.callbacks import (  # noqa: F401
    BroadcastGlobalVariablesCallback,
    Callback,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    MetricAverageCallback,
    ModelCheckpointCallback,
    average_metrics,
    multiplier_schedule,
    warmup_schedule,
)
from horovod_tpu.checkpoint import (  # noqa: F401
    latest_checkpoint,
    list_checkpoints,
    load_model,
    restore_checkpoint,
    save_checkpoint,
    wait_for_checkpoints,
)
from horovod_tpu.optim.eager_optimizer import EagerDistributedOptimizer  # noqa: F401
from horovod_tpu.optim.zero import ZeroStepResult, make_zero_train_step  # noqa: F401
from horovod_tpu.optim.fsdp import (  # noqa: F401
    FsdpStepResult,
    fsdp_partition_specs,
    make_fsdp_train_step,
    shard_params,
)
from horovod_tpu.training import fit, make_eval_step  # noqa: F401
from horovod_tpu.data import (  # noqa: F401
    ShardedLoader,
    prefetch_to_device,
    shard_indices,
)
from horovod_tpu.timeline import start_timeline, stop_timeline  # noqa: F401
from horovod_tpu import ops  # noqa: F401
from horovod_tpu import elastic  # noqa: F401  (hvd.elastic.State / .run)
from horovod_tpu import metrics  # noqa: F401  (hvd.metrics.DEFAULT / .snapshot)
from horovod_tpu import monitor  # noqa: F401  (hvd.monitor.MonitorServer / aggregate_snapshots)
from horovod_tpu.basics import HorovodInternalError  # noqa: F401

__version__ = "0.1.0"
