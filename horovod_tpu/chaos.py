"""Deterministic chaos campaigns for the serving fleet.

The :mod:`~horovod_tpu.faults` registry made single faults
reproducible; this module makes *storms* reproducible.  A
:class:`ChaosSchedule` is a pure function of its seed — a set of
step-counted fault rules over the registry's named sites plus
replica-kill events — so a failing campaign is a one-integer bug
report: same seed, same workload → same faults, same recovery, same
bits.  No wall clock enters the schedule (kills and faults fire on hit
*counts*, the registry's own determinism contract); wall clock only
bounds the overall campaign.

:func:`run_campaign` drives one seeded storm against a live
router+supervisor fleet serving a canned workload, then checks the
**invariant oracles** that define "self-healing" for this codebase:

* ``bit_identical`` — every chaos-run request that terminated ``OK``
  produced exactly the fault-free reference tokens (greedy determinism
  must survive retry, failover, respawn, and journal replay).
* ``no_leaked_tickets`` — the router's ticket table is empty once
  every result is read and reaped: a storm must not strand bookkeeping.
* ``no_leaked_blocks`` — every surviving engine passes
  ``prefix.check_consistency()`` and every KV block is free or cached
  (reference counts drained to zero).
* ``metrics_monotonic`` — counters sampled across the campaign never
  decrease (a storm must not corrupt the observability plane).
* ``faults_logged`` — every fault the registry fired appears as a
  ``"fault"`` event in the structured event log: if chaos is
  invisible, postmortems are fiction.
* ``healed`` — after the storm, every replica a kill took down is
  routable again (the supervisor respawned it within its budget).
* ``alerts_covered`` (``alert_oracle=True`` campaigns) — the health
  plane saw the storm: every immediate alert rule whose condition ever
  held fired, every fired alert resolved after heal, and kills tripped
  ``replica_death``.  Alerting that misses a storm it watched is a
  broken pager.

:func:`run_autoscale_campaign` is the elastic-fleet variant: a
deterministic traffic step with scripted
:class:`~horovod_tpu.autoscaler.FleetAutoscaler` actuations
interleaved — a faulted grow that must degrade to ``hold``, a real
grow whose replica must serve routed traffic, and a scale-down that
lands while a keyed wave is in flight, so the cordoned victim fails
open into journal/failover replay.  Its oracles add ``zero_dropped``
(every routed request terminates ``OK``), ``exactly_once``
(resubmitting every idempotency key after the epoch bump answers from
the journal without touching a replica), ``grew_and_served``, and
``drained_and_retired`` to the storm invariants above.

:func:`soak` repeats campaigns with consecutive seeds until a
wall-clock budget runs out (the long-haul mode); :func:`compare_campaigns`
is the JSON regression gate (the ``profile_report.py --compare``
contract: exit nonzero when recovery got worse).  The CLI lives in
``tools/chaos_run.py``; the bench arm
(:func:`measure_chaos_goodput`) reports goodput retention under a
canned storm versus the fault-free fleet.
"""

from __future__ import annotations

import dataclasses
import os
import random
import tempfile
import time
from typing import Any, Sequence

from horovod_tpu import faults as faults_mod
from horovod_tpu import metrics as metrics_mod
from horovod_tpu.router import RouterServer
from horovod_tpu.serving import OK, Request
from horovod_tpu.supervisor import ReplicaSupervisor

#: Engine-internal sites a storm may hit freely: each is covered by a
#: recovery path (bounded retry, admission quarantine, cache
#: quarantine), so a firing rule must never corrupt *other* requests.
STORM_SITES = ("serve.prefill", "serve.tick", "serve.admit",
               "serve.cache")

#: The replica-kill site (the LocalReplica pump; key = replica name).
KILL_SITE = "serve.router"


@dataclasses.dataclass(frozen=True)
class ChaosRule:
    """One scheduled fault, in registry terms (see
    :meth:`~horovod_tpu.faults.FaultRegistry.inject`)."""

    site: str
    on_hit: int
    count: int = 1
    key: Any = None

    def arm(self, fr: faults_mod.FaultRegistry) -> faults_mod.FaultRule:
        return fr.inject(self.site, on_hit=self.on_hit,
                         count=self.count, key=self.key)


class ChaosSchedule:
    """A seed-deterministic storm: engine-site fault rules plus
    replica kills.  ``generate`` guarantees site *coverage* — the
    first ``len(sites)`` rules cycle every storm site once, so any
    ``n_faults >= len(sites)`` exercises at least that many distinct
    sites — then spreads the rest randomly.  Kills are transient
    single-shot rules on the pump site keyed by replica name: the pump
    dies once at the scheduled hit, and the respawned replica's pump
    advances the same counter past the window instead of re-dying
    forever.  Kill hit windows are kept early (``kill_max_hit``): the
    pump's site-hit count tracks engine steps, which drift slightly
    with inbox batching, so a late window might never be reached —
    an early one always is."""

    def __init__(self, seed: int, rules: Sequence[ChaosRule],
                 kills: Sequence[ChaosRule]):
        self.seed = seed
        self.rules = tuple(rules)
        self.kills = tuple(kills)

    @staticmethod
    def generate(seed: int, *,
                 replica_names: Sequence[str],
                 sites: Sequence[str] = STORM_SITES,
                 n_faults: int = 6,
                 n_kills: int = 1,
                 max_hit: int = 12,
                 kill_min_hit: int = 2,
                 kill_max_hit: int = 8) -> "ChaosSchedule":
        rng = random.Random(seed)
        rules = []
        for i in range(n_faults):
            site = (sites[i % len(sites)] if i < len(sites)
                    else rng.choice(sites))
            rules.append(ChaosRule(site=site,
                                   on_hit=rng.randint(1, max_hit),
                                   count=rng.randint(1, 2)))
        kills = [ChaosRule(site=KILL_SITE,
                           on_hit=rng.randint(kill_min_hit,
                                              kill_max_hit),
                           key=rng.choice(list(replica_names)))
                 for _ in range(n_kills)]
        return ChaosSchedule(seed, rules, kills)

    def arm(self, fr: faults_mod.FaultRegistry) -> None:
        for rule in self.rules + self.kills:
            rule.arm(fr)

    def sites(self) -> list[str]:
        return sorted({r.site for r in self.rules}
                      | {k.site for k in self.kills})

    def to_json(self) -> dict:
        return {"seed": self.seed,
                "rules": [dataclasses.asdict(r) for r in self.rules],
                "kills": [dataclasses.asdict(k) for k in self.kills]}


def _workload(n_groups: int, waves: int, *, prefix_len: int = 16,
              suffix_len: int = 4, max_new_tokens: int = 6,
              ) -> list[Request]:
    """The router bench's prompt-family shape, chaos-sized: shared
    per-group prefixes keep the shadow index (and therefore warm
    respawn) meaningful."""
    out = []
    for w in range(waves):
        for g in range(n_groups):
            prefix = [(7 + 11 * g + i) % 89 + 2 for i in range(prefix_len)]
            suffix = [(31 + 5 * g + 3 * w + i) % 89 + 2
                      for i in range(suffix_len)]
            out.append(Request(prompt=prefix + suffix,
                               max_new_tokens=max_new_tokens))
    return out


def _counters_regressed(samples: Sequence[dict]) -> list[str]:
    """Counter names that ever decreased across ordered snapshots."""
    bad = []
    for prev, cur in zip(samples, samples[1:]):
        for name, v in prev.items():
            if cur.get(name, v) < v and name not in bad:
                bad.append(name)
    return bad


def run_campaign(params: dict, cfg: Any, *, seed: int = 0,
                 n_replicas: int = 3, n_groups: int = 4,
                 waves: int = 4, n_faults: int = 6, n_kills: int = 1,
                 n_slots: int = 2, max_len: int = 64, chunk: int = 8,
                 backoff_s: float = 0.01, max_restarts: int = 5,
                 event_log: str | None = None,
                 timeout_s: float = 300.0,
                 extra_rules: Sequence[ChaosRule] = (),
                 slo_window: int = 8,
                 sample_s: float = 0.005,
                 alert_time_scale: float = 0.01,
                 recovery_waves: int = 0,
                 alert_oracle: bool = False,
                 alert_drain_s: float = 10.0) -> dict:
    """One seeded chaos campaign; returns the oracle report (see the
    module docstring for the oracles).  ``report["ok"]`` is the AND of
    every oracle — the smoke test and the soak loop key off it.

    The campaign carries the health plane: a
    :class:`~horovod_tpu.timeseries.MetricsSampler` (``sample_s``) and
    an :class:`~horovod_tpu.alerts.AlertManager` whose production rule
    windows are compressed by ``alert_time_scale`` ride the router
    poller, so every report includes an ``alerts`` section and the
    event log carries the ``alert.*`` transitions.  With
    ``alert_oracle=True`` the campaign additionally serves
    ``recovery_waves`` clean waves after heal (their prompts repeat the
    storm workload, so the fault-free reference covers them), drains
    until no rule is firing (bounded by ``alert_drain_s``), and adds
    the ``alerts_covered`` oracle: every zero-``pending_s`` rule whose
    condition ever held must have FIRED, every fired rule must have
    RESOLVED, and a campaign with kills must have fired
    ``replica_death`` — alert coverage as a tested invariant.
    ``extra_rules`` appends deterministic
    :class:`ChaosRule`\\ s to the seeded schedule (the acceptance test
    forces a goodput dip with a consecutive-prefill-fault rule)."""
    from horovod_tpu import alerts as alerts_mod
    from horovod_tpu import timeseries as timeseries_mod
    from horovod_tpu.serving_scheduler import ServeEngine

    workload = _workload(n_groups, waves)
    recovery = (_workload(n_groups, recovery_waves)
                if recovery_waves else [])
    names = [f"replica{i}" for i in range(n_replicas)]
    schedule = ChaosSchedule.generate(
        seed, replica_names=names, n_faults=n_faults, n_kills=n_kills)

    # Fault-free reference: one solo engine (routing never changes
    # tokens — the router bench asserts that — so a single engine's
    # greedy output IS the fleet's fault-free output).  Covers the
    # recovery waves too — same prompt generator, so OK bits must
    # match there as well.
    ref_engine = ServeEngine(params, cfg, n_slots=n_slots,
                             max_len=max_len, chunk=chunk,
                             prefix_cache=True, monitor=False,
                             metrics=metrics_mod.NULL)
    reference = ref_engine.run(workload + recovery)

    # The chaos fleet: engines, registry, storm, supervisor, journal-
    # free router (journal determinism has its own tests; the campaign
    # exercises engine faults + kills + respawn).  A small SLO window
    # lets fleet goodput both sag under the storm and recover within
    # the recovery waves.
    fr = faults_mod.FaultRegistry()
    schedule.arm(fr)
    for rule in extra_rules:
        rule.arm(fr)
    reg = metrics_mod.MetricsRegistry()
    engines = [ServeEngine(params, cfg, n_slots=n_slots,
                           max_len=max_len, chunk=chunk,
                           prefix_cache=True, monitor=False,
                           faults=fr, metrics=reg,
                           slo_window=slo_window, sampler=False)
               for _ in range(n_replicas)]
    if event_log is None:
        event_log = os.path.join(
            tempfile.mkdtemp(prefix="hvd-chaos-"),
            f"chaos-{seed}-{os.getpid()}.jsonl")
    prior_log = os.environ.get("HVD_TPU_EVENT_LOG")
    os.environ["HVD_TPU_EVENT_LOG"] = event_log

    sampler = timeseries_mod.MetricsSampler(
        reg, sample_s=sample_s, raw_points=4096)
    alerts = alerts_mod.AlertManager(sampler, registry=reg,
                                     time_scale=alert_time_scale)
    router = RouterServer(engines, policy="round_robin", registry=reg,
                          faults=fr, sampler=sampler, alerts=alerts)
    ReplicaSupervisor(router, max_restarts=max_restarts,
                      backoff_s=backoff_s, warm_prefixes=4)
    samples: list[dict] = []
    results: list[Any] = []
    deadline = time.monotonic() + timeout_s

    def _serve(wave: list[Request]) -> None:
        rids = [router.route(r) for r in wave]
        for rid in rids:
            while True:
                res = router.result(rid, timeout=0.05)
                if res is not None:
                    results.append(res)
                    break
                router.poll_now()
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"chaos campaign stalled (seed={seed})")

    try:
        for w in range(waves):
            _serve(workload[w * n_groups:(w + 1) * n_groups])
            samples.append(dict(reg.snapshot()["counters"]))
        # Heal window: give the supervisor polls until every replica
        # is routable again (backoff is tiny; this is hit-bounded by
        # the wall-clock deadline, not by sleeps).
        while time.monotonic() < deadline:
            router.poll_now()
            _, health = router.health()
            if health["healthy"] == n_replicas:
                break
            time.sleep(backoff_s)
        # Clean recovery traffic: storm-window SLO failures only age
        # out of the per-engine goodput windows when fresh terminals
        # displace them — a gauge nobody writes never recovers.  Under
        # ``alert_oracle`` the waves interleave with the alert drain:
        # histogram-backed rules (drift) need fresh deltas while their
        # hysteresis clears, because a quiet histogram is "no data"
        # and no-data deliberately holds alert state.
        served = 0
        if alert_oracle:
            # Alert drain: keep polling (sampler + rules keep ticking)
            # until every firing rule has cleared its hysteresis, so
            # "resolved after heal" is observed, not assumed.
            drain_deadline = min(deadline,
                                 time.monotonic() + alert_drain_s)
            while (alerts.firing()
                   and time.monotonic() < drain_deadline):
                if served < recovery_waves:
                    _serve(recovery[served * n_groups:
                                    (served + 1) * n_groups])
                    served += 1
                router.poll_now()
                time.sleep(backoff_s)
        for w in range(served, recovery_waves):
            _serve(recovery[w * n_groups:(w + 1) * n_groups])
        samples.append(dict(reg.snapshot()["counters"]))
        router.reap_tickets(0)
        leaked_tickets = router.memory_report()["tickets"]
        leaked_blocks = 0
        block_errors: list[str] = []
        for r in router.replicas:
            eng = getattr(r, "engine", None)
            if eng is None:
                continue
            total = int(eng.pcache.k.shape[1]) - 1
            free = eng.free_block_count() + eng.cached_block_count()
            leaked_blocks += total - free
            if eng.prefix is not None:
                try:
                    eng.prefix.check_consistency()
                except AssertionError as e:
                    block_errors.append(f"{r.name}: {e}")
        _, health = router.health()
    finally:
        os.environ.pop("HVD_TPU_EVENT_LOG", None)
        if prior_log is not None:
            os.environ["HVD_TPU_EVENT_LOG"] = prior_log
        router.stop()

    fired = list(fr.log)
    logged = [(e.get("site"), e.get("key"), e.get("hit"))
              for e in metrics_mod.EventLog.read(event_log)
              if e.get("kind") == "fault"]
    missing = [f for f in fired if (f[0], f[1], f[2]) not in logged]
    regressed = _counters_regressed(samples)
    storm_results = results[:len(workload)]
    n_ok = sum(1 for r in storm_results if r.status == OK)
    mismatches = [i for i, (res, ref) in enumerate(zip(results,
                                                       reference))
                  if res.status == OK and list(res) != list(ref)]
    counters = samples[-1] if samples else {}
    kills_fired = sum(1 for s, _k, _h in fired if s == KILL_SITE)

    alert_states = alerts.states()
    immediate = {r["name"] for r in alerts.rules
                 if not float(r.get("pending_s", 0))}
    ever_true = {n for n, st in alert_states.items()
                 if st["ever_true"]}
    fired_rules = {n for n, st in alert_states.items()
                   if st["fired"]}
    resolved_rules = {n for n, st in alert_states.items()
                      if st["resolved"]}
    still_firing = alerts.firing()

    oracles = {
        "bit_identical": not mismatches,
        "no_leaked_tickets": leaked_tickets == 0,
        "no_leaked_blocks": leaked_blocks == 0 and not block_errors,
        "metrics_monotonic": not regressed,
        "faults_logged": not missing,
        "healed": health["healthy"] == n_replicas,
    }
    if alert_oracle:
        # Alert coverage: every immediate (zero-pending) rule whose
        # condition was ever observed true must have fired; every
        # fired rule must have resolved (nothing still firing after
        # the drain); and a storm with kills must have tripped
        # replica_death.
        oracles["alerts_covered"] = (
            (ever_true & immediate) <= fired_rules
            and fired_rules <= resolved_rules
            and not still_firing
            and (kills_fired == 0 or "replica_death" in fired_rules))
    return {
        "seed": seed,
        "schedule": schedule.to_json(),
        "sites_fired": sorted({s for s, _k, _h in fired}),
        "n_requests": len(workload),
        "n_ok": n_ok,
        "ok_fraction": n_ok / len(workload),
        "faults_fired": len(fired),
        "kills_fired": kills_fired,
        "respawns": counters.get("supervisor.respawns", 0),
        "permanent_deaths": counters.get(
            "supervisor.permanent_deaths", 0),
        "failovers": counters.get("router.failovers", 0),
        "leaked_tickets": leaked_tickets,
        "leaked_blocks": leaked_blocks,
        "block_errors": block_errors,
        "counter_regressions": regressed,
        "unlogged_faults": [list(f) for f in missing],
        "mismatched_requests": mismatches,
        "alerts": {
            "fired": sorted(fired_rules),
            "resolved": sorted(resolved_rules),
            "ever_true": sorted(ever_true),
            "still_firing": still_firing,
            "transitions": len(alerts.report()["history"]),
        },
        "event_log": event_log,
        "oracles": oracles,
        "ok": all(oracles.values()),
    }


def soak(params: dict, cfg: Any, *, seconds: float,
         start_seed: int = 0, **campaign_kw: Any) -> dict:
    """Run consecutive-seed campaigns until the wall-clock budget runs
    out (at least one always runs).  Returns the aggregate: campaign
    count, failing seeds with their broken oracles, total faults."""
    t0 = time.monotonic()
    seed = start_seed
    reports: list[dict] = []
    while not reports or time.monotonic() - t0 < seconds:
        reports.append(run_campaign(params, cfg, seed=seed,
                                    **campaign_kw))
        seed += 1
    failures = [{"seed": r["seed"],
                 "oracles": {k: v for k, v in r["oracles"].items()
                             if not v}}
                for r in reports if not r["ok"]]
    return {
        "campaigns": len(reports),
        "seconds": time.monotonic() - t0,
        "seeds": [r["seed"] for r in reports],
        "faults_fired": sum(r["faults_fired"] for r in reports),
        "kills_fired": sum(r["kills_fired"] for r in reports),
        "min_ok_fraction": min(r["ok_fraction"] for r in reports),
        "failures": failures,
        "ok": not failures,
    }


def compare_campaigns(old: dict, new: dict, *,
                      threshold: float = 0.1) -> tuple[bool, list[str]]:
    """The regression gate (``chaos_run.py --compare OLD NEW``): fail
    when any oracle that held in ``old`` broke in ``new``, or when the
    OK fraction dropped more than ``threshold`` absolute.  Accepts
    single-campaign or soak reports (a soak report gates on ``ok`` and
    ``min_ok_fraction``)."""
    problems: list[str] = []
    for name, held in old.get("oracles", {}).items():
        if held and not new.get("oracles", {}).get(name, True):
            problems.append(f"oracle {name}: held before, broken now")
    if old.get("ok", True) and not new.get("ok", True):
        if not problems:
            problems.append("campaign ok: passed before, fails now")
    for key in ("ok_fraction", "min_ok_fraction"):
        if key in old and key in new:
            drop = old[key] - new[key]
            if drop > threshold:
                problems.append(
                    f"{key} dropped {drop:.3f} "
                    f"({old[key]:.3f} -> {new[key]:.3f}, "
                    f"threshold {threshold})")
    return (not problems), problems


def measure_chaos_goodput(params: dict, cfg: Any, *, seed: int = 0,
                          **campaign_kw: Any) -> dict:
    """The ``serve_chaos_*`` bench arm: one seeded storm campaign,
    reporting what fraction of the workload still terminated ``OK``
    (the fault-free fleet completes everything, so OK fraction IS
    goodput retention) plus the storm's shape for context."""
    report = run_campaign(params, cfg, seed=seed, **campaign_kw)
    return {
        "serve_chaos_seed": seed,
        "serve_chaos_requests": report["n_requests"],
        "serve_chaos_faults_fired": report["faults_fired"],
        "serve_chaos_kills_fired": report["kills_fired"],
        "serve_chaos_respawns": report["respawns"],
        "serve_chaos_ok_fraction": report["ok_fraction"],
        "serve_chaos_goodput_retention": report["ok_fraction"],
        "serve_chaos_oracles_ok": report["ok"],
    }


def run_autoscale_campaign(params: dict, cfg: Any, *,
                           n_replicas: int = 2, n_groups: int = 3,
                           waves: int = 6, n_slots: int = 2,
                           max_len: int = 64, chunk: int = 8,
                           backoff_s: float = 0.01,
                           event_log: str | None = None,
                           journal: str | None = None,
                           timeout_s: float = 300.0,
                           drain_s: float = 0.0,
                           fault_first_grow: bool = True) -> dict:
    """One deterministic elastic-fleet campaign: a traffic step with
    scripted autoscaler actuations interleaved into live serving.

    The script (no randomness — every phase is a fixed function of the
    arguments, so a failure is exactly reproducible):

    1. **Calm**: the first third of the waves on the starting fleet.
    2. **Faulted grow** (``fault_first_grow``): a ``serve.autoscale``
       rule armed on the first actuation attempt must degrade the
       scale-up to ``hold`` — membership untouched, nothing dropped.
    3. **Grow**: the retry joins a fresh replica through the
       supervisor's factory seam (epoch bump #1).
    4. **Burst**: the middle third of the waves routed as one block —
       the traffic step the grow answered; the new replica must have
       served routed traffic by the end of it.
    5. **Shrink under load**: one wave is routed with idempotency keys
       and the scale-down is actuated while it is in flight.  With the
       default ``drain_s=0`` the cordoned victim fails open through
       the crash path: in-flight callbacks fire ``None`` and the
       router replays each request on a survivor, bit-identically.
       The drain converges to a retire (epoch bump #2).
    6. **Exactly-once probe**: every key from phase 5 is resubmitted
       after the epoch bump; the journal must answer all of them
       without a single new engine submission.
    7. **Tail**: the remaining waves on the shrunk fleet.

    The autoscaler runs with its organic advisor loop idle (no sampler
    in the fleet, so ``router.advisor`` is ``None``) and zeroed
    cooldown/stabilization guards — the campaign owns the decision
    sequence; the guards and the advisor loop have their own
    virtual-clock tests.  Returns an oracle report shaped like
    :func:`run_campaign`'s; ``report["ok"]`` is the AND of every
    oracle."""
    from horovod_tpu.autoscaler import FleetAutoscaler
    from horovod_tpu.serving_scheduler import ServeEngine

    if waves < 5:
        raise ValueError("the autoscale campaign needs waves >= 5 "
                         "(calm / burst / shrink / tail phases)")
    workload = _workload(n_groups, waves)
    calm = max(waves // 3, 1)
    burst = max(waves // 3, 1)

    # Fault-free reference: as in run_campaign, one solo engine's
    # greedy output IS the elastic fleet's expected output — joins,
    # cordons, forced drains, and journal dedup must not change bits.
    ref_engine = ServeEngine(params, cfg, n_slots=n_slots,
                             max_len=max_len, chunk=chunk,
                             prefix_cache=True, monitor=False,
                             metrics=metrics_mod.NULL)
    reference = ref_engine.run(workload)

    fr = faults_mod.FaultRegistry()
    if fault_first_grow:
        fr.inject("serve.autoscale", on_hit=1, count=1)
    reg = metrics_mod.MetricsRegistry()
    engines = [ServeEngine(params, cfg, n_slots=n_slots,
                           max_len=max_len, chunk=chunk,
                           prefix_cache=True, monitor=False,
                           faults=fr, metrics=reg, sampler=False)
               for _ in range(n_replicas)]
    tmpdir = (tempfile.mkdtemp(prefix="hvd-autoscale-")
              if event_log is None or journal is None else None)
    if event_log is None:
        event_log = os.path.join(tmpdir, "autoscale-events.jsonl")
    if journal is None:
        journal = os.path.join(tmpdir, "autoscale-journal.jsonl")
    prior_log = os.environ.get("HVD_TPU_EVENT_LOG")
    os.environ["HVD_TPU_EVENT_LOG"] = event_log

    router = RouterServer(engines, policy="round_robin", registry=reg,
                          faults=fr, journal=journal)
    sup = ReplicaSupervisor(router, backoff_s=backoff_s,
                            warm_prefixes=4)
    asc = FleetAutoscaler(router, supervisor=sup, enabled=True,
                          cooldown_s=0.0, stable_s=0.0,
                          min_replicas=1, max_replicas=n_replicas + 2,
                          step=1, drain_s=drain_s, faults=fr)

    samples: list[dict] = []
    results: list[Any] = []
    decisions: dict[str, dict] = {}
    deadline = time.monotonic() + timeout_s

    def _collect(rids: list[int]) -> list[Any]:
        out = []
        for rid in rids:
            while True:
                res = router.result(rid, timeout=0.05)
                if res is not None:
                    out.append(res)
                    break
                router.poll_now()
                if time.monotonic() > deadline:
                    raise RuntimeError("autoscale campaign stalled")
        return out

    def _wave(w: int) -> list[Request]:
        return workload[w * n_groups:(w + 1) * n_groups]

    try:
        for w in range(calm):
            results.extend(_collect([router.route(r)
                                     for r in _wave(w)]))
        samples.append(dict(reg.snapshot()["counters"]))

        if fault_first_grow:
            decisions["faulted_grow"] = asc.actuate(
                {"action": "scale_up", "n": 1,
                 "reason": "campaign traffic step"})
        with router._lock:
            size_after_fault = len(router.replicas)
        decisions["grow"] = asc.actuate(
            {"action": "scale_up", "n": 1,
             "reason": "campaign traffic step"})
        grown = list(decisions["grow"].get("replicas", []))
        with router._lock:
            grown_size = len(router.replicas)

        lo, hi = calm * n_groups, (calm + burst) * n_groups
        results.extend(_collect([router.route(r)
                                 for r in workload[lo:hi]]))
        with router._lock:
            routed_new = sum(router._routed.get(n, 0) for n in grown)
        samples.append(dict(reg.snapshot()["counters"]))

        # Shrink while the keyed wave is in flight: the cordon lands
        # between route and result, so the victim drains (or fails
        # open) under real load.
        drain_reqs = _wave(calm + burst)
        keys = [f"autoscale-{i}" for i in range(len(drain_reqs))]
        rids = [router.route(r, idempotency_key=k)
                for r, k in zip(drain_reqs, keys)]
        decisions["shrink"] = asc.actuate(
            {"action": "scale_down", "n": 1,
             "reason": "campaign step down"})
        drained = _collect(rids)
        results.extend(drained)
        while asc.draining() and time.monotonic() < deadline:
            router.poll_now()
            time.sleep(backoff_s)

        submitted_before = reg.snapshot()["counters"].get(
            "serve.requests_submitted", 0)
        dedups_before = reg.snapshot()["counters"].get(
            "router.journal_dedups", 0)
        dups = _collect([router.route(r, idempotency_key=k)
                         for r, k in zip(drain_reqs, keys)])
        counters_now = reg.snapshot()["counters"]
        new_submits = (counters_now.get("serve.requests_submitted", 0)
                       - submitted_before)
        new_dedups = (counters_now.get("router.journal_dedups", 0)
                      - dedups_before)

        for w in range(calm + burst + 1, waves):
            results.extend(_collect([router.route(r)
                                     for r in _wave(w)]))
        samples.append(dict(reg.snapshot()["counters"]))

        router.reap_tickets(0)
        leaked_tickets = router.memory_report()["tickets"]
        leaked_blocks = 0
        block_errors: list[str] = []
        with router._lock:
            survivors = list(router.replicas)
        for r in survivors:
            eng = getattr(r, "engine", None)
            if eng is None:
                continue
            total = int(eng.pcache.k.shape[1]) - 1
            free = eng.free_block_count() + eng.cached_block_count()
            leaked_blocks += total - free
            if eng.prefix is not None:
                try:
                    eng.prefix.check_consistency()
                except AssertionError as e:
                    block_errors.append(f"{r.name}: {e}")
        final_size = len(survivors)
        final_cordoned = router.cordoned()
        epoch = asc.epoch.snapshot()
    finally:
        os.environ.pop("HVD_TPU_EVENT_LOG", None)
        if prior_log is not None:
            os.environ["HVD_TPU_EVENT_LOG"] = prior_log
        router.stop()

    fired = list(fr.log)
    events = metrics_mod.EventLog.read(event_log)
    logged = [(e.get("site"), e.get("key"), e.get("hit"))
              for e in events if e.get("kind") == "fault"]
    missing = [f for f in fired if (f[0], f[1], f[2]) not in logged]
    drain_forced = any(e.get("kind") == "autoscaler.drain_force"
                       for e in events)
    regressed = _counters_regressed(samples)
    n_ok = sum(1 for r in results if r.status == OK)
    mismatches = [i for i, (res, ref) in enumerate(zip(results,
                                                       reference))
                  if list(res) != list(ref) or res.status != OK]
    dup_mismatches = [i for i, (dup, orig) in enumerate(zip(dups,
                                                            drained))
                      if dup.status != OK or list(dup) != list(orig)]
    faulted = decisions.get("faulted_grow")

    oracles = {
        "bit_identical": not mismatches,
        "zero_dropped": n_ok == len(workload),
        "exactly_once": (not dup_mismatches
                         and new_submits == 0
                         and new_dedups == len(keys)),
        "grew_and_served": (decisions["grow"]["action"] == "scale_up"
                            and grown_size == n_replicas + 1
                            and routed_new > 0),
        "drained_and_retired": (
            decisions["shrink"]["action"] == "scale_down"
            and final_size == n_replicas
            and not final_cordoned
            and epoch["generation"] >= 2),
        "fault_degraded_to_hold": (
            not fault_first_grow
            or (faulted is not None
                and faulted["action"] == "hold"
                and size_after_fault == n_replicas)),
        "no_leaked_tickets": leaked_tickets == 0,
        "no_leaked_blocks": leaked_blocks == 0 and not block_errors,
        "metrics_monotonic": not regressed,
        "faults_logged": not missing,
    }
    counters = samples[-1] if samples else {}
    return {
        "n_requests": len(workload),
        "n_ok": n_ok,
        "ok_fraction": n_ok / len(workload),
        "grown_replicas": grown,
        "routed_to_grown": routed_new,
        "drain_forced": drain_forced,
        "dedups": new_dedups,
        "epoch": epoch,
        "decisions": decisions,
        "scale_ups": counters.get("autoscaler.scale_ups", 0),
        "scale_downs": counters.get("autoscaler.scale_downs", 0),
        "hold_faults": counters.get("autoscaler.hold_faults", 0),
        "failovers": counters.get("router.failovers", 0),
        "leaked_tickets": leaked_tickets,
        "leaked_blocks": leaked_blocks,
        "block_errors": block_errors,
        "counter_regressions": regressed,
        "unlogged_faults": [list(f) for f in missing],
        "mismatched_requests": mismatches,
        "event_log": event_log,
        "oracles": oracles,
        "ok": all(oracles.values()),
    }
