"""MNIST models — the reference's canonical examples
(reference: examples/tensorflow_mnist.py:37-67 conv net,
examples/pytorch_mnist.py:60-78 Net, examples/keras_mnist.py:40-52).

TPU notes: NHWC layout (XLA's native conv layout on TPU), bfloat16-friendly,
static shapes throughout.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MnistConvNet(nn.Module):
    """The 2-conv + 2-fc MNIST net every reference frontend trains.

    Mirrors examples/pytorch_mnist.py:60-78 (conv 10/20 5x5, fc 50) in
    spirit; sizes are rounded to MXU-friendly multiples.
    """

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        # x: [B, 28, 28, 1]
        x = x.astype(self.dtype)
        x = nn.Conv(32, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.max_pool(nn.relu(x), (2, 2), (2, 2))
        x = nn.Conv(64, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.max_pool(nn.relu(x), (2, 2), (2, 2))
        x = x.reshape(x.shape[0], -1)
        x = nn.relu(nn.Dense(128, dtype=self.dtype)(x))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class MnistMLP(nn.Module):
    """Plain MLP variant (keras_mnist.py:40-52 Dense-Dense-Dense)."""

    num_classes: int = 10
    hidden: int = 512
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.reshape(x.shape[0], -1).astype(self.dtype)
        x = nn.relu(nn.Dense(self.hidden, dtype=self.dtype)(x))
        x = nn.relu(nn.Dense(self.hidden, dtype=self.dtype)(x))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
