"""Inception V3 — the reference's other headline benchmark model
(reference: README.md:51-57 and docs/benchmarks.md:1-7 publish ~90% scaling
efficiency for Inception V3 on 512 GPUs; the model itself comes from the
external tf_cnn_benchmarks suite, so this is a from-scratch TPU-first
implementation of the standard architecture, not a port).

Same conventions as :mod:`horovod_tpu.models.resnet`: NHWC layout, bf16
compute / f32 params via ``dtype``, optional cross-replica BatchNorm via
``bn_axis_name``.  The auxiliary classifier head is included behind
``aux_logits`` (returned as a second output in train mode) since the
canonical training recipe weights it 0.4; throughput benchmarks can leave
it off.
"""

from __future__ import annotations

from functools import partial

import flax.linen as nn
import jax.numpy as jnp


class ConvBN(nn.Module):
    """conv → BN → relu, the Inception building block."""

    filters: int
    kernel: tuple[int, int]
    strides: tuple[int, int] = (1, 1)
    padding: str | tuple = "SAME"
    dtype: jnp.dtype = jnp.float32
    bn_axis_name: str | None = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(self.filters, self.kernel, strides=self.strides,
                    padding=self.padding, use_bias=False, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-3, dtype=self.dtype,
                         axis_name=self.bn_axis_name)(x)
        return nn.relu(x)


def _pool_avg(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")


class InceptionA(nn.Module):
    pool_features: int
    dtype: jnp.dtype = jnp.float32
    bn_axis_name: str | None = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype, bn_axis_name=self.bn_axis_name)
        b1 = cbn(64, (1, 1))(x, train)
        b5 = cbn(48, (1, 1))(x, train)
        b5 = cbn(64, (5, 5))(b5, train)
        b3 = cbn(64, (1, 1))(x, train)
        b3 = cbn(96, (3, 3))(b3, train)
        b3 = cbn(96, (3, 3))(b3, train)
        bp = cbn(self.pool_features, (1, 1))(_pool_avg(x), train)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):
    """Grid reduction 35→17."""

    dtype: jnp.dtype = jnp.float32
    bn_axis_name: str | None = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype, bn_axis_name=self.bn_axis_name)
        b3 = cbn(384, (3, 3), strides=(2, 2), padding="VALID")(x, train)
        bd = cbn(64, (1, 1))(x, train)
        bd = cbn(96, (3, 3))(bd, train)
        bd = cbn(96, (3, 3), strides=(2, 2), padding="VALID")(bd, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionC(nn.Module):
    """Factorized 7×7 (1×7 then 7×1) branches."""

    channels_7x7: int
    dtype: jnp.dtype = jnp.float32
    bn_axis_name: str | None = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype, bn_axis_name=self.bn_axis_name)
        c7 = self.channels_7x7
        b1 = cbn(192, (1, 1))(x, train)
        b7 = cbn(c7, (1, 1))(x, train)
        b7 = cbn(c7, (1, 7))(b7, train)
        b7 = cbn(192, (7, 1))(b7, train)
        bd = cbn(c7, (1, 1))(x, train)
        bd = cbn(c7, (7, 1))(bd, train)
        bd = cbn(c7, (1, 7))(bd, train)
        bd = cbn(c7, (7, 1))(bd, train)
        bd = cbn(192, (1, 7))(bd, train)
        bp = cbn(192, (1, 1))(_pool_avg(x), train)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nn.Module):
    """Grid reduction 17→8."""

    dtype: jnp.dtype = jnp.float32
    bn_axis_name: str | None = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype, bn_axis_name=self.bn_axis_name)
        b3 = cbn(192, (1, 1))(x, train)
        b3 = cbn(320, (3, 3), strides=(2, 2), padding="VALID")(b3, train)
        b7 = cbn(192, (1, 1))(x, train)
        b7 = cbn(192, (1, 7))(b7, train)
        b7 = cbn(192, (7, 1))(b7, train)
        b7 = cbn(192, (3, 3), strides=(2, 2), padding="VALID")(b7, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionE(nn.Module):
    """Expanded-filter-bank blocks (split 3×3 into 1×3 ‖ 3×1)."""

    dtype: jnp.dtype = jnp.float32
    bn_axis_name: str | None = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype, bn_axis_name=self.bn_axis_name)
        b1 = cbn(320, (1, 1))(x, train)
        b3 = cbn(384, (1, 1))(x, train)
        b3 = jnp.concatenate(
            [cbn(384, (1, 3))(b3, train), cbn(384, (3, 1))(b3, train)], axis=-1
        )
        bd = cbn(448, (1, 1))(x, train)
        bd = cbn(384, (3, 3))(bd, train)
        bd = jnp.concatenate(
            [cbn(384, (1, 3))(bd, train), cbn(384, (3, 1))(bd, train)], axis=-1
        )
        bp = cbn(192, (1, 1))(_pool_avg(x), train)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionAux(nn.Module):
    num_classes: int
    dtype: jnp.dtype = jnp.float32
    bn_axis_name: str | None = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype, bn_axis_name=self.bn_axis_name)
        x = nn.avg_pool(x, (5, 5), strides=(3, 3), padding="VALID")
        x = cbn(128, (1, 1))(x, train)
        x = cbn(768, (5, 5), padding="VALID")(x, train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=self.dtype)(x).astype(jnp.float32)


class InceptionV3(nn.Module):
    """Standard Inception V3 (299×299 canonical; any H,W ≥ 75 works).

    Returns logits, or ``(logits, aux_logits)`` when ``aux_logits=True`` and
    ``train=True``.
    """

    num_classes: int = 1000
    aux_logits: bool = False
    dtype: jnp.dtype = jnp.float32
    bn_axis_name: str | None = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype, bn_axis_name=self.bn_axis_name)
        blk = dict(dtype=self.dtype, bn_axis_name=self.bn_axis_name)
        x = x.astype(self.dtype)
        # Stem: 299 → 35×35×192
        x = cbn(32, (3, 3), strides=(2, 2), padding="VALID")(x, train)
        x = cbn(32, (3, 3), padding="VALID")(x, train)
        x = cbn(64, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = cbn(80, (1, 1), padding="VALID")(x, train)
        x = cbn(192, (3, 3), padding="VALID")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        # 35×35
        x = InceptionA(pool_features=32, **blk)(x, train)
        x = InceptionA(pool_features=64, **blk)(x, train)
        x = InceptionA(pool_features=64, **blk)(x, train)
        x = InceptionB(**blk)(x, train)
        # 17×17
        x = InceptionC(channels_7x7=128, **blk)(x, train)
        x = InceptionC(channels_7x7=160, **blk)(x, train)
        x = InceptionC(channels_7x7=160, **blk)(x, train)
        x = InceptionC(channels_7x7=192, **blk)(x, train)
        aux = None
        if self.aux_logits and train:
            aux = InceptionAux(self.num_classes, **blk)(x, train)
        x = InceptionD(**blk)(x, train)
        # 8×8
        x = InceptionE(**blk)(x, train)
        x = InceptionE(**blk)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        logits = nn.Dense(self.num_classes, dtype=self.dtype,
                          name="head")(x).astype(jnp.float32)
        if aux is not None:
            return logits, aux
        return logits
