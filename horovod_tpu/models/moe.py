"""Mixture-of-Experts layer with expert parallelism.

No reference equivalent (the reference is a data-parallel-only framework,
SURVEY.md §2.3); this supplies the EP axis of the framework's parallelism
matrix, TPU-first:

* **Dense dispatch**: routing is one-hot einsums over a fixed expert
  capacity — static shapes, MXU-friendly batched matmuls, no scatter/sort
  (the standard TPU MoE formulation; GPU implementations sort tokens
  instead, which XLA:TPU would handle poorly).
* **Top-k router** (top-2 default) with softmax gates renormalized over
  the selected experts and the Switch-Transformer load-balancing
  auxiliary loss: ``E · sum_e(frac_tokens_e · mean_router_prob_e)``,
  where frac_tokens counts first-choice assignments (no top-k factor).
* **Expert parallelism**: expert-stacked weights ``[E, ...]`` shard over
  the ``ep`` mesh axis via :func:`param_partition_specs`; under ``jit``
  GSPMD turns the dispatch/combine einsums into all-to-alls over ICI.
  :func:`expert_parallel_mlp` is the explicit ``shard_map`` form (manual
  ``lax.all_to_all``) for the hand-scheduled path.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    dim: int = 512
    ffn_dim: int = 1024
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    # Router z-loss (ST-MoE): penalizes large router logit norms —
    # log²(Σe^logit) per token — which keeps the softmax out of its
    # saturated region and stabilizes bf16 training.  0 disables.
    z_loss_weight: float = 0.0
    # Multiplicative jitter on router inputs during training (Switch
    # Transformer's input noise): x · U[1−ε, 1+ε].  0 disables.
    router_jitter: float = 0.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32


def init_params(cfg: MoEConfig, key: jax.Array) -> dict:
    ks = jax.random.split(key, 3)
    e, d, f = cfg.n_experts, cfg.dim, cfg.ffn_dim
    dt = cfg.param_dtype

    def dense(k, shape, fan_in):
        return jax.random.normal(k, shape, dt) / jnp.sqrt(fan_in)

    return {
        "router": dense(ks[0], (d, e), d),
        "w_in": dense(ks[1], (e, d, f), d),
        "w_out": dense(ks[2], (e, f, d), f),
    }


def param_partition_specs(*, ep_axis: str = "ep") -> dict:
    """Expert-stacked weights shard over the expert axis; the router is
    replicated (every token scores every expert)."""
    return {
        "router": P(None, None),
        "w_in": P(ep_axis, None, None),
        "w_out": P(ep_axis, None, None),
    }


def _capacity(n_tokens: int, cfg: MoEConfig) -> int:
    cap = int(cfg.capacity_factor * n_tokens * cfg.top_k / cfg.n_experts)
    return max(cap, cfg.top_k)


def route(cfg: MoEConfig, logits: jax.Array) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Top-k routing with per-expert capacity.

    logits: [T, E] → (dispatch [T, E, C] one-hot, combine [T, E, C] gated,
    aux loss scalar).  All static shapes; position-in-expert computed with
    a cumulative sum over the token axis (deterministic tie-break by token
    order, the standard TPU formulation).
    """
    t = logits.shape[0]
    e = cfg.n_experts
    cap = _capacity(t, cfg)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [T, E]

    gate_vals, expert_idx = lax.top_k(probs, cfg.top_k)          # [T, k]
    gate_vals = gate_vals / jnp.clip(
        gate_vals.sum(-1, keepdims=True), 1e-9
    )  # renormalize over the selected experts

    # One-hot per choice: [k, T, E]
    choice_oh = jax.nn.one_hot(expert_idx.T, e, dtype=jnp.float32)
    # Position of each (choice, token) within its expert queue, counting
    # first-choice tokens before second-choice tokens (priority to top-1).
    flat = choice_oh.reshape(cfg.top_k * t, e)                    # [k*T, E]
    pos = jnp.cumsum(flat, axis=0) - flat                         # [k*T, E]
    pos = (pos * flat).sum(-1).reshape(cfg.top_k, t)              # [k, T]
    keep = pos < cap
    pos_oh = jax.nn.one_hot(
        pos.astype(jnp.int32), cap, dtype=jnp.float32
    ) * keep[..., None]

    # dispatch[t, e, c] = 1 iff token t occupies slot c of expert e.
    dispatch = jnp.einsum("kte,ktc->tec", choice_oh, pos_oh)
    combine = jnp.einsum(
        "kte,ktc,tk->tec", choice_oh, pos_oh, gate_vals.astype(jnp.float32)
    )

    # Load-balancing aux loss (Switch): E · sum_e(frac_tokens_e · mean_prob_e).
    frac_tokens = choice_oh[0].mean(0)          # first-choice assignment share
    mean_prob = probs.mean(0)
    aux = cfg.n_experts * jnp.sum(frac_tokens * mean_prob)
    return dispatch.astype(jnp.float32), combine.astype(jnp.float32), aux


def router_logits(
    params: dict, x: jax.Array, cfg: MoEConfig,
    *, noise_key: jax.Array | None = None,
) -> jax.Array:
    """Router scores [T, E], with optional training-time jitter: the Switch
    Transformer's multiplicative input noise ``x · U[1−ε, 1+ε]``
    (``cfg.router_jitter``), applied only when a ``noise_key`` is given."""
    xf = x.astype(jnp.float32)
    if noise_key is not None and cfg.router_jitter > 0.0:
        eps = cfg.router_jitter
        xf = xf * jax.random.uniform(
            noise_key, xf.shape, jnp.float32, 1.0 - eps, 1.0 + eps
        )
    return xf @ params["router"].astype(jnp.float32)


def weighted_aux(cfg: MoEConfig, aux: jax.Array,
                 logits: jax.Array) -> jax.Array:
    """Combine the Switch balance loss with the ST-MoE router z-loss —
    ``mean(log²Σ_e e^logit)``, which keeps router logits small and the
    softmax out of its saturated region (bf16 stability)."""
    total = cfg.aux_loss_weight * aux
    if cfg.z_loss_weight:
        z = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
        total = total + cfg.z_loss_weight * jnp.mean(z ** 2)
    return total


def forward(
    params: dict, x: jax.Array, cfg: MoEConfig,
    *, noise_key: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """MoE MLP: x [T, D] → (y [T, D], aux_loss).  ``noise_key`` enables
    the training-time router jitter (see :func:`router_logits`).

    The GSPMD path: with ``w_in``/``w_out`` sharded over ``ep`` and the
    einsums below, XLA inserts the token all-to-alls — same comm pattern a
    hand-written EP implementation issues, derived from the sharding.
    """
    dt = cfg.dtype
    logits = router_logits(params, x, cfg, noise_key=noise_key)
    dispatch, combine, aux = route(cfg, logits)
    # Tokens → expert buffers: [E, C, D]
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(dt), x.astype(dt))
    h = jax.nn.silu(
        jnp.einsum("ecd,edf->ecf", expert_in, params["w_in"].astype(dt))
    )
    expert_out = jnp.einsum("ecf,efd->ecd", h, params["w_out"].astype(dt))
    y = jnp.einsum("tec,ecd->td", combine.astype(dt), expert_out)
    return y.astype(x.dtype), weighted_aux(cfg, aux, logits)


def expert_parallel_mlp(
    params: dict, x: jax.Array, cfg: MoEConfig, *, axis_name: str = "ep",
    noise_key: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Explicit shard_map form: each device holds E/n experts and its own
    token shard; tokens move via ``lax.all_to_all`` (the MoE dispatch
    collective), compute runs on local experts, and a second all-to-all
    brings results home.

    x: per-device token shard [T_loc, D]; params: per-device expert shard
    (``w_in``/``w_out`` leading dim E/n, router replicated).
    """
    n = lax.axis_size(axis_name)
    e_loc = params["w_in"].shape[0]
    dt = cfg.dtype
    full_cfg = dataclasses.replace(cfg, n_experts=e_loc * n)

    if noise_key is not None:
        # Per-shard decorrelation: inside shard_map every device sees the
        # same replicated key and the same local shape, so without the
        # fold-in each token shard would draw IDENTICAL jitter.
        noise_key = jax.random.fold_in(noise_key, lax.axis_index(axis_name))
    logits = router_logits(params, x, cfg, noise_key=noise_key)
    dispatch, combine, aux = route(full_cfg, logits)

    # Local dispatch to ALL experts' buffers, then all-to-all exchanges
    # buffer ownership: [E, C, D] -> [E/n, n·C, D] on each device (expert
    # index is group-major: expert e = g·e_loc + j lives on device g, so a
    # tiled split over axis 0 routes chunk g to device g; received chunks
    # stack along the slot axis).
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(dt), x.astype(dt))
    expert_in = lax.all_to_all(expert_in, axis_name, 0, 1, tiled=True)

    h = jax.nn.silu(
        jnp.einsum("ecd,edf->ecf", expert_in, params["w_in"].astype(dt))
    )
    out = jnp.einsum("ecf,efd->ecd", h, params["w_out"].astype(dt))

    # Inverse exchange: slot chunk s came from device s; send results home
    # and restack along the expert axis -> [E, C, D] per device.
    out = lax.all_to_all(out, axis_name, 1, 0, tiled=True)
    y = jnp.einsum("tec,ecd->td", combine.astype(dt), out)
    # aux is computed from the local token shard; mean over devices.
    total = lax.pmean(weighted_aux(full_cfg, aux, logits), axis_name)
    return y.astype(x.dtype), total
