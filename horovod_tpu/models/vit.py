"""Vision Transformer (ViT) family — the transformer-era counterpart of
the reference's CNN zoo (the reference imports torchvision/keras models;
its own zoo stops at ResNet/VGG/Inception, so ViT is beyond-parity model
breadth built from this repo's own attention stack).

TPU-first choices:
* Patchify as a single strided conv ([P,P] kernel, stride P) — one big
  MXU contraction, no gather/reshape shuffle.
* Attention through :func:`horovod_tpu.parallel.flash_attention` on TPU
  (the pallas kernel benched 1.16–2.4× over dense on-chip, see
  docs/artifacts/) with a dense fallback for CPU simulation and tiny
  sequence lengths — resolved by ``attn_impl``.
* bfloat16 compute / float32 params via ``dtype=jnp.bfloat16`` (MXU
  native), pre-LN blocks (stable without warmup tricks), learned
  position embeddings, mean-pool head (no CLS token: a masked-token
  readout adds a ragged access XLA can't fuse as well as a reduce).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class _Attention(nn.Module):
    n_heads: int
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "dense"        # "dense" | "flash"

    @nn.compact
    def __call__(self, x):
        b, l, d = x.shape
        head_dim = d // self.n_heads
        qkv = nn.Dense(3 * d, dtype=self.dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, l, self.n_heads, head_dim)
        k = k.reshape(b, l, self.n_heads, head_dim)
        v = v.reshape(b, l, self.n_heads, head_dim)
        if self.attn_impl == "flash":
            from horovod_tpu.parallel.flash_attention import flash_attention

            # Bidirectional (causal=False): every patch attends to all.
            out = flash_attention(q, k, v, causal=False)
        elif self.attn_impl != "dense":
            # Same contract as models/llama.py: an unknown impl raises —
            # a typo must not silently run dense attention.
            raise ValueError(
                f"unknown attn_impl {self.attn_impl!r}; "
                f"expected 'dense' or 'flash'")
        else:
            scores = jnp.einsum(
                "blhd,bmhd->bhlm", q, k
            ) / jnp.sqrt(jnp.asarray(head_dim, self.dtype))
            probs = nn.softmax(scores.astype(jnp.float32), axis=-1)
            out = jnp.einsum("bhlm,bmhd->blhd", probs.astype(self.dtype), v)
        out = out.reshape(b, l, d)
        return nn.Dense(d, dtype=self.dtype, name="proj")(out)


class _Block(nn.Module):
    n_heads: int
    mlp_ratio: int = 4
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "dense"

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        h = nn.LayerNorm(dtype=self.dtype, name="ln1")(x)
        x = x + _Attention(self.n_heads, self.dtype, self.attn_impl,
                           name="attn")(h)
        h = nn.LayerNorm(dtype=self.dtype, name="ln2")(x)
        h = nn.Dense(self.mlp_ratio * d, dtype=self.dtype, name="fc1")(h)
        h = nn.gelu(h)
        h = nn.Dense(d, dtype=self.dtype, name="fc2")(h)
        return x + h


class ViT(nn.Module):
    """Patchify → pre-LN transformer encoder → mean-pool → linear head."""

    patch: int = 16
    dim: int = 768
    depth: int = 12
    n_heads: int = 12
    num_classes: int = 1000
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "dense"

    @nn.compact
    def __call__(self, x, train: bool = True):
        del train                    # no dropout/BN: API parity with ResNet
        x = x.astype(self.dtype)
        x = nn.Conv(self.dim, (self.patch, self.patch),
                    strides=(self.patch, self.patch),
                    dtype=self.dtype, name="patchify")(x)
        b, hh, ww, d = x.shape
        x = x.reshape(b, hh * ww, d)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, hh * ww, d), jnp.float32)
        x = x + pos.astype(self.dtype)
        for i in range(self.depth):
            x = _Block(self.n_heads, dtype=self.dtype,
                       attn_impl=self.attn_impl, name=f"block{i}")(x)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_out")(x)
        x = x.mean(axis=1)
        return nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)


def ViT_S16(**kw) -> ViT:
    """ViT-Small/16 (22M params)."""
    return ViT(patch=16, dim=384, depth=12, n_heads=6, **kw)


def ViT_B16(**kw) -> ViT:
    """ViT-Base/16 (86M params) — the standard benchmark config."""
    return ViT(patch=16, dim=768, depth=12, n_heads=12, **kw)


def ViT_L16(**kw) -> ViT:
    """ViT-Large/16 (307M params)."""
    return ViT(patch=16, dim=1024, depth=24, n_heads=16, **kw)
