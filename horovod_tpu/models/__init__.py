"""Model zoo: the reference's example families (MNIST conv/MLP, ResNet,
VGG) plus the Llama-3 flagship for the transformer-era baseline configs."""

from horovod_tpu.models.mnist import MnistConvNet, MnistMLP  # noqa: F401
from horovod_tpu.models.resnet import ResNet50, ResNet101, ResNet152  # noqa: F401
from horovod_tpu.models.vgg import VGG16  # noqa: F401
from horovod_tpu.models.inception import InceptionV3  # noqa: F401
from horovod_tpu.models.vit import ViT, ViT_S16, ViT_B16, ViT_L16  # noqa: F401
from horovod_tpu.models import llama  # noqa: F401
from horovod_tpu.models import moe  # noqa: F401
