"""ResNet v1.5 family — the reference's benchmark workhorse
(reference: examples/pytorch_imagenet_resnet50.py, keras_imagenet_resnet50.py,
docs/benchmarks.md resnet101 runs; the reference imports torchvision/keras
model zoos, so this is a from-scratch TPU-first implementation, not a port).

TPU-first choices:
* NHWC + channels-last conv kernels (XLA TPU native layout; keeps the MXU fed
  with [spatial, C_in] × [C_in, C_out] contractions).
* bfloat16 compute / float32 params-and-BN via ``dtype=jnp.bfloat16``.
* Optional cross-replica BatchNorm: pass ``bn_axis_name="hvd"`` to psum batch
  statistics over the data axis (the reference trains with per-GPU local BN;
  syncing is the TPU-era upgrade, off by default for parity).
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class BottleneckBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: jnp.dtype = jnp.float32
    bn_axis_name: str | None = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            axis_name=self.bn_axis_name,
        )
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = nn.relu(norm()(y))
        y = conv(self.filters * 4, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)  # zero-init last BN gamma
        if residual.shape != y.shape:
            residual = conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides),
                name="downsample_conv",
            )(residual)
            residual = norm(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    width: int = 64
    dtype: jnp.dtype = jnp.float32
    bn_axis_name: str | None = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        # x: [B, H, W, 3] NHWC
        x = x.astype(self.dtype)
        x = nn.Conv(self.width, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.dtype, name="conv_init")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=self.dtype,
                         axis_name=self.bn_axis_name, name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = BottleneckBlock(
                    self.width * 2 ** i,
                    strides=strides,
                    dtype=self.dtype,
                    bn_axis_name=self.bn_axis_name,
                )(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


def ResNet50(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), **kw)


def ResNet101(**kw) -> ResNet:
    """docs/benchmarks.md's tf_cnn_benchmarks resnet101 config."""
    return ResNet(stage_sizes=(3, 4, 23, 3), **kw)


def ResNet152(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 8, 36, 3), **kw)
