"""Llama-3-family transformer — the flagship model (BASELINE config 5:
"Llama-3 8B data-parallel via DistributedOptimizer on v5p-128").

The reference has no transformer (its zoo is ResNet/MNIST-era); this is the
capability-extension model the baseline tracks, built TPU-first:

* **Stacked-layer ``lax.scan``**: all L layers' weights are stacked on a
  leading axis and the forward is one scanned block → O(1) HLO size, fast
  compiles at 8B scale, natural remat boundary.
* **bfloat16 activations / float32 master params** (cast at use).
* **GQA** (n_kv_heads < n_heads), rotary embeddings, SwiGLU, RMSNorm —
  matching Llama-3 architecture.  Rotary uses the half-split (HF/NeoX)
  convention, so HuggingFace-layout checkpoints map 1:1; Meta-native
  checkpoints need the standard per-head interleave→half permutation of
  wq/wk first.
* **Pluggable attention engine**: dense / blockwise (O(L) memory) /
  ring (sequence-parallel over a mesh axis) / ulysses (all-to-all SP) from
  :mod:`horovod_tpu.parallel.attention`, plus the pallas flash kernel.
* **Explicit partition specs** for DP/TP/SP: :func:`param_partition_specs`
  returns the GSPMD sharding pytree (megatron-style column/row splits) so
  ``jit(in_shardings=...)`` lays q/k/v/gate/up column-parallel and
  o/down row-parallel over the ``tp`` axis — XLA inserts the psums.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel import attention as attn_mod


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    max_seq_len: int = 8192
    dtype: Any = jnp.bfloat16          # activation/compute dtype
    param_dtype: Any = jnp.float32     # master weights
    attn_impl: str = "dense"           # dense | blockwise | ring | ulysses | flash
    attn_block_size: int = 512
    remat: bool = True                 # jax.checkpoint each scanned layer

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


def llama3_8b(**overrides) -> LlamaConfig:
    return dataclasses.replace(LlamaConfig(), **overrides)


def llama_tiny(**overrides) -> LlamaConfig:
    """Test/dryrun configuration: same architecture, toy widths."""
    base = LlamaConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_dim=128, max_seq_len=128, rope_theta=10000.0, remat=False,
    )
    return dataclasses.replace(base, **overrides)


def init_params(cfg: LlamaConfig, key: jax.Array) -> dict:
    """Stacked-layer parameter pytree.

    Layout (L = n_layers, D = dim, H·Dh = dim, K = n_kv_heads·head_dim,
    F = ffn_dim):
      embed      [V, D]
      layers:
        attn_norm [L, D]   wq [L, D, H·Dh]  wk [L, D, K]  wv [L, D, K]
        wo        [L, H·Dh, D]
        mlp_norm  [L, D]   w_gate [L, D, F] w_up [L, D, F] w_down [L, F, D]
      final_norm [D]
      lm_head    [D, V]
    """
    keys = jax.random.split(key, 10)
    d, f = cfg.dim, cfg.ffn_dim
    kdim = cfg.n_kv_heads * cfg.head_dim
    L = cfg.n_layers
    dt = cfg.param_dtype

    def dense_init(k, shape, fan_in):
        return (jax.random.normal(k, shape, dt) / jnp.sqrt(fan_in)).astype(dt)

    return {
        "embed": dense_init(keys[0], (cfg.vocab_size, d), d),
        "layers": {
            "attn_norm": jnp.ones((L, d), dt),
            "wq": dense_init(keys[1], (L, d, d), d),
            "wk": dense_init(keys[2], (L, d, kdim), d),
            "wv": dense_init(keys[3], (L, d, kdim), d),
            "wo": dense_init(keys[4], (L, d, d), d),
            "mlp_norm": jnp.ones((L, d), dt),
            "w_gate": dense_init(keys[5], (L, d, f), d),
            "w_up": dense_init(keys[6], (L, d, f), d),
            "w_down": dense_init(keys[7], (L, f, d), f),
        },
        "final_norm": jnp.ones((d,), dt),
        "lm_head": dense_init(keys[8], (d, cfg.vocab_size), d),
    }


def param_partition_specs(cfg: LlamaConfig, *, tp_axis: str = "tp") -> dict:
    """Megatron-style tensor-parallel layout over ``tp_axis``.

    Column-parallel (output dim sharded): wq/wk/wv/w_gate/w_up + lm_head.
    Row-parallel (input dim sharded): wo/w_down — GSPMD inserts the psum
    after the row-parallel matmul, exactly the collective placement of
    hand-written Megatron TP, derived from these specs.
    """
    t = tp_axis
    return {
        "embed": P(None, t),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, None, t),
            "wk": P(None, None, t),
            "wv": P(None, None, t),
            "wo": P(None, t, None),
            "mlp_norm": P(None, None),
            "w_gate": P(None, None, t),
            "w_up": P(None, None, t),
            "w_down": P(None, t, None),
        },
        "final_norm": P(None),
        "lm_head": P(None, t),
    }


def rmsnorm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * scale).astype(x.dtype) * w.astype(x.dtype)


def rope_tables(cfg: LlamaConfig, positions: jax.Array) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables for ``positions`` [..., L] → [..., L, head_dim//2]."""
    half = cfg.head_dim // 2
    freqs = cfg.rope_theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freqs
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotary rotation, half-split (HF/NeoX) convention: dimension i pairs
    with i + Dh/2.  x: [B, L, H, Dh]."""
    x1, x2 = jnp.split(x, 2, axis=-1)
    c = cos[:, :, None, :]
    s = sin[:, :, None, :]
    return jnp.concatenate(
        [x1 * c - x2 * s, x1 * s + x2 * c], axis=-1
    ).astype(x.dtype)


def _attention(cfg: LlamaConfig, q, k, v, *, positions_offset, sp_axis):
    impl = cfg.attn_impl
    if impl == "dense":
        return attn_mod.dense_attention(
            q, k, v, causal=True,
            q_offset=positions_offset, kv_offset=positions_offset,
        )
    if impl == "blockwise":
        return attn_mod.blockwise_attention(
            q, k, v, causal=True, block_size=cfg.attn_block_size,
            q_offset=positions_offset, kv_offset=positions_offset,
        )
    if impl == "ring":
        return attn_mod.ring_attention(q, k, v, axis_name=sp_axis, causal=True)
    if impl == "ulysses":
        return attn_mod.ulysses_attention(q, k, v, axis_name=sp_axis, causal=True)
    if impl == "flash":
        from horovod_tpu.parallel.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=True)
    raise ValueError(f"unknown attn_impl {impl!r}")


def forward(
    params: dict,
    tokens: jax.Array,
    cfg: LlamaConfig,
    *,
    positions_offset: int | jax.Array = 0,
    sp_axis: str | None = None,
) -> jax.Array:
    """Token ids [B, L] → logits [B, L, V].

    ``positions_offset``: global position of tokens[:, 0] (nonzero on
    sequence shards).  ``sp_axis``: mesh axis name for ring/ulysses
    attention (call under shard_map with the sequence axis sharded).
    """
    b, l = tokens.shape
    dt = cfg.dtype
    # gather first, THEN cast: converts [B, L, D] activations, not a full
    # [V, D] bf16 copy of the table (~1 GB at 8B scale) every step.
    x = params["embed"][tokens].astype(dt)  # [B, L, D]
    positions = positions_offset + jnp.arange(l)[None, :]
    cos, sin = rope_tables(cfg, jnp.broadcast_to(positions, (b, l)))

    def layer(x, lp):
        h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q = (h @ lp["wq"].astype(dt)).reshape(b, l, cfg.n_heads, cfg.head_dim)
        k = (h @ lp["wk"].astype(dt)).reshape(b, l, cfg.n_kv_heads, cfg.head_dim)
        v = (h @ lp["wv"].astype(dt)).reshape(b, l, cfg.n_kv_heads, cfg.head_dim)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        o = _attention(cfg, q, k, v, positions_offset=positions_offset,
                       sp_axis=sp_axis)
        x = x + o.reshape(b, l, cfg.dim) @ lp["wo"].astype(dt)
        h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        gate = jax.nn.silu(h @ lp["w_gate"].astype(dt))
        up = h @ lp["w_up"].astype(dt)
        x = x + (gate * up) @ lp["w_down"].astype(dt)
        return x, None

    if cfg.remat:
        layer = jax.checkpoint(layer)

    x, _ = lax.scan(layer, x, params["layers"])
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = x @ params["lm_head"].astype(dt)
    return logits.astype(jnp.float32)


def loss_fn(
    params: dict, batch: tuple[jax.Array, jax.Array], cfg: LlamaConfig,
    **fw_kwargs,
) -> jax.Array:
    """Next-token cross-entropy; batch = (tokens [B, L], targets [B, L])."""
    tokens, targets = batch
    logits = forward(params, tokens, cfg, **fw_kwargs)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def make_loss_fn(cfg: LlamaConfig, **fw_kwargs) -> Callable:
    return partial(loss_fn, cfg=cfg, **fw_kwargs)


def num_params(cfg: LlamaConfig) -> int:
    d, f, L, v = cfg.dim, cfg.ffn_dim, cfg.n_layers, cfg.vocab_size
    kdim = cfg.n_kv_heads * cfg.head_dim
    per_layer = 2 * d + d * d * 2 + 2 * d * kdim + 3 * d * f
    return v * d * 2 + L * per_layer + d
