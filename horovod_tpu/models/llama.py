"""Llama-3-family transformer — the flagship model (BASELINE config 5:
"Llama-3 8B data-parallel via DistributedOptimizer on v5p-128").

The reference has no transformer (its zoo is ResNet/MNIST-era); this is the
capability-extension model the baseline tracks, built TPU-first:

* **Stacked-layer ``lax.scan``**: all L layers' weights are stacked on a
  leading axis and the forward is one scanned block → O(1) HLO size, fast
  compiles at 8B scale, natural remat boundary.
* **bfloat16 activations / float32 master params** (cast at use).
* **GQA** (n_kv_heads < n_heads), rotary embeddings, SwiGLU, RMSNorm —
  matching Llama-3 architecture.  Rotary uses the half-split (HF/NeoX)
  convention, so HuggingFace-layout checkpoints map 1:1; Meta-native
  checkpoints need the standard per-head interleave→half permutation of
  wq/wk first.
* **Pluggable attention engine**: dense / blockwise (O(L) memory) /
  ring (sequence-parallel over a mesh axis) / ulysses (all-to-all SP) from
  :mod:`horovod_tpu.parallel.attention`, plus the pallas flash kernel.
* **Explicit partition specs** for DP/TP/SP: :func:`param_partition_specs`
  returns the GSPMD sharding pytree (megatron-style column/row splits) so
  ``jit(in_shardings=...)`` lays q/k/v/gate/up column-parallel and
  o/down row-parallel over the ``tp`` axis — XLA inserts the psums.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel import attention as attn_mod


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    max_seq_len: int = 8192
    dtype: Any = jnp.bfloat16          # activation/compute dtype
    param_dtype: Any = jnp.float32     # master weights
    attn_impl: str = "dense"  # dense | blockwise | ring | ulysses | ulysses_flash | flash
    attn_block_size: int = 512
    remat: bool = True                 # jax.checkpoint each scanned layer
    # Named jax.checkpoint policy for the layer remat — the middle ground
    # between remat=False (keep everything) and full remat (recompute
    # everything).  "dots_saveable" keeps every matmul output (incl.
    # attention scores) and recomputes only the cheap elementwise chains —
    # usually the best FLOPs/HBM trade on TPU.
    # "dots_with_no_batch_dims_saveable" keeps just the weight-projection
    # matmuls and also recomputes the head-batched attention einsums — a
    # notch more recompute/less memory than dots_saveable (NOT near-full
    # remat: the eight projections per layer are all saved).
    # None = full remat (save nothing).
    remat_policy: str | None = None
    # Chunked fused linear+cross-entropy (ops/fused_xent.py): loss without
    # the [B·L, V] logits tensor; None keeps the plain path.
    fused_loss_chunk: int | None = None

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


def llama3_8b(**overrides) -> LlamaConfig:
    return dataclasses.replace(LlamaConfig(), **overrides)


def llama_tiny(**overrides) -> LlamaConfig:
    """Test/dryrun configuration: same architecture, toy widths."""
    base = LlamaConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_dim=128, max_seq_len=128, rope_theta=10000.0, remat=False,
    )
    return dataclasses.replace(base, **overrides)


def init_params(cfg: LlamaConfig, key: jax.Array) -> dict:
    """Stacked-layer parameter pytree.

    Layout (L = n_layers, D = dim, H·Dh = dim, K = n_kv_heads·head_dim,
    F = ffn_dim):
      embed      [V, D]
      layers:
        attn_norm [L, D]   wq [L, D, H·Dh]  wk [L, D, K]  wv [L, D, K]
        wo        [L, H·Dh, D]
        mlp_norm  [L, D]   w_gate [L, D, F] w_up [L, D, F] w_down [L, F, D]
      final_norm [D]
      lm_head    [D, V]
    """
    keys = jax.random.split(key, 10)
    d, f = cfg.dim, cfg.ffn_dim
    kdim = cfg.n_kv_heads * cfg.head_dim
    L = cfg.n_layers
    dt = cfg.param_dtype

    def dense_init(k, shape, fan_in):
        return (jax.random.normal(k, shape, dt) / jnp.sqrt(fan_in)).astype(dt)

    return {
        "embed": dense_init(keys[0], (cfg.vocab_size, d), d),
        "layers": {
            "attn_norm": jnp.ones((L, d), dt),
            "wq": dense_init(keys[1], (L, d, d), d),
            "wk": dense_init(keys[2], (L, d, kdim), d),
            "wv": dense_init(keys[3], (L, d, kdim), d),
            "wo": dense_init(keys[4], (L, d, d), d),
            "mlp_norm": jnp.ones((L, d), dt),
            "w_gate": dense_init(keys[5], (L, d, f), d),
            "w_up": dense_init(keys[6], (L, d, f), d),
            "w_down": dense_init(keys[7], (L, f, d), f),
        },
        "final_norm": jnp.ones((d,), dt),
        "lm_head": dense_init(keys[8], (d, cfg.vocab_size), d),
    }


def param_partition_specs(cfg: LlamaConfig, *, tp_axis: str = "tp") -> dict:
    """Megatron-style tensor-parallel layout over ``tp_axis``.

    Column-parallel (output dim sharded): wq/wk/wv/w_gate/w_up + lm_head.
    Row-parallel (input dim sharded): wo/w_down — GSPMD inserts the psum
    after the row-parallel matmul, exactly the collective placement of
    hand-written Megatron TP, derived from these specs.
    """
    t = tp_axis
    return {
        "embed": P(None, t),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, None, t),
            "wk": P(None, None, t),
            "wv": P(None, None, t),
            "wo": P(None, t, None),
            "mlp_norm": P(None, None),
            "w_gate": P(None, None, t),
            "w_up": P(None, None, t),
            "w_down": P(None, t, None),
        },
        "final_norm": P(None),
        "lm_head": P(None, t),
    }


def paged_cache_partition_specs(*, tp_axis: str = "tp") -> "PagedKVCache":
    """Head-sharded layout for the paged KV pool over ``tp_axis``.

    k/v ``[n_layers, n_blocks, block_size, KVH, Dh]`` shard on the KV-head
    axis — the same heads the column-parallel wk/wv produce locally, so a
    sharded decode writes its own head slice with zero cross-chip traffic
    and the per-chip pool holds ``KVH / tp`` heads (KV HBM split across
    chips).  ``block_table``/``length`` stay replicated: block ids are
    host-side bookkeeping, one logical block id addresses the same slot of
    every chip's head slice, which is what keeps the BlockPool / radix
    prefix cache / preemption replay shard-agnostic.
    """
    return PagedKVCache(
        k=P(None, None, None, tp_axis, None),
        v=P(None, None, None, tp_axis, None),
        block_table=P(),
        length=P(),
    )


def rmsnorm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * scale).astype(x.dtype) * w.astype(x.dtype)


def rope_tables(cfg: LlamaConfig, positions: jax.Array) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables for ``positions`` [..., L] → [..., L, head_dim//2]."""
    half = cfg.head_dim // 2
    freqs = cfg.rope_theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freqs
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotary rotation, half-split (HF/NeoX) convention: dimension i pairs
    with i + Dh/2.  x: [B, L, H, Dh]."""
    x1, x2 = jnp.split(x, 2, axis=-1)
    c = cos[:, :, None, :]
    s = sin[:, :, None, :]
    return jnp.concatenate(
        [x1 * c - x2 * s, x1 * s + x2 * c], axis=-1
    ).astype(x.dtype)


def _attention(cfg: LlamaConfig, q, k, v, *, positions_offset, sp_axis):
    impl = cfg.attn_impl
    if impl == "dense":
        return attn_mod.dense_attention(
            q, k, v, causal=True,
            q_offset=positions_offset, kv_offset=positions_offset,
        )
    if impl == "blockwise":
        return attn_mod.blockwise_attention(
            q, k, v, causal=True, block_size=cfg.attn_block_size,
            q_offset=positions_offset, kv_offset=positions_offset,
        )
    if impl == "ring":
        return attn_mod.ring_attention(q, k, v, axis_name=sp_axis, causal=True)
    if impl in ("ulysses", "ulysses_flash"):
        local = None
        if impl == "ulysses_flash":
            # Sequence-parallel a2a re-shard + the pallas kernel as the
            # local engine: the long-context fast path.
            from horovod_tpu.parallel.flash_attention import flash_attention

            local = flash_attention
        return attn_mod.ulysses_attention(
            q, k, v, axis_name=sp_axis, causal=True, impl=local
        )
    if impl == "flash":
        from horovod_tpu.parallel.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=True)
    raise ValueError(f"unknown attn_impl {impl!r}")


# Zero-config policies only: jax.checkpoint_policies also exposes policy
# FACTORIES (save_only_these_names, save_from_both_policies, ...) that
# take arguments — passing one of those bare to jax.checkpoint misbehaves
# at trace time instead of failing fast, hence the explicit allowlist.
_REMAT_POLICIES = (
    "dots_saveable",
    "dots_with_no_batch_dims_saveable",
    "everything_saveable",
    "nothing_saveable",
)


def _resolve_remat_policy(cfg: "LlamaConfig"):
    if cfg.remat_policy is None:
        return None
    if cfg.remat_policy not in _REMAT_POLICIES:
        raise ValueError(
            f"unknown remat_policy {cfg.remat_policy!r}; pick one of "
            f"{_REMAT_POLICIES}"
        )
    return getattr(jax.checkpoint_policies, cfg.remat_policy)


def forward(
    params: dict,
    tokens: jax.Array,
    cfg: LlamaConfig,
    *,
    positions_offset: int | jax.Array = 0,
    sp_axis: str | None = None,
    return_hidden: bool = False,
) -> jax.Array:
    """Token ids [B, L] → logits [B, L, V].

    ``positions_offset``: global position of tokens[:, 0] (nonzero on
    sequence shards).  ``sp_axis``: mesh axis name for ring/ulysses
    attention (call under shard_map with the sequence axis sharded).
    ``return_hidden=True`` stops after the final norm ([B, L, D]) so the
    fused loss can stream the vocab projection itself.
    """
    if cfg.remat_policy is not None and not cfg.remat:
        raise ValueError(
            "remat_policy is set but remat=False — policy-based remat "
            "needs remat=True (remat_policy alone does nothing)"
        )
    _resolve_remat_policy(cfg)      # fail fast on a bad name either way
    b, l = tokens.shape
    dt = cfg.dtype
    # gather first, THEN cast: converts [B, L, D] activations, not a full
    # [V, D] bf16 copy of the table (~1 GB at 8B scale) every step.
    x = params["embed"][tokens].astype(dt)  # [B, L, D]
    positions = positions_offset + jnp.arange(l)[None, :]
    cos, sin = rope_tables(cfg, jnp.broadcast_to(positions, (b, l)))

    def layer(x, lp):
        h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q = (h @ lp["wq"].astype(dt)).reshape(b, l, cfg.n_heads, cfg.head_dim)
        k = (h @ lp["wk"].astype(dt)).reshape(b, l, cfg.n_kv_heads, cfg.head_dim)
        v = (h @ lp["wv"].astype(dt)).reshape(b, l, cfg.n_kv_heads, cfg.head_dim)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        o = _attention(cfg, q, k, v, positions_offset=positions_offset,
                       sp_axis=sp_axis)
        x = x + o.reshape(b, l, cfg.dim) @ lp["wo"].astype(dt)
        h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        gate = jax.nn.silu(h @ lp["w_gate"].astype(dt))
        up = h @ lp["w_up"].astype(dt)
        x = x + (gate * up) @ lp["w_down"].astype(dt)
        return x, None

    if cfg.remat:
        layer = jax.checkpoint(layer, policy=_resolve_remat_policy(cfg))

    x, _ = lax.scan(layer, x, params["layers"])
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    if return_hidden:
        return x
    logits = x @ params["lm_head"].astype(dt)
    return logits.astype(jnp.float32)


def loss_fn(
    params: dict, batch: tuple[jax.Array, jax.Array], cfg: LlamaConfig,
    **fw_kwargs,
) -> jax.Array:
    """Next-token cross-entropy; batch = (tokens [B, L], targets [B, L]).

    With ``cfg.fused_loss_chunk`` the vocab projection and the softmax run
    chunk-by-chunk (ops/fused_xent.py) — same math, no [B·L, V] logits
    residency."""
    tokens, targets = batch
    # `is not None`, not truthiness: fused_loss_chunk=0 must hit the op's
    # chunk validation, not silently select the materialized path.
    if cfg.fused_loss_chunk is not None:
        from horovod_tpu.ops.fused_xent import fused_linear_cross_entropy

        hidden = forward(params, tokens, cfg, return_hidden=True,
                         **fw_kwargs)
        b, l, d = hidden.shape
        return fused_linear_cross_entropy(
            hidden.reshape(b * l, d),
            params["lm_head"].astype(cfg.dtype),
            targets.reshape(-1),
            chunk_size=cfg.fused_loss_chunk,
        )
    logits = forward(params, tokens, cfg, **fw_kwargs)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def make_loss_fn(cfg: LlamaConfig, **fw_kwargs) -> Callable:
    return partial(loss_fn, cfg=cfg, **fw_kwargs)


def num_params(cfg: LlamaConfig) -> int:
    d, f, L, v = cfg.dim, cfg.ffn_dim, cfg.n_layers, cfg.vocab_size
    kdim = cfg.n_kv_heads * cfg.head_dim
    per_layer = 2 * d + d * d * 2 + 2 * d * kdim + 3 * d * f
    return v * d * 2 + L * per_layer + d


# ---------------------------------------------------------------------------
# Autoregressive decoding with a KV cache (inference path).
#
# The reference's inference story is "load the checkpoint, run it in one
# process" (its docs/inference.md); for a transformer that means prefill +
# cached decode.  TPU-first shape: the cache is a static [n_layers, B,
# max_len, KVH, Dh] buffer updated with dynamic_update_slice, the decode
# step is one scanned layer block (same stacked-params layout as forward),
# and generation is a lax.scan over steps — one compiled program, no
# per-token retracing.
# ---------------------------------------------------------------------------


class KVCache(NamedTuple):
    """Per-layer key/value buffers: k/v [n_layers, B, max_len, KVH, Dh];
    ``length`` is the number of filled positions — a scalar int32 when all
    rows are in lockstep (the fast path: one dynamic_update_slice per
    step), or [B] int32 for ragged rows (continuous-batching shape: each
    row's next write lands at its own position via scatter)."""

    k: jax.Array
    v: jax.Array
    length: jax.Array


def init_cache(cfg: LlamaConfig, batch_size: int, max_len: int) -> KVCache:
    shape = (cfg.n_layers, batch_size, max_len, cfg.n_kv_heads, cfg.head_dim)
    return KVCache(
        k=jnp.zeros(shape, cfg.dtype),
        v=jnp.zeros(shape, cfg.dtype),
        length=jnp.zeros((), jnp.int32),
    )


def _validate_lengths(lengths, b: int, l: int, fn: str) -> None:
    """Concrete-value precondition check for ragged ``lengths`` [B] in
    [1, padded width]; traced values are the caller's contract."""
    if lengths is None or isinstance(lengths, jax.core.Tracer):
        return
    ln = np.asarray(lengths)
    if ln.shape != (b,) or ln.min() < 1 or ln.max() > l:
        raise ValueError(
            f"{fn} lengths must be [batch]={b} values in [1, padded "
            f"width {l}], got shape {ln.shape} range "
            f"[{ln.min() if ln.size else '-'}, "
            f"{ln.max() if ln.size else '-'}]")


def prefill(
    params: dict, tokens: jax.Array, cfg: LlamaConfig, cache: KVCache,
    lengths: jax.Array | None = None,
) -> tuple[jax.Array, KVCache]:
    """Run the prompt through the model, filling cache[:, :, :L].

    Returns (last-position logits [B, V], updated cache).  Uses the same
    stacked-layer scan as :func:`forward`; attention is the configured
    engine (the flash kernel applies here — prefill is the MXU-bound
    phase).

    ``lengths`` [B]: optional per-row prompt lengths for RIGHT-padded
    ragged batches (continuous-batching shape), each in [1, L].
    Causality already keeps valid queries from seeing the padded tail,
    the returned logits come from each row's last valid position, and
    the cache becomes per-row-length (pad slots carry garbage K/V that
    the decode mask never reads and later writes overwrite).
    """
    b, l = tokens.shape
    _validate_lengths(lengths, b, l, "prefill")
    dt = cfg.dtype
    x = params["embed"][tokens].astype(dt)
    positions = jnp.broadcast_to(jnp.arange(l)[None, :], (b, l))
    cos, sin = rope_tables(cfg, positions)

    def layer(x, lp):
        h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q = (h @ lp["wq"].astype(dt)).reshape(b, l, cfg.n_heads, cfg.head_dim)
        k = (h @ lp["wk"].astype(dt)).reshape(b, l, cfg.n_kv_heads, cfg.head_dim)
        v = (h @ lp["wv"].astype(dt)).reshape(b, l, cfg.n_kv_heads, cfg.head_dim)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        o = _attention(cfg, q, k, v, positions_offset=0, sp_axis=None)
        x = x + o.reshape(b, l, cfg.dim) @ lp["wo"].astype(dt)
        h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        gate = jax.nn.silu(h @ lp["w_gate"].astype(dt))
        up = h @ lp["w_up"].astype(dt)
        x = x + (gate * up) @ lp["w_down"].astype(dt)
        return x, (k, v)

    x, (ks, vs) = lax.scan(layer, x, params["layers"])
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    if lengths is None:
        last = x[:, -1]
        new_len = jnp.asarray(l, jnp.int32)
    else:
        last = x[jnp.arange(b), jnp.asarray(lengths, jnp.int32) - 1]
        new_len = jnp.asarray(lengths, jnp.int32)     # [B] — ragged cache
    logits = (last @ params["lm_head"].astype(dt)).astype(jnp.float32)
    cache = KVCache(
        k=lax.dynamic_update_slice(cache.k, ks, (0, 0, 0, 0, 0)),
        v=lax.dynamic_update_slice(cache.v, vs, (0, 0, 0, 0, 0)),
        length=new_len,
    )
    return logits, cache


def decode_step(
    params: dict, token: jax.Array, cfg: LlamaConfig, cache: KVCache,
) -> tuple[jax.Array, KVCache]:
    """One autoregressive step: ``token`` [B] → logits [B, V] + cache.

    Attends over the cached keys/values (masked past ``length``); the new
    position's K/V are written at index ``length``.  Decode is
    matvec-bound, so attention is a plain masked einsum in f32 — no kernel
    needed.

    A scalar ``cache.length`` is the lockstep fast path (one
    dynamic_update_slice per step); a [B] ``cache.length`` (ragged
    prefill / continuous batching) delegates to :func:`decode_chunk`
    with T=1 — identical math, per-row scatter writes and masks.
    """
    if jnp.ndim(cache.length) > 0:           # ragged: one code path (T=1)
        logits, cache = decode_chunk(params, token[:, None], cfg, cache)
        return logits[:, 0], cache
    b = token.shape[0]
    dt = cfg.dtype
    max_len = cache.k.shape[2]
    pos = cache.length                       # scalar int32
    x = params["embed"][token][:, None, :].astype(dt)     # [B, 1, D]
    cos, sin = rope_tables(cfg, jnp.broadcast_to(pos, (b, 1)))
    n_rep = cfg.n_heads // cfg.n_kv_heads
    scale = 1.0 / (cfg.head_dim ** 0.5)
    # mask over cache positions: attend to [0, pos] inclusive —
    # broadcasts over the [B, KVH, R, 1, M] score layout
    valid = (jnp.arange(max_len) <= pos)[None, None, None, None, :]

    def layer(x, inputs):
        lp, kc, vc = inputs                               # kc/vc [B, M, KVH, Dh]
        h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q = (h @ lp["wq"].astype(dt)).reshape(b, 1, cfg.n_heads, cfg.head_dim)
        k = (h @ lp["wk"].astype(dt)).reshape(b, 1, cfg.n_kv_heads, cfg.head_dim)
        v = (h @ lp["wv"].astype(dt)).reshape(b, 1, cfg.n_kv_heads, cfg.head_dim)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        kc = lax.dynamic_update_slice(kc, k, (0, pos, 0, 0))
        vc = lax.dynamic_update_slice(vc, v, (0, pos, 0, 0))
        # GQA via grouped einsum: fold the query heads onto their KV head
        # ([B, 1, H, Dh] → [B, 1, KVH, R, Dh], q head h ↔ kv head h//R —
        # the same mapping _repeat_kv uses) instead of materializing the
        # repeat-expanded cache.  The expansion would read/write R× the
        # cache per step — decode's whole cost is cache traffic — while
        # the grouped form reads it once and hands the MXU R query rows
        # per KV-head matmul instead of one.
        qg = q.reshape(b, 1, cfg.n_kv_heads, n_rep, cfg.head_dim)
        s = jnp.einsum(
            "bqkrd,bmkd->bkrqm", qg.astype(jnp.float32),
            kc.astype(jnp.float32)
        ) * scale                                         # [B, KVH, R, 1, M]
        s = jnp.where(valid, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bkrqm,bmkd->bqkrd", p, vc.astype(jnp.float32))
        x = x + o.astype(dt).reshape(b, 1, cfg.dim) @ lp["wo"].astype(dt)
        h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        gate = jax.nn.silu(h @ lp["w_gate"].astype(dt))
        up = h @ lp["w_up"].astype(dt)
        x = x + (gate * up) @ lp["w_down"].astype(dt)
        return x, (kc, vc)

    x, (ks, vs) = lax.scan(layer, x, (params["layers"], cache.k, cache.v))
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = (x[:, 0] @ params["lm_head"].astype(dt)).astype(jnp.float32)
    return logits, KVCache(k=ks, v=vs, length=pos + 1)


def decode_chunk(
    params: dict, tokens: jax.Array, cfg: LlamaConfig, cache: KVCache,
) -> tuple[jax.Array, KVCache]:
    """Consume T tokens per row in ONE pass: ``tokens`` [B, T] →
    (logits [B, T, V], cache advanced by T).

    The T-token generalization of :func:`decode_step` (same per-row
    position/mask machinery, scalar or [B] ``cache.length``): token j of
    row r lands at cache position ``pos_r + j`` and attends to
    ``[0, pos_r + j]``.  Logits at every chunk position come back — this
    is the verification pass of speculative decoding (one MXU-friendly
    T-row matmul instead of T matvecs) and equally the chunked-prefill
    building block for feeding long prompts through a bounded window.
    """
    b, t = tokens.shape
    dt = cfg.dtype
    max_len = cache.k.shape[2]
    pos = cache.length
    posv = pos if jnp.ndim(pos) > 0 else jnp.broadcast_to(pos, (b,))
    x = params["embed"][tokens].astype(dt)                # [B, T, D]
    qpos = posv[:, None] + jnp.arange(t)[None, :]         # [B, T]
    cos, sin = rope_tables(cfg, qpos)
    n_rep = cfg.n_heads // cfg.n_kv_heads
    scale = 1.0 / (cfg.head_dim ** 0.5)
    # key m visible to query j of row r iff m <= pos_r + j
    valid = jnp.arange(max_len)[None, None, :] <= qpos[:, :, None]
    valid = valid[:, None, None, :, :]                    # [B,1,1,T,M]
    rows = jnp.arange(b)[:, None]

    def layer(x, inputs):
        lp, kc, vc = inputs
        h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q = (h @ lp["wq"].astype(dt)).reshape(b, t, cfg.n_heads, cfg.head_dim)
        k = (h @ lp["wk"].astype(dt)).reshape(b, t, cfg.n_kv_heads, cfg.head_dim)
        v = (h @ lp["wv"].astype(dt)).reshape(b, t, cfg.n_kv_heads, cfg.head_dim)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        kc = kc.at[rows, qpos].set(k)                     # [B,T,…] scatter
        vc = vc.at[rows, qpos].set(v)
        qg = q.reshape(b, t, cfg.n_kv_heads, n_rep, cfg.head_dim)
        s = jnp.einsum(
            "bqkrd,bmkd->bkrqm", qg.astype(jnp.float32),
            kc.astype(jnp.float32)
        ) * scale                                         # [B,KVH,R,T,M]
        s = jnp.where(valid, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bkrqm,bmkd->bqkrd", p, vc.astype(jnp.float32))
        x = x + o.astype(dt).reshape(b, t, cfg.dim) @ lp["wo"].astype(dt)
        h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        gate = jax.nn.silu(h @ lp["w_gate"].astype(dt))
        up = h @ lp["w_up"].astype(dt)
        x = x + (gate * up) @ lp["w_down"].astype(dt)
        return x, (kc, vc)

    x, (ks, vs) = lax.scan(layer, x, (params["layers"], cache.k, cache.v))
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"].astype(dt)).astype(jnp.float32)
    return logits, KVCache(k=ks, v=vs, length=pos + t)


# ---------------------------------------------------------------------------
# Block/paged KV cache (vLLM/PagedAttention layout, SOSP '23).
#
# The dense KVCache above reserves a full [B, max_len] stripe per slot; a
# serving pool that recycles slots wants cache memory to follow the LIVE
# requests instead.  Here K/V live in a pool of fixed-size blocks
# ([n_layers, n_blocks, block_size, KVH, Dh]) and each slot owns an int32
# ``block_table`` row mapping its logical positions to physical blocks.
# Admission allocates just the blocks a request needs; retirement returns
# them — all on the host, with device programs keeping ONE compiled
# signature (the tables are data, not shapes, so admission never retraces).
#
# Block 0 is the TRASH block: it is never allocated, and unallocated table
# entries point at it.  Free/idle rows that tick along with the batch (the
# fixed-signature tick decodes every row) scatter their garbage K/V into
# trash, where nothing valid ever reads it — the paged form of the slot
# pool's write-before-read invariant.
# ---------------------------------------------------------------------------


class PagedKVCache(NamedTuple):
    """Paged K/V pool: k/v ``[n_layers, n_blocks, block_size, KVH, Dh]``,
    ``block_table`` [B, blocks_per_slot] int32 (physical block of each
    logical block; 0 = trash), ``length`` [B] int32 filled positions."""

    k: jax.Array
    v: jax.Array
    block_table: jax.Array
    length: jax.Array

    @property
    def block_size(self) -> int:
        return self.k.shape[2]

    @property
    def logical_len(self) -> int:
        """Dense attention width each row's table spans (== max_len)."""
        return self.block_table.shape[1] * self.k.shape[2]


def init_paged_cache(
    cfg: LlamaConfig, n_slots: int, max_len: int, *,
    block_size: int, n_blocks: int | None = None,
) -> PagedKVCache:
    """A paged pool for ``n_slots`` rows of logical depth ``max_len``.

    ``n_blocks`` defaults to full backing (every slot can hold max_len)
    plus the trash block; pass less to overcommit — the paged win — and
    let the scheduler admission-gate on free blocks."""
    if max_len % block_size:
        raise ValueError(
            f"max_len {max_len} not a multiple of block_size {block_size}")
    per = max_len // block_size
    if n_blocks is None:
        n_blocks = n_slots * per + 1          # +1: the trash block
    if n_blocks < per + 1:
        raise ValueError(
            f"n_blocks {n_blocks} cannot back even one full slot "
            f"({per} blocks) plus the trash block")
    shape = (cfg.n_layers, n_blocks, block_size, cfg.n_kv_heads,
             cfg.head_dim)
    return PagedKVCache(
        k=jnp.zeros(shape, cfg.dtype),
        v=jnp.zeros(shape, cfg.dtype),
        block_table=jnp.zeros((n_slots, per), jnp.int32),
        length=jnp.zeros((n_slots,), jnp.int32),
    )


class BlockPool:
    """Host-side reference-counted allocator over the paged pool's
    physical blocks — the free-list's successor once blocks can be
    SHARED across slot rows (prefix caching: one physical block mapped
    by many block-table rows).

    Every physical block (1..n_blocks-1; block 0 is trash and never
    allocated) is in exactly one of three states:

    * **free** — on the free list, content garbage, allocatable;
    * **referenced** — mapped by >= 1 live rows (``refcount(b)`` users);
      never reclaimed while any reference remains;
    * **cached** — zero references but *indexed* by a prefix index
      (:class:`horovod_tpu.prefix_cache.RadixPrefixCache`): content is
      a valid, immutable KV chunk kept for future reuse.  Cached blocks
      sit in LRU order and are reclaimed by the index's eviction walk
      when admission needs them — eviction of cache always precedes
      preemption of live rows.

    The pool is policy-free: it tracks states and counts; *which*
    cached block to evict (leaf-first, LRU) is the radix index's call,
    because evictability depends on tree structure the pool can't see.
    All bookkeeping is host-side — device programs never observe any of
    it (block tables change data, never shapes).
    """

    def __init__(self, n_blocks: int):
        if n_blocks < 2:
            raise ValueError(
                f"n_blocks {n_blocks} leaves no allocatable block "
                f"beyond trash block 0")
        self.n_blocks = n_blocks
        # pop() takes low ids first, matching the old free-list order so
        # cache-off engines allocate bit-identical block layouts
        self._free = list(range(n_blocks - 1, 0, -1))
        self._ref: dict[int, int] = {}       # block -> live references
        self._indexed: set[int] = set()      # owned by a prefix index
        self._lru: dict[int, None] = {}      # zero-ref indexed, LRU order

    # -- counts ------------------------------------------------------------

    def free_count(self) -> int:
        return len(self._free)

    def cached_count(self) -> int:
        return len(self._lru)

    def ref_count(self) -> int:
        """Blocks currently mapped by at least one live row."""
        return len(self._ref)

    def refcount(self, block: int) -> int:
        return self._ref.get(block, 0)

    # -- allocation / references -------------------------------------------

    def alloc(self) -> int:
        """Take a free block (caller increfs it when a row maps it).
        Raises IndexError when the free list is empty — callers gate on
        ``free_count()`` (and evict cache first when they can)."""
        return self._free.pop()

    def incref(self, block: int) -> int:
        """One more row maps ``block``; a cached block leaves the LRU
        (it is pinned while referenced — eviction can't touch it)."""
        self._lru.pop(block, None)
        n = self._ref.get(block, 0) + 1
        self._ref[block] = n
        return n

    def decref(self, block: int) -> int:
        """One row unmapped ``block``.  At zero references an indexed
        block parks in the LRU cache (release-to-cache); an unindexed
        one returns to the free list."""
        n = self._ref[block] - 1
        if n > 0:
            self._ref[block] = n
            return n
        del self._ref[block]
        if block in self._indexed:
            self._lru[block] = None          # MRU end
        else:
            self._free.append(block)
        return 0

    # -- index ownership ----------------------------------------------------

    def mark_indexed(self, block: int) -> None:
        """A prefix index now owns ``block``'s content (it became a tree
        node): zero-ref no longer means free, it means cached."""
        self._indexed.add(block)

    def drop_indexed(self, block: int) -> None:
        """The index evicted ``block`` (must be zero-ref): back to the
        free list."""
        if block in self._ref:
            raise RuntimeError(
                f"evicting block {block} with {self._ref[block]} live "
                f"references")
        self._indexed.discard(block)
        self._lru.pop(block, None)
        self._free.append(block)

    def lru_blocks(self) -> list[int]:
        """Zero-ref cached blocks, least-recently-used first (the
        eviction candidate order)."""
        return list(self._lru)

    def state_lines(self) -> list[str]:
        """Human-readable pool picture for scheduler state dumps."""
        shared = {b: n for b, n in sorted(self._ref.items()) if n > 1}
        return [
            f"block pool: free={len(self._free)} "
            f"cached_zero_ref={len(self._lru)} "
            f"referenced={len(self._ref)} "
            f"of {self.n_blocks - 1} allocatable",
            f"  lru (old->new)={list(self._lru)} shared_refcounts="
            f"{shared if shared else '{}'}",
        ]


def _paged_attend(params, tokens, cfg: LlamaConfig, kv_k, kv_v,
                  qpos, wflat, gflat):
    """Shared body of the paged decode paths: scatter the chunk's K/V at
    flat physical positions ``wflat`` [B, T], gather each row's dense
    [M] view via ``gflat`` [B, M], and run :func:`decode_chunk`'s exact
    mask/einsum math on it.  The gather width M equals the logical depth,
    so for identical cache VALUES the masked softmax/matvec sequence is
    the same XLA computation as the dense path — bit-identical logits
    (gathered garbage beyond a row's frontier is masked to an exact-zero
    softmax term, just like dense pad slots)."""
    b, t = tokens.shape
    nl, n_blocks, bs, kvh, dh = kv_k.shape
    m = gflat.shape[1]
    dt = cfg.dtype
    x = params["embed"][tokens].astype(dt)                # [B, T, D]
    cos, sin = rope_tables(cfg, qpos)
    n_rep = cfg.n_heads // cfg.n_kv_heads
    scale = 1.0 / (cfg.head_dim ** 0.5)
    valid = jnp.arange(m)[None, None, :] <= qpos[:, :, None]
    valid = valid[:, None, None, :, :]                    # [B,1,1,T,M]

    def layer(x, inputs):
        lp, kc, vc = inputs                 # kc/vc [n_blocks, bs, KVH, Dh]
        h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q = (h @ lp["wq"].astype(dt)).reshape(b, t, cfg.n_heads, cfg.head_dim)
        k = (h @ lp["wk"].astype(dt)).reshape(b, t, cfg.n_kv_heads, cfg.head_dim)
        v = (h @ lp["wv"].astype(dt)).reshape(b, t, cfg.n_kv_heads, cfg.head_dim)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        kf = kc.reshape(n_blocks * bs, kvh, dh).at[wflat].set(k)
        vf = vc.reshape(n_blocks * bs, kvh, dh).at[wflat].set(v)
        kd = kf[gflat]                                    # [B, M, KVH, Dh]
        vd = vf[gflat]
        qg = q.reshape(b, t, cfg.n_kv_heads, n_rep, cfg.head_dim)
        s = jnp.einsum(
            "bqkrd,bmkd->bkrqm", qg.astype(jnp.float32),
            kd.astype(jnp.float32)
        ) * scale                                         # [B,KVH,R,T,M]
        s = jnp.where(valid, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bkrqm,bmkd->bqkrd", p, vd.astype(jnp.float32))
        x = x + o.astype(dt).reshape(b, t, cfg.dim) @ lp["wo"].astype(dt)
        h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        gate = jax.nn.silu(h @ lp["w_gate"].astype(dt))
        up = h @ lp["w_up"].astype(dt)
        x = x + (gate * up) @ lp["w_down"].astype(dt)
        return x, (kf.reshape(n_blocks, bs, kvh, dh),
                   vf.reshape(n_blocks, bs, kvh, dh))

    x, (ks, vs) = lax.scan(layer, x, (params["layers"], kv_k, kv_v))
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"].astype(dt)).astype(jnp.float32)
    return logits, ks, vs


def decode_chunk_paged(
    params: dict, tokens: jax.Array, cfg: LlamaConfig,
    pcache: PagedKVCache, *, advance: jax.Array | None = None,
) -> tuple[jax.Array, PagedKVCache]:
    """Paged :func:`decode_chunk`: T tokens per row against the block
    pool; token j of row r lands in the physical block its table maps
    position ``length_r + j`` to.

    ``advance`` [B]: optional per-row length increments (0 or T) so a
    fixed-signature serving tick can hold idle rows in place — idle rows
    still compute (one program for the whole pool) but their writes land
    in their table's blocks (trash for free rows) and their length stays
    put.  ``None`` advances every row by T."""
    b, t = tokens.shape
    bs = pcache.block_size
    per = pcache.block_table.shape[1]
    pos = pcache.length                                   # [B]
    qpos = pos[:, None] + jnp.arange(t)[None, :]          # [B, T]
    # writes past the table (an overflowing row) clamp into its last
    # logical block — in-bounds garbage, never validly read
    wblk = jnp.take_along_axis(
        pcache.block_table, jnp.clip(qpos // bs, 0, per - 1), axis=1)
    wflat = wblk * bs + qpos % bs                         # [B, T]
    gflat = (pcache.block_table[:, :, None] * bs
             + jnp.arange(bs)[None, None, :]).reshape(b, per * bs)
    logits, ks, vs = _paged_attend(
        params, tokens, cfg, pcache.k, pcache.v, qpos, wflat, gflat)
    adv = (jnp.asarray(t, jnp.int32) if advance is None
           else jnp.asarray(advance, jnp.int32))
    return logits, pcache._replace(k=ks, v=vs, length=pos + adv)


def spec_verify_paged(
    params: dict, cfg: LlamaConfig, pcache: PagedKVCache,
    last_logits: jax.Array, drafts: jax.Array, active: jax.Array,
) -> tuple[jax.Array, jax.Array, jax.Array, PagedKVCache]:
    """One batched self-speculation verify round over the paged pool:
    every row argmaxes its last logits into ``tok`` and decodes the
    fixed ``(K + 1)``-wide chunk ``[tok, d_1..d_K]`` in ONE
    :func:`decode_chunk_paged` dispatch; greedy longest-matching-prefix
    acceptance is computed IN-PROGRAM (a cumprod of per-position
    matches), so the host never round-trips between dispatch and the
    length advance.  ``drafts`` [B, K] pads with ``-1`` — argmax preds
    are always >= 0, so pads can never be accepted — and ``active`` [B]
    gates the advance exactly as the plain tick's does.

    Rollback of rejected positions is the per-row ``length`` alone: the
    chunk's K/V writes beyond ``length + 1 + accept`` are stale garbage
    in the row's own private frontier blocks (or trash, for inactive
    rows), masked by every reader and overwritten before the frontier
    reaches them — the same write-before-read invariant the slot pool
    already relies on, so no block-table or cache surgery is needed.

    With greedy acceptance every emitted token is the target's own
    argmax (accepted ``d_i`` equals ``preds[i-1]`` by construction), so
    the output stream is bit-identical to solo greedy :func:`generate`
    no matter what the drafter proposed.  Returns ``(tok, accept,
    next_logits, pcache)``: the unconditional token [B], accepted draft
    counts [B], the logits following each row's last accepted token
    [B, V] (seeding the next round), and the advanced cache.
    """
    b, k = drafts.shape
    tok = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)       # [B]
    chunk = jnp.concatenate([tok[:, None], drafts], axis=1)   # [B, K+1]
    hold = jnp.zeros((b,), jnp.int32)
    logits, pcache = decode_chunk_paged(
        params, chunk, cfg, pcache, advance=hold)
    preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)     # [B, K+1]
    match = (drafts == preds[:, :k]).astype(jnp.int32)
    accept = jnp.sum(jnp.cumprod(match, axis=1), axis=1)           # [B]
    adv = jnp.asarray(active, jnp.int32) * (1 + accept)
    pcache = pcache._replace(length=pcache.length + adv)
    next_logits = logits[jnp.arange(b), accept]                 # [B, V]
    return tok, accept, next_logits, pcache


def decode_chunk_paged_row(
    params: dict, tokens: jax.Array, cfg: LlamaConfig,
    pcache: PagedKVCache, slot: jax.Array, *, new_length: jax.Array,
) -> tuple[jax.Array, PagedKVCache]:
    """One row's T-token chunk against the pool: the chunked-prefill
    admission program.  ``tokens`` [1, T] continue slot ``slot`` from its
    current length; the row's length becomes ``new_length`` (the true
    frontier — for a padded final prefill window that is less than
    ``length + T``, exactly :func:`prefill_chunked`'s contract).  Only
    this slot's blocks (and trash, for pad overflow) are touched, so
    in-flight rows are untouched mid-prefill."""
    b, t = tokens.shape
    if b != 1:
        raise ValueError(f"decode_chunk_paged_row is a B=1 program, "
                         f"got batch {b}")
    bs = pcache.block_size
    per = pcache.block_table.shape[1]
    slot = jnp.asarray(slot, jnp.int32)
    row_table = pcache.block_table[slot]                  # [per]
    pos = pcache.length[slot]
    qpos = (pos + jnp.arange(t))[None, :]                 # [1, T]
    wblk = row_table[jnp.clip(qpos // bs, 0, per - 1)]
    wflat = wblk * bs + qpos % bs
    gflat = (row_table[None, :, None] * bs
             + jnp.arange(bs)[None, None, :]).reshape(1, per * bs)
    logits, ks, vs = _paged_attend(
        params, tokens, cfg, pcache.k, pcache.v, qpos, wflat, gflat)
    length = pcache.length.at[slot].set(
        jnp.asarray(new_length, jnp.int32))
    return logits, pcache._replace(k=ks, v=vs, length=length)


def prefill_chunked(
    params: dict, tokens: jax.Array, cfg: LlamaConfig, cache: KVCache,
    *, window: int, lengths: jax.Array | None = None,
) -> tuple[jax.Array, KVCache]:
    """Prefill a long prompt through fixed-size :func:`decode_chunk`
    windows: activation memory is O(window·L_cache) instead of O(L²) —
    the chunked-prefill pattern serving engines use to keep long-prompt
    admission from spiking memory (and to interleave it with decode
    ticks).  Output == :func:`prefill` (each row's last-valid-position
    logits + an equivalent cache: scalar length stays scalar, so the
    decode fast path is preserved).

    The padded width must satisfy ``L % window == 0``; ragged true
    lengths go in ``lengths`` [B] exactly as in :func:`prefill` (pad
    positions beyond a row's length are masked by later decodes and
    overwritten by its next tokens).  One ``lax.scan`` over windows —
    compile size is one chunk body regardless of prompt length.
    """
    b, l = tokens.shape
    if l % window:
        raise ValueError(f"padded prompt length {l} not a multiple of "
                         f"window {window}")
    _validate_lengths(lengths, b, l, "prefill_chunked")
    base = cache.length                              # scalar or [B]
    if not isinstance(base, jax.core.Tracer):
        # decode_chunk's scatter DROPS out-of-bounds writes, so an
        # overflowing chunked prefill would silently return logits
        # attending to never-written slots — fail loudly instead (the
        # analogous one-shot prefill overflow fails at trace time).
        if int(np.max(np.asarray(base))) + l > cache.k.shape[2]:
            raise ValueError(
                f"prefill_chunked would overflow the cache: base length "
                f"{int(np.max(np.asarray(base)))} + padded width {l} > "
                f"max_len {cache.k.shape[2]}")
    basev = (base if jnp.ndim(base) > 0
             else jnp.broadcast_to(base, (b,)))      # [B]
    true_len = (jnp.asarray(lengths, jnp.int32) if lengths is not None
                else jnp.full((b,), l, jnp.int32))
    target = basev + true_len - 1     # absolute pos of each last token
    windows = jnp.moveaxis(tokens.reshape(b, l // window, window), 1, 0)

    def step(carry, toks_w):
        cache, last = carry
        start = cache.length
        startv = (start if jnp.ndim(start) > 0
                  else jnp.broadcast_to(start, (b,)))
        logits, cache = decode_chunk(params, toks_w, cfg, cache)
        # rows whose last valid token falls inside this window pick
        # their logits; others keep what they have
        hit = (target >= startv) & (target < startv + window)
        idx = jnp.clip(target - startv, 0, window - 1)
        cand = logits[jnp.arange(b), idx]
        last = jnp.where(hit[:, None], cand, last)
        return (cache, last), None

    last0 = jnp.zeros((b, cfg.vocab_size), jnp.float32)
    (cache, last), _ = lax.scan(step, (cache, last0), windows)
    if lengths is not None:
        cache = cache._replace(length=basev + true_len)
    # else: decode_chunk preserved the scalar/[B] shape of `base`, and
    # the scanned advance already totals base + l.
    return last, cache


def sample_logits(
    logits: jax.Array,
    key: jax.Array,
    *,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
) -> jax.Array:
    """One sampling step on [B, V] logits → [B] token ids.

    ``temperature<=0`` is greedy argmax (filters are irrelevant there).
    ``top_k`` keeps the k largest logits; ``top_p`` keeps the smallest
    nucleus whose cumulative probability reaches p (always ≥ 1 token);
    both compose (top-k filter first, then the nucleus).  All branching is
    trace-time, so the whole thing jits into the decode scan.
    """
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1)
    return jax.random.categorical(
        key, filtered_logits(logits, temperature, top_k=top_k,
                             top_p=top_p), axis=-1)


def filtered_logits(
    logits: jax.Array,
    temperature,
    *,
    top_k: int | None = None,
    top_p: float | None = None,
) -> jax.Array:
    """Temperature-scaled, top-k/top-p-filtered logits [B, V] — the
    sampling math of :func:`sample_logits`, exposed so callers with a
    TRACED temperature (e.g. per-request temperatures in the serving
    batcher) compute bit-identical distributions.  ``temperature`` must
    be positive (the greedy short-circuit lives in the caller)."""
    logits = logits / temperature
    v = logits.shape[-1]
    use_k = top_k is not None and top_k < v
    if top_p is not None and top_p < 1.0:
        # ONE descending sort serves both filters (this runs per decoded
        # token inside the scan — no second O(V log V) pass): top-k is a
        # positional mask in sorted space, the nucleus is computed on the
        # (possibly k-masked) sorted logits.
        sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]
        if use_k:
            pos = jnp.arange(v)[None, :]
            sorted_desc = jnp.where(pos < top_k, sorted_desc, NEG_INF_LOGIT)
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        csum = jnp.cumsum(probs, axis=-1)
        # Keep a sorted position while the mass BEFORE it is < p — the
        # first token always qualifies (mass 0 < p).
        keep = (csum - probs) < top_p
        thresh = jnp.min(
            jnp.where(keep, sorted_desc, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits >= thresh, logits, NEG_INF_LOGIT)
    elif use_k:
        # top-k alone: lax.top_k gives the kth value without a full sort.
        kth = lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(logits >= kth, logits, NEG_INF_LOGIT)
    return logits


NEG_INF_LOGIT = -1e30


def generate(
    params: dict,
    prompt: jax.Array,
    cfg: LlamaConfig,
    *,
    max_new_tokens: int,
    max_len: int | None = None,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    key: jax.Array | None = None,
    prompt_lengths: jax.Array | None = None,
) -> jax.Array:
    """Greedy (or sampled) generation: prompt [B, L] → [B, max_new_tokens].

    One prefill + one ``lax.scan`` of cached decode steps; jit-friendly
    end to end (static shapes, no per-token retracing).  Sampling knobs:
    ``temperature`` (0 = greedy), ``top_k``, ``top_p`` (nucleus).

    ``prompt_lengths`` [B]: per-row lengths of a RIGHT-padded ragged
    prompt batch — each row continues from its own last valid token
    (mixed-length serving without per-length bucketing; the cache runs
    ragged from the prefill on).
    """
    b, l = prompt.shape
    max_len = max_len or (l + max_new_tokens)
    if max_len < l + max_new_tokens:
        raise ValueError(
            f"max_len={max_len} < prompt {l} + max_new_tokens {max_new_tokens}"
        )
    cache = init_cache(cfg, b, max_len)
    logits, cache = prefill(params, prompt, cfg, cache,
                            lengths=prompt_lengths)
    if key is None:
        key = jax.random.key(0)

    def pick(logits, k):
        return sample_logits(
            logits, k, temperature=temperature, top_k=top_k, top_p=top_p
        ).astype(prompt.dtype)

    def step(carry, k):
        logits, cache = carry
        tok = pick(logits, k)
        logits, cache = decode_step(params, tok, cfg, cache)
        return (logits, cache), tok

    keys = jax.random.split(key, max_new_tokens)
    (_, _), toks = lax.scan(step, (logits, cache), keys)
    return jnp.moveaxis(toks, 0, 1)                       # [B, T]
