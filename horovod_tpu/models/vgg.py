"""VGG-16 — the reference's fusion stress benchmark
(reference: README.md:51-57 cites VGG-16 at 68 % scaling on 512 GPUs — its
138 M mostly-fc parameters are exactly what Tensor Fusion exists for;
BASELINE.md config 4 tracks "VGG-16 gradient bucketing → fused psum").

From-scratch NHWC implementation; ``dtype=jnp.bfloat16`` for MXU throughput.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

# Channel plan per stage, 'M' = maxpool — the classic 16-layer configuration.
_VGG16_PLAN: Sequence = (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
                         512, 512, 512, "M", 512, 512, 512, "M")


class VGG16(nn.Module):
    num_classes: int = 1000
    dtype: jnp.dtype = jnp.float32
    classifier_width: int = 4096

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        for step in _VGG16_PLAN:
            if step == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(step, (3, 3), padding="SAME", dtype=self.dtype)(x)
                x = nn.relu(x)
        x = x.reshape(x.shape[0], -1)
        x = nn.relu(nn.Dense(self.classifier_width, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(self.classifier_width, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
