"""Cross-rank observability: live exporter, exact distributed metric
merge, straggler detection, and SLO goodput windows.

PR 4 (:mod:`horovod_tpu.metrics`) gave every process a registry, traces,
and an event log — but each rank was still an island.  This module is
the fleet layer on top, in four pillars:

* :class:`MonitorServer` / :func:`maybe_start_monitor` — a stdlib-only
  HTTP exporter (one daemon thread per rank, ``ThreadingHTTPServer``)
  serving ``/metrics`` (Prometheus 0.0.4 text), ``/snapshot`` (registry
  JSON), ``/healthz`` (liveness + last-step age; 503 once the engine's
  no-progress watchdog would fire), and ``/state`` (the engine
  ``state_dump()``).  Enabled per-rank via ``HVD_TPU_MONITOR_PORT``
  (rank offsets the port, so one host running N ranks exposes N
  scrape targets) or explicitly via ``ServeEngine(monitor=...)``.

* :func:`merge_snapshots` / :func:`aggregate_snapshots` — exact
  distributed merge in the Monarch (Adams et al., VLDB 2020) style:
  counters sum, gauges keep per-rank values plus min/max/mean, and
  histograms merge EXACTLY by summing their fixed log-bucket counts —
  merged p50/p90/p99 are recomputed from the summed counts through the
  very same :func:`~horovod_tpu.metrics.percentile_from_buckets` code
  path a single process uses, so the fleet view is bit-identical to a
  single histogram fed the union of observations.
  :func:`aggregate_snapshots` rides the engine's negotiation/grouped-
  allgather plane (``allgather_object``), so ANY rank can produce the
  same fleet view.

* :class:`StragglerDetector` — rolling-window per-rank step time and
  ``hvd.negotiate_s`` wait tracking; ``check()`` allgathers per-rank
  reports, publishes ``hvd.step_skew_s`` (slowest minus median rank),
  and emits a ``monitor.straggler`` event naming the slowest rank when
  the skew exceeds ``HVD_TPU_STRAGGLER_WARN_S``.

* :class:`SLOWindow` — a ring buffer of terminal request
  :class:`~horovod_tpu.metrics.Trace`\\ s on :class:`ServeEngine`
  answering "are we meeting SLOs *now*": ``serve.goodput`` (fraction
  OK-and-within-SLO over the window) plus windowed TTFT/TPOT/E2E
  percentiles, surfaced as ``slo_report()`` in ``metrics_snapshot()``
  and on the exporter.

Only :mod:`horovod_tpu.metrics` is imported at module level; the
collective plane (``optim.distributed_optimizer.allgather_object``) is
imported lazily inside :func:`aggregate_snapshots` so this module stays
importable before ``hvd.init()`` and free of import cycles.
"""

from __future__ import annotations

import collections
import json
import os
import statistics
import threading
import time
import warnings
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Iterable

from horovod_tpu import metrics as metrics_mod


def env_float(name: str, default: float) -> float:
    """Tolerant float env parsing (the ``_negotiate_timeout_s`` idiom):
    an unparsable value warns and falls back instead of crashing a job
    at import time."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        warnings.warn(f"{name}={raw!r} is not a float; using {default}",
                      RuntimeWarning, stacklevel=2)
        return default


# ---------------------------------------------------------------------------
# Pillar 1: live HTTP exporter.
# ---------------------------------------------------------------------------


class _Handler(BaseHTTPRequestHandler):
    """Routes one scrape.  The server object carries the registry and
    (optionally) the engine; handlers read both without extra locks —
    every surface they touch is itself thread-safe."""

    server: "MonitorServer._Server"  # type: ignore[assignment]

    protocol_version = "HTTP/1.1"

    def _reply(self, code: int, body: str, ctype: str) -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        mon = self.server.monitor
        mon._scrapes.inc()
        path = self.path.split("?", 1)[0]
        hist, errors = mon._scrape_obs(path.strip("/") or "root")
        t0 = time.perf_counter()
        failed = False
        try:
            self._route(mon, path)
        except BrokenPipeError:  # scraper hung up mid-reply
            pass
        except Exception:
            failed = True
            raise
        finally:
            hist.observe(time.perf_counter() - t0)
            if failed:
                errors.inc()

    def _route(self, mon: "MonitorServer", path: str) -> None:
        if path == "/metrics":
            self._reply(200, mon.registry.to_prometheus(),
                        "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/snapshot":
            # With an engine attached, the engine's view — it embeds
            # the SLO report next to the registry snapshot.
            snap = (mon.engine.metrics_snapshot() if mon.engine
                    is not None else mon.registry.snapshot())
            self._reply(200, json.dumps(snap), "application/json")
        elif path == "/healthz":
            code, body = mon.health()
            self._reply(code, json.dumps(body), "application/json")
        elif path == "/state":
            eng = mon.engine
            if eng is None:
                self._reply(404, "no engine attached\n", "text/plain")
            else:
                self._reply(200, eng.state_dump(),
                            "text/plain; charset=utf-8")
        elif path == "/profile":
            prof = getattr(mon.engine, "prof", None)
            if prof is None:
                self._reply(
                    404, "profiling off; construct the engine with "
                         "profile=True or set HVD_TPU_PROFILE=1\n",
                    "text/plain")
            else:
                self._reply(200, json.dumps(prof.report()),
                            "application/json")
        elif path == "/device":
            dev = getattr(mon.engine, "device", None)
            if dev is None:
                self._reply(
                    404, "device telemetry off; construct the engine "
                         "with device_telemetry=True or set "
                         "HVD_TPU_DEVICE_TELEMETRY=1\n",
                    "text/plain")
            else:
                self._reply(200, json.dumps(dev.report()),
                            "application/json")
        elif path == "/timeseries":
            sampler = getattr(mon.engine, "sampler", None)
            if sampler is None:
                self._reply(
                    404, "no sampler attached; construct the engine "
                         "with sampler=... or set HVD_TPU_SAMPLE_S\n",
                    "text/plain")
            else:
                self._reply(200, json.dumps(sampler.report()),
                            "application/json")
        elif path == "/alerts":
            alerts = getattr(mon.engine, "alerts", None)
            if alerts is None:
                self._reply(
                    404, "no alert manager attached; construct the "
                         "engine with alerts=... (HVD_TPU_ALERTS)\n",
                    "text/plain")
            else:
                self._reply(200, json.dumps(alerts.report()),
                            "application/json")
        elif path == "/advice":
            advisor = getattr(mon.engine, "advisor", None)
            if advisor is None:
                self._reply(404, "no capacity advisor attached\n",
                            "text/plain")
            else:
                advisor.recommend()
                self._reply(200, json.dumps(advisor.report()),
                            "application/json")
        elif path == "/traces":
            tracer = getattr(mon.engine, "tracer", None)
            if tracer is None:
                self._reply(404, "no tracer attached "
                                 "(engine off or pre-tracing)\n",
                            "text/plain")
            else:
                self._reply(200, json.dumps(tracer.recent()),
                            "application/json")
        else:
            self._reply(404, "unknown path; try /metrics /snapshot "
                             "/healthz /state /profile /device "
                             "/timeseries /alerts /advice /traces\n",
                        "text/plain")

    def log_message(self, fmt: str, *args: Any) -> None:
        pass  # scrapes must not spam the job's stderr


class MonitorServer:
    """A per-rank HTTP exporter: daemon thread + ``ThreadingHTTPServer``
    bound to ``host:port`` (``port=0`` picks an ephemeral port — read
    ``.port`` after ``start()``).  Stdlib only, so it costs nothing to
    deploy; scrapes never touch the engine's scheduling loop beyond the
    registry's shared lock — one short pass per scrape, with the
    rendered Prometheus text cached against the registry's generation
    counter so an idle registry serves scrapes without re-rendering."""

    class _Server(ThreadingHTTPServer):
        daemon_threads = True
        monitor: "MonitorServer"

    def __init__(self, registry: metrics_mod.MetricsRegistry | None = None,
                 engine: Any = None, port: int = 0,
                 host: str = "127.0.0.1"):
        self.registry = registry if registry is not None else metrics_mod.DEFAULT
        self.engine = engine
        # The scrape odometer writes on every scrape; left on the
        # registry's shared generation it would invalidate the rendered
        # /metrics cache each hit, defeating the cache exactly when it
        # matters.  A private generation cell keeps the counter live in
        # snapshots while letting its rendered value lag one scrape.
        self._scrapes = self.registry.counter("monitor.scrapes")
        self._scrapes._gen = metrics_mod._Gen()
        # Per-endpoint scrape self-observation on the same private-gen
        # trick: monitor.scrape_s.<endpoint> / monitor.scrape_errors.
        # <endpoint> stay live in snapshots without the act of scraping
        # invalidating the rendered /metrics cache it serves.
        self._scrape_instruments: dict[str, tuple[Any, Any]] = {}
        self._httpd = MonitorServer._Server((host, port), _Handler)
        self._httpd.monitor = self
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: threading.Thread | None = None

    _SCRAPE_ENDPOINTS = frozenset(
        {"metrics", "snapshot", "healthz", "state", "profile",
         "device", "timeseries", "alerts", "advice", "traces", "root"})

    def _scrape_obs(self, endpoint: str) -> tuple[Any, Any]:
        """(latency histogram, error counter) for one endpoint, created
        on first hit with private generation cells.  Unknown paths
        share one ``other`` family so request paths can't mint
        unbounded metric names."""
        if endpoint not in MonitorServer._SCRAPE_ENDPOINTS:
            endpoint = "other"
        pair = self._scrape_instruments.get(endpoint)
        if pair is None:
            hist = self.registry.histogram(
                "monitor.scrape_s." + endpoint)
            hist._gen = metrics_mod._Gen()
            errors = self.registry.counter(
                "monitor.scrape_errors." + endpoint)
            errors._gen = metrics_mod._Gen()
            # Benign race: both threads resolve the same registry
            # instruments, so last-write-wins is still correct.
            pair = self._scrape_instruments[endpoint] = (hist, errors)
        return pair

    def attach_engine(self, engine: Any) -> None:
        """Point ``/healthz`` and ``/state`` at a (new) engine."""
        self.engine = engine

    def health(self) -> tuple[int, dict]:
        """Liveness answer: 200 with uptime, plus engine progress when
        one is attached — 503 once the engine's no-progress watchdog
        would fire (``idle_steps >= watchdog_steps``), so an orchestrator
        restarts the rank the same moment the engine would declare the
        gang wedged."""
        body: dict[str, Any] = {
            "ok": True,
            "rank": metrics_mod.current_rank(),
            "pid": os.getpid(),
        }
        eng = self.engine
        if eng is not None:
            idle = getattr(eng, "_idle_steps", 0)
            wd = getattr(eng, "watchdog_steps", 0)
            last = getattr(eng, "_last_step_ts", None)
            body["step"] = getattr(eng, "step_index", 0)
            body["idle_steps"] = idle
            body["watchdog_steps"] = wd
            body["last_step_age_s"] = (
                None if last is None else time.monotonic() - last)
            if wd and idle >= wd:
                body["ok"] = False
                return 503, body
        return 200, body

    def start(self) -> "MonitorServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name=f"hvd-monitor-:{self.port}", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is not None:
            self._httpd.shutdown()
            self._thread.join(timeout=5)
            self._thread = None
        self._httpd.server_close()


def maybe_start_monitor(registry: metrics_mod.MetricsRegistry | None = None,
                        engine: Any = None) -> MonitorServer | None:
    """Start an exporter when ``HVD_TPU_MONITOR_PORT`` is set — bound to
    base port + rank, so N co-hosted ranks expose N distinct scrape
    targets.  Returns None (silently) when the env var is unset, with a
    warning (not a crash) when it is unparsable or the port is taken."""
    raw = os.environ.get("HVD_TPU_MONITOR_PORT")
    if not raw:
        return None
    try:
        base = int(raw)
    except ValueError:
        warnings.warn(f"HVD_TPU_MONITOR_PORT={raw!r} is not an int; "
                      "monitor disabled", RuntimeWarning, stacklevel=2)
        return None
    port = base + metrics_mod.current_rank()
    try:
        return MonitorServer(registry, engine, port=port).start()
    except OSError as e:
        warnings.warn(f"monitor port {port} unavailable ({e}); "
                      "monitor disabled", RuntimeWarning, stacklevel=2)
        return None


# ---------------------------------------------------------------------------
# Pillar 2: exact distributed merge.
# ---------------------------------------------------------------------------


def merge_snapshots(snaps: Iterable[dict],
                    ranks: Iterable[int] | None = None) -> dict:
    """Merge per-rank registry ``snapshot()`` dicts into one fleet view.

    Counters SUM.  Gauges (last-value semantics don't sum) become a
    ``per_rank`` map plus min/max/mean.  Histograms merge EXACTLY:
    their fixed log-bucket counts sum element-wise and the merged
    p50/p90/p99 are recomputed from the summed counts via
    :func:`~horovod_tpu.metrics.percentile_from_buckets` — identical to
    a single-process histogram over the union of observations (pinned
    by tests/test_monitor.py).  Metrics absent on some ranks merge from
    the ranks that have them; differing histogram bounds raise (bounds
    are fixed by construction, so a mismatch means skewed code
    versions)."""
    snaps = list(snaps)
    rank_ids = list(ranks) if ranks is not None else list(range(len(snaps)))
    if len(rank_ids) != len(snaps):
        raise ValueError(
            f"{len(snaps)} snapshots but {len(rank_ids)} rank ids")

    counters: dict[str, int] = {}
    gauge_per_rank: dict[str, dict[int, float]] = {}
    hists: dict[str, dict] = {}

    for rid, snap in zip(rank_ids, snaps):
        for name, v in snap.get("counters", {}).items():
            counters[name] = counters.get(name, 0) + v
        for name, v in snap.get("gauges", {}).items():
            gauge_per_rank.setdefault(name, {})[rid] = v
        for name, h in snap.get("histograms", {}).items():
            if "buckets" not in h:
                raise ValueError(
                    f"histogram {name!r} snapshot has no 'buckets' field "
                    "(pre-merge schema?)")
            m = hists.get(name)
            if m is None:
                hists[name] = {
                    "count": h["count"], "sum": h["sum"],
                    "min": h["min"], "max": h["max"],
                    "buckets": list(h["buckets"]),
                    "bounds": list(h["bounds"]),
                }
                continue
            if m["bounds"] != list(h["bounds"]):
                raise ValueError(
                    f"histogram {name!r} bounds differ across ranks")
            if h["count"]:
                if m["count"] == 0:
                    m["min"], m["max"] = h["min"], h["max"]
                else:
                    m["min"] = min(m["min"], h["min"])
                    m["max"] = max(m["max"], h["max"])
            m["count"] += h["count"]
            m["sum"] += h["sum"]
            m["buckets"] = [a + b for a, b in
                            zip(m["buckets"], h["buckets"])]

    for name, m in hists.items():
        if m["count"] == 0:
            m.update(min=0.0, max=0.0, p50=0.0, p90=0.0, p99=0.0)
        else:
            for key, q in (("p50", 0.50), ("p90", 0.90), ("p99", 0.99)):
                m[key] = metrics_mod.percentile_from_buckets(
                    m["bounds"], m["buckets"], m["count"],
                    m["min"], m["max"], q)

    gauges = {}
    for name, per_rank in gauge_per_rank.items():
        vals = list(per_rank.values())
        gauges[name] = {
            "per_rank": {int(r): v for r, v in sorted(per_rank.items())},
            "min": min(vals), "max": max(vals),
            "mean": sum(vals) / len(vals),
        }

    merged = {
        "ranks": [int(r) for r in rank_ids],
        "counters": dict(sorted(counters.items())),
        "gauges": dict(sorted(gauges.items())),
        "histograms": dict(sorted(hists.items())),
    }

    # Snapshots from engines with a sampler attached carry a
    # "timeseries" section; merge those bucket-for-bucket too.  Ranks
    # without one (older code, sampler off) just don't contribute.
    ts_reports = [(rid, s["timeseries"]) for rid, s in
                  zip(rank_ids, snaps)
                  if isinstance(s.get("timeseries"), dict)]
    if ts_reports:
        from horovod_tpu import timeseries as timeseries_mod
        merged["timeseries"] = timeseries_mod.merge_series(
            [r for _, r in ts_reports],
            ranks=[rid for rid, _ in ts_reports])
    return merged


def aggregate_snapshots(
        registry: metrics_mod.MetricsRegistry | None = None) -> dict:
    """Allgather every rank's ``snapshot()`` over the engine's
    negotiation/grouped-allgather plane and merge — every rank returns
    the SAME fleet view (pinned by the multiprocess test).  Requires
    ``hvd.init()``; single-process, it degenerates to merging the one
    local snapshot."""
    from horovod_tpu.optim.distributed_optimizer import allgather_object
    registry = registry if registry is not None else metrics_mod.DEFAULT
    snaps = allgather_object(registry.snapshot())
    merged = merge_snapshots(snaps)
    registry.counter("monitor.aggregations").inc()
    return merged


# ---------------------------------------------------------------------------
# Pillar 3: straggler detection.
# ---------------------------------------------------------------------------


class StragglerDetector:
    """Rolling-window per-rank step-time tracker with fleet skew checks.

    Feed it one ``record_step(dt)`` per training/engine step (it also
    observes ``hvd.step_s`` on the registry) and optionally negotiate
    waits via ``record_negotiate(dt)`` — or let ``check()`` pull the
    deltas of the shared ``hvd.negotiate_s`` histogram automatically.
    ``check()`` allgathers everyone's window report, computes
    ``skew = slowest − median`` of mean step time, publishes it as the
    ``hvd.step_skew_s`` gauge, and emits a ``monitor.straggler`` event
    naming the slowest rank when the skew exceeds ``warn_s``
    (``HVD_TPU_STRAGGLER_WARN_S``, default 1.0)."""

    # record_step arrives from the engine/training thread while the
    # monitor thread calls report()/check() — the windows and the
    # delta baseline are cross-thread state.
    _GUARDED_BY_LOCK = ("_steps", "_negotiates",
                        "_neg_seen_count", "_neg_seen_sum")

    def __init__(self, registry: metrics_mod.MetricsRegistry | None = None,
                 window: int = 64, warn_s: float | None = None):
        self.registry = (registry if registry is not None
                         else metrics_mod.DEFAULT)
        self.warn_s = (warn_s if warn_s is not None
                       else env_float("HVD_TPU_STRAGGLER_WARN_S", 1.0))
        self._lock = threading.Lock()
        self._steps: collections.deque[float] = collections.deque(
            maxlen=window)
        self._negotiates: collections.deque[float] = collections.deque(
            maxlen=window)
        # Delta baseline for pulling hvd.negotiate_s off the registry.
        self._neg_seen_count = 0
        self._neg_seen_sum = 0.0

    def record_step(self, dt_s: float) -> None:
        with self._lock:
            self._steps.append(float(dt_s))
        self.registry.histogram("hvd.step_s").observe(dt_s)

    def record_negotiate(self, dt_s: float) -> None:
        with self._lock:
            self._negotiates.append(float(dt_s))

    def _pull_negotiate_deltas_locked(self) -> None:
        """Fold in whatever ``hvd.negotiate_s`` observed since the last
        check — the eager engine feeds that histogram on every
        negotiated dispatch, so no extra plumbing is needed.  Caller
        holds ``self._lock`` (a plain Lock: re-taking it would wedge)."""
        h = self.registry.histogram("hvd.negotiate_s")
        count, total = h.count, h.sum
        dn = count - self._neg_seen_count
        if dn > 0:
            # The histogram only keeps aggregates; one mean-valued
            # sample per delta keeps the window honest enough for skew.
            mean = (total - self._neg_seen_sum) / dn
            for _ in range(min(dn, self._negotiates.maxlen or dn)):
                self._negotiates.append(mean)
        self._neg_seen_count, self._neg_seen_sum = count, total

    def report(self) -> dict:
        """This rank's window summary (the unit ``check()`` gathers)."""
        with self._lock:
            self._pull_negotiate_deltas_locked()
            steps = list(self._steps)
            negs = list(self._negotiates)
        return {
            "rank": metrics_mod.current_rank(),
            "n_steps": len(steps),
            "step_mean_s": (sum(steps) / len(steps)) if steps else 0.0,
            "step_max_s": max(steps) if steps else 0.0,
            "negotiate_mean_s": (sum(negs) / len(negs)) if negs else 0.0,
        }

    @staticmethod
    def _evaluate(reports: list[dict]) -> dict:
        """Pure skew computation over gathered reports (unit-testable
        with synthetic multi-rank data): slowest minus median of
        per-rank mean step time."""
        means = [r["step_mean_s"] for r in reports]
        med = statistics.median(means)
        slowest = max(reports, key=lambda r: r["step_mean_s"])
        return {
            "skew_s": slowest["step_mean_s"] - med,
            "median_step_s": med,
            "slowest_rank": slowest["rank"],
            "slowest_step_s": slowest["step_mean_s"],
            "reports": reports,
        }

    def check(self) -> dict:
        """Gather all ranks' reports, publish ``hvd.step_skew_s``, and
        flag the slowest rank when the skew exceeds ``warn_s``.  Every
        rank returns the same verdict (it is an allgather).  Collective:
        all ranks must call it together."""
        from horovod_tpu.optim.distributed_optimizer import allgather_object
        verdict = self._evaluate(allgather_object(self.report()))
        self.registry.gauge("hvd.step_skew_s").set(verdict["skew_s"])
        if verdict["skew_s"] > self.warn_s:
            self.registry.event(
                "monitor.straggler",
                straggler_rank=verdict["slowest_rank"],
                skew_s=verdict["skew_s"],
                median_step_s=verdict["median_step_s"],
                slowest_step_s=verdict["slowest_step_s"])
        return verdict


# ---------------------------------------------------------------------------
# Pillar 4: SLO goodput windows.
# ---------------------------------------------------------------------------


def _sample_percentile(sorted_vals: list[float], q: float) -> float:
    """Linear-interpolated quantile over a small sorted sample (the
    window is a few hundred traces — exact beats bucketed here)."""
    if not sorted_vals:
        return 0.0
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = q * (len(sorted_vals) - 1)
    i = int(pos)
    frac = pos - i
    if i + 1 >= len(sorted_vals):
        return sorted_vals[-1]
    return sorted_vals[i] + (sorted_vals[i + 1] - sorted_vals[i]) * frac


class SLOWindow:
    """Ring buffer of terminal request traces answering "are we meeting
    SLOs *now*?" — process-lifetime histograms can't: a latency
    regression 10 minutes into a 10-hour run vanishes in their tails.

    A request is GOOD when it terminated ``OK`` AND met its latency
    target: its own ``Request.slo_s`` when set, else the window default
    (``slo_e2e_s`` / ``HVD_TPU_SLO_E2E_S``); with neither, OK alone is
    good (pure completion goodput).  ``goodput()`` is the good fraction
    of the last ``window`` terminal requests; ``report()`` adds windowed
    TTFT/TPOT/E2E percentiles."""

    _GUARDED_BY_LOCK = ("_traces",)

    def __init__(self, window: int = 256, slo_e2e_s: float | None = None):
        if window <= 0:
            raise ValueError(f"window must be positive, got {window}")
        self.slo_e2e_s = (slo_e2e_s if slo_e2e_s is not None
                          else (env_float("HVD_TPU_SLO_E2E_S", 0.0) or None))
        self._lock = threading.Lock()
        self._traces: collections.deque = collections.deque(maxlen=window)

    def add(self, trace: Any, slo_s: float | None = None) -> None:
        """Record one TERMINAL trace (``ServeEngine._finalize_trace``
        calls this); ``slo_s`` is the request's own target, overriding
        the window default."""
        with self._lock:
            self._traces.append((trace, slo_s))

    def __len__(self) -> int:
        """Terminal traces currently in the window (the engine's memory
        accounting sizes the ring with this)."""
        with self._lock:
            return len(self._traces)

    def _good(self, trace: Any, slo_s: float | None) -> bool:
        if trace.status != "OK":
            return False
        target = slo_s if slo_s is not None else self.slo_e2e_s
        if target is None:
            return True
        e2e = trace.e2e_s
        return e2e is not None and e2e <= target

    def goodput(self) -> float:
        """Fraction of windowed terminal requests that were good; 1.0
        when the window is empty (no evidence of badness)."""
        with self._lock:
            items = list(self._traces)
        if not items:
            return 1.0
        return sum(self._good(t, s) for t, s in items) / len(items)

    def report(self) -> dict:
        """Windowed SLO summary: goodput, status mix, and TTFT/TPOT/E2E
        p50/p90/p99 over the last ``window`` terminal requests."""
        with self._lock:
            items = list(self._traces)
        out: dict[str, Any] = {
            "window": self._traces.maxlen,
            "n": len(items),
            "slo_e2e_s": self.slo_e2e_s,
            "goodput": 1.0,
            "statuses": {},
        }
        if not items:
            out.update(ttft_s={}, tpot_s={}, e2e_s={})
            return out
        good = 0
        statuses: dict[str, int] = {}
        series: dict[str, list[float]] = {
            "ttft_s": [], "tpot_s": [], "e2e_s": []}
        for t, s in items:
            good += self._good(t, s)
            statuses[t.status or "?"] = statuses.get(t.status or "?", 0) + 1
            for key in series:
                v = getattr(t, key)
                if v is not None:
                    series[key].append(v)
        out["goodput"] = good / len(items)
        out["statuses"] = dict(sorted(statuses.items()))
        for key, vals in series.items():
            vals.sort()
            out[key] = ({"p50": _sample_percentile(vals, 0.50),
                         "p90": _sample_percentile(vals, 0.90),
                         "p99": _sample_percentile(vals, 0.99),
                         "n": len(vals)} if vals else {})
        return out
