"""Horovod Timeline — Chrome-tracing profiler for the eager engine.

Parity with the reference timeline (reference: horovod/common/timeline.h/.cc,
docs/timeline.md): a ``chrome://tracing`` JSON file written when
``HOROVOD_TIMELINE=<path>`` is set, in which every named tensor is modeled as
its own "process" (pid) whose track shows the phases of its collective:

  NEGOTIATE_ALLREDUCE / NEGOTIATE_ALLGATHER / NEGOTIATE_BROADCAST
      reference timeline.cc:98-132 — time between enqueue and the engine
      deciding to run the op (here: time in the fusion queue until the cycle
      flush picks the tensor up).
  NEGOTIATE_TICK_r<k> / NEGOTIATE_TICK_ALL
      per-rank readiness instants inside the NEGOTIATE span (reference
      timeline.cc:98-132; single-controller jobs see all ranks at once).
  ALLREDUCE / ALLGATHER / BROADCAST  top-level op span (``fused_with: N``
      annotates tensor-fusion grouping)
  DISPATCH / WAIT_FOR_OUTPUT
      TPU-native activity vocabulary replacing the reference's
      MEMCPY_IN_FUSION_BUFFER / NCCL_ALLREDUCE etc. (operations.h:29-46):
      XLA owns the memcpys and the wire, so what the host can observe is
      dispatch (trace/compile/launch) and the wait on the device future
      in ``synchronize``.

Device-side detail (per-HLO timing, ICI traffic) belongs to the JAX/XLA
profiler; :func:`trace_annotation` bridges engine phases into it so both
timelines line up in TensorBoard.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import TextIO

import jax

NEGOTIATE = "NEGOTIATE"
DISPATCH = "DISPATCH"
WAIT_FOR_OUTPUT = "WAIT_FOR_OUTPUT"


class Timeline:
    """Thread-safe Chrome-trace writer (reference timeline.cc:24-188).

    Events are buffered and flushed at most every second (reference
    timeline.cc flush cadence) or on close.
    """

    def __init__(self, path: str, mark_cycles: bool = False) -> None:
        self._lock = threading.Lock()
        self._path = path
        self.mark_cycles = mark_cycles
        self._file: TextIO = open(path, "w")
        self._file.write("[\n")
        self._start = time.perf_counter()
        self._pids: dict[str, int] = {}
        self._next_pid = 1
        self._buffer: list[str] = []
        self._last_flush = time.monotonic()
        self._closed = False

    def _ts_us(self) -> float:
        return (time.perf_counter() - self._start) * 1e6

    def _pid(self, tensor_name: str) -> int:
        pid = self._pids.get(tensor_name)
        if pid is None:
            pid = self._next_pid
            self._next_pid += 1
            self._pids[tensor_name] = pid
            # Tensor-as-process metadata event (reference timeline.cc:51-67).
            self._emit(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": pid,
                    "args": {"name": tensor_name},
                }
            )
            self._emit(
                {"name": "process_sort_index", "ph": "M", "pid": pid,
                 "args": {"sort_index": pid}}
            )
        return pid

    def _emit(self, event: dict) -> None:
        self._buffer.append(json.dumps(event))
        now = time.monotonic()
        if now - self._last_flush > 1.0:
            self._flush_locked()
            self._last_flush = now

    def _flush_locked(self) -> None:
        if self._buffer:
            self._file.write(",\n".join(self._buffer) + ",\n")
            self._buffer.clear()
            self._file.flush()

    def start(self, tensor_name: str, activity: str, args: dict | None = None) -> None:
        with self._lock:
            if self._closed:
                return
            self._emit(
                {"name": activity, "ph": "B", "ts": self._ts_us(),
                 "pid": self._pid(tensor_name), "tid": 0,
                 **({"args": args} if args else {})}
            )

    def end(self, tensor_name: str, activity: str, args: dict | None = None) -> None:
        with self._lock:
            if self._closed:
                return
            self._emit(
                {"name": activity, "ph": "E", "ts": self._ts_us(),
                 "pid": self._pid(tensor_name), "tid": 0,
                 **({"args": args} if args else {})}
            )

    def instant(self, tensor_name: str, activity: str) -> None:
        """Negotiation-tick / scheduler-event instant (reference
        timeline.cc:118-126).  Emitted as a true Chrome instant event —
        ``ph: "i"`` with thread scope — not the zero-width complete
        event (``ph: "X", dur: 0``) earlier versions wrote, which
        chrome://tracing renders as an invisible sliver instead of the
        instant marker."""
        with self._lock:
            if self._closed:
                return
            self._emit(
                {"name": activity, "ph": "i", "ts": self._ts_us(),
                 "pid": self._pid(tensor_name), "tid": 0, "s": "t"}
            )

    def counter(self, tensor_name: str, activity: str,
                values: dict) -> None:
        """Chrome counter event (ph 'C'): a stacked time series on the
        track — the serving scheduler emits queue depth / slot occupancy
        / free-block counts (``SCHED``), cumulative lifecycle totals
        (``LIFECYCLE``: preemptions / timeouts / cancellations /
        rejections / retries / failures) and, with the prefix cache on,
        cumulative reuse totals (``PREFIX``: hits / blocks_reused /
        tokens_skipped / evictions) per step through this, and
        speculative decoding its per-round acceptance counts.
        ``values`` maps series name → number."""
        with self._lock:
            if self._closed:
                return
            self._emit(
                {"name": activity, "ph": "C", "ts": self._ts_us(),
                 "pid": self._pid(tensor_name), "args": values}
            )

    def async_start(self, tensor_name: str, activity: str, aid: int) -> None:
        """Begin an *async* span (Chrome ph 'b'): unlike B/E duration events
        these are matched by id, not the per-(pid,tid) stack, so spans that
        overlap other activities on the same track cannot mis-nest."""
        with self._lock:
            if self._closed:
                return
            self._emit(
                {"name": activity, "ph": "b", "cat": activity,
                 "id": aid, "ts": self._ts_us(),
                 "pid": self._pid(tensor_name), "tid": 0}
            )

    def async_end(self, tensor_name: str, activity: str, aid: int) -> None:
        with self._lock:
            if self._closed:
                return
            self._emit(
                {"name": activity, "ph": "e", "cat": activity,
                 "id": aid, "ts": self._ts_us(),
                 "pid": self._pid(tensor_name), "tid": 0}
            )

    def async_span(self, tensor_name: str, activity: str, aid: int,
                   t0: float, t1: float) -> None:
        """Closed async span with explicit ``perf_counter`` endpoints:
        both the 'b' and 'e' events in one lock pass, back-dated to the
        caller's own timestamps rather than emission time.  The serving
        profiler uses this so a whole tick's phase spans can be written
        after the fact without skewing their measured boundaries."""
        with self._lock:
            if self._closed:
                return
            pid = self._pid(tensor_name)
            self._emit(
                {"name": activity, "ph": "b", "cat": activity,
                 "id": aid, "ts": (t0 - self._start) * 1e6,
                 "pid": pid, "tid": 0}
            )
            self._emit(
                {"name": activity, "ph": "e", "cat": activity,
                 "id": aid, "ts": (t1 - self._start) * 1e6,
                 "pid": pid, "tid": 0}
            )

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._flush_locked()
            # Chrome tracing tolerates a trailing comma with a closing ']'
            # written on a fresh line; emit a terminator event for strictness.
            self._file.write(json.dumps({"name": "done", "ph": "i", "ts": self._ts_us(), "pid": 0, "s": "g"}))
            self._file.write("\n]\n")
            self._file.close()


def trace_annotation(name: str):
    """Bridge an engine phase into the JAX/XLA profiler (TensorBoard trace).

    The reference points users at chrome://tracing only; on TPU the XLA
    profiler is the richer source, so engine phases are mirrored there.
    """
    return jax.profiler.TraceAnnotation(name)


def maybe_create(path: str | None,
                 mark_cycles: bool = False) -> Timeline | None:
    """Create a timeline if configured.  Rank-0-only in multi-host jobs
    (reference operations.cc:1614-1618 gates on is_coordinator) —
    UNLESS ``path`` contains a ``{rank}`` template, in which case EVERY
    rank writes its own file (``trace_{rank}.json`` →
    ``trace_0.json`` ...), the per-rank inputs
    ``tools/timeline_summary.py --merge`` stitches into one fleet
    trace."""
    if not path:
        return None
    if "{rank}" in path:
        path = path.replace("{rank}", str(jax.process_index()))
    elif jax.process_index() != 0:
        return None
    dirname = os.path.dirname(path)
    if dirname:
        os.makedirs(dirname, exist_ok=True)
    return Timeline(path, mark_cycles=mark_cycles)


def start_timeline(path: str, mark_cycles: bool = False,
                   profiler_dir: str | None = None) -> None:
    """Start recording a timeline mid-run — the ``hvd.start_timeline``
    API the Horovod project added in 0.20 (the reference generation could
    only enable it via env var at init).

    ``mark_cycles=True`` adds an instant event per engine cycle tick, the
    same knob as upstream.  Rank-0 only in multi-host jobs (no-op
    elsewhere); raises if a timeline is already active.

    ``profiler_dir`` additionally captures a ``jax.profiler.trace`` for
    the same window (SURVEY §5's TPU mapping of timeline.cc:24-188): the
    engine's NEGOTIATE/DISPATCH phases land in the Chrome trace while the
    device-side detail (per-HLO timing, ICI traffic) lands in the XLA
    profile, and the ``trace_annotation`` bridge names line up across the
    two in TensorBoard.  Stopped by ``stop_timeline``; rank-0 only, like
    the timeline itself.
    """
    from horovod_tpu import basics

    st = basics._require_init()
    with st.lock:
        if st.timeline is not None:
            raise ValueError(
                "a timeline is already active; call stop_timeline() first"
            )
        tl = maybe_create(path, mark_cycles=mark_cycles)
        if tl is not None and profiler_dir:
            # Before st.timeline is assigned: a start_trace failure (e.g. a
            # user-started profiler session already active) must not leave
            # a half-open timeline that start_timeline retries reject.
            try:
                jax.profiler.start_trace(profiler_dir)
            except Exception:
                tl.close()
                raise
            st.profiler_active = True
        st.timeline = tl
        if st.engine is not None and tl is not None:
            st.engine.timeline = tl
            if st.engine.controller is not None:
                st.engine.controller.enable_tick_trace()


def stop_timeline() -> None:
    """Stop the active timeline and finalize its file (``hvd.stop_timeline``
    parity).  Idempotent when none is active."""
    from horovod_tpu import basics

    st = basics._require_init()
    with st.lock:
        tl, st.timeline = st.timeline, None
        profiling, st.profiler_active = st.profiler_active, False
        if st.engine is not None:
            st.engine.timeline = None
            if st.engine.controller is not None and tl is not None:
                # The drain site is gated on an active timeline; without
                # this the rank-0 tick buffer would grow with no consumer.
                st.engine.controller.enable_tick_trace(False)
    if profiling:
        try:
            jax.profiler.stop_trace()
        except Exception as e:  # pragma: no cover - depends on jax state
            # A profiler failure (xplane write error, trace already
            # stopped by user code) must not lose the Chrome trace below.
            import warnings

            warnings.warn(
                f"jax profiler stop failed ({type(e).__name__}: {e}); "
                "the timeline file is still finalized",
                RuntimeWarning,
                stacklevel=2,
            )
    if tl is not None:
        tl.close()
