"""EagerDistributedOptimizer — the torch-frontend optimizer semantics.

Parity with the reference's hook-based ``_DistributedOptimizer``
(reference: horovod/torch/__init__.py:86-267): during backward, each
parameter's gradient fires an async allreduce as soon as it is produced
(grad-accumulator hooks, :120-165); ``step()`` synchronizes every handle,
decompresses, and applies the base optimizer (:189-227).  Fork extras
carried over: ``is_sparse`` top-k mode (:141-151, 202-216) and the
``local`` no-communication flag (:115, 158).

TPU-native shape: there are no backward hooks in a functional autodiff
world, so "backward" is explicit — :meth:`backward` computes *per-rank*
gradients (``vmap`` of ``value_and_grad`` over the rank axis of a
rank-major batch) and immediately enqueues one named async allreduce per
parameter, exactly the traffic pattern the hooks produce.  The engine's
cycle thread fuses and dispatches them while Python is still walking the
tree; :meth:`step` then drains the handles and applies the update.

For the fully-compiled fast path use
:func:`horovod_tpu.DistributedOptimizer` instead; this class exists for
define-by-run workflows and API parity.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax

from horovod_tpu import basics
from horovod_tpu.ops import eager as eager_ops
from horovod_tpu.ops.compression import Compression, TopKCompressor


def _path_name(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:  # pragma: no cover - future pytree key types
            parts.append(str(p))
    return ".".join(parts) or "param"


class EagerDistributedOptimizer:
    """Async-handle distributed optimizer over an optax base optimizer."""

    def __init__(
        self,
        optimizer: optax.GradientTransformation,
        *,
        compression=Compression.none,
        is_sparse: bool = False,
        sparse_ratio: float = 0.01,
        local: bool = False,
        backward_passes_per_step: int = 1,
        op=None,
    ):
        """``op=`` selects the gradient combination — ``hvd.Average``
        (default), ``hvd.Sum``, or ``hvd.Adasum`` (the scaled-sensitivity
        rule; torch ``DistributedOptimizer(op=hvd.Adasum)`` parity).
        ``process_set`` is deliberately absent: this class drives ONE
        replicated parameter copy, and subset reductions make ranks
        diverge — use the compiled ``DistributedOptimizer(process_set=...)``
        inside shard_map with rank-major params for that."""
        from horovod_tpu.ops.collective_ops import Adasum, Average, Sum
        from horovod_tpu.ops.powersgd import ErrorFeedback

        op = Average if op is None else op
        if op not in (Sum, Average, Adasum):
            raise ValueError(
                f"op= accepts hvd.Sum / hvd.Average / hvd.Adasum, got {op}"
            )
        if op is Adasum and is_sparse:
            raise ValueError("Adasum does not compose with the sparse path")
        # Error feedback on the hook path: the optimizer OBJECT holds the
        # per-parameter residuals (the define-by-run analogue of the state
        # the compiled DistributedOptimizer threads through opt_state).
        self.error_feedback: ErrorFeedback | None = None
        if isinstance(compression, ErrorFeedback):
            self.error_feedback = compression
            compression = Compression.none   # the EF path picks the wire
            if is_sparse or local:
                raise ValueError(
                    "ErrorFeedback compression already defines the wire; "
                    "drop is_sparse/local"
                )
            if op is Adasum:
                raise ValueError(
                    "Adasum does not compose with ErrorFeedback compression"
                )
        if op is Adasum and callable(
            getattr(compression, "quantized_allreduce", None)
        ):
            # Fail here, not asynchronously inside the first step()'s
            # handle drain, far from the misconfiguration.
            raise ValueError(
                "Adasum does not support wire-format compressors (int8); "
                "use Compression.fp16/bf16"
            )
        self.op = op
        self.tx = optimizer
        self.compression = compression
        self.is_sparse = is_sparse
        self.sparse_ratio = sparse_ratio
        self.local = local
        self.backward_passes_per_step = backward_passes_per_step
        self._handles: list[tuple[str, int]] = []
        self._treedef = None
        self._accum: list[jax.Array] | None = None
        self._passes = 0
        self._loss_handle: int | None = None
        self._grad_fn_cache: dict[int, Callable] = {}
        self._residuals: dict[str, jax.Array] = {}
        # handle → (grad name, residual-to-commit): the residual write is
        # DEFERRED until the handle drains successfully in synchronize();
        # committing at enqueue time would absorb the dropped component
        # into EF state even when the collective errors and the step is
        # retried (advisor r2).
        self._pending_residuals: dict[int, tuple[str, jax.Array]] = {}
        self._handle_dtypes: dict[int, Any] = {}

    def init(self, params: Any):
        return self.tx.init(params)

    # ------------------------------------------------------------- backward

    def backward(self, loss_fn: Callable[[Any, Any], jax.Array], params: Any,
                 batch: Any) -> jax.Array:
        """Compute per-rank grads and fire async allreduces (the hook phase).

        ``batch`` leaves are rank-major ``[size * b, ...]``; the per-rank
        grad is ``vmap(value_and_grad(loss_fn))`` over the rank axis.
        Returns the rank-averaged loss (itself an async allreduce, so the
        value is a future under JAX's async dispatch).
        """
        n = basics.size()
        key = id(loss_fn)
        vg = self._grad_fn_cache.get(key)
        if vg is None:
            vg = jax.jit(
                jax.vmap(jax.value_and_grad(loss_fn), in_axes=(None, 0))
            )
            self._grad_fn_cache[key] = vg

        def split_ranks(leaf):
            return leaf.reshape((n, leaf.shape[0] // n) + leaf.shape[1:])

        per_rank_batch = jax.tree.map(split_ranks, batch)
        losses, grads = vg(params, per_rank_batch)  # leaves: [size, ...]

        flat, self._treedef = jax.tree.flatten_with_path(grads)
        if self._accum is not None:
            flat = [(p, a + g) for (p, g), a in zip(flat, self._accum)]
        self._passes += 1
        if self._passes < self.backward_passes_per_step:
            # Local accumulation between communication steps (reference
            # backward_passes_per_step, torch/__init__.py:106-118).
            self._accum = [g for _, g in flat]
            return jnp.mean(losses)
        self._accum = None
        self._passes = 0

        if not self.local:
            for path, g in flat:
                name = "grad." + _path_name(path)
                if self.error_feedback is not None:
                    h = self._enqueue_with_error_feedback(name, g)
                elif self.is_sparse:
                    h = eager_ops.sparse_allreduce_async(
                        g, name=name, average=True, ratio=self.sparse_ratio
                    )
                else:
                    h = eager_ops.allreduce_async(
                        g, name=name, op=self.op,
                        compression=self.compression,
                    )
                self._handles.append((name, h))
        else:
            # self.local: keep the controller's own (rank-0) gradient with
            # no communication, matching the fork's skip-communication mode.
            self._local_grads = [g[0] for _, g in flat]
        self._loss_handle = eager_ops.allreduce_async(
            losses, average=True, name="loss"
        )
        return jnp.mean(losses)

    def _enqueue_with_error_feedback(self, name: str, g: jax.Array) -> int:
        """Residual-corrected lossy allreduce on the hook path.

        ``g`` is rank-major [size, ...]; the residual is rank-major too
        (each rank's own compression error), keyed by the stable gradient
        name.  The wire is the inner compressor's collective (top-k
        allgather / int8 all-gather); the local ``transmitted`` copy is
        ``ErrorFeedback.transmitted`` — the SAME definition the compiled
        path uses — and int8 ops enqueue with ``no_fuse=True`` so the
        wire quantizes THIS tensor alone (a fused buffer's block scales
        would differ from the per-tensor roundtrip and bias the residual).
        """
        inner = self.error_feedback.inner
        res = self._residuals.get(name)
        if res is None or res.shape != g.shape:
            res = jnp.zeros(g.shape, jnp.float32)
        corrected = g.astype(jnp.float32) + res
        from horovod_tpu.ops.collective_ops import Average

        transmitted = jax.vmap(self.error_feedback.transmitted)(corrected)
        if isinstance(inner, TopKCompressor):
            h = eager_ops.sparse_allreduce_async(
                corrected, name=name, average=self.op is Average,
                ratio=inner.ratio, k=inner.k,
            )
        else:                                 # quantized wire (int8/int4)
            # ErrorFeedback.__init__ normalizes inner to an instance.  The
            # one-shot variant keeps the residual exact (see
            # Int8Compressor.one_shot); two-shot's second rounding would
            # leak past it.  Third-party protocol conformers without a
            # one_shot() keep their own default.
            cls = type(inner)
            if callable(getattr(cls, "one_shot", None)):
                cls = cls.one_shot()
            h = eager_ops.allreduce_async(
                corrected, name=name, op=self.op,
                compression=cls, no_fuse=True,
            )
        self._pending_residuals[h] = (name, corrected - transmitted)
        # The wire moved fp32; restore the caller's grad dtype on drain so
        # opt_state dtypes match init (the compiled path's .astype(g.dtype)).
        self._handle_dtypes[h] = g.dtype
        return h

    # ----------------------------------------------------------------- step

    def synchronize(self) -> Any:
        """Drain all outstanding gradient handles → replicated grad pytree
        (reference synchronize(), torch/__init__.py:189-222)."""
        if self._treedef is None:
            raise RuntimeError(
                "EagerDistributedOptimizer.synchronize() before backward()"
            )
        if self.local:
            leaves = self._local_grads
        else:
            leaves = []
            commits: list[tuple[str, jax.Array]] = []
            try:
                for _, h in self._handles:
                    out = eager_ops.synchronize(h)
                    pend = self._pending_residuals.pop(h, None)
                    if pend is not None:
                        commits.append(pend)
                    want = self._handle_dtypes.pop(h, None)
                    if want is not None and out.dtype != want:
                        out = out.astype(want)
                    leaves.append(out)
                # Commit EF residuals only after the WHOLE drain succeeded:
                # a mid-loop failure discards every reduced gradient (the
                # caller retries the step), so residuals of already-drained
                # handles must stay at their prior values too — their
                # transmitted components were never applied to params.
                for name_r, res in commits:
                    self._residuals[name_r] = res
            except BaseException:
                # Failed drain: release EVERY undrained handle and drop its
                # bookkeeping so EF state keeps the PRIOR residuals (the
                # dropped components were never transmitted) and a retried
                # backward()+step() starts from clean handle state instead
                # of re-waiting on released handles.
                for _, h in self._handles:
                    self._pending_residuals.pop(h, None)
                    self._handle_dtypes.pop(h, None)
                    eager_ops.release(h)
                self._handles = []
                raise
        self._handles = []
        return jax.tree.unflatten(self._treedef, leaves)

    def step(self, params: Any, opt_state: Any) -> tuple[Any, Any]:
        """synchronize + base ``optimizer.step`` (reference :224-227)."""
        if self._passes != 0:
            raise RuntimeError(
                "step() called mid-accumulation: backward() has run "
                f"{self._passes}/{self.backward_passes_per_step} passes"
            )
        grads = self.synchronize()
        updates, opt_state = self.tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    def last_loss(self):
        """The rank-averaged loss of the last backward (blocks)."""
        if self._loss_handle is None:
            return None
        out = eager_ops.synchronize(self._loss_handle)
        self._loss_handle = None
        return jnp.mean(out)
