"""FSDP-style fully-sharded data parallelism — params AND optimizer state
live sharded between steps.

The memory ladder this framework offers (per chip, Adam, n chips):

=====================  =========================================
replicated DP           params P + grads P + state 2P
ZeRO (optim/zero.py)    params P + grads P/n + state 2P/n
FSDP (this module)      params P/n + state 2P/n (+ transient
                        gathered layers during compute)
=====================  =========================================

No reference equivalent (the reference replicates everything).  The
TPU-native form is *sharding annotations, not code*: each parameter's
largest divisible axis is sharded over the data axis and the training
step is a plain ``jit`` — GSPMD inserts the per-layer all-gathers before
use and reduce-scatters the gradients, overlapping both with compute.
That is the "pick a mesh, annotate shardings, let XLA insert collectives"
recipe, applied to parameter storage.

Use :func:`fsdp_partition_specs` to derive the specs,
:func:`make_fsdp_train_step` for the canonical step::

    specs = fsdp_partition_specs(params)
    step, init = make_fsdp_train_step(loss_fn, optax.adamw(3e-4))
    params = shard_params(params, specs)        # place shards
    opt_state = init(params)                    # state inherits the specs
    out = step(params, opt_state, batch)        # everything stays sharded
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu import basics
from horovod_tpu.basics import AXIS_NAME


class FsdpStepResult(NamedTuple):
    params: Any          # sharded per fsdp_partition_specs
    opt_state: Any       # sharded alike
    loss: jax.Array


def fsdp_partition_specs(
    params: Any,
    *,
    axis_name: str = AXIS_NAME,
    mesh: Mesh | None = None,
    min_shard_elems: int = 1024,
) -> Any:
    """Per-leaf PartitionSpec: the LARGEST axis divisible by the mesh-axis
    size is sharded; leaves smaller than ``min_shard_elems`` (or with no
    divisible axis) stay replicated — gathering a bias costs more latency
    than its bytes save."""
    if mesh is None:
        mesh = basics.mesh()
    n = int(np.prod([mesh.shape[a] for a in (
        axis_name if isinstance(axis_name, tuple) else (axis_name,)
    )]))

    def spec(leaf) -> P:
        if leaf.size < min_shard_elems:
            return P()
        dims = sorted(
            range(leaf.ndim), key=lambda d: leaf.shape[d], reverse=True
        )
        for d in dims:
            if leaf.shape[d] % n == 0:
                out = [None] * leaf.ndim
                out[d] = axis_name
                return P(*out)
        return P()

    return jax.tree.map(spec, params)


def shard_params(params: Any, specs: Any, *, mesh: Mesh | None = None) -> Any:
    """Place a (host or replicated) param pytree onto its FSDP shardings."""
    if mesh is None:
        mesh = basics.mesh()
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def _state_specs(opt_state: Any, params: Any, specs: Any) -> Any:
    """Optimizer-state specs: a state leaf matching some param's shape
    (Adam moments, momentum, …) inherits that param's spec; everything
    else (step counts, scalars) replicates."""
    by_shape: dict[tuple, P] = {}
    for leaf, s in zip(jax.tree.leaves(params),
                       jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))):
        by_shape.setdefault(tuple(leaf.shape), s)

    def spec(leaf) -> P:
        return by_shape.get(tuple(getattr(leaf, "shape", ())), P())

    return jax.tree.map(spec, opt_state)


def make_fsdp_train_step(
    loss_fn: Callable[..., jax.Array],
    optimizer: optax.GradientTransformation,
    *,
    mesh: Mesh | None = None,
    axis_name: str = AXIS_NAME,
    specs: Any = None,
    donate: bool = True,
) -> tuple[Callable[..., FsdpStepResult], Callable[[Any], Any]]:
    """Build ``(step, init_opt_state)`` with everything sharded.

    ``optimizer`` is a PLAIN optax transformation — no
    ``DistributedOptimizer`` wrapper and no explicit psum: the batch is
    sharded over ``axis_name``, so the loss is already the global mean and
    GSPMD emits the gradient reduce-scatters that the sharded-parameter
    output layout demands.

    ``specs``: precomputed :func:`fsdp_partition_specs` (derived from the
    params on first ``init`` call when None).
    """
    if mesh is None:
        mesh = basics.mesh()
    user_specs = specs is not None
    state: dict = {"specs": specs}

    def init(params: Any) -> Any:
        if not user_specs:
            state["specs"] = fsdp_partition_specs(
                params, axis_name=axis_name, mesh=mesh
            )
        opt_state = jax.eval_shape(optimizer.init, params)
        out_sh = jax.tree.map(
            lambda s: NamedSharding(mesh, s),
            _state_specs(opt_state, params, state["specs"]),
            is_leaf=lambda x: isinstance(x, P),
        )
        return jax.jit(optimizer.init, out_shardings=out_sh)(params)

    def raw_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return FsdpStepResult(params, opt_state, loss)

    compiled: dict = {}

    def _shape_key(tree) -> tuple:
        leaves, treedef = jax.tree.flatten(tree)
        return (treedef, tuple(
            (tuple(l.shape), str(getattr(l, "dtype", ""))) for l in leaves
        ))

    def step(params, opt_state, batch) -> FsdpStepResult:
        # Re-key per (structure, shapes, dtypes) — one step function may
        # serve differently-shaped models (the zero.py _build pattern);
        # a single forever-cache would apply the first model's shardings
        # to the second's pytree.
        key = (_shape_key(params), _shape_key(opt_state), _shape_key(batch))
        fn = compiled.get(key)
        if fn is None:
            if not user_specs:
                state["specs"] = fsdp_partition_specs(
                    params, axis_name=axis_name, mesh=mesh
                )
            ns = lambda s: NamedSharding(mesh, s)
            p_sh = jax.tree.map(ns, state["specs"],
                                is_leaf=lambda x: isinstance(x, P))
            o_sh = jax.tree.map(
                ns, _state_specs(opt_state, params, state["specs"]),
                is_leaf=lambda x: isinstance(x, P),
            )
            b_sh = jax.tree.map(lambda _: ns(P(axis_name)), batch)
            fn = jax.jit(
                raw_step,
                in_shardings=(p_sh, o_sh, b_sh),
                out_shardings=FsdpStepResult(p_sh, o_sh, ns(P())),
                donate_argnums=(0, 1) if donate else (),
            )
            compiled[key] = fn
        out = fn(params, opt_state, batch)
        if jax.default_backend() == "cpu":
            # Same CPU-simulation throttle as make_train_step: cap async
            # depth at 1 to avoid XLA's in-process rendezvous deadlock.
            jax.block_until_ready(out.loss)
        return out

    return step, init
